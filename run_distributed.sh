#!/usr/bin/env bash
# Multi-host launch — the torchrun replacement (reference run_distributed.sh:2-3).
#
# TPU model: ONE process per host sees all local chips; hosts rendezvous via
# jax.distributed.initialize.  On a single host this collapses to a plain
# invocation (all chips already visible) — no process-per-device spawning.
#
# Multi-host usage (run on every host, e.g. via gcloud ... --worker=all):
#   FDT_COORDINATOR=<host0>:8476 FDT_NUM_PROCESSES=<n> FDT_PROCESS_ID=<i> \
#     bash run_distributed.sh
set -euo pipefail

DIST_FLAGS=""
if [[ "${FDT_NUM_PROCESSES:-1}" -gt 1 ]]; then
  DIST_FLAGS="--distributed"
fi

python resnet50_test.py ${DIST_FLAGS} --bs 256 --lr 0.01 --meta_learning --ngd "$@"
python transformer_test.py ${DIST_FLAGS} --bs 64 --ngd "$@"
