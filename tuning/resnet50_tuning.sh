#!/usr/bin/env bash
# ResNet grid: mixup alpha x LR-decay gamma — the reference sweep
# (tuning/resnet50_tuning.sh:1-11: 3 alphas x 3 gammas, NGD, 5 epochs,
# 1/10 subset) as one aggregated run.
set -euo pipefail
cd "$(dirname "$0")/.."
python tuning/sweep.py resnet --ngd \
  --grid alpha=0.2,0.4,0.6 gamma=0.1,0.2,0.3 \
  --out tuning/resnet_results.json "$@"
