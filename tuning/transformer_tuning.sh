#!/usr/bin/env bash
# Transformer grid: lr x weight decay — the reference sweep
# (tuning/transformer_tuning.sh:1-11: 3 lrs x 3 weight decays, 5 epochs,
# 1/10 subset; note its line 8 echoes a misspelled --weighted_decay flag,
# fixed here) as one aggregated run.
set -euo pipefail
cd "$(dirname "$0")/.."
python tuning/sweep.py transformer --ngd \
  --grid lr=1e-5,5e-5,1e-4 weight_decay=1e-4,1e-3,1e-2 \
  --out tuning/transformer_results.json "$@"
