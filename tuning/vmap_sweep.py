#!/usr/bin/env python
"""vmap-over-trials hyperparameter sweep — one compiled program trains K
configurations simultaneously.

The reference runs its grid as K sequential full processes
(tuning/resnet50_tuning.sh bash loop).  On TPU, small-model trials leave
the chip mostly idle; vmapping the train step over a trial axis turns the
sweep into one big batched program (K× the matmul batch — MXU-friendly),
and sharding the trial axis over the `dp` mesh axis spreads trials across
chips/hosts (BASELINE.json config 5).

Per-trial hyperparameters:
  * lr     — via optax.inject_hyperparams, so the learning rate lives in
             the (vmapped) optimizer state instead of a baked schedule;
  * alpha  — mixup Beta parameter, traced into jax.random.beta;
  * gamma  — optional per-trial LR-decay factor: the NGD tuning pairing's
             step schedule (decay by gamma every `decay_steps`,
             optim/builder.py "step" / tuning/resnet50_tuning.py:435)
             is computed per step in the scan body and written into the
             injected hyperparams — a baked optax schedule would be one
             shared closure, which is exactly what a per-trial grid can't
             use;
  * seed   — independent PRNG stream per trial.

Supported optimizers: sgd | madgrad | mirror_madgrad | ngd.  NGD's
Fisher state is a pure pytree (optim/ngd.py ScaleByNGDState), so it
vmaps like any other leaf; its update-period gating reads the per-trial
`t` scalar, which the trial axis carries too.  This makes the
reference's flagship alpha x gamma NGD grid
(tuning/resnet50_tuning.sh:1-11) runnable as ONE compiled program.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, Iterable, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from faster_distributed_training_tpu.config import TrainConfig
from faster_distributed_training_tpu.models import get_model
from faster_distributed_training_tpu.optim.madgrad import (madgrad,
                                                           mirror_madgrad)
from faster_distributed_training_tpu.optim.ngd import ngd
from faster_distributed_training_tpu.train import mixup_data, mixup_criterion
from faster_distributed_training_tpu.train.losses import cross_entropy

_FACTORIES = {
    "sgd": lambda lr: optax.sgd(lr, momentum=0.9),
    "madgrad": lambda lr: madgrad(lr),
    "mirror_madgrad": lambda lr: mirror_madgrad(lr),
    # the reference tuning grid's optimizer (resnet50_tuning.sh --ngd):
    # momentum matches the reference pairing; Fisher state vmaps per trial
    "ngd": lambda lr: ngd(lr, momentum=0.9, use_ngd=True),
}


def _make_tx(optimizer: str) -> optax.GradientTransformation:
    factory = _FACTORIES[optimizer]
    return optax.inject_hyperparams(
        lambda learning_rate: factory(learning_rate))(learning_rate=0.0)


def vmap_trials(cfg: TrainConfig,
                lrs: Iterable[float],
                alphas: Iterable[float],
                data: Tuple[np.ndarray, np.ndarray],
                optimizer: str = "sgd",
                steps: Optional[int] = None,
                mesh=None,
                model=None,
                gammas: Optional[Iterable[float]] = None,
                decay_steps: Optional[int] = None) -> Dict[str, np.ndarray]:
    """Train K=len(lrs) trials in one vmapped program; returns per-trial
    final loss / train accuracy arrays.

    lrs/alphas must have equal length K.  `data` is an in-memory (images
    NHWC float, labels) tuple; every trial sees the same batch stream
    (common random numbers — variance reduction for the grid comparison).
    With `mesh`, trial-axis leaves are sharded over the `dp` axis.
    `model` overrides the cfg.model lookup with an arbitrary Flax module
    (tests use a tiny CNN — vmapping a full ResNet multiplies its already
    large graph by K, which the single-core CPU compiler chews on for
    many minutes).
    gammas (optional, length K): per-trial step-decay factor — the
    effective LR at step s is lr * gamma^(s // decay_steps), the NGD
    tuning pairing (optim/builder.py "step": decay every 2 epochs).
    decay_steps defaults to 2 epochs' worth of steps.
    """
    lrs = jnp.asarray(list(lrs), jnp.float32)
    alphas = jnp.asarray(list(alphas), jnp.float32)
    K = lrs.shape[0]
    assert alphas.shape[0] == K, "lrs and alphas must have equal length"
    gammas = (None if gammas is None
              else jnp.asarray(list(gammas), jnp.float32))
    assert gammas is None or gammas.shape[0] == K

    model = model if model is not None else get_model(cfg.model,
                                                      cfg.num_classes)
    tx = _make_tx(optimizer)
    x_all, y_all = data
    x_all = jnp.asarray(x_all, jnp.float32)
    y_all = jnp.asarray(y_all, jnp.int32)
    n = x_all.shape[0]
    bs = min(cfg.batch_size, n)
    steps_per_epoch = max(n // bs, 1)
    steps = steps or steps_per_epoch * cfg.epochs
    if decay_steps is None:
        decay_steps = 2 * steps_per_epoch      # "step" pairing: every 2 epochs

    def init_trial(seed, lr):
        variables = model.init({"params": seed}, x_all[:1], train=False)
        opt_state = tx.init(variables["params"])
        opt_state = opt_state._replace(hyperparams={"learning_rate": lr})
        return (variables["params"], variables.get("batch_stats", {}),
                opt_state)

    def trial_step(carry, inputs, alpha, lr_now):
        params, stats, opt_state, rng = carry
        xb, yb = inputs
        rng, k_mix, k_drop = jax.random.split(rng, 3)
        if lr_now is not None:
            # per-step scheduled LR written into the injected hyperparams
            opt_state = opt_state._replace(
                hyperparams={**opt_state.hyperparams,
                             "learning_rate": lr_now})

        def loss_fn(p):
            xm, y_a, y_b, lam = mixup_data(k_mix, xb, yb, alpha)
            out, mutated = model.apply(
                {"params": p, "batch_stats": stats}, xm, train=True,
                rngs={"dropout": k_drop}, mutable=["batch_stats"])
            loss = mixup_criterion(cross_entropy, out, y_a, y_b, lam)
            return loss, (mutated.get("batch_stats", stats), out, y_a)

        (loss, (stats, out, y_a)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        acc = jnp.mean(jnp.argmax(out, -1) == y_a)
        return (params, stats, opt_state, rng), (loss, acc)

    seeds = jax.vmap(lambda i: jax.random.fold_in(
        jax.random.PRNGKey(cfg.seed), i))(jnp.arange(K))

    @jax.jit
    def run(seeds, lrs, alphas):
        states = jax.vmap(init_trial)(seeds, lrs)
        rngs = jax.vmap(lambda s: jax.random.fold_in(s, 7))(seeds)

        def scan_body(carry, step_idx):
            params, stats, opt_state, rngs = carry
            start = (step_idx * bs) % max(n - bs + 1, 1)
            xb = jax.lax.dynamic_slice_in_dim(x_all, start, bs)
            yb = jax.lax.dynamic_slice_in_dim(y_all, start, bs)
            if gammas is not None:
                lr_now = lrs * gammas ** (step_idx // decay_steps)
                in_axes = (0, None, 0, 0)
            else:
                lr_now = None
                in_axes = (0, None, 0, None)
            (params, stats, opt_state, rngs), (loss, acc) = jax.vmap(
                trial_step, in_axes=in_axes
            )((params, stats, opt_state, rngs), (xb, yb), alphas, lr_now)
            return (params, stats, opt_state, rngs), (loss, acc)

        carry = (states[0], states[1], states[2], rngs)
        carry, (losses, accs) = jax.lax.scan(
            scan_body, carry, jnp.arange(steps))
        return losses, accs

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        trial_sharding = NamedSharding(mesh, P("dp"))
        seeds, lrs, alphas = (jax.device_put(a, trial_sharding)
                              for a in (seeds, lrs, alphas))
    losses, accs = run(seeds, lrs, alphas)
    return {"final_loss": np.asarray(losses[-1]),
            "final_acc": np.asarray(accs[-1]),
            "loss_curve": np.asarray(losses),
            "acc_curve": np.asarray(accs)}


def main(argv=None):
    import argparse

    from faster_distributed_training_tpu.data import synthetic_cifar
    from faster_distributed_training_tpu.parallel import make_mesh

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="resnet18")
    p.add_argument("--optimizer", default="sgd",
                   choices=sorted(_FACTORIES))
    p.add_argument("--lrs", default="0.01,0.05,0.1,0.2")
    p.add_argument("--alphas", default="0.2,0.2,0.2,0.2")
    p.add_argument("--gammas", default="",
                   help="per-trial LR step-decay factors (the reference "
                        "NGD grid's gamma axis, resnet50_tuning.sh:2)")
    p.add_argument("--bs", type=int, default=64)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--device", default="auto")
    p.add_argument("--mesh_trials", action="store_true",
                   help="shard the trial axis over a dp mesh")
    args = p.parse_args(argv)

    cfg = TrainConfig(model=args.model, batch_size=args.bs, device=args.device)
    from faster_distributed_training_tpu.cli import setup_platform
    setup_platform(cfg)
    lrs = [float(v) for v in args.lrs.split(",")]
    alphas = [float(v) for v in args.alphas.split(",")]
    gammas = ([float(v) for v in args.gammas.split(",")]
              if args.gammas else None)
    data = synthetic_cifar(n=1024)
    mesh = make_mesh(("dp",)) if args.mesh_trials else None
    out = vmap_trials(cfg, lrs, alphas, data, optimizer=args.optimizer,
                      steps=args.steps, mesh=mesh, gammas=gammas)
    print(f"{'lr':>8} {'alpha':>6} {'gamma':>6} {'loss':>8} {'acc':>6}")
    for i, (lr, a) in enumerate(zip(lrs, alphas)):
        g = gammas[i] if gammas else float("nan")
        print(f"{lr:>8.4g} {a:>6.2f} {g:>6.2f} "
              f"{out['final_loss'][i]:>8.4f} {out['final_acc'][i]:>6.3f}")


if __name__ == "__main__":
    main()
