#!/usr/bin/env python
"""Grid-search tuning harness — the reference's tuning/ subtree re-designed.

The reference replays full training scripts on a 1/10 stride subset for 5
epochs, driven by bash loops, with results read manually from stdout
(tuning/resnet50_tuning.sh, tuning/transformer_tuning.sh; SURVEY.md §3.5).
Here ONE runner does the grid in-process (no re-import / re-compile of
identical shapes between trials — XLA's compile cache persists across
trials), and aggregates results into a JSON file + printed table, which
the reference never had.

Usage (mirrors the reference sweeps):
  python tuning/sweep.py resnet --ngd --grid alpha=0.2,0.4,0.6 gamma=0.1,0.2,0.3
  python tuning/sweep.py transformer --ngd --grid lr=1e-5,5e-5,1e-4 weight_decay=1e-4,1e-3,1e-2

Any TrainConfig field with a float/int value can be swept.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from faster_distributed_training_tpu.config import TrainConfig  # noqa: E402


def parse_grid(items: List[str]) -> Dict[str, List[float]]:
    grid = {}
    for item in items:
        name, _, vals = item.partition("=")
        if not vals:
            raise SystemExit(f"bad --grid entry {item!r}; want name=v1,v2,...")
        grid[name] = [float(v) for v in vals.split(",")]
    return grid


def run_sweep(base: TrainConfig, grid: Dict[str, List[float]],
              out_path: str = "tuning/results.json") -> List[dict]:
    from faster_distributed_training_tpu.cli import run_training

    names = sorted(grid)
    results = []
    combos = list(itertools.product(*(grid[n] for n in names)))
    for i, combo in enumerate(combos):
        overrides = dict(zip(names, combo))
        # int-valued fields must stay ints through the float grid parse
        for k, v in overrides.items():
            if isinstance(getattr(base, k), int) and not isinstance(
                    getattr(base, k), bool):
                overrides[k] = int(v)
        cfg = base.replace(**overrides, plot=False)
        t0 = time.monotonic()
        print(f"[sweep {i + 1}/{len(combos)}] {overrides}")
        out = run_training(cfg)
        results.append({
            "params": overrides,
            "best_acc": out["best_acc"],
            "final_train_loss": out["history"]["train_loss"][-1]
            if out["history"]["train_loss"] else None,
            "epoch_times": out["history"]["epoch_time"],
            "wall_s": round(time.monotonic() - t0, 1),
        })
        # incremental write so a crashed sweep keeps finished trials
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(results, f, indent=2)
    results.sort(key=lambda r: -r["best_acc"])
    print(f"\n{'rank':>4} {'best_acc':>9}  params")
    for rank, r in enumerate(results, 1):
        print(f"{rank:>4} {r['best_acc']:>9.4f}  {r['params']}")
    print(f"\nresults -> {out_path}")
    return results


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("workload", choices=["resnet", "transformer"])
    p.add_argument("--grid", nargs="+", required=True,
                   metavar="name=v1,v2,...")
    p.add_argument("--ngd", action="store_true")
    p.add_argument("--epoch", type=int, default=5)        # reference: 5
    p.add_argument("--subset_stride", type=int, default=10)  # reference: 1/10
    p.add_argument("--bs", type=int, default=None)
    p.add_argument("--dataset", type=str, default=None)
    p.add_argument("--device", type=str, default="auto")
    p.add_argument("--out", type=str, default="tuning/results.json")
    # small-model overrides so CPU smoke sweeps stay fast
    p.add_argument("--model", type=str, default=None)
    p.add_argument("--seq_len", type=int, default=None)
    p.add_argument("--n_layers", type=int, default=None)
    p.add_argument("--d_model", type=int, default=None)
    p.add_argument("--d_ff", type=int, default=None)
    p.add_argument("--n_heads", type=int, default=None)
    args = p.parse_args(argv)

    if args.workload == "resnet":
        base = TrainConfig(model="resnet50", dataset="cifar10",
                           num_classes=10, lr=0.1, batch_size=64)
    else:
        base = TrainConfig(model="transformer", dataset="agnews",
                           num_classes=4, lr=5e-5, batch_size=16)
    base = base.replace(use_ngd=args.ngd, epochs=args.epoch,
                        subset_stride=args.subset_stride, device=args.device,
                        checkpoint_dir="./tuning_checkpoint")
    for field in ("bs", "dataset", "model", "seq_len", "n_layers", "d_model",
                  "d_ff", "n_heads"):
        v = getattr(args, field)
        if v is not None:
            base = base.replace(**{"batch_size" if field == "bs" else field: v})
    run_sweep(base, parse_grid(args.grid), args.out)


if __name__ == "__main__":
    main()
