"""Stateless hash dropout — no mask tensor ever reaches HBM.

The reference applies torch dropout at five transformer sites
(transformer.py:64,262-274 + the pooler): encodings, both residual
connections per layer, the FFN hidden, and the pooled CLS vector.  A
straight port (``nn.Dropout``) pays three hidden costs per site on TPU:
the PRNG draw for the mask (threefry: ~100 vector ops/element, measured
34% of the whole train step in round 3), the mask's HBM round-trip, and
the mask being *saved as a backward residual* (written in forward, read
in backward).  At the reference config the mask volume is
B·L·12800 elements/step — ~839M at bs=256/seq=256.

This module removes all three costs:

  * the keep decision for element ``i`` is a pure function of
    ``(seed, i)`` — one murmur3 32-bit finalizer (full avalanche, the
    same mixer the attention kernels use, ops/attention.py:51) over
    ``seed ^ i``, a handful of u32 VPU ops that fuse into the
    surrounding elementwise work (no RNG state, no bits tensor);
  * the backward is a ``jax.custom_vjp`` whose only residual is the
    u32 seed — the mask is REGENERATED from indices in the backward,
    so nothing mask-shaped is stored or loaded;
  * the bits are plain u32 xor/shift/multiply ops — deterministic
    across backends and jax versions, unlike the rbg hardware-RNG
    path, so bit-reproducible training comes back for free (the
    round-3 trade-off ADVICE r3 #2 flagged).

Statistical note: because the stream is ``fmix(seed ^ i)``, two sites
with seeds s1, s2 see masks related by the index permutation
``i -> i ^ s1 ^ s2`` — a random xor-shift of one another, not fresh
independent draws.  For dropout this is immaterial (any FIXED pair of
elements collides with probability 2^-32 over the seed pair), and each
site draws a fresh seed from the threefry rng tree per step.

Keep-probability granularity is 1/65536 (the hash's top 16 bits against
a u16 threshold): rate=0.1 realizes as drop probability 6554/65536 ≈
0.100006.  The survivor scale uses the REALIZED keep probability and is
applied in float32 with ONE final cast to the activation dtype (ADVICE
r4 #3 — scaling in bf16 would round 1/keep to 8 mantissa bits, a
systematic ~0.4% scale bias), so E[dropout(x)] == x holds exactly in
fp32 and to one final-rounding ulp in bf16; the ≤1/65536 quantization of
the rate itself is statistically irrelevant and tested.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from faster_distributed_training_tpu.ops.attention import _fmix32


_GRID = 1 << 16  # keep-prob quantization grid (per-element u16 compare)

# The documented uint32 global-index ceiling (keep_factor_rows
# docstring), now a LOUD runtime guard instead of a silent wrap: the
# element index global_row*cols + c mixes in uint32, so past 2^32
# global elements distant positions silently share mask bits and the
# per-element-draw contract is gone.  Shapes are static under jit, so
# the check costs nothing at run time — it fires at trace time.
_INDEX_CEILING = 1 << 32


def guard_index_ceiling(n_elements: int, site: str = "hash dropout"
                        ) -> None:
    """Raise when a mask stream would address more than 2^32 global
    elements.  Callers with a global-shape view (hash_dropout's full
    tensor, the fused-FFN wrappers' rows x cols index space) invoke
    this before building the stream; the fix when it fires is to widen
    the mixing to 64 bits (two fmix rounds over row and column), not to
    rely on the wrap."""
    if int(n_elements) > _INDEX_CEILING:
        raise ValueError(
            f"{site}: {int(n_elements)} global elements exceed the "
            f"uint32 index ceiling (2^32) of the stateless hash-dropout "
            f"stream — positions past it would silently share mask "
            f"bits.  Reduce the global activation size, set the site's "
            f"dropout rate to 0, or use --dropout_impl xla for this "
            f"run; the durable fix is widening ops/dropout.py's index "
            f"mixing to 64 bits.")


def _thresh_u16(rate: float) -> int:
    """Threshold on the u16 grid: keep iff (hash >> 16) < t; realized
    keep prob = t / 65536."""
    return max(min(int(round((1.0 - rate) * _GRID)), _GRID), 0)


def hash_words(seed: jax.Array, n: int) -> jax.Array:
    """[n] uniform uint32 stream: one murmur3 finalizer over
    seed ^ element-index.  Element i's word depends only on (seed, i) —
    placement/sharding-independent, recomputable, and PURE u32
    elementwise ops, so XLA fuses the whole generation into whatever
    consumes it (measured: a byte-granular bitcast variant that hashed
    one word per 4 elements was 11% SLOWER end-to-end — sub-word dtypes
    force Mosaic relayouts that cost more than the extra hashing)."""
    return _fmix32(seed.astype(jnp.uint32) ^ lax.iota(jnp.uint32, n))


def keep_factor_rows(seed: jax.Array, global_rows: jax.Array, cols: int,
                     rate: float, col0=0,
                     cols_glob: int = 0) -> jax.Array:
    """fp32 {0, GRID/t} keep factors for a tile whose per-row GLOBAL row
    ids are ``global_rows`` ((rows,) or (rows,1) u32) — THE single
    source of truth for the hash-dropout mask stream: element (r, c)
    keeps iff the top 16 hash bits of ``fmix(seed ^ (global_rows[r] *
    cols_glob + col0 + c))`` clear the rate threshold.  Explicit row ids
    let sharded callers (ops/fused_ffn.py under shard_map) address the
    GLOBAL index space even when their local rows are not globally
    contiguous (sequence-sharded layouts) — masks depend only on
    (seed, global position), never on device placement.  ``col0`` /
    ``cols_glob`` extend the same contract to COLUMN-sharded tiles (the
    Megatron column-parallel fused-FFN hidden, r19): the local tile
    covers global columns [col0, col0+cols) of a cols_glob-wide tensor.
    The defaults (0, 0 -> cols) reduce to the original full-width
    stream bit-for-bit.

    CEILING (ADVICE r5 low): the element index mixes in uint32, so the
    placement-invariance contract holds only for global activation
    tensors up to 2^32 elements (~4.3 G elements; at d_ff=1024 that is
    a global batch*seq of ~4.2 M rows).  Past it the index wraps and
    distant positions silently share mask bits — statistically harmless
    (the wrapped stream is still uniform) but no longer a unique
    per-element draw.  If larger global tensors come into scope, widen
    the mixing to 64 bits (two fmix rounds over row and column) rather
    than relying on the wrap."""
    t = _thresh_u16(rate)
    rows = int(np.shape(global_rows)[0])
    if t <= 0:   # rate within half a grid step of 1: drop everything
        return jnp.zeros((rows, cols), jnp.float32)
    width = int(cols_glob) if cols_glob else cols
    c = lax.broadcasted_iota(jnp.uint32, (rows, cols), 1)
    idx = global_rows.astype(jnp.uint32).reshape(rows, 1) \
        * jnp.uint32(width) + jnp.asarray(col0, jnp.uint32) + c
    h16 = _fmix32(seed.astype(jnp.uint32) ^ idx) >> jnp.uint32(16)
    inv = np.float32(_GRID / t)  # exact-unbiasedness scale (realized keep)
    return jnp.where(h16 < jnp.uint32(t), inv, np.float32(0.0))


def keep_factor_tile(seed: jax.Array, row0: jax.Array, rows: int, cols: int,
                     rate: float) -> jax.Array:
    """keep_factor_rows for a globally-CONTIGUOUS tile starting at row
    ``row0``; ``row0=0`` over the full tensor reproduces
    ``hash_dropout``'s mask exactly."""
    r = row0.astype(jnp.uint32) + lax.iota(jnp.uint32, rows)
    return keep_factor_rows(seed, r, cols, rate)


def _keep_factor(seed: jax.Array, shape, rate: float,
                 offset: int = 0) -> jax.Array:
    """0 or 1/realized_keep per element, shaped like the input — ALWAYS
    float32: the scale multiplies in fp32 and the product is cast back
    to the activation dtype once (ADVICE r4 #3; casting the factor
    itself to bf16 first would bias the scale by up to ~0.4%).  Built on
    keep_factor_tile so every consumer shares one stream definition.

    ``offset`` (static python int) shifts the element indices: element i
    of this tensor draws the stream word of global element offset+i.  A
    pipeline microbatch covering rows [row0, row0+rows) of the full
    batch passes offset = row0 * prod(shape[1:]) and reproduces exactly
    the slice of the full-tensor mask pp=1 would apply to those rows
    (parallel/pipeline.py r23).  offset=0 traces the original
    keep_factor_tile path so pp=1 programs stay byte-identical."""
    n = int(np.prod(shape)) if shape else 1
    guard_index_ceiling(int(offset) + n)
    if offset:
        return keep_factor_rows(seed, jnp.zeros((1,), jnp.uint32), n,
                                rate, col0=int(offset)).reshape(shape)
    return keep_factor_tile(seed, jnp.uint32(0), 1, n, rate).reshape(shape)


def _scale(x: jax.Array, factor: jax.Array) -> jax.Array:
    """x * factor computed in fp32, one rounding back to x.dtype."""
    return (x.astype(jnp.float32) * factor).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _hash_dropout(x: jax.Array, seed: jax.Array, rate: float,
                  offset: int = 0) -> jax.Array:
    return _scale(x, _keep_factor(seed, x.shape, rate, offset))


def _hd_fwd(x, seed, rate, offset):
    # residual: the scalar seed ONLY — no mask, no input
    return _hash_dropout(x, seed, rate, offset), seed


def _hd_bwd(rate, offset, seed, g):
    # the cotangent has the primal's shape/dtype; the mask is REGENERATED
    dx = _scale(g, _keep_factor(seed, g.shape, rate, offset))
    return dx, np.zeros((), jax.dtypes.float0)


_hash_dropout.defvjp(_hd_fwd, _hd_bwd)


def hash_dropout(x: jax.Array, seed: jax.Array, rate: float,
                 deterministic: bool = False,
                 offset: int = 0) -> jax.Array:
    """Apply stateless hash dropout.  seed: u32 scalar (one fresh value
    per site per step); rate: static python float in [0, 1]; offset:
    static global-element index of this tensor's element 0 (0 = the
    whole tensor — the default; pipeline microbatches pass their row
    offset so the mask equals pp=1's slice, see _keep_factor)."""
    if deterministic or rate <= 0.0:
        return x
    t = _thresh_u16(rate)
    if t >= _GRID:    # rate below half a grid step -> keep everything
        return x
    if t <= 0:        # rate above 1 - half a grid step -> drop everything
        return jnp.zeros_like(x)
    return _hash_dropout(x, jnp.asarray(seed), rate, int(offset))


def realized_rate(rate: float) -> float:
    """The drop probability hash_dropout actually applies (1/65536 grid)."""
    t = _thresh_u16(rate)
    return 1.0 - min(t, _GRID) / _GRID


try:  # flax is an optional dependency of this module's function core
    from flax import linen as nn

    class FastDropout(nn.Module):
        """Drop-in ``nn.Dropout`` replacement with selectable engine.

        impl:
          hash — stateless index-hash mask, seed-only backward residual
                 (the default: fastest measured and bit-reproducible);
          xla  — flax ``nn.Dropout`` (threefry or rbg depending on the
                 dropout rng key's impl — the train step picks per
                 ``cfg.dropout_rng_impl``);
          none — dropout disabled (roofline floor probes).

        ``pp_ctx`` (a parallel.pipeline.PipelineTickCtx, r23): the site
        draws its seed ONCE (first tick — make_rng fold count 0, i.e.
        pp=1's key for this module path) and offsets the hash stream by
        the current microbatch's global row so every microbatch applies
        exactly pp=1's mask slice.  hash impl only; None (every pp=1
        program) leaves the trace untouched.
        """
        rate: float
        impl: str = "hash"
        rng_collection: str = "dropout"
        pp_ctx: object = None

        @nn.compact
        def __call__(self, x: jax.Array,
                     deterministic: bool = False) -> jax.Array:
            if deterministic or self.rate <= 0.0 or self.impl == "none":
                return x
            if self.impl == "xla":
                return nn.Dropout(self.rate, deterministic=False,
                                  rng_collection=self.rng_collection)(x)
            draw = lambda: jax.random.bits(     # noqa: E731
                self.make_rng(self.rng_collection), dtype=jnp.uint32)
            if self.pp_ctx is not None:
                site = "/".join(str(p) for p in self.scope.path)
                seed = self.pp_ctx.site_seed(site, draw)
                offset = self.pp_ctx.row0 * int(np.prod(x.shape[1:]))
                return hash_dropout(x, seed, self.rate, offset=offset)
            return hash_dropout(x, draw(), self.rate)
except ImportError:  # pragma: no cover
    pass
