"""The reference's hand-rolled LayerNorm math, ONE definition.

The reference normalizes with UNBIASED std and adds eps to the std, not
the variance (transformer.py:230-242) — nonstandard on both counts, so
the fp32 core lives here and every consumer delegates:
``models.transformer.TorchLayerNorm`` (the Flax module) and
``ops.fused_ffn`` (the fused FFN-sublayer kernel and its reference/
backward fn).  A semantics change in one place cannot silently
desynchronize the implementations (the checkpoint-interchange guarantee
between ``ffn_impl`` settings depends on them agreeing).

Two entry points:

  * ``torch_layernorm_f32`` — the pure fp32 math under default XLA
    autodiff.  This is what runs INSIDE the Pallas FFN kernel (Mosaic
    traces the primal only) and is the oracle the saved-stats VJP is
    tested against.
  * ``torch_layernorm`` — the same primal wrapped in a ``custom_vjp``
    that saves per-row ``(mean, rstd)`` — two scalars per row — beside
    the input (VERDICT r4/r5 #4: the r5 identity-LN probe measured the
    transformer's 13 LN sites at ~7.5 ms/step @ bs256/seq256 of pure
    HBM round-trips; the fused-FFN recompute-backward attack measured a
    net LOSS, so this is the standard saved-stats alternative).  XLA's
    default autodiff saves the centered input and the rsqrt chain —
    O(rows·d) extra residual traffic per site; here the backward
    rebuilds x̂ from ``(x, mean, rstd)`` with one fused elementwise
    pass, so residual traffic per site drops to the input (alive
    anyway, it feeds the sublayer residual add) plus 2 scalars/row.
    Kill switch ``FDT_LN_SAVED_STATS=0`` restores default autodiff for
    A/B probes (scripts/transformer_roofline.py).

The backward math, for y = γ·x̂ + β with x̂ = (x − μ)·r,
r = 1/(σ + eps), σ = √(Σ(x−μ)²/(n−1)) (UNBIASED, n−1):

    gy  = g · γ
    dβ  = Σ_rows g          dγ = Σ_rows g · x̂
    dx  = r·(gy − mean_j gy) − x̂ · Σ_j(gy·x̂) / (σ·(n−1))

(The second term differs from standard LayerNorm's 1/n by the unbiased
n−1, and σ = 1/r − eps re-derives the std from the saved rstd; both are
pinned against XLA autodiff of the raw math by
tests/test_ops.py::TestSavedStatsLayerNorm.)
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp


def torch_layernorm_f32(x32: jax.Array, scale: jax.Array, bias: jax.Array,
                        eps: float) -> jax.Array:
    """fp32 TorchLayerNorm over the last axis: unbiased variance (n-1),
    eps added to the STD.  Inputs and outputs fp32; callers cast.
    Pure math under default autodiff — the in-kernel / oracle form."""
    d = x32.shape[-1]
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.sum(jnp.square(x32 - mean), axis=-1, keepdims=True) / (d - 1)
    return scale * ((x32 - mean) / (jnp.sqrt(var) + eps)) + bias


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ln_saved_stats(x32, scale, bias, eps):
    return torch_layernorm_f32(x32, scale, bias, eps)


def _ln_fwd(x32, scale, bias, eps):
    d = x32.shape[-1]
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.sum(jnp.square(x32 - mean), axis=-1, keepdims=True) / (d - 1)
    std = jnp.sqrt(var) + eps
    # primal via the SAME division expression as torch_layernorm_f32 so
    # the forward is bit-identical to the pure form (the fused-FFN
    # kernel-vs-reference agreement depends on one forward definition);
    # rstd is a residual only
    out = scale * ((x32 - mean) / std) + bias
    return out, (x32, scale, mean, 1.0 / std)


def _ln_bwd(eps, res, g):
    x32, scale, mean, rstd = res
    d = x32.shape[-1]
    xhat = (x32 - mean) * rstd                       # rebuilt, not stored
    # dtype-generic: fp32 from the model callers (they cast), fp64 under
    # the gradcheck-style tests — never downcast the cotangent
    g32 = g.astype(jnp.promote_types(g.dtype, jnp.float32))
    dbias = jnp.sum(g32.reshape(-1, d), axis=0)
    dscale = jnp.sum((g32 * xhat).reshape(-1, d), axis=0)
    gy = g32 * scale
    c1 = jnp.mean(gy, axis=-1, keepdims=True)
    c2 = jnp.sum(gy * xhat, axis=-1, keepdims=True)
    # sigma re-derived from the saved rstd (sigma = 1/r - eps); the
    # unbiased variance makes the projection term 1/(sigma*(d-1)), not
    # the standard 1/(sigma*d)
    sigma = 1.0 / rstd - eps
    dx = rstd * (gy - c1) - xhat * (c2 / (sigma * (d - 1)))
    return dx, dscale, dbias


_ln_saved_stats.defvjp(_ln_fwd, _ln_bwd)


def torch_layernorm(x32: jax.Array, scale: jax.Array, bias: jax.Array,
                    eps: float) -> jax.Array:
    """torch_layernorm_f32 with the saved-stats custom_vjp backward (the
    hot-path form — see module docstring).  FDT_LN_SAVED_STATS=0 falls
    back to the pure function under default autodiff (A/B probes)."""
    if os.environ.get("FDT_LN_SAVED_STATS", "1") == "0":
        return torch_layernorm_f32(x32, scale, bias, eps)
    return _ln_saved_stats(x32, scale, bias, eps)
