"""The reference's hand-rolled LayerNorm math, ONE definition.

The reference normalizes with UNBIASED std and adds eps to the std, not
the variance (transformer.py:230-242) — nonstandard on both counts, so
the fp32 core lives here and every consumer delegates:
``models.transformer.TorchLayerNorm`` (the Flax module) and
``ops.fused_ffn`` (the fused FFN-sublayer kernel and its reference/
backward fn).  A semantics change in one place cannot silently
desynchronize the implementations (the checkpoint-interchange guarantee
between ``ffn_impl`` settings depends on them agreeing).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def torch_layernorm_f32(x32: jax.Array, scale: jax.Array, bias: jax.Array,
                        eps: float) -> jax.Array:
    """fp32 TorchLayerNorm over the last axis: unbiased variance (n-1),
    eps added to the STD.  Inputs and outputs fp32; callers cast."""
    d = x32.shape[-1]
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.sum(jnp.square(x32 - mean), axis=-1, keepdims=True) / (d - 1)
    return scale * ((x32 - mean) / (jnp.sqrt(var) + eps)) + bias
