"""Fused pre-LN FFN sublayer: one Pallas kernel for
LN -> Dense(d_ff) -> GELU -> dropout -> Dense(d_model) -> dropout -> +residual.

Motivation (VERDICT r4 #1 "attack the gap"): the round-5 identity-LN
probe measured the transformer's 13 LayerNorm sites at ~7.5 ms of the
112 ms step @ bs256/seq256 (`scripts/transformer_roofline.py
ngd_256_256_noln`) — pure HBM round-trips, which XLA cannot fuse into
the adjacent GEMMs (reductions only fuse with elementwise consumers,
never into a dot).  This kernel computes the WHOLE pre-LN FFN sublayer
of `models/transformer.py::EncoderLayer` per row-block with every
intermediate (LN output, d_ff hidden, GELU, dropout masks, residual sum)
living only in VMEM: HBM traffic drops from ~5 tensor round-trips to
read-h + write-out.

Design:
  * forward — Pallas kernel, grid over row blocks; weights VMEM-resident
    ((512,1024)+(1024,512) bf16 = 2 MiB of the ~16 MiB budget).  LN runs
    in fp32 with the reference's exact semantics (TorchLayerNorm,
    transformer.py:230-242: UNBIASED variance, eps added to the std);
    GEMMs accumulate fp32 on the MXU; GELU is the exact erf form
    (torch nn.GELU default); both dropout sites are the stateless
    index-hash masks of `ops/dropout.py` (murmur3 finalizer over
    seed ^ global-flat-index, keep iff top-16 bits < t, survivor scale
    GRID/t applied in fp32) so the backward can regenerate them
    bit-exactly from the two u32 seeds.
  * backward — ``jax.custom_vjp`` whose residuals are the INPUTS only
    (h, LN params, weights, seeds); the bwd pass is ``jax.vjp`` of the
    pure-XLA reference forward below, so gradients are correct by
    construction and the big dW GEMMs run as single XLA dots (measured
    at ~82% MFU on this chip — a hand-tiled Pallas accumulation would
    be slower).  This also makes the sublayer remat-free: nothing
    FFN-shaped is ever saved for backward.
  * off-TPU the kernel runs in Pallas interpret mode (tests); the model
    integration gates the kernel behind ``ffn_impl="pallas"`` and keeps
    the Flax composition as the default/ablation arm.

Numerics note: the kernel's GELU/dropout/second-GEMM chain runs in fp32
until the final cast while the Flax composition casts to bf16 between
every op, so kernel-vs-Flax outputs differ by normal bf16 rounding
(~1e-2 relative on bf16 activations); kernel-vs-REFERENCE-fn (same op
order) agrees to fp32/bf16 tolerance and is what the tests pin.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from faster_distributed_training_tpu.ops.dropout import (guard_index_ceiling,
                                                         keep_factor_rows)
from faster_distributed_training_tpu.ops.layernorm import (torch_layernorm,
                                                           torch_layernorm_f32)

try:
    from jax.experimental import pallas as pl
except ImportError:  # pragma: no cover
    pl = None


def _erf_f32(x: jax.Array) -> jax.Array:
    """erf via the Abramowitz-Stegun 7.1.26 polynomial (|err| measured
    4.2e-7 in fp32, far below bf16's ~8e-3 resolution) — Mosaic has no
    erf primitive, so the
    kernel AND the reference/backward fn share this implementation (they
    must agree bit-for-bit for the vjp-of-reference backward to see the
    forward's exact activations)."""
    a1, a2, a3 = np.float32(0.254829592), np.float32(-0.284496736), \
        np.float32(1.421413741)
    a4, a5, p = np.float32(-1.453152027), np.float32(1.061405429), \
        np.float32(0.3275911)
    s = jnp.sign(x)
    ax = jnp.abs(x)
    t = 1.0 / (1.0 + p * ax)
    y = 1.0 - (((((a5 * t + a4) * t) + a3) * t + a2) * t + a1) * t \
        * jnp.exp(-ax * ax)
    return s * y


def _gelu_f32(h1: jax.Array) -> jax.Array:
    """Exact-form GELU (torch nn.GELU default) on fp32 pre-activations."""
    return 0.5 * h1 * (1.0 + _erf_f32(h1 * np.float32(1.0 / np.sqrt(2.0))))


# TorchLayerNorm's fp32 core — ONE definition shared with the Flax
# module (ops/layernorm.py), so kernel and model can't desynchronize.
# The Pallas kernel traces the PURE primal (Mosaic never differentiates
# it); the XLA reference fn — which the custom_vjp backward jax.vjp's —
# uses the saved-stats form so the recompute backward's inner LN also
# saves (mean, rstd) instead of re-deriving the rsqrt chain.  Both share
# one forward definition, so kernel-vs-reference outputs stay identical.
_ln_f32 = torch_layernorm_f32
_ln_saved = torch_layernorm


# the mask stream lives in ops/dropout.py (one source of truth); this
# module addresses it by GLOBAL row id (see _global_rows): masks depend
# only on (seed, global position), never on sharding/placement
_keep_rows = keep_factor_rows


def _global_rows(r_local: jax.Array, b0, s0, l_loc: int,
                 l_glob: int) -> jax.Array:
    """Map LOCAL flattened row indices to GLOBAL row ids.

    The (possibly sharded) activation is (B_local, L_local, d) flattened
    to rows r = b_local * l_loc + s_local; the shard starts at batch
    offset ``b0`` and sequence offset ``s0`` of a global (B, l_glob, d)
    tensor.  Unsharded callers use the defaults b0=s0=0, l_loc=l_glob=1,
    which reduce to g == r (the plain contiguous stream)."""
    r = r_local.astype(jnp.uint32)
    return ((jnp.uint32(b0) + r // jnp.uint32(l_loc)) * jnp.uint32(l_glob)
            + jnp.uint32(s0) + r % jnp.uint32(l_loc))


def ffn_sublayer_reference(h: jax.Array, ln_scale: jax.Array,
                           ln_bias: jax.Array, w1: jax.Array, b1: jax.Array,
                           w2: jax.Array, b2: jax.Array,
                           hid_seed: jax.Array, out_seed: jax.Array,
                           rate_hidden: float, rate_conn: float,
                           eps: float = 1e-6, b0=0, s0=0,
                           l_loc: int = 1, l_glob: int = 1) -> jax.Array:
    """Pure-XLA oracle with the kernel's exact op order and dtypes.
    Weights in Flax Dense layout (in, out).  Also the bwd math source:
    the custom_vjp backward is jax.vjp of THIS function.  b0/s0/l_loc/
    l_glob address the global dropout index space for sharded callers
    (defaults = unsharded)."""
    lead = h.shape[:-1]
    d = h.shape[-1]
    x32 = h.reshape(-1, d).astype(jnp.float32)
    n_rows = x32.shape[0]
    grows = _global_rows(lax.iota(jnp.uint32, n_rows), b0, s0, l_loc, l_glob)
    f = _ln_saved(x32, ln_scale.astype(jnp.float32),
                  ln_bias.astype(jnp.float32), eps).astype(h.dtype)
    h1 = jnp.dot(f, w1, preferred_element_type=jnp.float32) \
        + b1.astype(jnp.float32)
    a = _gelu_f32(h1)
    if rate_hidden > 0.0:
        a = a * _keep_rows(hid_seed, grows, a.shape[1], rate_hidden)
    a = a.astype(h.dtype)
    f2 = jnp.dot(a, w2, preferred_element_type=jnp.float32) \
        + b2.astype(jnp.float32)
    if rate_conn > 0.0:
        f2 = f2 * _keep_rows(out_seed, grows, f2.shape[1], rate_conn)
    out = x32 + f2
    return out.astype(h.dtype).reshape(*lead, d)


def _ffn_kernel(h_ref, lns_ref, lnb_ref, w1_ref, b1_ref, w2_ref, b2_ref,
                seeds_ref, o_ref, *, block_rows: int,
                rate_hidden: float, rate_conn: float, eps: float,
                l_loc: int, l_glob: int):
    row0 = pl.program_id(0) * block_rows
    x32 = h_ref[...].astype(jnp.float32)
    rows = x32.shape[0]
    f = _ln_f32(x32, lns_ref[...].astype(jnp.float32),
                lnb_ref[...].astype(jnp.float32), eps).astype(h_ref.dtype)
    h1 = jax.lax.dot(f, w1_ref[...],
                     preferred_element_type=jnp.float32) \
        + b1_ref[...].astype(jnp.float32)
    a = _gelu_f32(h1)
    if rate_hidden > 0.0 or rate_conn > 0.0:
        # (rows, 1) — Mosaic wants >=2D iota; keep_factor_rows reshapes
        r_local = (jnp.uint32(row0)
                   + lax.broadcasted_iota(jnp.uint32, (rows, 1), 0))
        grows = _global_rows(r_local, seeds_ref[0, 2], seeds_ref[0, 3],
                             l_loc, l_glob)
    if rate_hidden > 0.0:
        a = a * _keep_rows(seeds_ref[0, 0], grows, a.shape[1], rate_hidden)
    a = a.astype(h_ref.dtype)
    f2 = jax.lax.dot(a, w2_ref[...],
                     preferred_element_type=jnp.float32) \
        + b2_ref[...].astype(jnp.float32)
    if rate_conn > 0.0:
        f2 = f2 * _keep_rows(seeds_ref[0, 1], grows, f2.shape[1], rate_conn)
    o_ref[...] = (x32 + f2).astype(o_ref.dtype)


# Static VMEM budget for the kernel's resident set (ADVICE r5 low): both
# weight matrices + the fp32 hidden/row tiles must fit scoped VMEM or
# Mosaic dies with an opaque compile error at large --d_model/--d_ff.
# 12 MiB of the ~16 MiB budget leaves margin for Pallas double-buffering
# of the in/out row blocks; the default 512/1024 config sits at ~5.6 MiB.
_FFN_VMEM_BUDGET = 12 * 1024 * 1024


def _ffn_vmem_bytes(d: int, d_ff: int, w_bytes: int,
                    block_rows: int) -> int:
    """Resident-set model: w1+w2 at their dtype, fp32 hidden pair
    (pre-GELU + activation), and the x32/LN/out fp32 row tiles."""
    return (2 * d * d_ff * w_bytes
            + 2 * block_rows * d_ff * 4
            + 3 * block_rows * d * 4)


def ffn_kernel_fits_vmem(d: int, d_ff: int, w_bytes: int = 2) -> bool:
    """True iff the kernel fits the VMEM budget at its SMALLEST row tile
    — the static go/no-go check build_model mirrors (falling back to the
    flax composition, like the tp-mesh fallback) before handing the
    model a kernel that cannot compile."""
    return _ffn_vmem_bytes(d, d_ff, w_bytes, 32) <= _FFN_VMEM_BUDGET


def _ffn_fwd_pallas(h2d, ln_scale, ln_bias, w1, b1, w2, b2, seeds,
                    rate_hidden, rate_conn, eps, l_loc, l_glob,
                    block_rows=256):
    B, d = h2d.shape
    d_ff = w1.shape[1]
    w_bytes = jnp.dtype(w1.dtype).itemsize
    block_rows = min(block_rows, B)
    # degrade the row tile before giving up: the hidden tiles scale with
    # block_rows, so halving buys headroom down to the 32-row floor
    while (block_rows > 32
           and _ffn_vmem_bytes(d, d_ff, w_bytes,
                               block_rows) > _FFN_VMEM_BUDGET):
        block_rows //= 2
    if _ffn_vmem_bytes(d, d_ff, w_bytes, block_rows) > _FFN_VMEM_BUDGET:
        import warnings
        warnings.warn(
            f"fused FFN kernel resident set for d_model={d}, d_ff={d_ff} "
            f"exceeds the ~{_FFN_VMEM_BUDGET >> 20} MiB VMEM budget even "
            f"at the minimum row tile; computing this sublayer with the "
            f"XLA reference path instead (same math, default autodiff)",
            stacklevel=2)
        return ffn_sublayer_reference(
            h2d, ln_scale, ln_bias, w1, b1, w2, b2, seeds[0, 0],
            seeds[0, 1], rate_hidden, rate_conn, eps, seeds[0, 2],
            seeds[0, 3], l_loc, l_glob)
    nb = -(-B // block_rows)
    pad = nb * block_rows - B
    if pad:
        # NOTE: padded rows still hash dropout indices past B*d — fine,
        # they are sliced away and real rows' indices are unaffected.
        h2d = jnp.pad(h2d, ((0, pad), (0, 0)))
    kern = functools.partial(_ffn_kernel, block_rows=block_rows,
                             rate_hidden=rate_hidden, rate_conn=rate_conn,
                             eps=eps, l_loc=l_loc, l_glob=l_glob)
    out = pl.pallas_call(
        kern,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((d, d_ff), lambda i: (0, 0)),
            pl.BlockSpec((1, d_ff), lambda i: (0, 0)),
            pl.BlockSpec((d_ff, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, 4), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nb * block_rows, d), h2d.dtype),
        interpret=(jax.default_backend() != "tpu"),
    )(h2d, ln_scale.reshape(1, d), ln_bias.reshape(1, d), w1,
      b1.reshape(1, d_ff), w2, b2.reshape(1, d), seeds)
    return out[:B] if pad else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(11, 12, 13, 14, 15))
def _ffn_core(h, ln_scale, ln_bias, w1, b1, w2, b2,
              hid_seed, out_seed, b0, s0,
              rate_hidden: float, rate_conn: float, eps: float,
              l_loc: int, l_glob: int):
    lead = h.shape[:-1]
    d = h.shape[-1]
    seeds = jnp.stack([jnp.asarray(hid_seed, jnp.uint32),
                       jnp.asarray(out_seed, jnp.uint32),
                       jnp.asarray(b0, jnp.uint32),
                       jnp.asarray(s0, jnp.uint32)]).reshape(1, 4)
    out = _ffn_fwd_pallas(h.reshape(-1, d), ln_scale, ln_bias, w1, b1,
                          w2, b2, seeds, rate_hidden, rate_conn, eps,
                          l_loc, l_glob)
    return out.reshape(*lead, d)


def _ffn_vjp_fwd(h, ln_scale, ln_bias, w1, b1, w2, b2, hid_seed, out_seed,
                 b0, s0, rate_hidden, rate_conn, eps, l_loc, l_glob):
    out = _ffn_core(h, ln_scale, ln_bias, w1, b1, w2, b2,
                    hid_seed, out_seed, b0, s0,
                    rate_hidden, rate_conn, eps, l_loc, l_glob)
    # residuals: INPUTS only — nothing FFN-shaped is saved (the whole
    # sublayer is recomputed by the reference fn inside the bwd vjp)
    return out, (h, ln_scale, ln_bias, w1, b1, w2, b2, hid_seed, out_seed,
                 b0, s0)


def _ffn_vjp_bwd(rate_hidden, rate_conn, eps, l_loc, l_glob, res, g):
    (h, ln_scale, ln_bias, w1, b1, w2, b2, hid_seed, out_seed,
     b0, s0) = res
    _, vjp = jax.vjp(
        lambda h_, s_, bi_, w1_, b1_, w2_, b2_: ffn_sublayer_reference(
            h_, s_, bi_, w1_, b1_, w2_, b2_, hid_seed, out_seed,
            rate_hidden, rate_conn, eps, b0, s0, l_loc, l_glob),
        h, ln_scale, ln_bias, w1, b1, w2, b2)
    zero = np.zeros((), jax.dtypes.float0)
    return (*vjp(g), zero, zero, zero, zero)


_ffn_core.defvjp(_ffn_vjp_fwd, _ffn_vjp_bwd)


def fused_ffn_sublayer(h, ln_scale, ln_bias, w1, b1, w2, b2,
                       hid_seed, out_seed,
                       rate_hidden: float = 0.0, rate_conn: float = 0.0,
                       eps: float = 1e-6):
    """out = h + drop(Dense2(drop(gelu(Dense1(LN(h)))))) in ONE Pallas
    kernel (see module docstring).  h: (..., d_model); weights in Flax
    (in, out) layout; seeds: u32 scalars (ignored when the static rates
    are 0 — pass anything).  Gradients flow to h, LN params, weights and
    biases; seeds are non-differentiable.  Dropout indices are the plain
    contiguous stream (global offsets are the sharded wrapper's job)."""
    if rate_hidden > 0.0 or rate_conn > 0.0:
        # loud guard on the documented 2^32 index ceiling (was a
        # docstring-only caveat): rows x the widest ACTIVE mask must
        # fit the uint32 stream — a rate-0 site draws no mask, so its
        # width must not be able to reject a legal config
        rows = int(np.prod(h.shape[:-1]))
        width = max(int(w1.shape[1]) if rate_hidden > 0.0 else 0,
                    int(h.shape[-1]) if rate_conn > 0.0 else 0)
        guard_index_ceiling(rows * width, site="fused FFN dropout")
    return _ffn_core(h, ln_scale, ln_bias, w1, b1, w2, b2,
                     hid_seed, out_seed, jnp.uint32(0), jnp.uint32(0),
                     rate_hidden, rate_conn, eps, 1, 1)


def fused_ffn_sublayer_sharded(h, ln_scale, ln_bias, w1, b1, w2, b2,
                               hid_seed, out_seed, mesh,
                               rate_hidden: float = 0.0,
                               rate_conn: float = 0.0,
                               eps: float = 1e-6):
    """SPMD wrapper: the kernel runs PER SHARD under ``jax.shard_map``
    over the mesh's data axes (batch over dp/fsdp, sequence over sp),
    weights replicated (an fsdp/ZeRO-3-sharded weight is all-gathered by
    the partitioner at the shard_map boundary — the same gather the Flax
    path's dot would trigger).  Each shard addresses the GLOBAL dropout
    index space through its (batch, sequence) offsets — the same
    placement-invariance convention as every other sharded dropout
    consumer (ops/attention.py dropout_keep): masks depend only on
    (seed, global position), so the SAME global batch draws the SAME
    masks on dp=1, dp=4 or dp=8, bit-for-bit.  The global index space is
    uint32 — the contract holds up to 2^32 elements per activation
    tensor (see ops.dropout.keep_factor_rows for the documented wrap
    behavior past it).  tp-sharded FFN weights remain unsupported
    (build_model falls back — gathering tensor-parallel weights per
    step would defeat tp)."""
    from jax.sharding import PartitionSpec as P

    batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names
                       and mesh.shape[a] > 1)
    seq_axis = "sp" if ("sp" in mesh.axis_names
                        and mesh.shape["sp"] > 1) else None
    if not batch_axes and seq_axis is None:
        return fused_ffn_sublayer(h, ln_scale, ln_bias, w1, b1, w2, b2,
                                  hid_seed, out_seed, rate_hidden,
                                  rate_conn, eps)
    if h.ndim != 3:
        raise ValueError("fused_ffn_sublayer_sharded expects (B, L, d) "
                         f"activations, got shape {h.shape}")
    if rate_hidden > 0.0 or rate_conn > 0.0:
        # the wrap behavior this guard replaces was only documented:
        # global rows (B*L) x the widest ACTIVE mask must fit uint32
        # or distant shards would silently share mask bits (rate-0
        # sites draw no mask and must not reject a legal config)
        width = max(int(w1.shape[1]) if rate_hidden > 0.0 else 0,
                    int(h.shape[-1]) if rate_conn > 0.0 else 0)
        guard_index_ceiling(int(h.shape[0]) * int(h.shape[1]) * width,
                            site="fused FFN dropout (sharded)")
    data_spec = P(batch_axes if len(batch_axes) != 1 else batch_axes[0],
                  seq_axis, None)
    rep = P(None)
    sp_size = mesh.shape[seq_axis] if seq_axis else 1

    def per_shard(h_, lns_, lnb_, w1_, b1_, w2_, b2_, s1_, s2_):
        b_loc, l_loc = h_.shape[0], h_.shape[1]
        bi = jnp.uint32(0)
        for ax in batch_axes:
            bi = bi * jnp.uint32(mesh.shape[ax]) \
                + jax.lax.axis_index(ax).astype(jnp.uint32)
        b0 = bi * jnp.uint32(b_loc)
        s0 = (jax.lax.axis_index(seq_axis).astype(jnp.uint32)
              * jnp.uint32(l_loc) if seq_axis else jnp.uint32(0))
        return _ffn_core(h_, lns_, lnb_, w1_, b1_, w2_, b2_, s1_, s2_,
                         b0, s0, rate_hidden, rate_conn, eps,
                         l_loc, l_loc * sp_size)

    from faster_distributed_training_tpu.compat import shard_map
    return shard_map(
        per_shard, mesh=mesh,
        in_specs=(data_spec, rep, rep, rep, rep, rep, rep, P(), P()),
        out_specs=data_spec,
        # the pallas_call's out_shape carries no varying-mesh-axes info,
        # so VMA checking cannot see through it
        check_vma=False,
    )(h, ln_scale, ln_bias, w1, b1, w2, b2,
      jnp.asarray(hid_seed, jnp.uint32), jnp.asarray(out_seed, jnp.uint32))
