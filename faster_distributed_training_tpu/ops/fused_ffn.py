"""Fused pre-LN FFN sublayer: one Pallas kernel for
LN -> Dense(d_ff) -> GELU -> dropout -> Dense(d_model) -> dropout -> +residual.

Motivation (VERDICT r4 #1 "attack the gap"): the round-5 identity-LN
probe measured the transformer's 13 LayerNorm sites at ~7.5 ms of the
112 ms step @ bs256/seq256 (`scripts/transformer_roofline.py
ngd_256_256_noln`) — pure HBM round-trips, which XLA cannot fuse into
the adjacent GEMMs (reductions only fuse with elementwise consumers,
never into a dot).  This kernel computes the WHOLE pre-LN FFN sublayer
of `models/transformer.py::EncoderLayer` per row-block with every
intermediate (LN output, d_ff hidden, GELU, dropout masks, residual sum)
living only in VMEM: HBM traffic drops from ~5 tensor round-trips to
read-h + write-out.

Design:
  * forward — Pallas kernel, grid over row blocks; weights VMEM-resident
    ((512,1024)+(1024,512) bf16 = 2 MiB of the ~16 MiB budget).  LN runs
    in fp32 with the reference's exact semantics (TorchLayerNorm,
    transformer.py:230-242: UNBIASED variance, eps added to the std);
    GEMMs accumulate fp32 on the MXU; GELU is the exact erf form
    (torch nn.GELU default); both dropout sites are the stateless
    index-hash masks of `ops/dropout.py` (murmur3 finalizer over
    seed ^ global-flat-index, keep iff top-16 bits < t, survivor scale
    GRID/t applied in fp32) so the backward can regenerate them
    bit-exactly from the two u32 seeds.
  * backward — ``jax.custom_vjp`` whose residuals are the INPUTS only
    (h, LN params, weights, seeds); the bwd pass is ``jax.vjp`` of the
    pure-XLA reference forward below, so gradients are correct by
    construction and the big dW GEMMs run as single XLA dots (measured
    at ~82% MFU on this chip — a hand-tiled Pallas accumulation would
    be slower).  This also makes the sublayer remat-free: nothing
    FFN-shaped is ever saved for backward.
  * off-TPU the kernel runs in Pallas interpret mode (tests); the model
    integration gates the kernel behind ``ffn_impl="pallas"`` and keeps
    the Flax composition as the default/ablation arm.

Numerics note: the kernel's GELU/dropout/second-GEMM chain runs in fp32
until the final cast while the Flax composition casts to bf16 between
every op, so kernel-vs-Flax outputs differ by normal bf16 rounding
(~1e-2 relative on bf16 activations); kernel-vs-REFERENCE-fn (same op
order) agrees to fp32/bf16 tolerance and is what the tests pin.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from faster_distributed_training_tpu.ops.dropout import (guard_index_ceiling,
                                                         keep_factor_rows)
from faster_distributed_training_tpu.ops.layernorm import (torch_layernorm,
                                                           torch_layernorm_f32)

try:
    from jax.experimental import pallas as pl
except ImportError:  # pragma: no cover
    pl = None


def _erf_f32(x: jax.Array) -> jax.Array:
    """erf via the Abramowitz-Stegun 7.1.26 polynomial (|err| measured
    4.2e-7 in fp32, far below bf16's ~8e-3 resolution) — Mosaic has no
    erf primitive, so the
    kernel AND the reference/backward fn share this implementation (they
    must agree bit-for-bit for the vjp-of-reference backward to see the
    forward's exact activations)."""
    a1, a2, a3 = np.float32(0.254829592), np.float32(-0.284496736), \
        np.float32(1.421413741)
    a4, a5, p = np.float32(-1.453152027), np.float32(1.061405429), \
        np.float32(0.3275911)
    s = jnp.sign(x)
    ax = jnp.abs(x)
    t = 1.0 / (1.0 + p * ax)
    y = 1.0 - (((((a5 * t + a4) * t) + a3) * t + a2) * t + a1) * t \
        * jnp.exp(-ax * ax)
    return s * y


def _gelu_f32(h1: jax.Array) -> jax.Array:
    """Exact-form GELU (torch nn.GELU default) on fp32 pre-activations."""
    return 0.5 * h1 * (1.0 + _erf_f32(h1 * np.float32(1.0 / np.sqrt(2.0))))


# TorchLayerNorm's fp32 core — ONE definition shared with the Flax
# module (ops/layernorm.py), so kernel and model can't desynchronize.
# The Pallas kernel traces the PURE primal (Mosaic never differentiates
# it); the XLA reference fn — which the custom_vjp backward jax.vjp's —
# uses the saved-stats form so the recompute backward's inner LN also
# saves (mean, rstd) instead of re-deriving the rsqrt chain.  Both share
# one forward definition, so kernel-vs-reference outputs stay identical.
_ln_f32 = torch_layernorm_f32
_ln_saved = torch_layernorm


# the mask stream lives in ops/dropout.py (one source of truth); this
# module addresses it by GLOBAL row id (see _global_rows): masks depend
# only on (seed, global position), never on sharding/placement
_keep_rows = keep_factor_rows


def _global_rows(r_local: jax.Array, b0, s0, l_loc: int,
                 l_glob: int) -> jax.Array:
    """Map LOCAL flattened row indices to GLOBAL row ids.

    The (possibly sharded) activation is (B_local, L_local, d) flattened
    to rows r = b_local * l_loc + s_local; the shard starts at batch
    offset ``b0`` and sequence offset ``s0`` of a global (B, l_glob, d)
    tensor.  Unsharded callers use the defaults b0=s0=0, l_loc=l_glob=1,
    which reduce to g == r (the plain contiguous stream)."""
    r = r_local.astype(jnp.uint32)
    return ((jnp.uint32(b0) + r // jnp.uint32(l_loc)) * jnp.uint32(l_glob)
            + jnp.uint32(s0) + r % jnp.uint32(l_loc))


def ffn_sublayer_reference(h: jax.Array, ln_scale: jax.Array,
                           ln_bias: jax.Array, w1: jax.Array, b1: jax.Array,
                           w2: jax.Array, b2: jax.Array,
                           hid_seed: jax.Array, out_seed: jax.Array,
                           rate_hidden: float, rate_conn: float,
                           eps: float = 1e-6, b0=0, s0=0,
                           l_loc: int = 1, l_glob: int = 1) -> jax.Array:
    """Pure-XLA oracle with the kernel's exact op order and dtypes.
    Weights in Flax Dense layout (in, out).  Also the bwd math source:
    the custom_vjp backward is jax.vjp of THIS function.  b0/s0/l_loc/
    l_glob address the global dropout index space for sharded callers
    (defaults = unsharded)."""
    lead = h.shape[:-1]
    d = h.shape[-1]
    x32 = h.reshape(-1, d).astype(jnp.float32)
    n_rows = x32.shape[0]
    grows = _global_rows(lax.iota(jnp.uint32, n_rows), b0, s0, l_loc, l_glob)
    f = _ln_saved(x32, ln_scale.astype(jnp.float32),
                  ln_bias.astype(jnp.float32), eps).astype(h.dtype)
    h1 = jnp.dot(f, w1, preferred_element_type=jnp.float32) \
        + b1.astype(jnp.float32)
    a = _gelu_f32(h1)
    if rate_hidden > 0.0:
        a = a * _keep_rows(hid_seed, grows, a.shape[1], rate_hidden)
    a = a.astype(h.dtype)
    f2 = jnp.dot(a, w2, preferred_element_type=jnp.float32) \
        + b2.astype(jnp.float32)
    if rate_conn > 0.0:
        f2 = f2 * _keep_rows(out_seed, grows, f2.shape[1], rate_conn)
    out = x32 + f2
    return out.astype(h.dtype).reshape(*lead, d)


def _ffn_kernel(h_ref, lns_ref, lnb_ref, w1_ref, b1_ref, w2_ref, b2_ref,
                seeds_ref, o_ref, *, block_rows: int,
                rate_hidden: float, rate_conn: float, eps: float,
                l_loc: int, l_glob: int):
    row0 = pl.program_id(0) * block_rows
    x32 = h_ref[...].astype(jnp.float32)
    rows = x32.shape[0]
    f = _ln_f32(x32, lns_ref[...].astype(jnp.float32),
                lnb_ref[...].astype(jnp.float32), eps).astype(h_ref.dtype)
    h1 = jax.lax.dot(f, w1_ref[...],
                     preferred_element_type=jnp.float32) \
        + b1_ref[...].astype(jnp.float32)
    a = _gelu_f32(h1)
    if rate_hidden > 0.0 or rate_conn > 0.0:
        # (rows, 1) — Mosaic wants >=2D iota; keep_factor_rows reshapes
        r_local = (jnp.uint32(row0)
                   + lax.broadcasted_iota(jnp.uint32, (rows, 1), 0))
        grows = _global_rows(r_local, seeds_ref[0, 2], seeds_ref[0, 3],
                             l_loc, l_glob)
    if rate_hidden > 0.0:
        a = a * _keep_rows(seeds_ref[0, 0], grows, a.shape[1], rate_hidden)
    a = a.astype(h_ref.dtype)
    f2 = jax.lax.dot(a, w2_ref[...],
                     preferred_element_type=jnp.float32) \
        + b2_ref[...].astype(jnp.float32)
    if rate_conn > 0.0:
        f2 = f2 * _keep_rows(seeds_ref[0, 1], grows, f2.shape[1], rate_conn)
    o_ref[...] = (x32 + f2).astype(o_ref.dtype)


# Static VMEM budget for the kernel's resident set (ADVICE r5 low): both
# weight matrices + the fp32 hidden/row tiles must fit scoped VMEM or
# Mosaic dies with an opaque compile error at large --d_model/--d_ff.
# 12 MiB of the ~16 MiB budget leaves margin for Pallas double-buffering
# of the in/out row blocks; the default 512/1024 config sits at ~5.6 MiB.
_FFN_VMEM_BUDGET = 12 * 1024 * 1024


def _ffn_vmem_bytes(d: int, d_ff: int, w_bytes: int,
                    block_rows: int) -> int:
    """Resident-set model: w1+w2 at their dtype, fp32 hidden pair
    (pre-GELU + activation), and the x32/LN/out fp32 row tiles."""
    return (2 * d * d_ff * w_bytes
            + 2 * block_rows * d_ff * 4
            + 3 * block_rows * d * 4)


def ffn_kernel_fits_vmem(d: int, d_ff: int, w_bytes: int = 2) -> bool:
    """True iff the kernel fits the VMEM budget at its SMALLEST row tile
    — the static go/no-go check build_model mirrors (falling back to the
    flax composition, like the tp-mesh fallback) before handing the
    model a kernel that cannot compile."""
    return _ffn_vmem_bytes(d, d_ff, w_bytes, 32) <= _FFN_VMEM_BUDGET


def _ffn_fwd_pallas(h2d, ln_scale, ln_bias, w1, b1, w2, b2, seeds,
                    rate_hidden, rate_conn, eps, l_loc, l_glob,
                    block_rows=256):
    B, d = h2d.shape
    d_ff = w1.shape[1]
    w_bytes = jnp.dtype(w1.dtype).itemsize
    block_rows = min(block_rows, B)
    # degrade the row tile before giving up: the hidden tiles scale with
    # block_rows, so halving buys headroom down to the 32-row floor
    while (block_rows > 32
           and _ffn_vmem_bytes(d, d_ff, w_bytes,
                               block_rows) > _FFN_VMEM_BUDGET):
        block_rows //= 2
    if _ffn_vmem_bytes(d, d_ff, w_bytes, block_rows) > _FFN_VMEM_BUDGET:
        import warnings
        warnings.warn(
            f"fused FFN kernel resident set for d_model={d}, d_ff={d_ff} "
            f"exceeds the ~{_FFN_VMEM_BUDGET >> 20} MiB VMEM budget even "
            f"at the minimum row tile; computing this sublayer with the "
            f"XLA reference path instead (same math, default autodiff)",
            stacklevel=2)
        return ffn_sublayer_reference(
            h2d, ln_scale, ln_bias, w1, b1, w2, b2, seeds[0, 0],
            seeds[0, 1], rate_hidden, rate_conn, eps, seeds[0, 2],
            seeds[0, 3], l_loc, l_glob)
    nb = -(-B // block_rows)
    pad = nb * block_rows - B
    if pad:
        # NOTE: padded rows still hash dropout indices past B*d — fine,
        # they are sliced away and real rows' indices are unaffected.
        h2d = jnp.pad(h2d, ((0, pad), (0, 0)))
    kern = functools.partial(_ffn_kernel, block_rows=block_rows,
                             rate_hidden=rate_hidden, rate_conn=rate_conn,
                             eps=eps, l_loc=l_loc, l_glob=l_glob)
    out = pl.pallas_call(
        kern,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((d, d_ff), lambda i: (0, 0)),
            pl.BlockSpec((1, d_ff), lambda i: (0, 0)),
            pl.BlockSpec((d_ff, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, 4), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nb * block_rows, d), h2d.dtype),
        interpret=(jax.default_backend() != "tpu"),
    )(h2d, ln_scale.reshape(1, d), ln_bias.reshape(1, d), w1,
      b1.reshape(1, d_ff), w2, b2.reshape(1, d), seeds)
    return out[:B] if pad else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(11, 12, 13, 14, 15))
def _ffn_core(h, ln_scale, ln_bias, w1, b1, w2, b2,
              hid_seed, out_seed, b0, s0,
              rate_hidden: float, rate_conn: float, eps: float,
              l_loc: int, l_glob: int):
    lead = h.shape[:-1]
    d = h.shape[-1]
    seeds = jnp.stack([jnp.asarray(hid_seed, jnp.uint32),
                       jnp.asarray(out_seed, jnp.uint32),
                       jnp.asarray(b0, jnp.uint32),
                       jnp.asarray(s0, jnp.uint32)]).reshape(1, 4)
    out = _ffn_fwd_pallas(h.reshape(-1, d), ln_scale, ln_bias, w1, b1,
                          w2, b2, seeds, rate_hidden, rate_conn, eps,
                          l_loc, l_glob)
    return out.reshape(*lead, d)


def _ffn_vjp_fwd(h, ln_scale, ln_bias, w1, b1, w2, b2, hid_seed, out_seed,
                 b0, s0, rate_hidden, rate_conn, eps, l_loc, l_glob):
    out = _ffn_core(h, ln_scale, ln_bias, w1, b1, w2, b2,
                    hid_seed, out_seed, b0, s0,
                    rate_hidden, rate_conn, eps, l_loc, l_glob)
    # residuals: INPUTS only — nothing FFN-shaped is saved (the whole
    # sublayer is recomputed by the reference fn inside the bwd vjp)
    return out, (h, ln_scale, ln_bias, w1, b1, w2, b2, hid_seed, out_seed,
                 b0, s0)


def _ffn_vjp_bwd(rate_hidden, rate_conn, eps, l_loc, l_glob, res, g):
    (h, ln_scale, ln_bias, w1, b1, w2, b2, hid_seed, out_seed,
     b0, s0) = res
    _, vjp = jax.vjp(
        lambda h_, s_, bi_, w1_, b1_, w2_, b2_: ffn_sublayer_reference(
            h_, s_, bi_, w1_, b1_, w2_, b2_, hid_seed, out_seed,
            rate_hidden, rate_conn, eps, b0, s0, l_loc, l_glob),
        h, ln_scale, ln_bias, w1, b1, w2, b2)
    zero = np.zeros((), jax.dtypes.float0)
    return (*vjp(g), zero, zero, zero, zero)


_ffn_core.defvjp(_ffn_vjp_fwd, _ffn_vjp_bwd)


def pack_scales(quant_scales) -> jax.Array:
    """THE (4,) fp32 scales operand every fused-FFN shard_map layer
    ships to the generalized kernel: [sx1, sw1, sx2, sw2] stacked from
    traced scalars, or zeros(4) when quantization is off (None).  One
    definition so fused_ffn_sublayer_sharded, ffn_core_generalized and
    parallel/kernel_shard.fused_ffn_sublayer_tp can never disagree on
    the operand layout."""
    if quant_scales is None:
        return jnp.zeros((4,), jnp.float32)
    return jnp.stack([jnp.asarray(s, jnp.float32).reshape(())
                      for s in quant_scales])


def fused_ffn_sublayer(h, ln_scale, ln_bias, w1, b1, w2, b2,
                       hid_seed, out_seed,
                       rate_hidden: float = 0.0, rate_conn: float = 0.0,
                       eps: float = 1e-6):
    """out = h + drop(Dense2(drop(gelu(Dense1(LN(h)))))) in ONE Pallas
    kernel (see module docstring).  h: (..., d_model); weights in Flax
    (in, out) layout; seeds: u32 scalars (ignored when the static rates
    are 0 — pass anything).  Gradients flow to h, LN params, weights and
    biases; seeds are non-differentiable.  Dropout indices are the plain
    contiguous stream (global offsets are the sharded wrapper's job)."""
    if rate_hidden > 0.0 or rate_conn > 0.0:
        # loud guard on the documented 2^32 index ceiling (was a
        # docstring-only caveat): rows x the widest ACTIVE mask must
        # fit the uint32 stream — a rate-0 site draws no mask, so its
        # width must not be able to reject a legal config
        rows = int(np.prod(h.shape[:-1]))
        width = max(int(w1.shape[1]) if rate_hidden > 0.0 else 0,
                    int(h.shape[-1]) if rate_conn > 0.0 else 0)
        guard_index_ceiling(rows * width, site="fused FFN dropout")
    return _ffn_core(h, ln_scale, ln_bias, w1, b1, w2, b2,
                     hid_seed, out_seed, jnp.uint32(0), jnp.uint32(0),
                     rate_hidden, rate_conn, eps, 1, 1)


def fused_ffn_sublayer_sharded(h, ln_scale, ln_bias, w1, b1, w2, b2,
                               hid_seed, out_seed, mesh,
                               rate_hidden: float = 0.0,
                               rate_conn: float = 0.0,
                               eps: float = 1e-6,
                               quant_fmt: Optional[str] = None,
                               quant_scales=None,
                               grad_fmt: Optional[str] = None):
    """SPMD wrapper: the kernel runs PER SHARD under ``jax.shard_map``
    over the mesh's data axes (batch over dp/fsdp, sequence over sp),
    weights replicated (an fsdp/ZeRO-3-sharded weight is all-gathered by
    the partitioner at the shard_map boundary — the same gather the Flax
    path's dot would trigger).  Each shard addresses the GLOBAL dropout
    index space through its (batch, sequence) offsets — the same
    placement-invariance convention as every other sharded dropout
    consumer (ops/attention.py dropout_keep): masks depend only on
    (seed, global position), so the SAME global batch draws the SAME
    masks on dp=1, dp=4 or dp=8, bit-for-bit.  The global index space is
    uint32 — the contract holds up to 2^32 elements per activation
    tensor (see ops.dropout.keep_factor_rows for the documented wrap
    behavior past it).  tp-SHARDED FFN weights take the Megatron
    column-then-row decomposition in parallel/kernel_shard.py instead
    (this wrapper keeps the weights replicated).

    ``quant_fmt``/``quant_scales``/``grad_fmt`` (r19): run the two GEMMs
    quantized in-kernel through the generalized core; returns
    ``(out, amax2)`` with amax2 the GLOBAL (2,) [amax_f, amax_a] for the
    delayed-scaling history roll (pmax'd over every sharded axis)."""
    from jax.sharding import PartitionSpec as P

    batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names
                       and mesh.shape[a] > 1)
    seq_axis = "sp" if ("sp" in mesh.axis_names
                        and mesh.shape["sp"] > 1) else None
    if not batch_axes and seq_axis is None:
        if quant_fmt is not None:
            return ffn_core_generalized(
                h, ln_scale, ln_bias, w1, b1, w2, b2, hid_seed, out_seed,
                0, 0, 0, rate_hidden, rate_conn, eps, 1, 1,
                dff_glob=int(w1.shape[1]), quant_fmt=quant_fmt,
                quant_scales=quant_scales, grad_fmt=grad_fmt)
        return fused_ffn_sublayer(h, ln_scale, ln_bias, w1, b1, w2, b2,
                                  hid_seed, out_seed, rate_hidden,
                                  rate_conn, eps)
    if h.ndim != 3:
        raise ValueError("fused_ffn_sublayer_sharded expects (B, L, d) "
                         f"activations, got shape {h.shape}")
    if rate_hidden > 0.0 or rate_conn > 0.0:
        # the wrap behavior this guard replaces was only documented:
        # global rows (B*L) x the widest ACTIVE mask must fit uint32
        # or distant shards would silently share mask bits (rate-0
        # sites draw no mask and must not reject a legal config)
        width = max(int(w1.shape[1]) if rate_hidden > 0.0 else 0,
                    int(h.shape[-1]) if rate_conn > 0.0 else 0)
        guard_index_ceiling(int(h.shape[0]) * int(h.shape[1]) * width,
                            site="fused FFN dropout (sharded)")
    data_spec = P(batch_axes if len(batch_axes) != 1 else batch_axes[0],
                  seq_axis, None)
    rep = P(None)
    sp_size = mesh.shape[seq_axis] if seq_axis else 1
    sharded_axes = batch_axes + ((seq_axis,) if seq_axis else ())

    def per_shard(h_, lns_, lnb_, w1_, b1_, w2_, b2_, s1_, s2_, scales_):
        b_loc, l_loc = h_.shape[0], h_.shape[1]
        bi = jnp.uint32(0)
        for ax in batch_axes:
            bi = bi * jnp.uint32(mesh.shape[ax]) \
                + jax.lax.axis_index(ax).astype(jnp.uint32)
        b0 = bi * jnp.uint32(b_loc)
        s0 = (jax.lax.axis_index(seq_axis).astype(jnp.uint32)
              * jnp.uint32(l_loc) if seq_axis else jnp.uint32(0))
        if quant_fmt is None:
            out = _ffn_core(h_, lns_, lnb_, w1_, b1_, w2_, b2_, s1_, s2_,
                            b0, s0, rate_hidden, rate_conn, eps,
                            l_loc, l_loc * sp_size)
            return out, jnp.zeros((2,), jnp.float32)
        qscales = tuple(scales_[i] for i in range(4))
        out, amax2 = ffn_core_generalized(
            h_, lns_, lnb_, w1_, b1_, w2_, b2_, s1_, s2_, b0, s0, 0,
            rate_hidden, rate_conn, eps, l_loc, l_loc * sp_size,
            dff_glob=int(w1_.shape[1]), quant_fmt=quant_fmt,
            quant_scales=qscales, grad_fmt=grad_fmt,
            grad_axes=sharded_axes)
        # globalize the per-tensor amaxes: every shard sees a slice of
        # the same logical tensors, so the (2,) output is pmax'd over
        # every sharded axis and leaves the boundary truly replicated.
        # stop_gradient first: amaxes feed the scale-history roll, not
        # the loss, and pmax has no differentiation rule
        amax2 = jax.lax.stop_gradient(amax2)
        for ax in sharded_axes:
            amax2 = jax.lax.pmax(amax2, ax)
        return out, amax2

    from faster_distributed_training_tpu.compat import shard_map
    out, amax2 = shard_map(
        per_shard, mesh=mesh,
        in_specs=(data_spec, rep, rep, rep, rep, rep, rep, P(), P(), P()),
        out_specs=(data_spec, P()),
        # the pallas_call's out_shape carries no varying-mesh-axes info,
        # so VMA checking cannot see through it
        check_vma=False,
    )(h, ln_scale, ln_bias, w1, b1, w2, b2,
      jnp.asarray(hid_seed, jnp.uint32), jnp.asarray(out_seed, jnp.uint32),
      pack_scales(quant_scales if quant_fmt is not None else None))
    if quant_fmt is None:
        return out
    return out, amax2


# ---------------------------------------------------------------------------
# r19: the generalized core behind the shard_map kernel layer
# (parallel/kernel_shard.py) and the quantized fused-FFN composition.
#
# Two orthogonal extensions of the kernel above, parameterized statically
# so they compose (quant x partial x column offsets):
#   * quant (fmt != None) — the two GEMMs run on int8/fp8 operands with
#     per-tensor delayed scales (ops/quant.py recipe): the x side (LN
#     output / hidden activation) is quantized IN-KERNEL at the delayed
#     scale, the weights arrive pre-quantized, and the kernel emits the
#     two current-step amaxes (max-accumulated across the row-block
#     grid) so the caller can roll the histories — recombining the
#     LN/dropout fusion with the r13 quantized GEMMs (the kernel was
#     bf16-only under quant before this).
#   * partial (Megatron column-then-row tp tile) — w1 is a COLUMN shard
#     [d, d_ff/tp], w2 the matching ROW shard [d_ff/tp, d]; the kernel
#     computes LN -> GEMM1 -> GELU -> hidden dropout (addressing global
#     d_ff columns via c0/dff_glob) -> GEMM2 and stops BEFORE b2 / the
#     connection dropout / the residual, emitting the fp32 partial the
#     wrapper psums over tp — exactly ONE collective per sublayer, no
#     per-step weight gather.
#
# The backward for every combination is jax.vjp of ONE pure-XLA oracle
# (_ffn_body_reference) with the kernel's exact op order; the quant
# GEMMs inside it are ops.quant.quant_dot custom_vjp calls, so the
# straight-through estimator (and the optional fp8-E5M2 quantized
# gradient GEMMs) come along by construction.
# ---------------------------------------------------------------------------


def _ffn_body_reference(h, ln_scale, ln_bias, w1, b1, w2, b2,
                        hid_seed, out_seed, rate_hidden, rate_conn, eps,
                        b0, s0, l_loc, l_glob, c0=0, dff_glob=0,
                        partial=False, quant=None, return_amax=False):
    """The generalized pure-XLA oracle (op order == the generalized
    kernel).  ``quant``: None or (fmt, sx1, sw1, sx2, sw2, grad_fmt,
    grad_axes) — scales are traced scalars, the rest static.  partial:
    stop before b2/connection-dropout/residual and return the fp32
    GEMM2 product.  return_amax: also return the (2,) [amax_f, amax_a]
    current-step amaxes (zeros when quant is None)."""
    from faster_distributed_training_tpu.ops.quant import (quant_dot,
                                                           tensor_amax)

    lead = h.shape[:-1]
    d = h.shape[-1]
    x32 = h.reshape(-1, d).astype(jnp.float32)
    n_rows = x32.shape[0]
    grows = _global_rows(lax.iota(jnp.uint32, n_rows), b0, s0, l_loc, l_glob)
    f = _ln_saved(x32, ln_scale.astype(jnp.float32),
                  ln_bias.astype(jnp.float32), eps).astype(h.dtype)
    amax_f = amax_a = jnp.float32(0.0)
    if quant is not None:
        fmt, sx1, sw1, sx2, sw2, gfmt, gaxes = quant
        if return_amax:
            amax_f = tensor_amax(f)
        h1 = quant_dot(f, w1, sx1, sw1, fmt, use_pallas=False,
                       grad_fmt=gfmt, grad_axes=gaxes
                       ).astype(jnp.float32) + b1.astype(jnp.float32)
    else:
        h1 = jnp.dot(f, w1, preferred_element_type=jnp.float32) \
            + b1.astype(jnp.float32)
    a = _gelu_f32(h1)
    if rate_hidden > 0.0:
        a = a * _keep_rows(hid_seed, grows, a.shape[1], rate_hidden,
                           c0, dff_glob)
    a = a.astype(h.dtype)
    if quant is not None:
        if return_amax:
            amax_a = tensor_amax(a)
        f2 = quant_dot(a, w2, sx2, sw2, fmt, use_pallas=False,
                       grad_fmt=gfmt, grad_axes=gaxes).astype(jnp.float32)
    else:
        f2 = jnp.dot(a, w2, preferred_element_type=jnp.float32)
    if partial:
        out = f2.reshape(*lead, d)
    else:
        f2 = f2 + b2.astype(jnp.float32)
        if rate_conn > 0.0:
            f2 = f2 * _keep_rows(out_seed, grows, f2.shape[1], rate_conn)
        out = (x32 + f2).astype(h.dtype).reshape(*lead, d)
    if return_amax:
        return out, jnp.stack([amax_f, amax_a])
    return out


def _ffn_kernel2(h_ref, lns_ref, lnb_ref, w1_ref, b1_ref, w2_ref, b2_ref,
                 seeds_ref, scales_ref, o_ref, *amax_refs, block_rows: int,
                 rate_hidden: float, rate_conn: float, eps: float,
                 l_loc: int, l_glob: int, dff_glob: int, fmt,
                 partial: bool):
    """The generalized row-block kernel (see the section comment).
    seeds_ref (1, 5) SMEM u32: [hid_seed, out_seed, b0, s0, c0];
    scales_ref (1, 4) fp32: the RAW delayed scales [sx1, sw1, sx2, sw2]
    (quant only) — the kernel derives each GEMM's descale 1/(sx·sw)
    itself, callers never pass precomputed inverses."""
    from faster_distributed_training_tpu.ops.quant import QMAX

    row0 = pl.program_id(0) * block_rows
    x32 = h_ref[...].astype(jnp.float32)
    rows = x32.shape[0]
    f = _ln_f32(x32, lns_ref[...].astype(jnp.float32),
                lnb_ref[...].astype(jnp.float32), eps).astype(h_ref.dtype)

    def qdot(x, wq_ref, sx, inv):
        # mirror ops.quant.quant_dot's round-trip exactly: quantize the
        # compute-dtype operand, contract, descale in fp32, ONE cast to
        # the compute dtype, upcast f32 for the bias/GELU chain
        xs = x.astype(jnp.float32) * sx
        if fmt == "int8":
            xq = jnp.clip(jnp.round(xs), -QMAX["int8"],
                          QMAX["int8"]).astype(jnp.int8)
            acc = jax.lax.dot(xq, wq_ref[...],
                              preferred_element_type=jnp.int32
                              ).astype(jnp.float32)
        else:
            qmax = QMAX["fp8"]
            xq = jnp.clip(xs, -qmax, qmax).astype(jnp.float8_e4m3fn)
            acc = jax.lax.dot(xq.astype(jnp.float32),
                              wq_ref[...].astype(jnp.float32),
                              preferred_element_type=jnp.float32)
        return (acc * inv).astype(h_ref.dtype).astype(jnp.float32)

    if fmt is not None:
        amax_blk_f = jnp.max(jnp.abs(f.astype(jnp.float32)))
        h1 = qdot(f, w1_ref, scales_ref[0, 0],
                  1.0 / (scales_ref[0, 0] * scales_ref[0, 1])) \
            + b1_ref[...].astype(jnp.float32)
    else:
        h1 = jax.lax.dot(f, w1_ref[...],
                         preferred_element_type=jnp.float32) \
            + b1_ref[...].astype(jnp.float32)
    a = _gelu_f32(h1)
    if rate_hidden > 0.0 or rate_conn > 0.0:
        r_local = (jnp.uint32(row0)
                   + lax.broadcasted_iota(jnp.uint32, (rows, 1), 0))
        grows = _global_rows(r_local, seeds_ref[0, 2], seeds_ref[0, 3],
                             l_loc, l_glob)
    if rate_hidden > 0.0:
        a = a * _keep_rows(seeds_ref[0, 0], grows, a.shape[1],
                           rate_hidden, seeds_ref[0, 4], dff_glob)
    a = a.astype(h_ref.dtype)
    if fmt is not None:
        amax_blk_a = jnp.max(jnp.abs(a.astype(jnp.float32)))
        f2 = qdot(a, w2_ref, scales_ref[0, 2],
                  1.0 / (scales_ref[0, 2] * scales_ref[0, 3]))
    else:
        f2 = jax.lax.dot(a, w2_ref[...],
                         preferred_element_type=jnp.float32)
    if partial:
        o_ref[...] = f2.astype(o_ref.dtype)
    else:
        f2 = f2 + b2_ref[...].astype(jnp.float32)
        if rate_conn > 0.0:
            f2 = f2 * _keep_rows(seeds_ref[0, 1], grows, f2.shape[1],
                                 rate_conn)
        o_ref[...] = (x32 + f2).astype(o_ref.dtype)
    if fmt is not None:
        # (1, 1) running amaxes, max-accumulated across the sequential
        # row-block grid (every block maps the same output block)
        af_ref, aa_ref = amax_refs

        @pl.when(pl.program_id(0) == 0)
        def _init():
            af_ref[0, 0] = amax_blk_f
            aa_ref[0, 0] = amax_blk_a

        @pl.when(pl.program_id(0) > 0)
        def _acc():
            af_ref[0, 0] = jnp.maximum(af_ref[0, 0], amax_blk_f)
            aa_ref[0, 0] = jnp.maximum(aa_ref[0, 0], amax_blk_a)


def _ffn_fwd_pallas2(h2d, ln_scale, ln_bias, w1, b1, w2, b2, seeds,
                     scales, rate_hidden, rate_conn, eps, l_loc, l_glob,
                     dff_glob, fmt, grad_fmt, grad_axes, partial,
                     block_rows=256):
    """Generalized forward dispatch: the Pallas kernel when the resident
    set fits VMEM (weights pre-quantized to 1 byte/elem under quant),
    the oracle otherwise (warned).  Returns (out2d, amax2) — amax2 is
    (2,) fp32 [amax_f, amax_a], zeros when fmt is None."""
    B, d = h2d.shape
    d_ff = w1.shape[1]
    d_out = w2.shape[1]
    w_bytes = 1 if fmt is not None else jnp.dtype(w1.dtype).itemsize
    block_rows = min(block_rows, B)
    while (block_rows > 32
           and _ffn_vmem_bytes(d, d_ff, w_bytes,
                               block_rows) > _FFN_VMEM_BUDGET):
        block_rows //= 2
    if _ffn_vmem_bytes(d, d_ff, w_bytes, block_rows) > _FFN_VMEM_BUDGET:
        import warnings
        warnings.warn(
            f"fused FFN kernel resident set for d_model={d}, d_ff={d_ff} "
            f"exceeds the ~{_FFN_VMEM_BUDGET >> 20} MiB VMEM budget even "
            f"at the minimum row tile; computing this sublayer with the "
            f"XLA reference path instead (same math, default autodiff)",
            stacklevel=2)
        quant = (None if fmt is None else
                 (fmt, scales[0], scales[1], scales[2], scales[3],
                  grad_fmt, grad_axes))
        return _ffn_body_reference(
            h2d, ln_scale, ln_bias, w1, b1, w2, b2, seeds[0, 0],
            seeds[0, 1], rate_hidden, rate_conn, eps, seeds[0, 2],
            seeds[0, 3], l_loc, l_glob, seeds[0, 4], dff_glob,
            partial, quant, return_amax=True)
    if fmt is not None:
        # weights quantize ONCE per call at their delayed scales — the
        # kernel sees 1-byte operands (and the quantize sits inside the
        # custom_vjp boundary, so the straight-through estimator in the
        # reference backward bridges the rounding)
        from faster_distributed_training_tpu.ops.quant import quantize
        w1 = quantize(w1, scales[1], fmt)
        w2 = quantize(w2, scales[3], fmt)
    nb = -(-B // block_rows)
    pad = nb * block_rows - B
    if pad:
        h2d = jnp.pad(h2d, ((0, pad), (0, 0)))
    kern = functools.partial(_ffn_kernel2, block_rows=block_rows,
                             rate_hidden=rate_hidden, rate_conn=rate_conn,
                             eps=eps, l_loc=l_loc, l_glob=l_glob,
                             dff_glob=dff_glob, fmt=fmt, partial=partial)
    out_specs = [pl.BlockSpec((block_rows, d_out), lambda i: (i, 0))]
    out_dtype = jnp.float32 if partial else h2d.dtype
    out_shape = [jax.ShapeDtypeStruct((nb * block_rows, d_out), out_dtype)]
    if fmt is not None:
        out_specs += [pl.BlockSpec((1, 1), lambda i: (0, 0))] * 2
        out_shape += [jax.ShapeDtypeStruct((1, 1), jnp.float32)] * 2
    res = pl.pallas_call(
        kern,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((d, d_ff), lambda i: (0, 0)),
            pl.BlockSpec((1, d_ff), lambda i: (0, 0)),
            pl.BlockSpec((d_ff, d_out), lambda i: (0, 0)),
            pl.BlockSpec((1, d_out), lambda i: (0, 0)),
            pl.BlockSpec((1, 5), lambda i: (0, 0)),
            pl.BlockSpec((1, 4), lambda i: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=(jax.default_backend() != "tpu"),
    )(h2d, ln_scale.reshape(1, d), ln_bias.reshape(1, d), w1,
      b1.reshape(1, d_ff), w2, b2.reshape(1, d_out), seeds,
      scales.reshape(1, 4))
    if fmt is not None:
        out, af, aa = res
        amax2 = jnp.stack([af[0, 0], aa[0, 0]])
    else:
        out = res[0] if isinstance(res, (list, tuple)) else res
        amax2 = jnp.zeros((2,), jnp.float32)
    return (out[:B] if pad else out), amax2


@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 10, 11, 12, 13,
                                                    14, 15, 16, 17, 18))
def _ffn_core2(h, ln_scale, ln_bias, w1, b1, w2, b2, seeds, scales,
               rate_hidden: float, rate_conn: float, eps: float,
               l_loc: int, l_glob: int, dff_glob: int, fmt,
               grad_fmt, grad_axes, partial: bool):
    """Generalized fused-FFN core: returns (out, amax2).  seeds (1, 5)
    u32 [hid_seed, out_seed, b0, s0, c0]; scales (4,) fp32 [sx1, sw1,
    sx2, sw2] (zeros when fmt is None).  partial=True emits the fp32
    GEMM2 product (pre-b2/connection-dropout/residual) for the tp
    psum."""
    lead = h.shape[:-1]
    d = h.shape[-1]
    out2d, amax2 = _ffn_fwd_pallas2(
        h.reshape(-1, d), ln_scale, ln_bias, w1, b1, w2, b2, seeds,
        scales, rate_hidden, rate_conn, eps, l_loc, l_glob, dff_glob,
        fmt, grad_fmt, grad_axes, partial)
    return out2d.reshape(*lead, out2d.shape[-1]), amax2


def _ffn_vjp2_fwd(h, ln_scale, ln_bias, w1, b1, w2, b2, seeds, scales,
                  rate_hidden, rate_conn, eps, l_loc, l_glob, dff_glob,
                  fmt, grad_fmt, grad_axes, partial):
    out = _ffn_core2(h, ln_scale, ln_bias, w1, b1, w2, b2, seeds, scales,
                     rate_hidden, rate_conn, eps, l_loc, l_glob, dff_glob,
                     fmt, grad_fmt, grad_axes, partial)
    # residuals: INPUTS only — the recompute-backward contract of
    # _ffn_core carries over to every quant/partial combination
    return out, (h, ln_scale, ln_bias, w1, b1, w2, b2, seeds, scales)


def _ffn_vjp2_bwd(rate_hidden, rate_conn, eps, l_loc, l_glob, dff_glob,
                  fmt, grad_fmt, grad_axes, partial, res, g):
    h, ln_scale, ln_bias, w1, b1, w2, b2, seeds, scales = res
    g_out, _g_amax = g          # the amax outputs feed state, not loss
    quant = (None if fmt is None else
             (fmt, scales[0], scales[1], scales[2], scales[3],
              grad_fmt, grad_axes))
    _, vjp = jax.vjp(
        lambda h_, s_, bi_, w1_, b1_, w2_, b2_: _ffn_body_reference(
            h_, s_, bi_, w1_, b1_, w2_, b2_, seeds[0, 0], seeds[0, 1],
            rate_hidden, rate_conn, eps, seeds[0, 2], seeds[0, 3],
            l_loc, l_glob, seeds[0, 4], dff_glob, partial, quant),
        h, ln_scale, ln_bias, w1, b1, w2, b2)
    zero = np.zeros(np.shape(seeds), jax.dtypes.float0)
    return (*vjp(g_out), zero, jnp.zeros_like(scales))


_ffn_core2.defvjp(_ffn_vjp2_fwd, _ffn_vjp2_bwd)


def ffn_core_generalized(h, ln_scale, ln_bias, w1, b1, w2, b2,
                         hid_seed, out_seed, b0, s0, c0,
                         rate_hidden: float, rate_conn: float,
                         eps: float, l_loc: int, l_glob: int,
                         dff_glob: int = 0, quant_fmt=None,
                         quant_scales=None, grad_fmt=None,
                         grad_axes: tuple = (), partial: bool = False):
    """The shard_map layer's entry to the generalized core (parallel/
    kernel_shard.py runs this per shard; models/transformer.py calls it
    directly for the unsharded quantized composition).  Returns
    (out, amax2) with amax2 = (2,) fp32 [amax_f, amax_a] current-step
    amaxes (zeros when quant_fmt is None).  b0/s0/c0: global batch-row
    / sequence / d_ff-column offsets of this shard; quant_scales:
    (sx1, sw1, sx2, sw2) traced scalars when quant_fmt is set."""
    seeds = jnp.stack([jnp.asarray(hid_seed, jnp.uint32),
                       jnp.asarray(out_seed, jnp.uint32),
                       jnp.asarray(b0, jnp.uint32),
                       jnp.asarray(s0, jnp.uint32),
                       jnp.asarray(c0, jnp.uint32)]).reshape(1, 5)
    scales = pack_scales(quant_scales if quant_fmt is not None else None)
    return _ffn_core2(h, ln_scale, ln_bias, w1, b1, w2, b2, seeds,
                      scales, float(rate_hidden), float(rate_conn),
                      float(eps), int(l_loc), int(l_glob),
                      int(dff_glob) if dff_glob else int(w1.shape[1]),
                      quant_fmt, grad_fmt, tuple(grad_axes),
                      bool(partial))


