"""Fused 2-layer MLP (linear → ReLU → linear) with hand-written backward.

TPU-native re-design of the reference's ``MLPScratch``
(``transformer.py:292-338``): one ``jax.custom_vjp`` covering both
linears and the activation so the pair of matmuls stays on the MXU with
the ReLU fused into the epilogue.

Reference-semantics notes:
  * weights are stored ``(out, in)`` like ``torch.nn.Linear`` in the
    reference's ``FusedMLP`` (``transformer.py:345-358``); biases are
    broadcast row vectors;
  * the reference's backward contains a *scalar Python loop* over every
    element for the ReLU mask (``transformer.py:323-324``) — a
    deliberate perf bug we fix with a vectorized ``where``;
  * the reference reduces bias gradients with ``mean`` over the batch
    axis (``transformer.py:311,327``), which is mathematically a factor
    1/B off; we default to the correct ``sum`` and expose
    ``mean_bias_grad=True`` for bit-parity experiments;
  * the reference saves the hidden activations for backward
    (``transformer.py:301``); we *recompute* the first linear instead
    (one extra matmul), the same rematerialization stance as the fused
    conv — cheaper in HBM, and XLA overlaps the recompute with the
    cotangent matmuls.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def mlp_reference(x: jax.Array, w1: jax.Array, b1: Optional[jax.Array],
                  w2: jax.Array, b2: Optional[jax.Array]) -> jax.Array:
    """Unfused oracle: linear→ReLU→linear with (out,in) weights."""
    h = x @ w1.T + (0.0 if b1 is None else b1)
    a = jax.nn.relu(h)
    return a @ w2.T + (0.0 if b2 is None else b2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def fused_mlp(x: jax.Array, w1: jax.Array, b1: Optional[jax.Array],
              w2: jax.Array, b2: Optional[jax.Array],
              mean_bias_grad: bool = False) -> jax.Array:
    h = x @ w1.T + (0.0 if b1 is None else b1)
    a = jax.nn.relu(h)
    return a @ w2.T + (0.0 if b2 is None else b2)


def _mlp_fwd(x, w1, b1, w2, b2, mean_bias_grad):
    h = x @ w1.T + (0.0 if b1 is None else b1)
    a = jax.nn.relu(h)
    out = a @ w2.T + (0.0 if b2 is None else b2)
    # residuals: inputs only — h and a are recomputed in backward.
    return out, (x, w1, b1, w2, b2)


def _mlp_bwd(mean_bias_grad, res, g):
    x, w1, b1, w2, b2 = res
    # recompute the hidden pre-activation (rematerialization)
    h = x @ w1.T + (0.0 if b1 is None else b1)
    a = jax.nn.relu(h)

    lead = x.shape[:-1]
    gf = g.reshape(-1, g.shape[-1])          # (B*, d_out)
    af = a.reshape(-1, a.shape[-1])          # (B*, d_hidden)
    xf = x.reshape(-1, x.shape[-1])          # (B*, d_in)

    d_w2 = gf.T @ af                          # (d_out, d_hidden)
    d_a = g @ w2                              # (..., d_hidden)
    # vectorized ReLU mask — fixes the scalar loop at transformer.py:323-324
    d_h = jnp.where(h > 0, d_a, 0.0)
    d_hf = d_h.reshape(-1, d_h.shape[-1])
    d_w1 = d_hf.T @ xf                        # (d_hidden, d_in)
    d_x = d_h @ w1

    red = jnp.mean if mean_bias_grad else jnp.sum
    d_b1 = None if b1 is None else red(d_hf, axis=0).reshape(b1.shape)
    d_b2 = None if b2 is None else red(gf, axis=0).reshape(b2.shape)
    del lead
    return d_x, d_w1, d_b1, d_w2, d_b2


fused_mlp.defvjp(_mlp_fwd, _mlp_bwd)
