"""Fused 2-layer MLP (linear → ReLU → linear) with hand-written backward.

TPU-native re-design of the reference's ``MLPScratch``
(``transformer.py:292-338``): one ``jax.custom_vjp`` covering both
linears and the activation so the pair of matmuls stays on the MXU with
the ReLU fused into the epilogue.

Reference-semantics notes:
  * weights are stored ``(out, in)`` like ``torch.nn.Linear`` in the
    reference's ``FusedMLP`` (``transformer.py:345-358``); biases are
    broadcast row vectors;
  * the reference's backward contains a *scalar Python loop* over every
    element for the ReLU mask (``transformer.py:323-324``) — a
    deliberate perf bug we fix with a vectorized ``where``;
  * the reference reduces bias gradients with ``mean`` over the batch
    axis (``transformer.py:311,327``), which is mathematically a factor
    1/B off; we default to the correct ``sum`` and expose
    ``mean_bias_grad=True`` for bit-parity experiments;
  * the reference saves the hidden activations for backward
    (``transformer.py:301``); we *recompute* the first linear instead
    (one extra matmul), the same rematerialization stance as the fused
    conv — cheaper in HBM, and XLA overlaps the recompute with the
    cotangent matmuls.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def mlp_reference(x: jax.Array, w1: jax.Array, b1: Optional[jax.Array],
                  w2: jax.Array, b2: Optional[jax.Array]) -> jax.Array:
    """Unfused oracle: linear→ReLU→linear with (out,in) weights."""
    h = x @ w1.T + (0.0 if b1 is None else b1)
    a = jax.nn.relu(h)
    return a @ w2.T + (0.0 if b2 is None else b2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def fused_mlp(x: jax.Array, w1: jax.Array, b1: Optional[jax.Array],
              w2: jax.Array, b2: Optional[jax.Array],
              mean_bias_grad: bool = False) -> jax.Array:
    h = x @ w1.T + (0.0 if b1 is None else b1)
    a = jax.nn.relu(h)
    return a @ w2.T + (0.0 if b2 is None else b2)


def _mlp_fwd(x, w1, b1, w2, b2, mean_bias_grad):
    h = x @ w1.T + (0.0 if b1 is None else b1)
    a = jax.nn.relu(h)
    out = a @ w2.T + (0.0 if b2 is None else b2)
    # residuals: inputs only — h and a are recomputed in backward.
    return out, (x, w1, b1, w2, b2)


def _mlp_bwd(mean_bias_grad, res, g):
    x, w1, b1, w2, b2 = res
    # recompute the hidden pre-activation (rematerialization)
    h = x @ w1.T + (0.0 if b1 is None else b1)
    a = jax.nn.relu(h)

    lead = x.shape[:-1]
    gf = g.reshape(-1, g.shape[-1])          # (B*, d_out)
    af = a.reshape(-1, a.shape[-1])          # (B*, d_hidden)
    xf = x.reshape(-1, x.shape[-1])          # (B*, d_in)

    d_w2 = gf.T @ af                          # (d_out, d_hidden)
    d_a = g @ w2                              # (..., d_hidden)
    # vectorized ReLU mask — fixes the scalar loop at transformer.py:323-324
    d_h = jnp.where(h > 0, d_a, 0.0)
    d_hf = d_h.reshape(-1, d_h.shape[-1])
    d_w1 = d_hf.T @ xf                        # (d_hidden, d_in)
    d_x = d_h @ w1

    red = jnp.mean if mean_bias_grad else jnp.sum
    d_b1 = None if b1 is None else red(d_hf, axis=0).reshape(b1.shape)
    d_b2 = None if b2 is None else red(gf, axis=0).reshape(b2.shape)
    del lead
    return d_x, d_w1, d_b1, d_w2, d_b2


fused_mlp.defvjp(_mlp_fwd, _mlp_bwd)


# ---------------------------------------------------------------------------
# Pallas forward kernel — both matmuls + ReLU in one VMEM-resident pass
# ---------------------------------------------------------------------------

def _mlp_kernel(x_ref, w1t_ref, b1_ref, w2t_ref, b2_ref, o_ref):
    """One row-block: h = x@w1ᵀ+b1; out = relu(h)@w2ᵀ+b2.

    The hidden activations live only in VMEM/registers — they are never
    written to HBM, which is the point of fusing (the reference instead
    *saves* them for backward, transformer.py:301)."""
    x = x_ref[...]
    h = jax.lax.dot(x, w1t_ref[...],
                    preferred_element_type=jnp.float32) + b1_ref[...]
    a = jnp.maximum(h, 0.0).astype(x.dtype)
    o = jax.lax.dot(a, w2t_ref[...],
                    preferred_element_type=jnp.float32) + b2_ref[...]
    o_ref[...] = o.astype(o_ref.dtype)


def _mlp_fwd_pallas(x2d: jax.Array, w1: jax.Array, b1: jax.Array,
                    w2: jax.Array, b2: jax.Array,
                    block_b: int = 256) -> jax.Array:
    """x2d [B, d_in]; weights (out, in) like torch.nn.Linear.  Weights are
    passed transposed and fully VMEM-resident (d_model≤1k → ≤4 MiB of the
    ~16 MiB budget); rows are tiled over the grid."""
    from jax.experimental import pallas as pl

    B, d_in = x2d.shape
    d_h, d_out = w1.shape[0], w2.shape[0]
    block_b = min(block_b, B)
    nb = -(-B // block_b)
    pad = nb * block_b - B
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        _mlp_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((block_b, d_in), lambda i: (i, 0)),
            pl.BlockSpec((d_in, d_h), lambda i: (0, 0)),
            pl.BlockSpec((1, d_h), lambda i: (0, 0)),
            pl.BlockSpec((d_h, d_out), lambda i: (0, 0)),
            pl.BlockSpec((1, d_out), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_b, d_out), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nb * block_b, d_out), x2d.dtype),
        interpret=(jax.default_backend() != "tpu"),
    )(x2d, w1.T, jnp.reshape(b1, (1, d_h)), w2.T, jnp.reshape(b2, (1, d_out)))
    return out[:B] if pad else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def fused_mlp_pallas(x: jax.Array, w1: jax.Array, b1: Optional[jax.Array],
                     w2: jax.Array, b2: Optional[jax.Array],
                     mean_bias_grad: bool = False) -> jax.Array:
    """Pallas-kernel forward of the fused MLP; backward is the same
    recompute-in-backward VJP as ``fused_mlp`` (plain MXU matmuls XLA
    already schedules well).  Interpreter mode runs it on CPU for tests."""
    zero1 = jnp.zeros((w1.shape[0],), x.dtype) if b1 is None else b1
    zero2 = jnp.zeros((w2.shape[0],), x.dtype) if b2 is None else b2
    lead = x.shape[:-1]
    out = _mlp_fwd_pallas(x.reshape(-1, x.shape[-1]), w1, zero1, w2, zero2)
    return out.reshape(*lead, w2.shape[0])


def _mlp_fwd_pallas_vjp(x, w1, b1, w2, b2, mean_bias_grad):
    return fused_mlp_pallas(x, w1, b1, w2, b2, mean_bias_grad), (
        x, w1, b1, w2, b2)


fused_mlp_pallas.defvjp(_mlp_fwd_pallas_vjp, _mlp_bwd)
