"""Custom kernels: fused Conv+BN, fused MLP, flash / ring attention.

These are the TPU-native counterparts of the reference's hand-written
autograd Functions (resnet.py:72-113 FusedConvBN2DFunction,
transformer.py:292-338 MLPScratch): `jax.custom_vjp` functions with
backward recomputation (activation rematerialization) plus Pallas TPU
kernels for the attention hot path.
"""

from faster_distributed_training_tpu.ops.conv_bn import (  # noqa: F401
    conv2d, conv_bn_train, fused_conv_bn, conv_bn_reference)
from faster_distributed_training_tpu.ops.fused_ffn import (  # noqa: F401
    ffn_sublayer_reference, fused_ffn_sublayer)
from faster_distributed_training_tpu.ops.fused_mlp import (  # noqa: F401
    fused_mlp, fused_mlp_pallas, mlp_reference)
from faster_distributed_training_tpu.ops.attention import (  # noqa: F401
    blockwise_attention, dense_attention_reference)
from faster_distributed_training_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention)
from faster_distributed_training_tpu.ops.ring_attention import (  # noqa: F401
    ring_attention, ring_self_attention)
from faster_distributed_training_tpu.ops.ulysses_attention import (  # noqa: F401
    ulysses_attention, ulysses_self_attention)
