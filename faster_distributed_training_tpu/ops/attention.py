"""Blockwise (online-softmax / flash-style) attention in pure JAX.

The reference's attention is the O(L²)-memory dense ScaledDotProduct
(transformer.py:180-193): it materializes the full [B,H,Lq,Lk] score and
probability tensors.  Blockwise attention streams over key/value blocks
with running (max, sum, accumulator) statistics, so peak memory is
O(Lq·block_k) — this is the long-context enabler and the shared math for
both the Pallas TPU kernel (ops/flash_attention.py) and ring
sequence-parallel attention (ops/ring_attention.py).

Mask convention matches models/transformer.py: mask==0 → masked out,
broadcastable to [B, H, Lq, Lk] (typically a [B,1,1,Lk] padding mask).
Softmax statistics are kept in fp32 regardless of input dtype.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e9  # matches models/transformer.py masking constant


def mask_to_bias(mask: Optional[jax.Array], dtype=jnp.float32
                 ) -> Optional[jax.Array]:
    """mask (…==0 masked) -> additive bias (0 keep, NEG_INF drop)."""
    if mask is None:
        return None
    return jnp.where(mask == 0, jnp.asarray(NEG_INF, dtype),
                     jnp.asarray(0.0, dtype))


def online_block_update(q: jax.Array, k_blk: jax.Array, v_blk: jax.Array,
                        bias_blk: Optional[jax.Array],
                        m: jax.Array, l: jax.Array, acc: jax.Array,
                        scale: float) -> Tuple[jax.Array, jax.Array,
                                               jax.Array]:
    """One online-softmax accumulation step.

    q [..., Lq, D], k_blk/v_blk [..., Bk, D], bias_blk broadcastable to
    [..., Lq, Bk]; m/l [..., Lq] fp32 running max / normalizer,
    acc [..., Lq, D] fp32 running numerator.  Returns updated (m, l, acc).
    """
    s = jnp.einsum("...qd,...kd->...qk", q, k_blk,
                   preferred_element_type=jnp.float32) * scale
    if bias_blk is not None:
        s = s + bias_blk.astype(jnp.float32)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # exp(NEG_INF - m_new) underflows to 0, so fully-masked columns drop out
    p = jnp.exp(s - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "...qk,...kd->...qd", p.astype(v_blk.dtype), v_blk,
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def finalize(m: jax.Array, l: jax.Array, acc: jax.Array,
             dtype) -> jax.Array:
    """acc / l with fully-masked-row protection (returns 0 there)."""
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(dtype)


def init_carry(q: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    # accumulators are DERIVED from q (0*q) rather than freshly created:
    # under shard_map, constants carry no varying-manual-axes while the
    # scan-body outputs vary over the mesh axes, and lax.scan requires the
    # carry types (incl. VMA sets) to match — deriving from q gives the
    # carry q's full VMA set (same trick as ops/ring_attention.py).
    zeros = q.astype(jnp.float32) * 0.0
    m = zeros[..., 0] - jnp.inf
    l = zeros[..., 0]
    acc = zeros
    return m, l, acc


@partial(jax.jit, static_argnames=("block_k", "causal"))
def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        mask: Optional[jax.Array] = None,
                        block_k: int = 128,
                        causal: bool = False) -> jax.Array:
    """Streaming attention over key blocks via lax.scan.

    q [B,H,Lq,D], k/v [B,H,Lk,D], mask broadcastable to [B,H,Lq,Lk]
    (mask==0 masked).  Numerically equal to dense softmax attention.

    causal=True applies the lower-triangular constraint ANALYTICALLY per
    key block (an [Lq, block_k] bias built inside the scan body from the
    block's key positions) — never an [Lq, Lk] tensor, so long-context
    callers (ops/ulysses_attention.py) stay O(L·block_k) in memory.
    Assumes query position i attends key positions <= i with q/k indexed
    from the same origin (Lq == Lk self-attention).
    """
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    scale = 1.0 / math.sqrt(D)
    block_k = min(block_k, Lk)
    n_blocks = -(-Lk // block_k)
    pad = n_blocks * block_k - Lk

    bias = mask_to_bias(mask)
    if bias is None:
        bias = jnp.zeros((1, 1, 1, Lk), jnp.float32)
    bias = jnp.broadcast_to(bias, (B,) + bias.shape[1:3] + (Lk,))
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
        bias = jnp.pad(bias, ((0, 0), (0, 0), (0, 0), (0, pad)),
                       constant_values=NEG_INF)

    # [n, B, H, block, D] blocks as scan sequence
    kb = jnp.moveaxis(k.reshape(B, H, n_blocks, block_k, D), 2, 0)
    vb = jnp.moveaxis(v.reshape(B, H, n_blocks, block_k, D), 2, 0)
    bb = jnp.moveaxis(
        bias.reshape(B, bias.shape[1], bias.shape[2], n_blocks, block_k),
        3, 0)

    q_pos = jnp.arange(Lq, dtype=jnp.int32)

    def body(carry, blk):
        m, l, acc = carry
        k_blk, v_blk, bias_blk, blk_idx = blk
        if causal:
            k_pos = blk_idx * block_k + jnp.arange(block_k, dtype=jnp.int32)
            cb = jnp.where(k_pos[None, :] <= q_pos[:, None], 0.0, NEG_INF)
            bias_blk = bias_blk + cb[None, None]       # [B,1,Lq,block_k]
        return online_block_update(q, k_blk, v_blk, bias_blk, m, l, acc,
                                   scale), None

    (m, l, acc), _ = lax.scan(
        body, init_carry(q),
        (kb, vb, bb, jnp.arange(n_blocks, dtype=jnp.int32)))
    return finalize(m, l, acc, q.dtype)


def dense_attention_reference(q, k, v, mask=None):
    """O(L²) reference (transformer.py:180-193 semantics) for tests."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    bias = mask_to_bias(mask)
    if bias is not None:
        s = s + bias
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
