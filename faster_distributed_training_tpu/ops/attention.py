"""Blockwise (online-softmax / flash-style) attention in pure JAX.

The reference's attention is the O(L²)-memory dense ScaledDotProduct
(transformer.py:180-193): it materializes the full [B,H,Lq,Lk] score and
probability tensors.  Blockwise attention streams over key/value blocks
with running (max, sum, accumulator) statistics, so peak memory is
O(Lq·block_k) — this is the long-context enabler and the shared math for
both the Pallas TPU kernel (ops/flash_attention.py) and ring
sequence-parallel attention (ops/ring_attention.py).

Mask convention matches models/transformer.py: mask==0 → masked out,
broadcastable to [B, H, Lq, Lk] (typically a [B,1,1,Lk] padding mask).
Softmax statistics are kept in fp32 regardless of input dtype.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e9  # matches models/transformer.py masking constant


def mask_to_bias(mask: Optional[jax.Array], dtype=jnp.float32
                 ) -> Optional[jax.Array]:
    """mask (…==0 masked) -> additive bias (0 keep, NEG_INF drop)."""
    if mask is None:
        return None
    return jnp.where(mask == 0, jnp.asarray(NEG_INF, dtype),
                     jnp.asarray(0.0, dtype))


# ------------------------------------------------- stateless hash dropout
# Attention-prob dropout for paths that never materialize the probability
# tensor (flash / blockwise / ring / ulysses): the keep decision for score
# element (bh, q, k) is a pure function of (seed, bh, q, k), so the
# forward kernel and any recompute-in-backward formulation regenerate the
# IDENTICAL mask from indices alone — no [B,H,Lq,Lk] mask tensor ever
# lives in HBM, and no RNG state threads through the scan.  The mixer is
# murmur3's 32-bit finalizer (full avalanche), plenty for dropout; every
# op (xor/shift/mul on u32) lowers on both XLA and Mosaic/Pallas-TPU.
# Matches the reference's dropout-after-softmax placement
# (transformer.py:190-192): the softmax normalizer uses ALL probabilities,
# the dropped ones are zeroed only in the value contraction.

def _fmix32(x: jax.Array) -> jax.Array:
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def dropout_keep(seed: jax.Array, bh: jax.Array, q_idx: jax.Array,
                 k_idx: jax.Array, rate: float) -> jax.Array:
    """fp32 keep/(1-rate) factor, broadcast over bh/q_idx/k_idx.

    seed: u32 scalar (one fresh value per step, e.g. jax.random.bits of
    the step's dropout rng); bh / q_idx / k_idx: integer index arrays
    broadcastable to the score block's shape (GLOBAL indices — sharded
    callers add their shard offsets so placement doesn't change the
    pattern); rate: static python float in [0, 1)."""
    h = _fmix32(seed.astype(jnp.uint32) ^ bh.astype(jnp.uint32))
    h = _fmix32(h ^ q_idx.astype(jnp.uint32))
    h = _fmix32(h ^ k_idx.astype(jnp.uint32))
    thresh = jnp.uint32(min(int((1.0 - rate) * 4294967296.0), 4294967295))
    return (h < thresh).astype(jnp.float32) / (1.0 - rate)


def online_block_update(q: jax.Array, k_blk: jax.Array, v_blk: jax.Array,
                        bias_blk: Optional[jax.Array],
                        m: jax.Array, l: jax.Array, acc: jax.Array,
                        scale: float,
                        keep_blk: Optional[jax.Array] = None
                        ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One online-softmax accumulation step.

    q [..., Lq, D], k_blk/v_blk [..., Bk, D], bias_blk broadcastable to
    [..., Lq, Bk]; m/l [..., Lq] fp32 running max / normalizer,
    acc [..., Lq, D] fp32 running numerator.  keep_blk: optional
    pre-scaled dropout factor (dropout_keep output) broadcastable to
    [..., Lq, Bk] — applied to the value contraction only, NOT to the
    normalizer, which is softmax-then-dropout semantics
    (transformer.py:190-192).  Returns updated (m, l, acc).
    """
    s = jnp.einsum("...qd,...kd->...qk", q, k_blk,
                   preferred_element_type=jnp.float32) * scale
    if bias_blk is not None:
        s = s + bias_blk.astype(jnp.float32)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # exp(NEG_INF - m_new) underflows to 0, so fully-masked columns drop out
    p = jnp.exp(s - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = p if keep_blk is None else p * keep_blk
    acc_new = acc * corr[..., None] + jnp.einsum(
        "...qk,...kd->...qd", pv.astype(v_blk.dtype), v_blk,
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def finalize(m: jax.Array, l: jax.Array, acc: jax.Array,
             dtype) -> jax.Array:
    """acc / l with fully-masked-row protection (returns 0 there)."""
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(dtype)


def init_carry(q: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    # accumulators are DERIVED from q (0*q) rather than freshly created:
    # under shard_map, constants carry no varying-manual-axes while the
    # scan-body outputs vary over the mesh axes, and lax.scan requires the
    # carry types (incl. VMA sets) to match — deriving from q gives the
    # carry q's full VMA set (same trick as ops/ring_attention.py).
    zeros = q.astype(jnp.float32) * 0.0
    m = zeros[..., 0] - jnp.inf
    l = zeros[..., 0]
    acc = zeros
    return m, l, acc


def bh_index(B: int, H: int) -> jax.Array:
    """[B,H,1,1] flattened batch*head index — the dropout stream id every
    attention path (Pallas grid n, blockwise, dense, ring, ulysses)
    agrees on; sharded callers offset it to global coordinates."""
    return (jnp.arange(B, dtype=jnp.int32)[:, None] * H
            + jnp.arange(H, dtype=jnp.int32)[None, :])[:, :, None, None]


@partial(jax.jit, static_argnames=("block_k", "causal", "dropout_rate"))
def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        mask: Optional[jax.Array] = None,
                        block_k: int = 128,
                        causal: bool = False,
                        dropout_rate: float = 0.0,
                        dropout_seed: Optional[jax.Array] = None,
                        dropout_bh: Optional[jax.Array] = None
                        ) -> jax.Array:
    """Streaming attention over key blocks via lax.scan.

    q [B,H,Lq,D], k/v [B,H,Lk,D], mask broadcastable to [B,H,Lq,Lk]
    (mask==0 masked).  Numerically equal to dense softmax attention;
    with dropout_rate > 0 (training), equal to softmax-then-hash-dropout
    (dense_attention_reference with the same seed).

    causal=True applies the lower-triangular constraint ANALYTICALLY per
    key block (an [Lq, block_k] bias built inside the scan body from the
    block's key positions) — never an [Lq, Lk] tensor, so long-context
    callers (ops/ulysses_attention.py) stay O(L·block_k) in memory.
    Assumes query position i attends key positions <= i with q/k indexed
    from the same origin (Lq == Lk self-attention).
    """
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    scale = 1.0 / math.sqrt(D)
    block_k = min(block_k, Lk)
    n_blocks = -(-Lk // block_k)
    pad = n_blocks * block_k - Lk

    bias = mask_to_bias(mask)
    if bias is None:
        bias = jnp.zeros((1, 1, 1, Lk), jnp.float32)
    bias = jnp.broadcast_to(bias, (B,) + bias.shape[1:3] + (Lk,))
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
        bias = jnp.pad(bias, ((0, 0), (0, 0), (0, 0), (0, pad)),
                       constant_values=NEG_INF)

    # [n, B, H, block, D] blocks as scan sequence
    kb = jnp.moveaxis(k.reshape(B, H, n_blocks, block_k, D), 2, 0)
    vb = jnp.moveaxis(v.reshape(B, H, n_blocks, block_k, D), 2, 0)
    bb = jnp.moveaxis(
        bias.reshape(B, bias.shape[1], bias.shape[2], n_blocks, block_k),
        3, 0)

    q_pos = jnp.arange(Lq, dtype=jnp.int32)
    # dropout_bh lets sharded callers (ops/ulysses_attention.py) pass the
    # GLOBAL [B,H,1,1] stream index so the drop pattern is placement-
    # independent; default is the local flattened b*H+h
    bh = bh_index(B, H) if dropout_bh is None else dropout_bh
    seed = (jnp.uint32(0) if dropout_seed is None
            else dropout_seed.astype(jnp.uint32))

    def body(carry, blk):
        m, l, acc = carry
        k_blk, v_blk, bias_blk, blk_idx = blk
        k_pos = blk_idx * block_k + jnp.arange(block_k, dtype=jnp.int32)
        if causal:
            cb = jnp.where(k_pos[None, :] <= q_pos[:, None], 0.0, NEG_INF)
            bias_blk = bias_blk + cb[None, None]       # [B,1,Lq,block_k]
        keep = None
        if dropout_rate > 0.0:
            keep = dropout_keep(seed, bh, q_pos[None, None, :, None],
                                k_pos[None, None, None, :], dropout_rate)
        return online_block_update(q, k_blk, v_blk, bias_blk, m, l, acc,
                                   scale, keep_blk=keep), None

    (m, l, acc), _ = lax.scan(
        body, init_carry(q),
        (kb, vb, bb, jnp.arange(n_blocks, dtype=jnp.int32)))
    return finalize(m, l, acc, q.dtype)


def dense_attention_reference(q, k, v, mask=None, dropout_rate: float = 0.0,
                              dropout_seed: Optional[jax.Array] = None,
                              dropout_bh: Optional[jax.Array] = None):
    """O(L²) reference (transformer.py:180-193 semantics).  With
    dropout_rate > 0 applies the same index-hash dropout as the
    blockwise/Pallas paths (softmax first, then drop+rescale).
    ``dropout_bh``: optional GLOBAL [B,H,1,1] stream index for sharded
    callers (parallel/kernel_shard.py head-sharded flash); default is
    the local flattened b*H+h — the blockwise_attention convention."""
    B, H, Lq, _ = q.shape
    Lk = k.shape[2]
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    bias = mask_to_bias(mask)
    if bias is not None:
        s = s + bias
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate > 0.0:
        seed = (jnp.uint32(0) if dropout_seed is None
                else dropout_seed.astype(jnp.uint32))
        p = p * dropout_keep(seed,
                             bh_index(B, H) if dropout_bh is None
                             else dropout_bh,
                             jnp.arange(Lq, dtype=jnp.int32)[None, None, :,
                                                             None],
                             jnp.arange(Lk, dtype=jnp.int32)[None, None,
                                                             None, :],
                             dropout_rate)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
