"""Quantized-training matmuls: int8 / fp8 GEMMs with per-tensor
delayed scaling (the ROADMAP "close the MFU gap with low-precision
compute" lever).

BENCH_LATEST pins the transformer at 29.1% MFU against a measured 35%
bf16 GEMM ceiling — ~6 points of headroom left at this precision.  The
MXU's int8/fp8 throughput is ~2x its bf16 peak, so the big remaining
lever is dropping the GEMM operand precision while keeping fp32
accumulation.  This module follows the established low-precision
training recipe:

  * **per-tensor delayed scaling** (FP8-LM / NVIDIA Transformer Engine
    style): each quantized tensor site keeps a short amax HISTORY; the
    scale used at step t is derived from the history of steps < t (so
    quantization is a cheap elementwise multiply+round with no
    serialized reduction before the GEMM), and step t's amax is pushed
    into the history for step t+1.  The history/scale state lives in
    the model's ``batch_stats`` collection — the existing cross-step
    statistics channel — so the r8 fused-dispatch carry, checkpointing
    and kill-at-N bitwise resume all carry it with ZERO new plumbing
    (exactly like the loss-scale/NGD state already in the carry).
  * **symmetric quantization with fp32 accumulation** (LLM.int8()-style
    per-tensor scaling): int8 GEMMs accumulate int32, fp8 GEMMs
    accumulate fp32, and the combined ``sx*sw`` dequant scale is applied
    once on the fp32 accumulator.
  * **quantized backward residuals**: ``quant_dot``'s custom_vjp saves
    the QUANTIZED operands (1 byte/elem) and dequantizes them inside the
    backward — the gradient GEMMs themselves run in the compute dtype
    (straight-through estimator through the rounding), so training
    dynamics stay close to the full-precision path while forward GEMMs
    and residual memory take the low-precision win.  ``--quant_grad
    fp8_e5m2`` (r19) completes the FP8-LM recipe: the cotangent is
    quantized to the wide-range E5M2 grid at a just-in-time per-tensor
    scale and BOTH gradient GEMMs run on quantized operands (the
    quantized-dW path).

Kernel routing follows the repo's Pallas idioms (ops/fused_ffn.py):
the tiled Pallas kernel runs only on TPU, respects a static VMEM-fit
guard (``quant_kernel_fits_vmem``) with a degrading row tile, and falls
back WARNED to the XLA reference path — same math, ``lax.dot_general``
on the quantized operands — on unsupported shapes.  On tp meshes the
kernel runs PER-SHARD on the Megatron column/row-sharded weight tiles
through the shard_map layer (parallel/kernel_shard.py, r19); the old
XLA-reference reroute survives only as the registered warned fallback
(FDT_KERNEL_SHARD=0 or non-dividing shapes).  ``FDT_QUANT=0`` kills
quantization entirely — every site computes the plain full-precision
matmul.

Determinism contract: quantization is round-to-nearest (no stochastic
rounding), amaxes are plain max-reductions, and the scale state rides
the train-state carry — so K=4 fused dispatch is bitwise-equal to K=1
and a kill-at-N resume is bitwise-equal to the uninterrupted run
(pinned by tests/test_quant.py).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

try:
    from jax.experimental import pallas as pl
except ImportError:  # pragma: no cover
    pl = None

ENV_KILL = "FDT_QUANT"

# symmetric-quantization grids: the largest magnitude each format
# represents.  int8 uses 127 (not 128) so the grid is symmetric; fp8
# uses the finite max of each IEEE-ish variant (E4M3 has no inf and
# tops out at 448; E5M2 keeps inf/nan and tops out at 57344 — the
# wide-range variant the fp8 literature reserves for GRADIENTS).
QMAX = {"int8": 127.0,
        "fp8": 448.0,        # forward operands ride E4M3
        "fp8_e4m3": 448.0,
        "fp8_e5m2": 57344.0}

_FMTS = ("int8", "fp8")


def quant_enabled() -> bool:
    """The FDT_QUANT=0 kill switch (read per call so tests can flip it):
    False means every quantized site computes plain full-precision."""
    return os.environ.get(ENV_KILL, "1") != "0"


# -- pure scale-state helpers (the delayed-scaling recipe) ----------------

def fresh_amax_history(length: int = 16) -> jax.Array:
    """Zero-initialized amax history — scale_from_history treats the
    all-zero history as "never observed" and returns scale 1.0."""
    return jnp.zeros((int(length),), jnp.float32)


def update_amax_history(history: jax.Array, amax: jax.Array) -> jax.Array:
    """Push the newest amax in at index 0, shifting the rest (the oldest
    falls off).  Pure, shapes static — safe inside the fused-dispatch
    scan."""
    amax = jnp.asarray(amax, jnp.float32).reshape(1)
    return jnp.concatenate([amax, history[:-1]])


def scale_from_history(history: jax.Array, fmt: str,
                       margin: float = 1.0) -> jax.Array:
    """Delayed scale for the NEXT quantization: qmax / (margin * running
    amax), where the running amax is the max over the history window
    (Transformer Engine's "max" amax_compute_algo).  An all-zero history
    (fresh state, or a genuinely all-zero tensor) yields scale 1.0 —
    quantizing zeros is exact at any scale, and the first real step
    seeds the history for the second."""
    return _scale_from_amax(jnp.max(history) * jnp.float32(margin), fmt)


def _scale_from_amax(amax: jax.Array, fmt: str) -> jax.Array:
    """THE amax→scale formula (zero-amax → identity scale, 1e-30
    floor): shared by the delayed forward scales (scale_from_history)
    and the just-in-time gradient scales (_jit_grad_scale) so the two
    recipes can never drift on the clamp/zero-guard convention."""
    qmax = QMAX[fmt]
    return jnp.where(amax > 0.0, qmax / jnp.maximum(amax, 1e-30),
                     jnp.float32(1.0)).astype(jnp.float32)


def tensor_amax(x: jax.Array) -> jax.Array:
    """Current-step amax in fp32 (computed on the pre-quantization
    values; fp16/bf16 inputs are upcast first so the reduction can't
    overflow or lose the true max to rounding)."""
    return jnp.max(jnp.abs(x.astype(jnp.float32)))


# -- quant/dequant helpers (pure, shared by kernel + reference) -----------

def quantize_int8(x: jax.Array, scale: jax.Array) -> jax.Array:
    """Symmetric int8: q = clip(round(x * scale), ±127).  jnp.round is
    round-half-even — deterministic across backends, which the bitwise
    K-dispatch/resume pins need (stochastic rounding would too, but
    only with key threading this recipe doesn't require)."""
    xs = x.astype(jnp.float32) * scale
    return jnp.clip(jnp.round(xs), -QMAX["int8"],
                    QMAX["int8"]).astype(jnp.int8)


def quantize_fp8(x: jax.Array, scale: jax.Array,
                 variant: str = "e4m3") -> jax.Array:
    """fp8 quantization: scale into the format's representable range,
    clip to the finite max (E4M3 has no inf — an unclipped overflow
    would land on NaN), and cast (round-to-nearest-even)."""
    dt = jnp.float8_e4m3fn if variant == "e4m3" else jnp.float8_e5m2
    qmax = QMAX[f"fp8_{variant}"]
    xs = jnp.clip(x.astype(jnp.float32) * scale, -qmax, qmax)
    return xs.astype(dt)


def quantize(x: jax.Array, scale: jax.Array, fmt: str) -> jax.Array:
    if fmt == "int8":
        return quantize_int8(x, scale)
    if fmt in ("fp8", "fp8_e4m3"):
        return quantize_fp8(x, scale, "e4m3")
    if fmt == "fp8_e5m2":
        return quantize_fp8(x, scale, "e5m2")
    raise ValueError(f"unknown quant format {fmt!r}; have int8/fp8"
                     f"/fp8_e4m3/fp8_e5m2")


def dequantize(q: jax.Array, scale: jax.Array,
               dtype=jnp.float32) -> jax.Array:
    """x ≈ q / scale.  The inverse is multiplied in fp32 and cast once —
    the same one-rounding discipline as ops/dropout.py's keep factors."""
    return (q.astype(jnp.float32) * (1.0 / scale)).astype(dtype)


# -- the quantized GEMM ---------------------------------------------------

def _acc_dtype(fmt: str):
    # int8 pairs accumulate exactly in int32 (the MXU's s8xs8->s32 path;
    # float accumulation would round past 2^24); fp8 accumulates fp32
    return jnp.int32 if fmt == "int8" else jnp.float32


def _dot_q(xq: jax.Array, wq: jax.Array, fmt: str) -> jax.Array:
    """The quantized-operand contraction, fp32 result (pre-descale).
    int8: s8 x s8 -> s32 exactly.  fp8: operands upcast to fp32 for the
    XLA path — every fp8 value is exactly representable in fp32, so this
    IS "fp8 operands, fp32 accumulation" math; on hardware with native
    fp8 MXU paths XLA may lower the fused cast+dot directly."""
    if fmt == "int8":
        acc = lax.dot_general(xq, wq, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
        return acc.astype(jnp.float32)
    return lax.dot_general(xq.astype(jnp.float32), wq.astype(jnp.float32),
                           (((1,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)


def quant_dot_reference(xq: jax.Array, wq: jax.Array, sx: jax.Array,
                        sw: jax.Array, fmt: str, out_dtype) -> jax.Array:
    """XLA-reference quantized GEMM on ALREADY-QUANTIZED operands:
    out = (xq · wq) / (sx*sw), accumulated per _dot_q, descaled in fp32,
    one final cast.  This is both the off-TPU/fallback compute path and
    the oracle the Pallas kernel is pinned against."""
    acc = _dot_q(xq, wq, fmt)
    inv = 1.0 / (sx.astype(jnp.float32) * sw.astype(jnp.float32))
    return (acc * inv).astype(out_dtype)


# Static VMEM budget for the Pallas kernel's resident set, patterned on
# ops/fused_ffn.py: the quantized weight matrix stays VMEM-resident
# across the row-block grid; each block holds its quantized x rows, the
# accumulator tile and the fp32/output tile.
_QUANT_VMEM_BUDGET = 12 * 1024 * 1024


def _quant_vmem_bytes(k: int, n: int, block_rows: int) -> int:
    """Resident-set model at 1 byte/elem quantized operands: wq (k,n) +
    xq block (block,k) + int32/fp32 accumulator and out tiles
    (2 * block * n * 4)."""
    return k * n + block_rows * k + 2 * block_rows * n * 4


def quant_kernel_fits_vmem(k: int, n: int) -> bool:
    """Static go/no-go at the SMALLEST row tile — the check callers
    mirror before handing shapes to the kernel (the
    ffn_kernel_fits_vmem idiom)."""
    return _quant_vmem_bytes(k, n, 32) <= _QUANT_VMEM_BUDGET


def _quant_matmul_kernel(xq_ref, wq_ref, inv_ref, o_ref, *, fmt: str):
    if fmt == "int8":
        acc = lax.dot(xq_ref[...], wq_ref[...],
                      preferred_element_type=jnp.int32).astype(jnp.float32)
    else:
        acc = lax.dot(xq_ref[...].astype(jnp.float32),
                      wq_ref[...].astype(jnp.float32),
                      preferred_element_type=jnp.float32)
    o_ref[...] = (acc * inv_ref[0, 0]).astype(o_ref.dtype)


def quant_dot_pallas(xq: jax.Array, wq: jax.Array, sx: jax.Array,
                     sw: jax.Array, fmt: str, out_dtype,
                     block_rows: int = 256) -> jax.Array:
    """Tiled Pallas quantized GEMM: grid over row blocks of xq, wq
    VMEM-resident, per-block ``dot`` with int32/fp32 accumulation and
    one fused descale.  Falls back (warned) to the XLA reference when
    even the minimum row tile busts the VMEM budget.  Off-TPU the
    kernel runs in interpret mode — test-only; production off-TPU
    callers route to quant_dot_reference (quant_dot below does)."""
    m, k = xq.shape
    n = wq.shape[1]
    br = min(block_rows, max(m, 1))
    while br > 32 and _quant_vmem_bytes(k, n, br) > _QUANT_VMEM_BUDGET:
        br //= 2
    if pl is None or _quant_vmem_bytes(k, n, br) > _QUANT_VMEM_BUDGET:
        import warnings
        warnings.warn(
            f"quant matmul kernel resident set for K={k}, N={n} exceeds "
            f"the ~{_QUANT_VMEM_BUDGET >> 20} MiB VMEM budget even at "
            f"the minimum row tile; computing this GEMM with the XLA "
            f"reference path instead (same math)", stacklevel=2)
        return quant_dot_reference(xq, wq, sx, sw, fmt, out_dtype)
    nb = -(-m // br)
    pad = nb * br - m
    if pad:
        xq = jnp.pad(xq, ((0, pad), (0, 0)))
    inv = (1.0 / (sx.astype(jnp.float32)
                  * sw.astype(jnp.float32))).reshape(1, 1)
    out = pl.pallas_call(
        functools.partial(_quant_matmul_kernel, fmt=fmt),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((br, k), lambda i: (i, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nb * br, n), out_dtype),
        interpret=(jax.default_backend() != "tpu"),
    )(xq, wq, inv)
    return out[:m] if pad else out


# -- differentiable site op ----------------------------------------------
#
# quant_dot(x, w, sx, sw): quantize both operands at the given DELAYED
# scales, contract at low precision, descale.  custom_vjp residuals are
# the QUANTIZED tensors (the memory win); the backward dequantizes them
# and runs the two gradient GEMMs in the cotangent's dtype — the
# straight-through estimator through the rounding, so d/dx passes
# through quantize∘dequantize as identity (at the dequantized values).
# grad_fmt="fp8_e5m2" (r19, the FP8-LM completion) additionally
# quantizes the incoming COTANGENT to the wide-range E5M2 grid with
# just-in-time per-tensor scaling and runs BOTH gradient GEMMs on
# quantized operands — dW contracts the saved xq against gq directly
# (the quantized-dW path), dx contracts gq against the saved wq.

_GRAD_FMTS = (None, "fp8_e5m2")


def _jit_grad_scale(amax: jax.Array, fmt: str) -> jax.Array:
    """Just-in-time (current-tensor) scale for gradient quantization:
    gradients exist only inside the backward, where no carried history
    can be updated — so their scale comes from THIS tensor's amax (the
    deterministic "current scaling" variant of the delayed recipe; the
    forward operands keep their delayed history scales).  Same
    amax→scale formula as the forward (_scale_from_amax)."""
    return _scale_from_amax(amax, fmt)


def _dot_q_mixed(a: jax.Array, b: jax.Array, dims) -> jax.Array:
    """Quantized-operand contraction with arbitrary dims, fp32 result.
    int8 x int8 pairs take the exact s8xs8->s32 path; any fp8 operand
    (every fp8/int8 value is exactly representable in fp32) upcasts."""
    if a.dtype == jnp.int8 and b.dtype == jnp.int8:
        return lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.int32
                               ).astype(jnp.float32)
    return lax.dot_general(a.astype(jnp.float32), b.astype(jnp.float32),
                           (dims, ((), ())),
                           preferred_element_type=jnp.float32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _quant_dot_core(x, w, sx, sw, fmt: str, use_pallas: bool,
                    grad_fmt: Optional[str], grad_axes: tuple):
    xq = quantize(x, sx, fmt)
    wq = quantize(w, sw, fmt)
    if use_pallas:
        return quant_dot_pallas(xq, wq, sx, sw, fmt, x.dtype)
    return quant_dot_reference(xq, wq, sx, sw, fmt, x.dtype)


def _quant_dot_fwd(x, w, sx, sw, fmt, use_pallas, grad_fmt, grad_axes):
    # quantize ONCE: the same arrays feed the GEMM and become the
    # residuals (1 byte/elem instead of 2/4, the quantized-training
    # residual-memory win) — no reliance on CSE to dedupe a second
    # quantize subgraph
    xq = quantize(x, sx, fmt)
    wq = quantize(w, sw, fmt)
    dot = quant_dot_pallas if use_pallas else quant_dot_reference
    return dot(xq, wq, sx, sw, fmt, x.dtype), (xq, wq, sx, sw)


def _quant_dot_bwd(fmt, use_pallas, grad_fmt, grad_axes, res, g):
    xq, wq, sx, sw = res
    if grad_fmt is not None:
        # fp8-E5M2 gradient quantization + quantized dW/dx path: the
        # cotangent rides the wide-range grid (E5M2 keeps inf/nan and
        # tops at 57344 — the variant the fp8 literature reserves for
        # gradients) at a just-in-time per-tensor scale, and both
        # gradient GEMMs contract quantized operands with fp32
        # accumulation.  grad_axes: mesh axes this op runs sharded over
        # (parallel/kernel_shard.py) — the amax is pmax'd over them so
        # the per-TENSOR scale stays placement-invariant.
        amax_g = tensor_amax(g)
        for ax in grad_axes:
            amax_g = lax.pmax(amax_g, ax)
        sg = _jit_grad_scale(amax_g, grad_fmt)
        gq = quantize(g, sg, grad_fmt)
        dx = (_dot_q_mixed(gq, wq, ((1,), (1,)))
              * (1.0 / (sg * sw.astype(jnp.float32)))).astype(g.dtype)
        dw = (_dot_q_mixed(xq, gq, ((0,), (0,)))
              * (1.0 / (sx.astype(jnp.float32) * sg))).astype(g.dtype)
        return dx, dw, jnp.zeros_like(sx), jnp.zeros_like(sw)
    x_deq = dequantize(xq, sx, g.dtype)
    w_deq = dequantize(wq, sw, g.dtype)
    # gradient GEMMs in the compute dtype with fp32 accumulation (the
    # "fwd quantized / bwd high precision" recipe; --quant_grad
    # fp8_e5m2 selects the quantized-gradient branch above)
    dx = lax.dot_general(g, w_deq, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32
                         ).astype(x_deq.dtype)
    dw = lax.dot_general(x_deq, g, (((0,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32
                         ).astype(w_deq.dtype)
    # scales are bookkeeping inputs, not optimization variables
    return dx, dw, jnp.zeros_like(sx), jnp.zeros_like(sw)


_quant_dot_core.defvjp(_quant_dot_fwd, _quant_dot_bwd)


def quant_dot(x: jax.Array, w: jax.Array, sx: jax.Array, sw: jax.Array,
              fmt: str, use_pallas: Optional[bool] = None,
              grad_fmt: Optional[str] = None,
              grad_axes: tuple = ()) -> jax.Array:
    """out[m,n] = dequant(quant(x) · quant(w)) with fp32/int32
    accumulation.  x: (M, K); w: (K, N); sx/sw: fp32 scalar DELAYED
    scales (ops.quant.scale_from_history).  use_pallas None = auto
    (TPU and the shape fits VMEM); the caller may force False (the
    registered warned fallbacks, cli.build_model) — tp meshes route the
    kernel per-shard through parallel/kernel_shard.py instead.
    grad_fmt "fp8_e5m2" quantizes the backward's cotangent (JIT-scaled)
    and contracts the gradient GEMMs on quantized operands; grad_axes
    names the mesh axes a sharded caller runs under (amax pmax)."""
    if fmt not in _FMTS:
        raise ValueError(f"quant_dot fmt must be one of {_FMTS}, "
                         f"got {fmt!r}")
    if grad_fmt not in _GRAD_FMTS:
        raise ValueError(f"quant_dot grad_fmt must be one of "
                         f"{_GRAD_FMTS}, got {grad_fmt!r}")
    if use_pallas is None:
        use_pallas = (jax.default_backend() == "tpu"
                      and quant_kernel_fits_vmem(x.shape[-1], w.shape[-1]))
    return _quant_dot_core(x, w, jnp.asarray(sx, jnp.float32),
                           jnp.asarray(sw, jnp.float32), fmt,
                           bool(use_pallas), grad_fmt, tuple(grad_axes))


# -- flax site modules ----------------------------------------------------

try:
    from flax import linen as nn

    class QuantDense(nn.Module):
        """Drop-in ``nn.Dense`` with int8/fp8 forward GEMM and delayed
        per-tensor scaling.

        The param tree ("kernel", "bias", same shapes/init) is
        IDENTICAL to nn.Dense so checkpoints interchange between the
        quantized and full-precision models (the _FFNParamMirror
        contract).  The scale state — one amax history per operand —
        lives in the ``batch_stats`` collection: the existing cross-step
        statistics channel already threaded through the train step's
        mutable call, the r8 fused-dispatch carry, checkpoints and the
        kill-at-N bitwise resume, so quantized state inherits every one
        of those contracts with no new plumbing.  When ``batch_stats``
        is immutable (eval), scales come from the stored history and
        nothing updates.

        ``features`` may be an int (Dense) or a tuple (DenseGeneral
        over the last input axis — the fused qkv projection's
        (3, h, d_k)); the GEMM itself is always the flattened 2D
        contraction, which is what the Pallas kernel serves.

        ``frozen_scales`` is the INFERENCE mode (the serve/ subsystem's
        contract): scales come from the RESTORED amax history and the
        history is never rolled — even when the caller passes
        ``batch_stats`` as mutable.  Serving N requests is then
        state-free, the per-request amax reduction disappears from the
        forward, and two identical requests return bitwise-identical
        logits regardless of what was served between them (pinned by
        tests/test_serve.py).  Training keeps the default (False):
        delayed scaling NEEDS the roll.
        """
        features: object            # int or tuple (DenseGeneral-style)
        fmt: str = "int8"
        amax_history_len: int = 16
        margin: float = 1.0
        use_pallas: Optional[bool] = None   # None = auto; False = the
                                            # registered warned fallback
        frozen_scales: bool = False         # True = inference: restored
                                            # amax history used, never
                                            # rolled (serve/engine.py)
        mesh: Optional[object] = None       # tp mesh: the GEMM runs
                                            # per-shard via the r19
                                            # shard_map kernel layer
        tp_dim: Optional[int] = None        # kernel dim sharded on tp
                                            # (0 = Megatron row-parallel,
                                            # >0 = column-parallel); None
                                            # = never shard this site
        grad_fmt: Optional[str] = None      # "fp8_e5m2": quantized
                                            # gradients + dW (quant_dot)
        kernel_init: object = nn.initializers.lecun_normal()
        bias_init: object = nn.initializers.zeros
        dtype: object = jnp.float32
        param_dtype: object = jnp.float32
        amax_cadence: object = None         # parallel.pipeline
                                            # .PipelineTickCtx (r23): on
                                            # a pp>1 mesh this site is
                                            # invoked once per pipeline
                                            # tick — the cadence keeps
                                            # delayed scaling at ONE
                                            # roll per optimizer step
                                            # (scales from the pre-step
                                            # history, pushes max-
                                            # reduced over the real
                                            # microbatches) so the
                                            # scale state matches pp=1
                                            # bitwise.  None (pp=1) =
                                            # the plain roll below

        @nn.compact
        def __call__(self, x: jax.Array) -> jax.Array:
            feats = (self.features if isinstance(self.features, tuple)
                     else (self.features,))
            d_in = x.shape[-1]
            n_out = int(np.prod(feats))
            kernel = self.param("kernel", self.kernel_init,
                                (d_in, *feats), self.param_dtype)
            bias = self.param("bias", self.bias_init, feats,
                              self.param_dtype)
            hist_x = self.variable("batch_stats", "amax_history_x",
                                   fresh_amax_history,
                                   self.amax_history_len)
            hist_w = self.variable("batch_stats", "amax_history_w",
                                   fresh_amax_history,
                                   self.amax_history_len)
            xc = x.astype(self.dtype)
            w2d = kernel.astype(self.dtype).reshape(d_in, n_out)
            lead = xc.shape[:-1]
            x2d = xc.reshape(-1, d_in)
            if not quant_enabled():
                # FDT_QUANT=0: the plain full-precision matmul, scale
                # state untouched (the A/B kill-switch arm)
                out = jnp.dot(x2d, w2d,
                              preferred_element_type=jnp.float32)
            else:
                # delayed scaling: this step QUANTIZES at the scale the
                # history implied BEFORE this step, then records this
                # step's amax for the next one — named for the XLA
                # trace so profiles show the refresh cost under one
                # vocabulary with the telemetry spans
                with jax.named_scope("fdt/quant_scale_refresh"):
                    cad = self.amax_cadence
                    if cad is not None:
                        # pipeline tick cadence: EVERY tick quantizes at
                        # the scales the pre-step history implies (the
                        # same scales pp=1 uses all step), and the
                        # history rolls once — the first real push
                        # rolls, later pushes max-reduce into slot 0,
                        # bubble ticks are skipped entirely (their
                        # recycled data could exceed the true batch
                        # amax).  End-of-step hist == pp=1's bitwise.
                        site = "/".join(str(p) for p in self.scope.path)
                        hx0 = cad.amax_pre(site + ":x", hist_x.value)
                        hw0 = cad.amax_pre(site + ":w", hist_w.value)
                        sx = scale_from_history(hx0, self.fmt,
                                                self.margin)
                        sw = scale_from_history(hw0, self.fmt,
                                                self.margin)
                        if (not self.frozen_scales
                                and self.is_mutable_collection(
                                    "batch_stats")):
                            hist_x.value = cad.amax_push(
                                site + ":x", hist_x.value,
                                tensor_amax(x2d))
                            hist_w.value = cad.amax_push(
                                site + ":w", hist_w.value,
                                tensor_amax(w2d))
                    else:
                        sx = scale_from_history(hist_x.value, self.fmt,
                                                self.margin)
                        sw = scale_from_history(hist_w.value, self.fmt,
                                                self.margin)
                        if (not self.frozen_scales
                                and self.is_mutable_collection(
                                    "batch_stats")):
                            hist_x.value = update_amax_history(
                                hist_x.value, tensor_amax(x2d))
                            hist_w.value = update_amax_history(
                                hist_w.value, tensor_amax(w2d))
                from faster_distributed_training_tpu.parallel import (
                    kernel_shard)
                if kernel_shard.quant_tp_routed(self.mesh, self.tp_dim,
                                                np.shape(kernel),
                                                self.use_pallas):
                    # r19 shard_map layer: the quant GEMM runs per-shard
                    # on the Megatron column/row tile this site's TP
                    # rule implies — the Pallas kernel partitions over
                    # tp instead of falling back to the XLA reference
                    out = kernel_shard.quant_dense_sharded(
                        x2d, kernel.astype(self.dtype), sx, sw, self.fmt,
                        self.mesh, self.tp_dim, grad_fmt=self.grad_fmt
                    ).astype(jnp.float32)
                else:
                    # the registered warned fallback: a tp mesh whose
                    # site can't route through the shard_map layer
                    # (kill switch / non-dividing shape / no tp_dim)
                    # must never hand a logically-global array to the
                    # Pallas kernel — the XLA reference dot partitions
                    # like any other dot
                    from faster_distributed_training_tpu.parallel.mesh \
                        import tp_size as _tp
                    up = False if _tp(self.mesh) > 1 else self.use_pallas
                    out = quant_dot(x2d, w2d, sx, sw, self.fmt,
                                    up, grad_fmt=self.grad_fmt
                                    ).astype(jnp.float32)
            out = out + bias.astype(jnp.float32).reshape(1, n_out)
            return out.astype(self.dtype).reshape(*lead, *feats)

except ImportError:  # pragma: no cover
    pass
