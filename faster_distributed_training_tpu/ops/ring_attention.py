"""Ring attention: sequence/context parallelism over an `sp` mesh axis.

The reference caps sequence length at 512 and computes O(L²) dense
attention on one device (transformer.py:35,180-193).  Here the sequence
dimension is sharded over the mesh's `sp` axis and K/V shards rotate
around the ring with `lax.ppermute` while each device accumulates
online-softmax statistics for its resident Q shard — attention memory
per device is O(L·L/sp) and the K/V transfers ride the ICI ring,
overlapping with the block computation.  This is the blockwise/ring
attention construction of Liu et al. (Ring Attention with Blockwise
Transformers), built from the same `online_block_update` primitive as
ops/attention.py so the math provably matches dense attention.

Gradients flow through `ppermute` (its transpose is the reverse
rotation), so the backward pass is ring-parallel too; the scan body is
`jax.checkpoint`-ed, keeping residual memory at one K/V shard per step.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from faster_distributed_training_tpu.ops.attention import (
    NEG_INF, bh_index, dropout_keep, finalize, init_carry, mask_to_bias,
    online_block_update)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str,
                   key_bias: Optional[jax.Array] = None,
                   causal: bool = False,
                   dropout_rate: float = 0.0,
                   dropout_seed: Optional[jax.Array] = None,
                   dropout_bh: Optional[jax.Array] = None) -> jax.Array:
    """Ring attention body — call INSIDE shard_map, sequence sharded on
    `axis_name`.

    q/k/v: [B, H, L_local, D] (this device's sequence shard),
    key_bias: [B, L_local] additive key bias (0 keep / NEG_INF drop) for
    this shard's keys, or None.  Returns [B, H, L_local, D].

    dropout_rate > 0 applies attention-prob dropout via the index hash
    (ops.attention.dropout_keep) with GLOBAL (stream, q, k) coordinates
    — sequence positions are already global here (idx/src · L + pos) and
    `dropout_bh` carries the caller's global batch·head index — so the
    pattern equals the dense/flash one for the same seed regardless of
    sp placement.
    """
    B, H, L, D = q.shape
    from faster_distributed_training_tpu.compat import axis_size
    sp = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(D)
    perm = [(j, (j + 1) % sp) for j in range(sp)]
    if key_bias is None:
        key_bias = jnp.zeros((B, L), jnp.float32)

    pos = jnp.arange(L, dtype=jnp.int32)
    if dropout_bh is None:
        dropout_bh = bh_index(B, H)
    seed = (jnp.uint32(0) if dropout_seed is None
            else dropout_seed.astype(jnp.uint32))

    @jax.checkpoint
    def body(carry, _):
        k_cur, v_cur, b_cur, src, m, l, acc = carry
        bias = b_cur[:, None, None, :]                    # [B,1,1,L]
        q_pos = idx * L + pos                             # global positions
        k_pos = src * L + pos
        if causal:
            bias = bias + jnp.where(k_pos[None, :] <= q_pos[:, None],
                                    0.0, NEG_INF)[None, None]
        keep = None
        if dropout_rate > 0.0:
            keep = dropout_keep(seed, dropout_bh,
                                q_pos[None, None, :, None],
                                k_pos[None, None, None, :], dropout_rate)
        m, l, acc = online_block_update(q, k_cur, v_cur, bias, m, l, acc,
                                        scale, keep_blk=keep)
        # rotate the K/V shard to the next rank; XLA overlaps the ICI
        # transfer with the next step's matmuls where possible
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        b_cur = lax.ppermute(b_cur, axis_name, perm)
        return (k_cur, v_cur, b_cur, (src - 1) % sp, m, l, acc), None

    # init_carry derives the accumulators from q, giving them q's full
    # varying-manual-axes set (dp AND sp) so the scan carry types stay
    # stable under shard_map's VMA checking
    m0, l0, acc0 = init_carry(q)
    # l0 is a q-derived zeros tensor; adding its [B, L] slice stamps q's
    # VMA set onto the bias without changing its values
    carry0 = (k, v, key_bias.astype(jnp.float32) + l0[:, 0, :],
              idx, m0, l0, acc0)
    (_, _, _, _, m, l, acc), _ = lax.scan(body, carry0, None, length=sp)
    return finalize(m, l, acc, q.dtype)


def _ring_body(q, k, v, axis_name, key_mask=None, causal=False,
               dropout_rate=0.0, dropout_seed=None, dropout_bh=None):
    """sequence_parallel.sp_self_attention body shim: per-shard keep-mask
    -> additive bias (elementwise, so per-shard == global conversion)."""
    key_bias = None if key_mask is None else mask_to_bias(key_mask)
    return ring_attention(q, k, v, axis_name, key_bias=key_bias,
                          causal=causal, dropout_rate=dropout_rate,
                          dropout_seed=dropout_seed, dropout_bh=dropout_bh)


def ring_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        mask: Optional[jax.Array], mesh: Mesh,
                        sp_axis: str = "sp",
                        causal: bool = False,
                        dropout_rate: float = 0.0,
                        dropout_seed: Optional[jax.Array] = None
                        ) -> jax.Array:
    """shard_map wrapper: globally-shaped [B,H,L,D] in and out, with L
    sharded over `sp_axis`, B over the data axes, heads over tp when
    divisible (shared scaffolding: ops/sequence_parallel.py).

    mask: None, [B, L], or [B,1,1,L] key-padding mask (mask==0 masked)."""
    from faster_distributed_training_tpu.ops.sequence_parallel import (
        sp_self_attention)

    return sp_self_attention(_ring_body, q, k, v, mask, mesh,
                             sp_axis=sp_axis, causal=causal,
                             dropout_rate=dropout_rate,
                             dropout_seed=dropout_seed)
