"""Flash attention: Pallas TPU forward kernel + recompute backward.

TPU-first replacement for the reference's dense ScaledDotProduct
(transformer.py:180-193).  Design:

  * forward — a Pallas kernel tiled (batch·head, query-block) with K/V
    resident in VMEM: one MXU matmul for scores, row-softmax in fp32,
    one MXU matmul for the context.  Probabilities never touch HBM.
    Attention-prob dropout (training) is an in-kernel index-hash mask
    (ops.attention.dropout_keep) — still no HBM probabilities.
  * backward — On TPU the default inside the monolithic envelope is
    now the SAVED-STATS Pallas kernel pair (r6, VERDICT r5 #3 — the
    L=512 retune): the forward emits the row lse beside the context,
    and the backward rebuilds exactly-normalized probabilities as
    p = exp(s - lse) with delta = Σ dO·out precomputed in XLA from the
    saved primal out — deleting the out-recompute matmul and both
    softmax row sweeps per q-block (5 MXU passes instead of 6) and
    admitting a one-step-larger backward q-tile (_bwd_block_q_stats).
    Residuals grow by lse ([N, Lq] fp32) and out (alive anyway).
    FDT_FLASH_SAVE_STATS=0 restores the r5 recompute-in-backward
    kernel (residuals just (q, k, v, mask, seed); softmax stats
    recomputed per q-block — measured faster than BOTH XLA-derived
    VJPs at every size tried on v5e: L=512 B=64: 6.9 vs 10.2 ms
    dense-VJP; L=2048 B=4: 9.0 vs 11.3/14.3).  Kill-switch
    FDT_DISABLE_PALLAS_BWD=1 restores the measured two-branch VJP
    policy (dense under a ~2 GB score budget — overridable via
    FDT_DENSE_BWD_BUDGET_MB — blockwise scan beyond), which is also
    the off-TPU path.  The monolithic kernels' padding-mask bias is no
    longer H-repeated in XLA: it stays [B, Lk] and heads share their
    batch row through the bias index map (_bias_operand).
  * long context — beyond the monolithic kernels' measured VMEM
    envelope (Lk·D > ~8k·64 fwd / ~4k·64 bwd) the K-BLOCKED
    FlashAttention-2-style kernels take over: grid over (q-tile,
    k-tile) with running softmax stats in VMEM scratch, forward emits
    the row lse, backward = two kernels (dq over the q-grid, dk/dv
    over the k-grid) driven by the saved (out, lse) — O(tile) VMEM,
    NO Lk cap, residuals stay O(L·D).
  * non-TPU backends (tests, CPU sim) use the blockwise path; set
    FDT_FORCE_PALLAS_INTERPRET=1 to exercise both kernels in
    interpreter mode on CPU.

Head-dim support set (VERDICT r3 #7): the K-blocked kernels require
``D <= 128 or D % 128 == 0`` (`_kblocked_supported` — the running-stat
lane broadcast needs a whole number of 128-lane repeats).  A model
whose head dim violates that (e.g. D=192) AND whose Lk·D exceeds the
monolithic envelope routes to the XLA blockwise formulation — slower
but functionally identical; pinned by `tests/test_attention.py::
TestKernelEnvelopeRouting::test_unsupported_head_dim_routes_to_blockwise`.
Odd
head dims inside the monolithic envelope run the monolithic kernels
as usual (Mosaic pads lanes).

Numerics note (ADVICE r3 #3): under autodiff, when the MONOLITHIC
backward is out of envelope (Lk·D/64 in (4096, 8192]) the forward is
computed by the K-BLOCKED kernel so its lse becomes a residual —
while the same-shape primal-only forward takes the monolithic kernel.
Both are exact streaming softmax, but the accumulation order differs,
so grad-traced vs inference outputs at those shapes diverge by normal
float rounding (~1e-3 bf16 / ~1e-6 fp32).  Intentional trade: saving
the lse avoids any full-row recompute in the backward.

Per-head K/V for supported workloads fits VMEM comfortably (e.g.
L=512, D=64, fp32 → 128 KiB per tensor of the ~16 MiB budget); longer
sequences shard L over the `sp` mesh axis first (ops/ring_attention.py),
so each shard stays VMEM-sized.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp

from faster_distributed_training_tpu.ops.attention import (
    NEG_INF, blockwise_attention, dense_attention_reference, mask_to_bias)


def _use_pallas() -> bool:
    if os.environ.get("FDT_FORCE_PALLAS_INTERPRET") == "1":
        return True
    return jax.default_backend() == "tpu"


def _pack_seed(dropout_seed, bh0=None) -> jax.Array:
    """(3,) uint32 dropout operand [seed, b0, h0]: the hash seed plus
    the caller's GLOBAL (batch, head) shard offsets.  Head-sharded
    callers (parallel/kernel_shard.py) pass their shard origin as
    ``bh0``; unsharded callers leave it (0, 0), which — together with
    h_glob == local H — makes the in-kernel global index reduce to the
    plain flattened b*H+h bit-for-bit (nothing changes for 1D runs)."""
    seed = (jnp.uint32(0) if dropout_seed is None
            else jnp.asarray(dropout_seed, jnp.uint32))
    if bh0 is None:
        b0 = h0 = jnp.uint32(0)
    else:
        b0 = jnp.asarray(bh0[0], jnp.uint32)
        h0 = jnp.asarray(bh0[1], jnp.uint32)
    return jnp.stack([seed.reshape(()), b0.reshape(()), h0.reshape(())])


def _bh_from(s_ref, n, h_loc: int, h_glob: int):
    """GLOBAL batch*head dropout stream index for local flattened
    instance ``n`` inside a kernel: (b0 + n//h_loc)*h_glob + h0 +
    n%h_loc, with (b0, h0) read from the packed seed operand.  The
    global index keeps the hash-dropout masks placement-invariant when
    the heads are sharded over tp (kernel_shard.flash_attention_sharded)
    — the same contract ops/fused_ffn.py keeps for sharded rows."""
    b0 = s_ref[0, 1].astype(jnp.int32)
    h0 = s_ref[0, 2].astype(jnp.int32)
    return (b0 + n // h_loc) * h_glob + h0 + n % h_loc


def _bh_array(B: int, H: int, seed3: jax.Array, h_glob: int) -> jax.Array:
    """[B,H,1,1] global stream indices — the XLA-path twin of _bh_from
    (blockwise/dense fallbacks take the whole index array at once)."""
    b0 = seed3[1].astype(jnp.int32)
    h0 = seed3[2].astype(jnp.int32)
    return ((b0 + jnp.arange(B, dtype=jnp.int32))[:, None] * h_glob
            + h0 + jnp.arange(H, dtype=jnp.int32)[None, :])[:, :, None, None]


def _bias_operand(key_bias, n_heads: int, lk: int):
    """(bias operand, index_map, has_bias) for the MONOLITHIC kernels.

    The bias stays [B, 1, Lk] and every head reads its batch row through
    the grid index map (n // H) — fusing the mask path into the kernel's
    addressing instead of materializing the H-repeated [B·H, Lk] copy
    the r5 kernels built in XLA per call (the repeat was pure HBM
    traffic + a fusion barrier before the kernel).  key_bias=None keeps
    a single shared zeros row (same block every step — the pipeline
    never re-fetches it) and has_bias=False lets the kernel skip the add
    entirely."""
    if key_bias is None:
        return (jnp.zeros((1, 1, lk), jnp.float32),
                (lambda *idx: (0, 0, 0)), False)
    b = key_bias.astype(jnp.float32)
    b = b.reshape(b.shape[0], 1, lk)
    return b, (lambda n, *idx: (n // n_heads, 0, 0)), True


def _flash_fwd_pallas(q: jax.Array, k: jax.Array, v: jax.Array,
                      key_bias: Optional[jax.Array], n_heads: int,
                      block_q: int, dropout_rate: float = 0.0,
                      seed3: Optional[jax.Array] = None,
                      emit_lse: bool = False,
                      h_glob: Optional[int] = None):
    """q/k/v [N, L, D] (N = B·H), key_bias [B, Lk] additive or None
    (heads share their batch row via the bias index map — no H-repeat).

    dropout_rate > 0 applies ops.attention.dropout_keep in-kernel: the
    keep mask is a pure hash of (seed, GLOBAL bh, global q row, k col)
    — seed3 is the _pack_seed [seed, b0, h0] operand and h_glob the
    global head count, so head-sharded shards regenerate the exact
    single-device mask — and the recompute backward regenerates it
    exactly without any HBM mask.

    emit_lse=True additionally returns the row lse [N, Lq] fp32 (stored
    at _KB_LANES lanes like the K-blocked kernels, sliced outside) so
    the saved-stats monolithic backward can skip the in-kernel softmax
    recompute — the L=512 retune (VERDICT r5 #3)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    from faster_distributed_training_tpu.ops.attention import dropout_keep

    N, Lq, D = q.shape
    Lk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    block_q = min(block_q, Lq)
    nq = -(-Lq // block_q)
    pad_q = nq * block_q - Lq
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0)))
    bias, bias_map, has_bias = _bias_operand(key_bias, n_heads, Lk)
    seed = (seed3 if seed3 is not None
            else _pack_seed(None)).reshape(1, 3).astype(jnp.uint32)
    hg = h_glob if h_glob is not None else n_heads

    def kernel(q_ref, k_ref, v_ref, b_ref, s_ref, o_ref, *lse_ref):
        qb = q_ref[0]                                   # [block_q, D]
        s = jax.lax.dot_general(
            qb, k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [block_q, Lk]
        if has_bias:
            s = s + b_ref[0]
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
        if dropout_rate > 0.0:
            bh = _bh_from(s_ref, pl.program_id(0), n_heads, hg)
            qrow = (pl.program_id(1) * block_q
                    + jax.lax.broadcasted_iota(jnp.int32, (block_q, Lk), 0))
            kcol = jax.lax.broadcasted_iota(jnp.int32, (block_q, Lk), 1)
            p = p * dropout_keep(s_ref[0, 0], bh, qrow, kcol, dropout_rate)
        ctx = jnp.dot(p.astype(v_ref.dtype), v_ref[0],
                      preferred_element_type=jnp.float32)
        o_ref[0] = (ctx / l).astype(o_ref.dtype)
        if emit_lse:
            lse_ref[0][0] = jnp.broadcast_to(m + jnp.log(l),
                                             (block_q, _KB_LANES))

    out_specs = [pl.BlockSpec((1, block_q, D), lambda n, i: (n, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((N, nq * block_q, D), q.dtype)]
    if emit_lse:
        out_specs.append(
            pl.BlockSpec((1, block_q, _KB_LANES), lambda n, i: (n, i, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((N, nq * block_q, _KB_LANES), jnp.float32))
    res = pl.pallas_call(
        kernel,
        grid=(N, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda n, i: (n, i, 0)),
            pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
            pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
            pl.BlockSpec((1, 1, Lk), bias_map),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=(jax.default_backend() != "tpu"),
    )(q, k, v, bias, seed)
    if emit_lse:
        return res[0][:, :Lq, :], res[1][:, :Lq, 0]
    return res[0][:, :Lq, :]


# ---------------------------------------------------------------------------
# K-blocked (FlashAttention-2-style) kernels — O(tile) VMEM, no Lk cap.
# The monolithic kernels above stay the default inside their measured
# envelope (they were faster at every size tried); these take over beyond
# it, replacing the old fall-off-the-cliff route to the XLA blockwise VJP
# (r2 ladder: 21.4 ms -> 78.8 ms at L=8192).  Running softmax statistics
# are carried in VMEM scratch at 128 lanes (the Mosaic minimum tile; the
# same layout the official jax.experimental TPU kernel uses), all lanes
# holding the same per-row value.  The forward also emits the row LSE so
# the backward kernels need no full-row recompute: residuals become
# (q, k, v, bias, seed, out, lse) — still O(L·D), never O(L²).
# ---------------------------------------------------------------------------

_KB_LANES = 128  # lse/delta/m/l lane width (Mosaic min tile)


def _kb_blocks(lq: int, lk: int):
    """(block_q, block_k) tiles: up to 512 square, degraded to the padded
    problem size; block_k a multiple of 128 (lane tiling), block_q a
    multiple of 8 (sublane tiling)."""
    bq = min(512, max(-(-lq // 8) * 8, 8))
    bk = min(512, max(-(-lk // _KB_LANES) * _KB_LANES, _KB_LANES))
    return bq, bk


def _kblocked_supported(d: int) -> bool:
    # the lane-broadcast of l to the accumulator needs D <= 128 or a
    # whole number of 128-lane repeats
    return d <= _KB_LANES or d % _KB_LANES == 0


def _lanes_to(x128, d: int):
    """[rows, 128] all-equal-lanes -> [rows, d]."""
    if d <= _KB_LANES:
        return x128[:, :d]
    return jnp.tile(x128, (1, d // _KB_LANES))


def _kb_pad(q, k, v, key_bias, bq, bk):
    """Pad q to bq multiples and k/v/bias to bk multiples (bias pads with
    NEG_INF so padded keys carry ~zero probability)."""
    N, Lq, D = q.shape
    Lk = k.shape[1]
    nq, nk = -(-Lq // bq), -(-Lk // bk)
    pad_q, pad_k = nq * bq - Lq, nk * bk - Lk
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0)))
    if key_bias is None:
        key_bias = jnp.zeros((N, Lk), jnp.float32)
    key_bias = key_bias.astype(jnp.float32)
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0)))
        key_bias = jnp.pad(key_bias, ((0, 0), (0, pad_k)),
                           constant_values=NEG_INF)
    return q, k, v, key_bias.reshape(N, 1, nk * bk), nq, nk


def _flash_fwd_kblocked(q: jax.Array, k: jax.Array, v: jax.Array,
                        key_bias, dropout_rate: float = 0.0,
                        seed3=None, n_heads: int = 1,
                        h_glob: Optional[int] = None):
    """q/k/v [N, L, D] (N = B·H).  Returns (out [N, Lq, D],
    lse [N, Lq] fp32).  Grid (N, q-block, k-block), k innermost;
    running (m, l, acc) in VMEM scratch; out and lse written on the
    last k step.  l accumulates PRE-dropout probability mass (softmax-
    then-dropout semantics, transformer.py:190-192), dropout applies to
    the value contraction only — matching every other impl.  seed3 /
    n_heads / h_glob: the _pack_seed global-bh dropout convention."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from faster_distributed_training_tpu.ops.attention import dropout_keep

    N, Lq, D = q.shape
    scale = 1.0 / math.sqrt(D)
    bq, bk = _kb_blocks(Lq, k.shape[1])
    q, k, v, bias, nq, nk = _kb_pad(q, k, v, key_bias, bq, bk)
    seed = (seed3 if seed3 is not None
            else _pack_seed(None)).reshape(1, 3).astype(jnp.uint32)
    hg = h_glob if h_glob is not None else n_heads
    kreps = bk // _KB_LANES

    def kernel(q_ref, k_ref, v_ref, b_ref, s_ref, o_ref, lse_ref,
               m_scr, l_scr, acc_scr):
        i, j = pl.program_id(1), pl.program_id(2)

        @pl.when(j == 0)
        def _init():
            m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
            l_scr[...] = jnp.zeros_like(l_scr)
            acc_scr[...] = jnp.zeros_like(acc_scr)

        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [bq, bk]
        s = s + b_ref[0]
        m_prev, l_prev = m_scr[...], l_scr[...]             # [bq, 128]
        m_curr = jnp.max(s, axis=-1, keepdims=True)         # [bq, 1]
        m_next = jnp.maximum(m_prev, m_curr)                # [bq, 128]
        p = jnp.exp(s - jnp.tile(m_next, (1, kreps)))
        alpha = jnp.exp(m_prev - m_next)
        l_next = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate > 0.0:
            bh = _bh_from(s_ref, pl.program_id(0), n_heads, hg)
            qrow = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kcol = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            p = p * dropout_keep(s_ref[0, 0], bh, qrow, kcol, dropout_rate)
        acc_scr[...] = (acc_scr[...] * _lanes_to(alpha, D)
                        + jnp.dot(p.astype(v_ref.dtype), v_ref[0],
                                  preferred_element_type=jnp.float32))
        m_scr[...], l_scr[...] = m_next, l_next

        @pl.when(j == nk - 1)
        def _fin():
            l = jnp.maximum(l_scr[...], 1e-30)
            o_ref[0] = (acc_scr[...] / _lanes_to(l, D)).astype(o_ref.dtype)
            lse_ref[0] = m_scr[...] + jnp.log(l)

    out, lse = pl.pallas_call(
        kernel,
        grid=(N, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda n, i, j: (n, i, 0)),
            pl.BlockSpec((1, bk, D), lambda n, i, j: (n, j, 0)),
            pl.BlockSpec((1, bk, D), lambda n, i, j: (n, j, 0)),
            pl.BlockSpec((1, 1, bk), lambda n, i, j: (n, 0, j)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda n, i, j: (n, i, 0)),
            pl.BlockSpec((1, bq, _KB_LANES), lambda n, i, j: (n, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, nq * bq, D), q.dtype),
            jax.ShapeDtypeStruct((N, nq * bq, _KB_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _KB_LANES), jnp.float32),
            pltpu.VMEM((bq, _KB_LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=(jax.default_backend() != "tpu"),
    )(q, k, v, bias, seed)
    return out[:, :Lq], lse[:, :Lq, 0]


def _flash_bwd_kblocked(q, k, v, key_bias, seed3, dropout_rate,
                        out, lse, h_glob: Optional[int] = None):
    """FA-2-style backward: two k-blocked kernels (dq over the q-grid,
    dk/dv over the k-grid), both O(tile) VMEM — no Lk cap.  Uses the
    forward-saved lse, so probabilities come back exactly normalized
    (p/l = exp(s - lse)) with no in-kernel row sweep; delta = Σ dO·out
    is precomputed in XLA.  q..v [B, H, L, D]; returns run(g)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from faster_distributed_training_tpu.ops.attention import dropout_keep

    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    N = B * H
    scale = 1.0 / math.sqrt(D)
    n3 = lambda x: x.reshape(N, x.shape[2], x.shape[3])  # noqa: E731
    qn, kn, vn, on = n3(q), n3(k), n3(v), n3(out)
    kb = jnp.repeat(key_bias, H, axis=0) if key_bias is not None else None
    bq, bk = _kb_blocks(Lq, Lk)
    qp, kp, vp, bias, nq, nk = _kb_pad(qn, kn, vn, kb, bq, bk)
    Lqp = nq * bq
    seed = (seed3 if seed3 is not None
            else _pack_seed(None)).reshape(1, 3).astype(jnp.uint32)
    hg = h_glob if h_glob is not None else H
    kreps = bk // _KB_LANES

    def pad_q_rows(x):
        return (jnp.pad(x, ((0, 0), (0, Lqp - Lq)) + ((0, 0),) * (x.ndim - 2))
                if Lqp != Lq else x)

    # lse/delta at 128 lanes (all lanes equal) — the input-side twin of
    # the scratch layout; the broadcast is transient O(L·128), not O(L²)
    lse128 = jnp.broadcast_to(pad_q_rows(lse)[..., None],
                              (N, Lqp, _KB_LANES))

    def common_block(q_blk, k_blk, b_blk, lse_blk):
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale + b_blk
        return jnp.exp(s - jnp.tile(lse_blk, (1, kreps)))  # p / l

    def dq_kernel(q_ref, k_ref, v_ref, b_ref, do_ref, lse_ref, dl_ref,
                  s_ref, dq_ref, dq_scr):
        i, j = pl.program_id(1), pl.program_id(2)

        @pl.when(j == 0)
        def _init():
            dq_scr[...] = jnp.zeros_like(dq_scr)

        p = common_block(q_ref[0], k_ref[0], b_ref[0], lse_ref[0])
        do = do_ref[0].astype(jnp.float32)
        dpterm = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bq, bk]
        if dropout_rate > 0.0:
            bh = _bh_from(s_ref, pl.program_id(0), H, hg)
            qrow = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kcol = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            dpterm = dpterm * dropout_keep(s_ref[0, 0], bh, qrow, kcol,
                                           dropout_rate)
        ds = p * (dpterm - jnp.tile(dl_ref[0], (1, kreps))) * scale
        dq_scr[...] += jnp.dot(ds.astype(k_ref.dtype), k_ref[0],
                               preferred_element_type=jnp.float32)

        @pl.when(j == nk - 1)
        def _fin():
            dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)

    def dkv_kernel(q_ref, k_ref, v_ref, b_ref, do_ref, lse_ref, dl_ref,
                   s_ref, dk_ref, dv_ref, dk_scr, dv_scr):
        j, i = pl.program_id(1), pl.program_id(2)

        @pl.when(i == 0)
        def _init():
            dk_scr[...] = jnp.zeros_like(dk_scr)
            dv_scr[...] = jnp.zeros_like(dv_scr)

        p = common_block(q_ref[0], k_ref[0], b_ref[0], lse_ref[0])
        do = do_ref[0].astype(jnp.float32)
        dpterm = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bq, bk]
        if dropout_rate > 0.0:
            bh = _bh_from(s_ref, pl.program_id(0), H, hg)
            qrow = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kcol = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            keep = dropout_keep(s_ref[0, 0], bh, qrow, kcol, dropout_rate)
            pt = p * keep
            dpterm = dpterm * keep
        else:
            pt = p
        dv_scr[...] += jax.lax.dot_general(
            pt.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bk, D]
        ds = p * (dpterm - jnp.tile(dl_ref[0], (1, kreps))) * scale
        dk_scr[...] += jax.lax.dot_general(
            ds, q_ref[0].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bk, D]

        @pl.when(i == nq - 1)
        def _fin():
            dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
            dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)

    interp = jax.default_backend() != "tpu"

    def run(g):
        gn = pad_q_rows(n3(g))
        delta = jnp.sum(gn.astype(jnp.float32)
                        * pad_q_rows(on).astype(jnp.float32),
                        axis=-1)                             # [N, Lqp]
        delta128 = jnp.broadcast_to(delta[..., None], (N, Lqp, _KB_LANES))
        dq = pl.pallas_call(
            dq_kernel,
            grid=(N, nq, nk),
            in_specs=[
                pl.BlockSpec((1, bq, D), lambda n, i, j: (n, i, 0)),
                pl.BlockSpec((1, bk, D), lambda n, i, j: (n, j, 0)),
                pl.BlockSpec((1, bk, D), lambda n, i, j: (n, j, 0)),
                pl.BlockSpec((1, 1, bk), lambda n, i, j: (n, 0, j)),
                pl.BlockSpec((1, bq, D), lambda n, i, j: (n, i, 0)),
                pl.BlockSpec((1, bq, _KB_LANES), lambda n, i, j: (n, i, 0)),
                pl.BlockSpec((1, bq, _KB_LANES), lambda n, i, j: (n, i, 0)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=pl.BlockSpec((1, bq, D), lambda n, i, j: (n, i, 0)),
            out_shape=jax.ShapeDtypeStruct((N, Lqp, D), jnp.float32),
            scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
            interpret=interp,
        )(qp, kp, vp, bias, gn, lse128, delta128, seed)
        dk, dv = pl.pallas_call(
            dkv_kernel,
            grid=(N, nk, nq),
            in_specs=[
                pl.BlockSpec((1, bq, D), lambda n, j, i: (n, i, 0)),
                pl.BlockSpec((1, bk, D), lambda n, j, i: (n, j, 0)),
                pl.BlockSpec((1, bk, D), lambda n, j, i: (n, j, 0)),
                pl.BlockSpec((1, 1, bk), lambda n, j, i: (n, 0, j)),
                pl.BlockSpec((1, bq, D), lambda n, j, i: (n, i, 0)),
                pl.BlockSpec((1, bq, _KB_LANES), lambda n, j, i: (n, i, 0)),
                pl.BlockSpec((1, bq, _KB_LANES), lambda n, j, i: (n, i, 0)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, bk, D), lambda n, j, i: (n, j, 0)),
                pl.BlockSpec((1, bk, D), lambda n, j, i: (n, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((N, nk * bk, D), jnp.float32),
                jax.ShapeDtypeStruct((N, nk * bk, D), jnp.float32),
            ],
            scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                            pltpu.VMEM((bk, D), jnp.float32)],
            interpret=interp,
        )(qp, kp, vp, bias, gn, lse128, delta128, seed)
        shape4 = lambda x, L: x[:, :L].reshape(B, H, L, D)  # noqa: E731
        return (shape4(dq, Lq).astype(q.dtype),
                shape4(dk, Lk).astype(k.dtype),
                shape4(dv, Lk).astype(v.dtype))

    return run


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_core(q, k, v, key_bias, seed3, block_q, dropout_rate,
                save_stats, h_glob):
    return _flash_impl(q, k, v, key_bias, seed3, block_q,
                       dropout_rate, h_glob)


def _fwd_kernel_fits(block_q: int, lk: int, d: int = 64) -> bool:
    """Empirical envelope (see _FWD_KERNEL_MAX_LK, scaled by 64/D) plus
    a tile-size bound so large-but-fitting Lk shrinks the q-tile."""
    return (lk * max(d, 1) <= _FWD_KERNEL_MAX_LK * 64
            and 3 * block_q * lk * 4 <= 6 * 1024 * 1024)


def _shrink_block_q(block_q: int, lk: int, d: int) -> int:
    """Halve the q-tile (floor 32) until the monolithic forward fits —
    ONE policy shared by the primal route (_flash_impl) and the
    saved-stats route selection (_flash_fwd), so they can never diverge
    on which tile the kernel would actually run."""
    while block_q > 32 and not _fwd_kernel_fits(block_q, lk, d):
        block_q //= 2
    return block_q


def _flash_impl(q, k, v, key_bias, seed3, block_q, dropout_rate,
                h_glob=None):
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    block_q = _shrink_block_q(block_q, Lk, D)
    if _use_pallas():
        n3 = lambda x: x.reshape(B * H, x.shape[2], x.shape[3])  # noqa: E731
        if _fwd_kernel_fits(block_q, Lk, D):
            out = _flash_fwd_pallas(n3(q), n3(k), n3(v), key_bias, H,
                                    block_q, dropout_rate, seed3,
                                    h_glob=h_glob)
            return out.reshape(B, H, Lq, D)
        if _kblocked_supported(D):
            kb = (jnp.repeat(key_bias, H, axis=0)
                  if key_bias is not None else None)
            out, _ = _flash_fwd_kblocked(n3(q), n3(k), n3(v), kb,
                                         dropout_rate, seed3,
                                         n_heads=H, h_glob=h_glob)
            return out.reshape(B, H, Lq, D)
    mask = None
    if key_bias is not None:
        mask = (key_bias > NEG_INF / 2).astype(jnp.int32)[:, None, None, :]
    seed3 = seed3 if seed3 is not None else _pack_seed(None)
    return blockwise_attention(
        q, k, v, mask, dropout_rate=dropout_rate, dropout_seed=seed3[0],
        dropout_bh=_bh_array(B, H, seed3, h_glob or H))


def _save_stats_enabled(save_stats=None) -> bool:
    """Monolithic saved-(out, lse) backward (the L=512 retune) — default
    ON; FDT_FLASH_SAVE_STATS=0 restores the in-kernel-recompute backward
    for A/B measurement.  An explicit save_stats (the model passes False
    inside rematted attention regions — see flash_attention's docstring)
    overrides the env default."""
    if save_stats is not None:
        return bool(save_stats)
    return os.environ.get("FDT_FLASH_SAVE_STATS", "1") != "0"


def _flash_fwd(q, k, v, key_bias, seed3, block_q, dropout_rate,
               save_stats, h_glob):
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    pallas_bwd = (_use_pallas()
                  and os.environ.get("FDT_DISABLE_PALLAS_BWD") != "1")
    # When the gradient will need the k-blocked backward (monolithic bwd
    # out of envelope), run the k-blocked forward HERE so its lse/out
    # become residuals — the backward then skips any full-row recompute.
    if pallas_bwd and _kblocked_supported(D) and not _bwd_kernel_fits(Lq, Lk,
                                                                      D):
        n3 = lambda x: x.reshape(B * H, x.shape[2], x.shape[3])  # noqa: E731
        kb = (jnp.repeat(key_bias, H, axis=0)
              if key_bias is not None else None)
        out, lse = _flash_fwd_kblocked(n3(q), n3(k), n3(v), kb,
                                       dropout_rate, seed3,
                                       n_heads=H, h_glob=h_glob)
        out = out.reshape(B, H, Lq, D)
        return out, (q, k, v, key_bias, seed3, out, lse)
    # Monolithic-envelope autodiff (VERDICT r5 #3, the flash-routed
    # bs64/seq512 shape): emit the row lse from the forward so the
    # monolithic backward skips its in-kernel softmax recompute AND the
    # out-recompute matmul (delta comes from the saved primal out) —
    # one fewer [bq,Lk]x[Lk,D] MXU pass and two fewer row sweeps per
    # q-block, and the smaller transient set buys a larger backward
    # q-tile (_bwd_block_q_stats: 512 vs 256 at Lk=512 — half the grid
    # steps per (b,h) instance).
    bq = _shrink_block_q(block_q, Lk, D)
    if (pallas_bwd and _save_stats_enabled(save_stats)
            and _bwd_kernel_fits(Lq, Lk, D)
            and _fwd_kernel_fits(bq, Lk, D)):
        n3 = lambda x: x.reshape(B * H, x.shape[2], x.shape[3])  # noqa: E731
        out, lse = _flash_fwd_pallas(n3(q), n3(k), n3(v), key_bias, H, bq,
                                     dropout_rate, seed3,
                                     emit_lse=True, h_glob=h_glob)
        out = out.reshape(B, H, Lq, D)
        return out, (q, k, v, key_bias, seed3, out, lse)
    return (_flash_impl(q, k, v, key_bias, seed3, block_q,
                        dropout_rate, h_glob),
            (q, k, v, key_bias, seed3, None, None))


# Backward-policy budget for the DENSE-VJP branch.  The dense backward
# holds ~3 score-shaped fp32 tensors at peak (the saved probabilities
# residual plus the ds/dp transients), so the comparison below multiplies
# scores_bytes by 3.  Measured on v5e (6L d512 transformer, bs=64, L=512):
# full step 95 ms dense-bwd vs 163 ms blockwise-bwd; the blockwise VJP's
# scan recompute only pays off once sequences outgrow this budget.
# The default assumes a v5e-class chip (16 GB HBM) with the rest of the
# step's working set resident; on smaller-memory platforms, or when the
# model/optimizer state crowds HBM, override without editing source via
# FDT_DENSE_BWD_BUDGET_MB (0 forces the blockwise VJP everywhere).
_DENSE_BWD_BUDGET_BYTES = 2 << 30


def _dense_bwd_budget_bytes() -> int:
    mb = os.environ.get("FDT_DENSE_BWD_BUDGET_MB")
    if mb is not None:
        return int(mb) << 20
    return _DENSE_BWD_BUDGET_BYTES


# The MONOLITHIC kernels keep the whole K/V (and for the backward, the
# dk/dv accumulators) VMEM-resident per (batch*head) grid cell, and
# Pallas double-buffers every input/output block — so their envelope is
# set by Lk·D, nearly independent of the q-tile.  Byte models
# underpredicted the compiler's scoped-vmem accounting (observed
# 16.0-16.2 MB right at the limit), so the caps below are EMPIRICAL,
# validated on v5e at D=64: each cap compiles and runs; the next power
# of two OOMs scoped vmem.  K/V residency scales linearly with the head
# dim, so the fit checks scale the cap by 64/D (ADVICE r2: a D=128
# model at Lk near the cap must route away instead of OOMing scoped
# VMEM at compile time).  Beyond the envelope the K-BLOCKED
# (FlashAttention-2-style) kernels below take over — O(tile) VMEM, no
# Lk cap; the XLA blockwise formulation remains the non-TPU path.
_FWD_KERNEL_MAX_LK = 8192   # at D=64; scaled by 64/D in _fwd_kernel_fits
_BWD_KERNEL_MAX_LK = 4096   # at D=64; scaled by 64/D in _bwd_kernel_fits


def _bwd_block_q(lq: int, lk: int) -> int:
    """q-tile for the backward kernel: ~6 fp32 score-shaped transients
    live at once, so shrink the tile as Lk grows.  The small-Lq clamp is
    rounded up to a sublane multiple of 8 — Mosaic tiling rejects or
    badly pads odd tile heights (padding already handles Lq % bq)."""
    clamp = -(-max(lq, 32) // 8) * 8
    for cand in (512, 256, 128, 64):
        if 6 * cand * lk * 4 <= 6 * 1024 * 1024:
            return min(cand, clamp)
    return 64


def _bwd_kernel_fits(lq: int, lk: int, d: int = 64) -> bool:
    return lk * max(d, 1) <= _BWD_KERNEL_MAX_LK * 64


def _bwd_block_q_stats(lq: int, lk: int) -> int:
    """q-tile for the SAVED-STATS backward kernel: dropping the softmax
    and out recompute leaves ~5 fp32 score-shaped transients at peak
    (s/p, pt, dpterm, ds, keep) instead of the recompute kernel's ~6, so
    the same 6 MB budget admits one tile size up — at Lk=512 that is
    bq=512 (vs 256): one q-block per (b,h) grid instance instead of two,
    halving the per-instance grid overhead the r5 attribution measured
    at the bs64/seq512 config."""
    clamp = -(-max(lq, 32) // 8) * 8
    for cand in (512, 256, 128, 64):
        if 5 * cand * lk * 4 <= 6 * 1024 * 1024:
            return min(cand, clamp)
    return 64


def _flash_bwd_pallas_stats(q, k, v, key_bias, seed3, dropout_rate,
                            out, lse, h_glob: Optional[int] = None):
    """Monolithic saved-stats backward (the L=512 retune, VERDICT r5
    #3): K/V stay VMEM-resident like _flash_bwd_pallas, but the softmax
    is NOT recomputed — probabilities come back exactly normalized from
    the forward-saved lse (p = exp(s - lse)), and delta = Σ dO·out is
    precomputed in XLA from the saved primal out.  Per q-block that
    deletes the out-recompute matmul ([bq,Lk]×[Lk,D]) and both row
    sweeps (max, sum) of the recompute kernel — 5 MXU passes instead of
    6 — at the price of the lse residual ([N,Lq] fp32, ~2 KB per (b,h)
    at L=512) and reading out back (alive anyway as the primal).
    q..v [B, H, L, D]; lse [N, Lq] fp32; returns run(g)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    from faster_distributed_training_tpu.ops.attention import dropout_keep

    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    N = B * H
    scale = 1.0 / math.sqrt(D)
    nq3 = lambda x: x.reshape(N, x.shape[2], x.shape[3])  # noqa: E731
    qn, kn, vn, on = nq3(q), nq3(k), nq3(v), nq3(out)
    bias, bias_map, has_bias = _bias_operand(key_bias, H, Lk)
    seed = (seed3 if seed3 is not None
            else _pack_seed(None)).reshape(1, 3).astype(jnp.uint32)
    hg = h_glob if h_glob is not None else H

    bq = _bwd_block_q_stats(Lq, Lk)
    nq = -(-Lq // bq)
    pad_q = nq * bq - Lq

    def pad_rows(x):
        return (jnp.pad(x, ((0, 0), (0, pad_q)) + ((0, 0),) * (x.ndim - 2))
                if pad_q else x)

    # lse/delta at _KB_LANES all-equal lanes — the proven K-blocked input
    # layout; transient O(L·128), never O(L²)
    lse128 = jnp.broadcast_to(pad_rows(lse)[..., None],
                              (N, nq * bq, _KB_LANES))

    def kernel(q_ref, k_ref, v_ref, b_ref, do_ref, lse_ref, dl_ref, s_ref,
               dq_ref, dk_ref, dv_ref):
        i = pl.program_id(1)
        qb = q_ref[0]                                      # [bq, D]
        do = do_ref[0].astype(jnp.float32)                 # [bq, D]
        kk = k_ref[0]                                      # [Lk, D]
        vv = v_ref[0]
        s = jax.lax.dot_general(
            qb, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [bq, Lk]
        if has_bias:
            s = s + b_ref[0]
        p = jnp.exp(s - lse_ref[0][:, :1])                 # normalized probs
        dpterm = jax.lax.dot_general(
            do, vv.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, Lk]
        if dropout_rate > 0.0:
            bh = _bh_from(s_ref, pl.program_id(0), H, hg)
            qrow = (i * bq
                    + jax.lax.broadcasted_iota(jnp.int32, (bq, Lk), 0))
            kcol = jax.lax.broadcasted_iota(jnp.int32, (bq, Lk), 1)
            keep = dropout_keep(s_ref[0, 0], bh, qrow, kcol, dropout_rate)
            pt = p * keep
            dpterm = dpterm * keep
        else:
            pt = p
        ds = p * (dpterm - dl_ref[0][:, :1]) * scale       # [bq, Lk]
        dq_ref[0] = jnp.dot(ds.astype(kk.dtype), kk,
                            preferred_element_type=jnp.float32
                            ).astype(dq_ref.dtype)
        dk_blk = jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [Lk, D]
        dv_blk = jax.lax.dot_general(
            pt.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [Lk, D]

        @pl.when(i == 0)
        def _init():
            dk_ref[0] = dk_blk.astype(dk_ref.dtype)
            dv_ref[0] = dv_blk.astype(dv_ref.dtype)

        @pl.when(i > 0)
        def _acc():
            dk_ref[0] += dk_blk.astype(dk_ref.dtype)
            dv_ref[0] += dv_blk.astype(dv_ref.dtype)

    qp = pad_rows(qn)

    def run(g):
        gn = nq3(g)
        gp = pad_rows(gn)
        delta = jnp.sum(gp.astype(jnp.float32)
                        * pad_rows(on).astype(jnp.float32),
                        axis=-1)                           # [N, Lqp]
        delta128 = jnp.broadcast_to(delta[..., None],
                                    (N, nq * bq, _KB_LANES))
        dq, dk, dv = pl.pallas_call(
            kernel,
            grid=(N, nq),
            in_specs=[
                pl.BlockSpec((1, bq, D), lambda n, i: (n, i, 0)),
                pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
                pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
                pl.BlockSpec((1, 1, Lk), bias_map),
                pl.BlockSpec((1, bq, D), lambda n, i: (n, i, 0)),
                pl.BlockSpec((1, bq, _KB_LANES), lambda n, i: (n, i, 0)),
                pl.BlockSpec((1, bq, _KB_LANES), lambda n, i: (n, i, 0)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, D), lambda n, i: (n, i, 0)),
                pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
                pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((N, nq * bq, D), jnp.float32),
                jax.ShapeDtypeStruct((N, Lk, D), jnp.float32),
                jax.ShapeDtypeStruct((N, Lk, D), jnp.float32),
            ],
            interpret=(jax.default_backend() != "tpu"),
        )(qp, kn, vn, bias, gp, lse128, delta128, seed)
        shape4 = lambda x, L: x.reshape(B, H, L, D)  # noqa: E731
        return (shape4(dq[:, :Lq], Lq).astype(q.dtype),
                shape4(dk, Lk).astype(k.dtype),
                shape4(dv, Lk).astype(v.dtype))

    return run


def _flash_bwd_pallas(q, k, v, key_bias, seed3, dropout_rate,
                      block_q, h_glob: Optional[int] = None):
    """Pallas backward kernel: dq/dk/dv with softmax stats RECOMPUTED
    per q-block inside the kernel (K/V stay VMEM-resident, so the full
    [block_q, Lk] score row costs one MXU matmul — no saved lse needed
    and residuals stay (q, k, v, bias, seed)).

    Math (m cancels out of out = acc/l, so treating it constant is
    exact; delta_i = dO_i . out_i):
      p    = exp(s - m),  l = sum_j p,  P~ = p * keep
      dv_j = sum_i (P~_ij / l_i) dO_i
      ds   = p * (keep * (dO V^T) - delta) / l * scale
      dq_i = sum_j ds_ij k_j,   dk_j = sum_i ds_ij q_i
    dk/dv accumulate across q-blocks by revisiting their (n)-indexed
    output block — the TPU grid runs sequentially, i innermost.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    from faster_distributed_training_tpu.ops.attention import dropout_keep

    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    N = B * H
    scale = 1.0 / math.sqrt(D)
    nq3 = lambda x: x.reshape(N, x.shape[2], x.shape[3])  # noqa: E731
    qn, kn, vn = nq3(q), nq3(k), nq3(v)

    bias, bias_map, has_bias = _bias_operand(key_bias, H, Lk)
    seed = (seed3 if seed3 is not None
            else _pack_seed(None)).reshape(1, 3).astype(jnp.uint32)
    hg = h_glob if h_glob is not None else H

    # backward holds ~4 score-shaped fp32 tiles (s/p, dpterm, ds, keep):
    # budget the q-tile so tiles + the resident K/V stay inside the
    # ~16 MB scoped-VMEM limit (measured: bq=128 at Lk=8192 overflows
    # by 192 KB).  _bwd_kernel_fits gates callers beyond the envelope.
    bq = _bwd_block_q(Lq, Lk)
    nq = -(-Lq // bq)
    pad_q = nq * bq - Lq

    def kernel(q_ref, k_ref, v_ref, b_ref, do_ref, s_ref,
               dq_ref, dk_ref, dv_ref):
        i = pl.program_id(1)
        qb = q_ref[0]                                      # [bq, D]
        do = do_ref[0].astype(jnp.float32)                 # [bq, D]
        kk = k_ref[0]                                      # [Lk, D]
        vv = v_ref[0]
        s = jax.lax.dot_general(
            qb, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [bq, Lk]
        if has_bias:
            s = s + b_ref[0]
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
        if dropout_rate > 0.0:
            bh = _bh_from(s_ref, pl.program_id(0), H, hg)
            qrow = (i * bq
                    + jax.lax.broadcasted_iota(jnp.int32, (bq, Lk), 0))
            kcol = jax.lax.broadcasted_iota(jnp.int32, (bq, Lk), 1)
            keep = dropout_keep(s_ref[0, 0], bh, qrow, kcol, dropout_rate)
            pt = p * keep
        else:
            keep = None
            pt = p
        out = jnp.dot(pt.astype(vv.dtype), vv,
                      preferred_element_type=jnp.float32) / l   # [bq, D]
        delta = jnp.sum(do * out, axis=-1, keepdims=True)       # [bq, 1]
        dpterm = jax.lax.dot_general(
            do, vv.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)                 # [bq, Lk]
        if keep is not None:
            dpterm = dpterm * keep
        ds = p * (dpterm - delta) / l * scale                   # [bq, Lk]
        dq_ref[0] = jnp.dot(ds.astype(kk.dtype), kk,
                            preferred_element_type=jnp.float32
                            ).astype(dq_ref.dtype)
        dk_blk = jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                 # [Lk, D]
        dv_blk = jax.lax.dot_general(
            (pt / l).astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                 # [Lk, D]

        @pl.when(i == 0)
        def _init():
            dk_ref[0] = dk_blk.astype(dk_ref.dtype)
            dv_ref[0] = dv_blk.astype(dv_ref.dtype)

        @pl.when(i > 0)
        def _acc():
            dk_ref[0] += dk_blk.astype(dk_ref.dtype)
            dv_ref[0] += dv_blk.astype(dv_ref.dtype)

    qp = jnp.pad(qn, ((0, 0), (0, pad_q), (0, 0))) if pad_q else qn

    def run(g):
        gn = nq3(g)
        gp = (jnp.pad(gn, ((0, 0), (0, pad_q), (0, 0))) if pad_q else gn)
        dq, dk, dv = pl.pallas_call(
            kernel,
            grid=(N, nq),
            in_specs=[
                pl.BlockSpec((1, bq, D), lambda n, i: (n, i, 0)),
                pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
                pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
                pl.BlockSpec((1, 1, Lk), bias_map),
                pl.BlockSpec((1, bq, D), lambda n, i: (n, i, 0)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, D), lambda n, i: (n, i, 0)),
                pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
                pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((N, nq * bq, D), jnp.float32),
                jax.ShapeDtypeStruct((N, Lk, D), jnp.float32),
                jax.ShapeDtypeStruct((N, Lk, D), jnp.float32),
            ],
            interpret=(jax.default_backend() != "tpu"),
        )(qp, kn, vn, bias, gp, seed)
        shape4 = lambda x, L: x.reshape(B, H, L, D)  # noqa: E731
        return (shape4(dq[:, :Lq], Lq).astype(q.dtype),
                shape4(dk, Lk).astype(k.dtype),
                shape4(dv, Lk).astype(v.dtype))

    return run


def _flash_bwd(block_q, dropout_rate, save_stats, h_glob, res, g):
    q, k, v, key_bias, seed3, out, lse = res
    mask = None
    if key_bias is not None:
        mask = (key_bias > NEG_INF / 2).astype(jnp.int32)[:, None, None, :]
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    scores_bytes = 4 * B * H * Lq * Lk
    # every branch regenerates the forward's dropout mask from
    # (seed, bh, q, k) indices — identical by construction (dropout_keep)
    if out is not None and _bwd_kernel_fits(Lq, Lk, D) and \
            _save_stats_enabled(save_stats):
        # in-envelope saved-stats route: the forward emitted (out, lse)
        # from the monolithic kernel, so the monolithic backward skips
        # its in-kernel softmax/out recompute (the L=512 retune)
        dq, dk, dv = _flash_bwd_pallas_stats(q, k, v, key_bias,
                                             seed3, dropout_rate,
                                             out, lse, h_glob=h_glob)(g)
    elif out is not None:
        # the forward took the k-blocked route (monolithic envelope
        # exceeded) and saved (out, lse): finish with the k-blocked
        # FA-2-style kernels — no Lk cap, O(tile) VMEM
        dq, dk, dv = _flash_bwd_kblocked(q, k, v, key_bias, seed3,
                                         dropout_rate, out, lse,
                                         h_glob=h_glob)(g)
    elif (_use_pallas() and os.environ.get("FDT_DISABLE_PALLAS_BWD") != "1"
            and _bwd_kernel_fits(Lq, Lk, D)):
        # On TPU the monolithic backward kernel wins at EVERY measured
        # size within its VMEM envelope (v5e bf16 fwd+bwd, interleaved
        # re-measure: L=2048 B=4 H=8: 9.0 ms vs 11.3 dense-VJP / 14.3
        # blockwise-VJP; L=512 B=64 H=8: 6.9 ms vs 10.2 dense-VJP)
        # while keeping O(L·block) memory — so it is the default inside
        # the envelope; the k-blocked branch above covers everything
        # beyond it.
        dq, dk, dv = _flash_bwd_pallas(q, k, v, key_bias, seed3,
                                       dropout_rate, block_q,
                                       h_glob=h_glob)(g)
    else:
        seed0 = (seed3 if seed3 is not None else _pack_seed(None))
        bh = _bh_array(B, H, seed0, h_glob or H)
        if 3 * scores_bytes <= _dense_bwd_budget_bytes():
            _, vjp = jax.vjp(
                lambda q_, k_, v_: dense_attention_reference(
                    q_, k_, v_, mask, dropout_rate=dropout_rate,
                    dropout_seed=seed0[0], dropout_bh=bh),
                q, k, v)
        else:
            # long context off-TPU: recompute-in-backward via the
            # blockwise formulation keeps peak memory O(L*block) at the
            # price of the scan recompute
            _, vjp = jax.vjp(
                lambda q_, k_, v_: blockwise_attention(
                    q_, k_, v_, mask, dropout_rate=dropout_rate,
                    dropout_seed=seed0[0], dropout_bh=bh),
                q, k, v)
        dq, dk, dv = vjp(g)
    return dq, dk, dv, None, None


_flash_core.defvjp(_flash_fwd, _flash_bwd)


def _auto_block_q(lq: int, lk: int) -> int:
    """Largest q-block in {1024..128} whose fp32 score tile (block_q x Lk)
    stays within ~8 MB of VMEM — measured on v5e @ L=2048 D=64 bf16:
    block_q=1024 runs ~20-25% faster than the 128 default (2.7-2.8 vs
    3.4-3.9 ms), and the budget degrades the block gracefully as the
    context grows (Lk=4096 -> 512, 8192 -> 256, 16384 -> 128)."""
    budget = 8 * 1024 * 1024
    for bq in (1024, 512, 256, 128):
        if bq * lk * 4 <= budget:
            return min(bq, max(lq, 128))
    return 128


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    mask: Optional[jax.Array] = None,
                    block_q: Optional[int] = None,
                    dropout_rate: float = 0.0,
                    dropout_seed: Optional[jax.Array] = None,
                    save_stats: Optional[bool] = None,
                    bh0=None,
                    h_glob: Optional[int] = None) -> jax.Array:
    """Drop-in for dense_attention (models/transformer.py:101-111),
    INCLUDING attention-prob dropout (transformer.py:190-192): the keep
    mask is an index hash (ops.attention.dropout_keep) computed inside
    the kernel, so probabilities still never touch HBM.

    q/k/v: [B, H, L, D].  mask: None or a key-padding mask broadcastable
    to [B, 1, 1, Lk] (mask==0 masked) — full [B,H,Lq,Lk] masks should use
    blockwise_attention directly.  block_q: q-tile rows; None picks the
    largest tile whose score buffer fits VMEM (_auto_block_q).
    dropout_rate/dropout_seed: training-path prob dropout; pass a fresh
    u32 seed per step (e.g. jax.random.bits of the step's dropout rng).
    save_stats: the monolithic saved-(out, lse) backward toggle — None
    follows the FDT_FLASH_SAVE_STATS env default (on).  Pass False when
    this call sits INSIDE a rematted region whose replay recomputes
    custom_vjp residuals (models/transformer.py does for the layer/
    attn_out/dots policies): out/lse residuals would force the forward
    kernel to re-run in the replay, whereas the recompute backward's
    input-only residuals let XLA DCE the replayed kernel entirely.
    bh0/h_glob: head-sharded callers (parallel/kernel_shard.py running
    this kernel per-shard under shard_map) pass their GLOBAL (batch,
    head) shard origin and the global head count so the in-kernel
    dropout hashes GLOBAL stream indices — masks stay placement-
    invariant; the defaults reduce to the local indices bit-for-bit.
    """
    if block_q is None:
        block_q = _auto_block_q(q.shape[2], k.shape[2])
    key_bias = None
    if mask is not None:
        kb = jnp.asarray(mask)
        if kb.ndim == 4:                     # [B,1,1,Lk] -> [B,Lk]
            kb = kb.reshape(kb.shape[0], kb.shape[-1])
        kb = jnp.broadcast_to(kb, (q.shape[0], k.shape[2]))
        key_bias = mask_to_bias(kb)
    return _flash_core(q, k, v, key_bias, _pack_seed(dropout_seed, bh0),
                       block_q, float(dropout_rate), save_stats,
                       h_glob if h_glob is not None else int(q.shape[1]))
