"""Flash attention: Pallas TPU forward kernel + recompute backward.

TPU-first replacement for the reference's dense ScaledDotProduct
(transformer.py:180-193).  Design:

  * forward — a Pallas kernel tiled (batch·head, query-block) with K/V
    resident in VMEM: one MXU matmul for scores, row-softmax in fp32,
    one MXU matmul for the context.  Probabilities never touch HBM.
    Attention-prob dropout (training) is an in-kernel index-hash mask
    (ops.attention.dropout_keep) — still no HBM probabilities.
  * backward — recompute-in-backward (the same memory trick as the
    reference's FusedConvBN, resnet.py:107-108): residuals are just
    (q, k, v, mask, seed).  On TPU the default is the Pallas backward
    KERNEL (softmax stats recomputed per q-block, dk/dv accumulated
    across the sequential grid — O(L·block) memory): measured faster
    than BOTH XLA-derived VJPs at every size tried on v5e (L=512
    B=64: 6.9 vs 10.2 ms dense-VJP; L=2048 B=4: 9.0 vs 11.3/14.3).
    Kill-switch FDT_DISABLE_PALLAS_BWD=1 restores the measured
    two-branch VJP policy (dense under a ~2 GB score budget —
    overridable via FDT_DENSE_BWD_BUDGET_MB — blockwise scan beyond),
    which is also the off-TPU path.
  * non-TPU backends (tests, CPU sim) use the blockwise path; set
    FDT_FORCE_PALLAS_INTERPRET=1 to exercise both kernels in
    interpreter mode on CPU.

Per-head K/V for supported workloads fits VMEM comfortably (e.g.
L=512, D=64, fp32 → 128 KiB per tensor of the ~16 MiB budget); longer
sequences shard L over the `sp` mesh axis first (ops/ring_attention.py),
so each shard stays VMEM-sized.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp

from faster_distributed_training_tpu.ops.attention import (
    NEG_INF, blockwise_attention, dense_attention_reference, mask_to_bias)


def _use_pallas() -> bool:
    if os.environ.get("FDT_FORCE_PALLAS_INTERPRET") == "1":
        return True
    return jax.default_backend() == "tpu"


def _flash_fwd_pallas(q: jax.Array, k: jax.Array, v: jax.Array,
                      key_bias: Optional[jax.Array],
                      block_q: int, dropout_rate: float = 0.0,
                      dropout_seed: Optional[jax.Array] = None) -> jax.Array:
    """q/k/v [N, L, D] (N = B·H), key_bias [N, Lk] additive or None.

    dropout_rate > 0 applies ops.attention.dropout_keep in-kernel: the
    keep mask is a pure hash of (seed, n, global q row, k col), so the
    recompute backward regenerates it exactly without any HBM mask."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    from faster_distributed_training_tpu.ops.attention import dropout_keep

    N, Lq, D = q.shape
    Lk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    block_q = min(block_q, Lq)
    nq = -(-Lq // block_q)
    pad_q = nq * block_q - Lq
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0)))
    if key_bias is None:
        key_bias = jnp.zeros((N, Lk), jnp.float32)
    key_bias = key_bias.reshape(N, 1, Lk).astype(jnp.float32)
    seed = (dropout_seed if dropout_seed is not None
            else jnp.uint32(0)).reshape(1, 1).astype(jnp.uint32)

    def kernel(q_ref, k_ref, v_ref, b_ref, s_ref, o_ref):
        qb = q_ref[0]                                   # [block_q, D]
        s = jax.lax.dot_general(
            qb, k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [block_q, Lk]
        s = s + b_ref[0]
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
        if dropout_rate > 0.0:
            n = pl.program_id(0)
            qrow = (pl.program_id(1) * block_q
                    + jax.lax.broadcasted_iota(jnp.int32, (block_q, Lk), 0))
            kcol = jax.lax.broadcasted_iota(jnp.int32, (block_q, Lk), 1)
            p = p * dropout_keep(s_ref[0, 0], n, qrow, kcol, dropout_rate)
        ctx = jnp.dot(p.astype(v_ref.dtype), v_ref[0],
                      preferred_element_type=jnp.float32)
        o_ref[0] = (ctx / l).astype(o_ref.dtype)

    out = pl.pallas_call(
        kernel,
        grid=(N, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda n, i: (n, i, 0)),
            pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
            pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
            pl.BlockSpec((1, 1, Lk), lambda n, i: (n, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda n, i: (n, i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, nq * block_q, D), q.dtype),
        interpret=(jax.default_backend() != "tpu"),
    )(q, k, v, key_bias, seed)
    return out[:, :Lq, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _flash_core(q, k, v, key_bias, dropout_seed, block_q, dropout_rate):
    return _flash_impl(q, k, v, key_bias, dropout_seed, block_q,
                       dropout_rate)


def _fwd_kernel_fits(block_q: int, lk: int) -> bool:
    """Empirical envelope (see _FWD_KERNEL_MAX_LK) plus a tile-size
    bound so large-but-fitting Lk shrinks the q-tile."""
    return (lk <= _FWD_KERNEL_MAX_LK
            and 3 * block_q * lk * 4 <= 6 * 1024 * 1024)


def _flash_impl(q, k, v, key_bias, dropout_seed, block_q, dropout_rate):
    B, H, Lq, D = q.shape
    while block_q > 32 and not _fwd_kernel_fits(block_q, k.shape[2]):
        block_q //= 2
    if _use_pallas() and _fwd_kernel_fits(block_q, k.shape[2]):
        nq = lambda x: x.reshape(B * H, x.shape[2], x.shape[3])  # noqa: E731
        kb = (jnp.repeat(key_bias, H, axis=0)
              if key_bias is not None else None)
        out = _flash_fwd_pallas(nq(q), nq(k), nq(v), kb, block_q,
                                dropout_rate, dropout_seed)
        return out.reshape(B, H, Lq, D)
    mask = None
    if key_bias is not None:
        mask = (key_bias > NEG_INF / 2).astype(jnp.int32)[:, None, None, :]
    return blockwise_attention(q, k, v, mask, dropout_rate=dropout_rate,
                               dropout_seed=dropout_seed)


def _flash_fwd(q, k, v, key_bias, dropout_seed, block_q, dropout_rate):
    return (_flash_core(q, k, v, key_bias, dropout_seed, block_q,
                        dropout_rate),
            (q, k, v, key_bias, dropout_seed))


# Backward-policy budget for the DENSE-VJP branch.  The dense backward
# holds ~3 score-shaped fp32 tensors at peak (the saved probabilities
# residual plus the ds/dp transients), so the comparison below multiplies
# scores_bytes by 3.  Measured on v5e (6L d512 transformer, bs=64, L=512):
# full step 95 ms dense-bwd vs 163 ms blockwise-bwd; the blockwise VJP's
# scan recompute only pays off once sequences outgrow this budget.
# The default assumes a v5e-class chip (16 GB HBM) with the rest of the
# step's working set resident; on smaller-memory platforms, or when the
# model/optimizer state crowds HBM, override without editing source via
# FDT_DENSE_BWD_BUDGET_MB (0 forces the blockwise VJP everywhere).
_DENSE_BWD_BUDGET_BYTES = 2 << 30


def _dense_bwd_budget_bytes() -> int:
    mb = os.environ.get("FDT_DENSE_BWD_BUDGET_MB")
    if mb is not None:
        return int(mb) << 20
    return _DENSE_BWD_BUDGET_BYTES


# The kernels keep the whole K/V (and for the backward, the dk/dv
# accumulators) VMEM-resident per (batch*head) grid cell, and Pallas
# double-buffers every input/output block — so the envelope is set by
# Lk, nearly independent of the q-tile.  Byte models underpredicted the
# compiler's scoped-vmem accounting (observed 16.0-16.2 MB right at the
# limit), so the caps below are EMPIRICAL, validated on v5e at D=64:
# each cap compiles and runs; the next power of two OOMs scoped vmem.
# Beyond them the blockwise formulations (O(L·block) in XLA) take over;
# k-blocking the kernels (FlashAttention-2 style) is the known next step.
_FWD_KERNEL_MAX_LK = 8192
_BWD_KERNEL_MAX_LK = 4096


def _bwd_block_q(lq: int, lk: int) -> int:
    """q-tile for the backward kernel: ~6 fp32 score-shaped transients
    live at once, so shrink the tile as Lk grows."""
    for cand in (512, 256, 128, 64):
        if 6 * cand * lk * 4 <= 6 * 1024 * 1024:
            return min(cand, max(lq, 32))
    return 64


def _bwd_kernel_fits(lq: int, lk: int) -> bool:
    return lk <= _BWD_KERNEL_MAX_LK


def _flash_bwd_pallas(q, k, v, key_bias, dropout_seed, dropout_rate,
                      block_q):
    """Pallas backward kernel: dq/dk/dv with softmax stats RECOMPUTED
    per q-block inside the kernel (K/V stay VMEM-resident, so the full
    [block_q, Lk] score row costs one MXU matmul — no saved lse needed
    and residuals stay (q, k, v, bias, seed)).

    Math (m cancels out of out = acc/l, so treating it constant is
    exact; delta_i = dO_i . out_i):
      p    = exp(s - m),  l = sum_j p,  P~ = p * keep
      dv_j = sum_i (P~_ij / l_i) dO_i
      ds   = p * (keep * (dO V^T) - delta) / l * scale
      dq_i = sum_j ds_ij k_j,   dk_j = sum_i ds_ij q_i
    dk/dv accumulate across q-blocks by revisiting their (n)-indexed
    output block — the TPU grid runs sequentially, i innermost.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    from faster_distributed_training_tpu.ops.attention import dropout_keep

    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    N = B * H
    scale = 1.0 / math.sqrt(D)
    nq3 = lambda x: x.reshape(N, x.shape[2], x.shape[3])  # noqa: E731
    qn, kn, vn = nq3(q), nq3(k), nq3(v)

    if key_bias is None:
        bias = jnp.zeros((B, Lk), jnp.float32)
    else:
        bias = key_bias
    bias = jnp.repeat(bias, H, axis=0).reshape(N, 1, Lk).astype(jnp.float32)
    seed = (dropout_seed if dropout_seed is not None
            else jnp.uint32(0)).reshape(1, 1).astype(jnp.uint32)

    # backward holds ~4 score-shaped fp32 tiles (s/p, dpterm, ds, keep):
    # budget the q-tile so tiles + the resident K/V stay inside the
    # ~16 MB scoped-VMEM limit (measured: bq=128 at Lk=8192 overflows
    # by 192 KB).  _bwd_kernel_fits gates callers beyond the envelope.
    bq = _bwd_block_q(Lq, Lk)
    nq = -(-Lq // bq)
    pad_q = nq * bq - Lq

    def kernel(q_ref, k_ref, v_ref, b_ref, do_ref, s_ref,
               dq_ref, dk_ref, dv_ref):
        i = pl.program_id(1)
        qb = q_ref[0]                                      # [bq, D]
        do = do_ref[0].astype(jnp.float32)                 # [bq, D]
        kk = k_ref[0]                                      # [Lk, D]
        vv = v_ref[0]
        s = jax.lax.dot_general(
            qb, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [bq, Lk]
        s = s + b_ref[0]
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
        if dropout_rate > 0.0:
            n = pl.program_id(0)
            qrow = (i * bq
                    + jax.lax.broadcasted_iota(jnp.int32, (bq, Lk), 0))
            kcol = jax.lax.broadcasted_iota(jnp.int32, (bq, Lk), 1)
            keep = dropout_keep(s_ref[0, 0], n, qrow, kcol, dropout_rate)
            pt = p * keep
        else:
            keep = None
            pt = p
        out = jnp.dot(pt.astype(vv.dtype), vv,
                      preferred_element_type=jnp.float32) / l   # [bq, D]
        delta = jnp.sum(do * out, axis=-1, keepdims=True)       # [bq, 1]
        dpterm = jax.lax.dot_general(
            do, vv.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)                 # [bq, Lk]
        if keep is not None:
            dpterm = dpterm * keep
        ds = p * (dpterm - delta) / l * scale                   # [bq, Lk]
        dq_ref[0] = jnp.dot(ds.astype(kk.dtype), kk,
                            preferred_element_type=jnp.float32
                            ).astype(dq_ref.dtype)
        dk_blk = jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                 # [Lk, D]
        dv_blk = jax.lax.dot_general(
            (pt / l).astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                 # [Lk, D]

        @pl.when(i == 0)
        def _init():
            dk_ref[0] = dk_blk.astype(dk_ref.dtype)
            dv_ref[0] = dv_blk.astype(dv_ref.dtype)

        @pl.when(i > 0)
        def _acc():
            dk_ref[0] += dk_blk.astype(dk_ref.dtype)
            dv_ref[0] += dv_blk.astype(dv_ref.dtype)

    qp = jnp.pad(qn, ((0, 0), (0, pad_q), (0, 0))) if pad_q else qn

    def run(g):
        gn = nq3(g)
        gp = (jnp.pad(gn, ((0, 0), (0, pad_q), (0, 0))) if pad_q else gn)
        dq, dk, dv = pl.pallas_call(
            kernel,
            grid=(N, nq),
            in_specs=[
                pl.BlockSpec((1, bq, D), lambda n, i: (n, i, 0)),
                pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
                pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
                pl.BlockSpec((1, 1, Lk), lambda n, i: (n, 0, 0)),
                pl.BlockSpec((1, bq, D), lambda n, i: (n, i, 0)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, D), lambda n, i: (n, i, 0)),
                pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
                pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((N, nq * bq, D), jnp.float32),
                jax.ShapeDtypeStruct((N, Lk, D), jnp.float32),
                jax.ShapeDtypeStruct((N, Lk, D), jnp.float32),
            ],
            interpret=(jax.default_backend() != "tpu"),
        )(qp, kn, vn, bias, gp, seed)
        shape4 = lambda x, L: x.reshape(B, H, L, D)  # noqa: E731
        return (shape4(dq[:, :Lq], Lq).astype(q.dtype),
                shape4(dk, Lk).astype(k.dtype),
                shape4(dv, Lk).astype(v.dtype))

    return run


def _flash_bwd(block_q, dropout_rate, res, g):
    q, k, v, key_bias, dropout_seed = res
    mask = None
    if key_bias is not None:
        mask = (key_bias > NEG_INF / 2).astype(jnp.int32)[:, None, None, :]
    B, H, Lq, _ = q.shape
    Lk = k.shape[2]
    scores_bytes = 4 * B * H * Lq * Lk
    # every branch regenerates the forward's dropout mask from
    # (seed, bh, q, k) indices — identical by construction (dropout_keep)
    if (_use_pallas() and os.environ.get("FDT_DISABLE_PALLAS_BWD") != "1"
            and _bwd_kernel_fits(Lq, Lk)):
        # On TPU the backward kernel wins at EVERY measured size within
        # its VMEM envelope (v5e bf16 fwd+bwd, interleaved re-measure:
        # L=2048 B=4 H=8: 9.0 ms vs 11.3 dense-VJP / 14.3 blockwise-VJP;
        # L=512 B=64 H=8: 6.9 ms vs 10.2 dense-VJP) while keeping
        # O(L·block) memory — so it is the default, not a branch.
        # Beyond the envelope (K/V no longer VMEM-resident, ~Lk > 8k at
        # D=64) the blockwise-VJP branch below takes over; k-blocking
        # the kernel itself is the known next step.
        dq, dk, dv = _flash_bwd_pallas(q, k, v, key_bias, dropout_seed,
                                       dropout_rate, block_q)(g)
    elif 3 * scores_bytes <= _dense_bwd_budget_bytes():
        _, vjp = jax.vjp(
            lambda q_, k_, v_: dense_attention_reference(
                q_, k_, v_, mask, dropout_rate=dropout_rate,
                dropout_seed=dropout_seed),
            q, k, v)
        dq, dk, dv = vjp(g)
    else:
        # long context off-TPU: recompute-in-backward via the blockwise
        # formulation keeps peak memory O(L*block) at the price of the
        # scan recompute
        _, vjp = jax.vjp(
            lambda q_, k_, v_: blockwise_attention(
                q_, k_, v_, mask, dropout_rate=dropout_rate,
                dropout_seed=dropout_seed),
            q, k, v)
        dq, dk, dv = vjp(g)
    return dq, dk, dv, None, None


_flash_core.defvjp(_flash_fwd, _flash_bwd)


def _auto_block_q(lq: int, lk: int) -> int:
    """Largest q-block in {1024..128} whose fp32 score tile (block_q x Lk)
    stays within ~8 MB of VMEM — measured on v5e @ L=2048 D=64 bf16:
    block_q=1024 runs ~20-25% faster than the 128 default (2.7-2.8 vs
    3.4-3.9 ms), and the budget degrades the block gracefully as the
    context grows (Lk=4096 -> 512, 8192 -> 256, 16384 -> 128)."""
    budget = 8 * 1024 * 1024
    for bq in (1024, 512, 256, 128):
        if bq * lk * 4 <= budget:
            return min(bq, max(lq, 128))
    return 128


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    mask: Optional[jax.Array] = None,
                    block_q: Optional[int] = None,
                    dropout_rate: float = 0.0,
                    dropout_seed: Optional[jax.Array] = None) -> jax.Array:
    """Drop-in for dense_attention (models/transformer.py:101-111),
    INCLUDING attention-prob dropout (transformer.py:190-192): the keep
    mask is an index hash (ops.attention.dropout_keep) computed inside
    the kernel, so probabilities still never touch HBM.

    q/k/v: [B, H, L, D].  mask: None or a key-padding mask broadcastable
    to [B, 1, 1, Lk] (mask==0 masked) — full [B,H,Lq,Lk] masks should use
    blockwise_attention directly.  block_q: q-tile rows; None picks the
    largest tile whose score buffer fits VMEM (_auto_block_q).
    dropout_rate/dropout_seed: training-path prob dropout; pass a fresh
    u32 seed per step (e.g. jax.random.bits of the step's dropout rng).
    """
    if block_q is None:
        block_q = _auto_block_q(q.shape[2], k.shape[2])
    key_bias = None
    if mask is not None:
        kb = jnp.asarray(mask)
        if kb.ndim == 4:                     # [B,1,1,Lk] -> [B,Lk]
            kb = kb.reshape(kb.shape[0], kb.shape[-1])
        kb = jnp.broadcast_to(kb, (q.shape[0], k.shape[2]))
        key_bias = mask_to_bias(kb)
    seed = (jnp.uint32(0) if dropout_seed is None
            else dropout_seed.astype(jnp.uint32))
    return _flash_core(q, k, v, key_bias, seed, block_q,
                       float(dropout_rate))
