"""Flash attention: Pallas TPU forward kernel + recompute backward.

TPU-first replacement for the reference's dense ScaledDotProduct
(transformer.py:180-193).  Design:

  * forward — a Pallas kernel tiled (batch·head, query-block) with K/V
    resident in VMEM: one MXU matmul for scores, row-softmax in fp32,
    one MXU matmul for the context.  Probabilities never touch HBM.
  * backward — recompute-in-backward (the same memory trick as the
    reference's FusedConvBN, resnet.py:107-108): residuals are just
    (q, k, v, mask).  The VJP formulation is a measured two-branch
    policy (_flash_bwd): dense when ~3 score-shaped fp32 transients fit
    the budget (v5e, 6L d512 bs=64 L=512: full step 95 ms vs 163 ms
    with the blockwise VJP), blockwise beyond it so long-context peak
    memory stays O(L·block).
  * non-TPU backends (tests, CPU sim) use the blockwise path; set
    FDT_FORCE_PALLAS_INTERPRET=1 to exercise the kernel in interpreter
    mode on CPU.

Per-head K/V for supported workloads fits VMEM comfortably (e.g.
L=512, D=64, fp32 → 128 KiB per tensor of the ~16 MiB budget); longer
sequences shard L over the `sp` mesh axis first (ops/ring_attention.py),
so each shard stays VMEM-sized.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp

from faster_distributed_training_tpu.ops.attention import (
    NEG_INF, blockwise_attention, dense_attention_reference, mask_to_bias)


def _use_pallas() -> bool:
    if os.environ.get("FDT_FORCE_PALLAS_INTERPRET") == "1":
        return True
    return jax.default_backend() == "tpu"


def _flash_fwd_pallas(q: jax.Array, k: jax.Array, v: jax.Array,
                      key_bias: Optional[jax.Array],
                      block_q: int) -> jax.Array:
    """q/k/v [N, L, D] (N = B·H), key_bias [N, Lk] additive or None."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    N, Lq, D = q.shape
    Lk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    block_q = min(block_q, Lq)
    nq = -(-Lq // block_q)
    pad_q = nq * block_q - Lq
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0)))
    if key_bias is None:
        key_bias = jnp.zeros((N, Lk), jnp.float32)
    key_bias = key_bias.reshape(N, 1, Lk).astype(jnp.float32)

    def kernel(q_ref, k_ref, v_ref, b_ref, o_ref):
        qb = q_ref[0]                                   # [block_q, D]
        s = jax.lax.dot_general(
            qb, k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [block_q, Lk]
        s = s + b_ref[0]
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
        ctx = jnp.dot(p.astype(v_ref.dtype), v_ref[0],
                      preferred_element_type=jnp.float32)
        o_ref[0] = (ctx / l).astype(o_ref.dtype)

    out = pl.pallas_call(
        kernel,
        grid=(N, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda n, i: (n, i, 0)),
            pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
            pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0)),
            pl.BlockSpec((1, 1, Lk), lambda n, i: (n, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda n, i: (n, i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, nq * block_q, D), q.dtype),
        interpret=(jax.default_backend() != "tpu"),
    )(q, k, v, key_bias)
    return out[:, :Lq, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _flash_core(q, k, v, key_bias, block_q):
    return _flash_impl(q, k, v, key_bias, block_q)


def _flash_impl(q, k, v, key_bias, block_q):
    B, H, Lq, D = q.shape
    if _use_pallas():
        nq = lambda x: x.reshape(B * H, x.shape[2], x.shape[3])  # noqa: E731
        kb = (jnp.repeat(key_bias, H, axis=0)
              if key_bias is not None else None)
        out = _flash_fwd_pallas(nq(q), nq(k), nq(v), kb, block_q)
        return out.reshape(B, H, Lq, D)
    mask = None
    if key_bias is not None:
        mask = (key_bias > NEG_INF / 2).astype(jnp.int32)[:, None, None, :]
    return blockwise_attention(q, k, v, mask)


def _flash_fwd(q, k, v, key_bias, block_q):
    return _flash_core(q, k, v, key_bias, block_q), (q, k, v, key_bias)


# Backward-policy budget for the DENSE-VJP branch.  The dense backward
# holds ~3 score-shaped fp32 tensors at peak (the saved probabilities
# residual plus the ds/dp transients), so the comparison below multiplies
# scores_bytes by 3.  Measured on v5e (6L d512 transformer, bs=64, L=512):
# full step 95 ms dense-bwd vs 163 ms blockwise-bwd; the blockwise VJP's
# scan recompute only pays off once sequences outgrow this budget.
_DENSE_BWD_BUDGET_BYTES = 2 << 30


def _flash_bwd(block_q, res, g):
    q, k, v, key_bias = res
    mask = None
    if key_bias is not None:
        mask = (key_bias > NEG_INF / 2).astype(jnp.int32)[:, None, None, :]
    B, H, Lq, _ = q.shape
    Lk = k.shape[2]
    scores_bytes = 4 * B * H * Lq * Lk
    if 3 * scores_bytes <= _DENSE_BWD_BUDGET_BYTES:
        _, vjp = jax.vjp(
            lambda q_, k_, v_: dense_attention_reference(q_, k_, v_, mask),
            q, k, v)
    else:
        # long context: recompute-in-backward via the blockwise formulation
        # keeps peak memory O(L*block) at the price of the scan recompute
        _, vjp = jax.vjp(
            lambda q_, k_, v_: blockwise_attention(q_, k_, v_, mask),
            q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_flash_core.defvjp(_flash_fwd, _flash_bwd)


def _auto_block_q(lq: int, lk: int) -> int:
    """Largest q-block in {1024..128} whose fp32 score tile (block_q x Lk)
    stays within ~8 MB of VMEM — measured on v5e @ L=2048 D=64 bf16:
    block_q=1024 runs ~20-25% faster than the 128 default (2.7-2.8 vs
    3.4-3.9 ms), and the budget degrades the block gracefully as the
    context grows (Lk=4096 -> 512, 8192 -> 256, 16384 -> 128)."""
    budget = 8 * 1024 * 1024
    for bq in (1024, 512, 256, 128):
        if bq * lk * 4 <= budget:
            return min(bq, max(lq, 128))
    return 128


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    mask: Optional[jax.Array] = None,
                    block_q: Optional[int] = None) -> jax.Array:
    """Drop-in for dense_attention (models/transformer.py:101-111), minus
    attention-prob dropout (probabilities are never materialized).

    q/k/v: [B, H, L, D].  mask: None or a key-padding mask broadcastable
    to [B, 1, 1, Lk] (mask==0 masked) — full [B,H,Lq,Lk] masks should use
    blockwise_attention directly.  block_q: q-tile rows; None picks the
    largest tile whose score buffer fits VMEM (_auto_block_q).
    """
    if block_q is None:
        block_q = _auto_block_q(q.shape[2], k.shape[2])
    key_bias = None
    if mask is not None:
        kb = jnp.asarray(mask)
        if kb.ndim == 4:                     # [B,1,1,Lk] -> [B,Lk]
            kb = kb.reshape(kb.shape[0], kb.shape[-1])
        kb = jnp.broadcast_to(kb, (q.shape[0], k.shape[2]))
        key_bias = mask_to_bias(kb)
    return _flash_core(q, k, v, key_bias, block_q)
