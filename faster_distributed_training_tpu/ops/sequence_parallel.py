"""Shared shard_map scaffolding for the sequence-parallel attention
strategies (ring — ops/ring_attention.py, Ulysses — ops/ulysses_attention.py).

One wrapper owns the mesh policy both strategies share, so it cannot
drift between them:
  * batch over the data axes (dp and/or fsdp),
  * sequence over `sp_axis`,
  * heads over `tp` when present and divisible — head-parallelism inside
    sequence-parallelism,
  * key-padding mask normalized to a [B, L] keep-mask sharded like the
    sequence.

The per-strategy `body` runs INSIDE shard_map on per-device shards with
signature body(q, k, v, axis_name=..., key_mask=None, causal=False).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def batch_axes(mesh: Mesh) -> Tuple[str, ...]:
    return tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)


def sp_self_attention(body: Callable, q: jax.Array, k: jax.Array,
                      v: jax.Array, mask: Optional[jax.Array], mesh: Mesh,
                      sp_axis: str = "sp", causal: bool = False,
                      heads_per_shard_divisor: int = 1,
                      dropout_rate: float = 0.0,
                      dropout_seed: Optional[jax.Array] = None
                      ) -> jax.Array:
    """Globally-shaped [B,H,L,D] in/out with L sharded over `sp_axis`,
    B over the data axes, H over tp when divisible.

    mask: None, [B, L], or [B,1,1,L] key-padding mask (mask==0 masked).
    heads_per_shard_divisor: extra divisibility the strategy needs from
    the per-device head count (Ulysses splits its local heads over sp
    again, so it passes the sp size; the ring passes 1).
    dropout_rate/dropout_seed: attention-prob hash dropout; the wrapper
    hands each body its GLOBAL [B_loc,H_loc,1,1] batch·head stream index
    (built from the dp/fsdp/tp axis indices) so the drop pattern is
    identical to the single-device one for the same seed."""
    B, H, L, D = q.shape
    batch = batch_axes(mesh)
    lead = batch if len(batch) != 1 else batch[0]
    # head-parallelism inside sequence-parallelism — UNLESS the tp axis
    # IS the sequence axis (a 2D (dp, tp) mesh running ring/ulysses over
    # tp, r11): one mesh axis cannot shard both heads and sequence
    tp = (mesh.shape["tp"]
          if "tp" in mesh.axis_names and sp_axis != "tp" else 1)
    head = ("tp" if tp > 1 and H % tp == 0
            and (H // tp) % heads_per_shard_divisor == 0 else None)
    qkv_spec = P(lead, head, sp_axis, None)
    mask_spec = P(lead, sp_axis)

    key_mask = None
    if mask is not None:
        mask = jnp.asarray(mask)
        if mask.ndim == 4:
            mask = mask.reshape(B, mask.shape[-1])
        key_mask = mask

    b_shards = 1
    for a in batch:
        b_shards *= mesh.shape[a]
    b_loc, h_loc = B // b_shards, H // (tp if head else 1)

    def global_bh():
        """[b_loc, h_loc, 1, 1] global b*H+h for this device's shard."""
        b_idx = jnp.int32(0)
        for a in batch:                      # row-major over the data axes
            b_idx = b_idx * mesh.shape[a] + lax.axis_index(a)
        b0 = b_idx * b_loc
        h0 = lax.axis_index("tp") * h_loc if head else jnp.int32(0)
        return ((b0 + jnp.arange(b_loc, dtype=jnp.int32))[:, None] * H
                + (h0 + jnp.arange(h_loc, dtype=jnp.int32))[None, :]
                )[:, :, None, None]

    fn = partial(body, axis_name=sp_axis, causal=causal)
    has_mask = key_mask is not None
    has_drop = dropout_rate > 0.0

    # build the operand list + specs dynamically: the traced dropout seed
    # enters shard_map as an explicit replicated operand, not a closure
    args, specs = [q, k, v], [qkv_spec] * 3
    if has_mask:
        args.append(key_mask)
        specs.append(mask_spec)
    if has_drop:
        seed = (jnp.uint32(0) if dropout_seed is None
                else dropout_seed.astype(jnp.uint32))
        args.append(seed)
        specs.append(P())

    def call(q_, k_, v_, *rest):
        rest = list(rest)
        kw = {}
        if has_mask:
            kw["key_mask"] = rest.pop(0)
        if has_drop:
            kw.update(dropout_rate=dropout_rate, dropout_seed=rest.pop(0),
                      dropout_bh=global_bh())
        return fn(q_, k_, v_, **kw)

    from faster_distributed_training_tpu.compat import shard_map
    return shard_map(call, mesh=mesh, in_specs=tuple(specs),
                     out_specs=qkv_spec)(*args)
