"""Shared shard_map scaffolding for the sequence-parallel attention
strategies (ring — ops/ring_attention.py, Ulysses — ops/ulysses_attention.py).

One wrapper owns the mesh policy both strategies share, so it cannot
drift between them:
  * batch over the data axes (dp and/or fsdp),
  * sequence over `sp_axis`,
  * heads over `tp` when present and divisible — head-parallelism inside
    sequence-parallelism,
  * key-padding mask normalized to a [B, L] keep-mask sharded like the
    sequence.

The per-strategy `body` runs INSIDE shard_map on per-device shards with
signature body(q, k, v, axis_name=..., key_mask=None, causal=False).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def batch_axes(mesh: Mesh) -> Tuple[str, ...]:
    return tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)


def sp_self_attention(body: Callable, q: jax.Array, k: jax.Array,
                      v: jax.Array, mask: Optional[jax.Array], mesh: Mesh,
                      sp_axis: str = "sp", causal: bool = False,
                      heads_per_shard_divisor: int = 1) -> jax.Array:
    """Globally-shaped [B,H,L,D] in/out with L sharded over `sp_axis`,
    B over the data axes, H over tp when divisible.

    mask: None, [B, L], or [B,1,1,L] key-padding mask (mask==0 masked).
    heads_per_shard_divisor: extra divisibility the strategy needs from
    the per-device head count (Ulysses splits its local heads over sp
    again, so it passes the sp size; the ring passes 1)."""
    B, H, L, D = q.shape
    batch = batch_axes(mesh)
    lead = batch if len(batch) != 1 else batch[0]
    tp = mesh.shape["tp"] if "tp" in mesh.axis_names else 1
    head = ("tp" if tp > 1 and H % tp == 0
            and (H // tp) % heads_per_shard_divisor == 0 else None)
    qkv_spec = P(lead, head, sp_axis, None)
    mask_spec = P(lead, sp_axis)

    key_mask = None
    if mask is not None:
        mask = jnp.asarray(mask)
        if mask.ndim == 4:
            mask = mask.reshape(B, mask.shape[-1])
        key_mask = mask

    fn = partial(body, axis_name=sp_axis, causal=causal)
    if key_mask is None:
        return jax.shard_map(
            lambda q_, k_, v_: fn(q_, k_, v_),
            mesh=mesh, in_specs=(qkv_spec,) * 3,
            out_specs=qkv_spec)(q, k, v)
    return jax.shard_map(
        lambda q_, k_, v_, m_: fn(q_, k_, v_, key_mask=m_),
        mesh=mesh, in_specs=(qkv_spec,) * 3 + (mask_spec,),
        out_specs=qkv_spec)(q, k, v, key_mask)
