"""Single-position attention against a padded KV cache.

The decode-mode transformer (models/decode.py) computes one query
position per sequence per step; keys/values live in the paged cache
(serve/decode/cache.py) whose trailing columns beyond each slot's
current length are garbage.  This op is `models.transformer
.dense_attention` specialized to q-length 1 with the mask built from
per-slot lengths instead of a materialized (B, 1, 1, C) array — same
NEG_INF constant, same fp32 softmax, same einsum contraction order.

Exactness of the padding: a masked column's score is NEG_INF (-1e9),
so after the softmax's max-subtraction its exp underflows to an exact
fp32 0.0 and contributes exact zeros to both the normalizer and the
probs @ v contraction — attention over a C-column cache with k valid
entries computes the same real-column contributions as attention over
exactly k columns.  (Token-for-token greedy parity against the
cacheless forward is pinned by tests/test_decode.py; logits may differ
in final ulps because XLA associates the wider reduction differently.)

No Pallas kernel: decode on the serving tier is bandwidth-bound on
reading the cache, which XLA's stock dot handles; the r15 observatory
accounts the programs either way.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from faster_distributed_training_tpu.models.transformer import NEG_INF


def cached_attention(q: jax.Array, kcache: jax.Array, vcache: jax.Array,
                     lengths: jax.Array) -> jax.Array:
    """One-position attention over the first ``lengths[b]`` cache
    columns of each slot.

    q:       (B, h, 1, d_k)  — the current position's query
    kcache:  (B, h, C, d_k)  — keys, columns >= lengths[b] are garbage
    vcache:  (B, h, C, d_k)
    lengths: (B,) int32      — valid cache entries per slot (INCLUDING
                               the current position, already written)
    returns: (B, h, 1, d_k)
    """
    d_k = q.shape[-1]
    C = kcache.shape[2]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, kcache) / math.sqrt(d_k)
    # (B, 1, 1, C) length mask, the dense_attention `mask == 0` idiom
    valid = (jnp.arange(C, dtype=jnp.int32)[None, :]
             < lengths[:, None].astype(jnp.int32))
    scores = jnp.where(valid[:, None, None, :], scores,
                       jnp.asarray(NEG_INF, scores.dtype))
    probs = jax.nn.softmax(scores.astype(jnp.float32),
                           axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, vcache)
