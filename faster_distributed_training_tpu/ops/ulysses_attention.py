"""Ulysses-style sequence parallelism: all-to-all head/sequence swap.

The second canonical long-context strategy next to ring attention
(ops/ring_attention.py) — the DeepSpeed-Ulysses construction.  The
sequence axis arrives sharded over the mesh's `sp` axis; one
`lax.all_to_all` re-shards the tensors from sequence-split to
HEAD-split, so every device computes ordinary full-length attention for
H/sp of the heads; a second all_to_all swaps back.

Trade-off vs the ring (why both exist):
  * Ulysses moves each Q/K/V/O tensor twice over the interconnect
    regardless of sp, and needs H % sp == 0 — but the inner attention
    is a plain full-L kernel (here: blockwise online-softmax, so the
    L×L matrix is never materialized) with no per-step collective, and
    its communication volume is O(B·H·L·D/sp) per tensor, independent
    of the number of ring steps.
  * The ring keeps K/V moving hop-by-hop (sp ppermutes) and supports
    any sp; its collectives interleave with compute.
The reference has neither (maxlen capped at 512, dense O(L²) on one
device — transformer.py:35,180-193, SURVEY.md §5 long-context).

Gradients flow through `all_to_all` (its transpose is the reverse
all_to_all), so the backward pass is sequence-parallel too.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from faster_distributed_training_tpu.ops.attention import (bh_index,
                                                           blockwise_attention)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str,
                      key_mask: Optional[jax.Array] = None,
                      causal: bool = False,
                      dropout_rate: float = 0.0,
                      dropout_seed: Optional[jax.Array] = None,
                      dropout_bh: Optional[jax.Array] = None) -> jax.Array:
    """Ulysses body — call INSIDE shard_map, sequence sharded on `axis_name`.

    q/k/v: [B, H, L_local, D] (this device's sequence shard); H must be
    divisible by the axis size.  key_mask: [B, L_local] boolean/0-1 key
    keep-mask for this shard's keys (0 = masked), or None.
    Returns [B, H, L_local, D].

    dropout_rate > 0 applies attention-prob hash dropout inside the
    inner blockwise attention.  `dropout_bh` is the caller's global
    [B,H_loc,1,1] batch·head index for the PRE-swap heads; after the
    all_to_all this device holds heads [j·H_loc/sp, (j+1)·H_loc/sp) of
    that range (j = this device's sp index), so the matching slice keeps
    the pattern equal to the dense/flash one for the same seed.
    """
    B, H, L_loc, D = q.shape
    from faster_distributed_training_tpu.compat import axis_size
    sp = axis_size(axis_name)
    if H % sp:
        raise ValueError(f"Ulysses needs heads ({H}) divisible by the "
                         f"sp axis size ({sp}); use ring attention otherwise")

    # seq-sharded [B, H, L/sp, D] -> head-sharded [B, H/sp, L, D]
    def seq_to_head(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_head(q), seq_to_head(k), seq_to_head(v)

    mask4 = None
    if key_mask is not None:
        # every device needs the mask for ALL keys once heads are split;
        # stays [B,1,1,L] — the causal constraint is applied analytically
        # per key block inside blockwise_attention, never as an [L,L] mask
        full = lax.all_gather(key_mask, axis_name, axis=1, tiled=True)
        mask4 = (full != 0)[:, None, None, :]                # [B,1,1,L]

    bh_post = None
    if dropout_rate > 0.0:
        if dropout_bh is None:
            dropout_bh = bh_index(B, H)
        j = lax.axis_index(axis_name)
        h_per = H // sp
        # this device's post-swap head slice of the global index table
        bh_post = lax.dynamic_slice_in_dim(dropout_bh, j * h_per, h_per,
                                           axis=1)

    # full-length attention on H/sp heads; blockwise keeps memory O(L·blk)
    out = blockwise_attention(qh, kh, vh, mask=mask4,
                              block_k=min(512, qh.shape[2]),
                              causal=causal, dropout_rate=dropout_rate,
                              dropout_seed=dropout_seed,
                              dropout_bh=bh_post)

    # head-sharded [B, H/sp, L, D] -> seq-sharded [B, H, L/sp, D]
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def ulysses_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           mask: Optional[jax.Array], mesh: Mesh,
                           sp_axis: str = "sp",
                           causal: bool = False,
                           dropout_rate: float = 0.0,
                           dropout_seed: Optional[jax.Array] = None
                           ) -> jax.Array:
    """shard_map wrapper mirroring ring_self_attention: globally-shaped
    [B,H,L,D] in/out with L sharded over `sp_axis`, B over the data axes,
    heads over tp when H % (tp * sp) == 0 (shared scaffolding:
    ops/sequence_parallel.py — the per-device head count must still split
    over sp inside the body, hence the extra divisor).

    mask: None, [B, L], or [B,1,1,L] key-padding mask (mask==0 masked)."""
    from faster_distributed_training_tpu.ops.sequence_parallel import (
        sp_self_attention)

    sp = mesh.shape[sp_axis] if sp_axis in mesh.axis_names else 1
    return sp_self_attention(ulysses_attention, q, k, v, mask, mesh,
                             sp_axis=sp_axis, causal=causal,
                             heads_per_shard_divisor=sp,
                             dropout_rate=dropout_rate,
                             dropout_seed=dropout_seed)
