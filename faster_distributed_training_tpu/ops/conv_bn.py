"""Fused Conv2D + BatchNorm with recompute-in-backward.

TPU-native re-design of the reference's ``FusedConvBN2DFunction``
(``resnet.py:72-113``): one ``jax.custom_vjp`` primitive whose forward
saves only ``(X, W, sum, sqrt_var)`` and whose backward *recomputes* the
convolution output before applying a hand-derived BatchNorm backward and
the convolution transpose — the same activation-rematerialization memory
trick as the reference (``resnet.py:107-108``), expressed so XLA fuses
the normalize into the conv epilogue on the MXU.

Semantics matched to the reference:
  * BN has no affine γ/β (``resnet.py:85-99``),
  * variance is the *unbiased* estimator (``resnet.py:86``),
  * eps is added to the *standard deviation*, not the variance
    (``denom = sqrt_var + eps``, ``resnet.py:94``), default 1e-3.

Why there is no Pallas kernel here (a deliberate decision, unlike
``ops/flash_attention.py`` / ``fused_mlp_pallas``): the convolution is a
single XLA HLO that the TPU conv emitter tiles onto the MXU, and the BN
normalize is an elementwise chain XLA fuses into that conv's epilogue —
there is no leftover fusion for a hand-written kernel to claim, only the
risk of losing the emitter's layout/pipelining.  The fused-kernel value
on this path is the *backward recompute policy* below, which is a
differentiation-level decision, not a kernel-level one.

Differences (deliberate, documented per SURVEY.md §7 "bugs to fix"):
  * layout is NHWC / HWIO (TPU-native) instead of NCHW / OIHW;
  * any stride is supported (reference asserts stride == 1,
    ``resnet.py:120``);
  * the op also returns ``(mean, var)`` so callers can maintain running
    statistics for deterministic eval — the reference uses batch stats
    at eval time (SURVEY.md §7 hard part 2);
  * under ``pjit`` with the batch sharded over a mesh axis, the
    channel reductions are *global* means/vars — i.e. cross-replica
    SyncBN falls out of the SPMD partitioner for free, unlike the
    reference's per-GPU batch stats.
"""

from __future__ import annotations

import functools
from typing import Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

Padding = Union[str, int, Tuple[Tuple[int, int], Tuple[int, int]]]


def _norm_padding(padding: Padding):
    if isinstance(padding, int):
        return ((padding, padding), (padding, padding))
    return padding


def conv2d(x: jax.Array, w: jax.Array, stride: int = 1,
           padding: Padding = 1) -> jax.Array:
    """Plain NHWC conv with HWIO kernel (maps straight onto the MXU)."""
    return lax.conv_general_dilated(
        x, w,
        window_strides=(stride, stride),
        padding=_norm_padding(padding),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _stats_dtype(dtype) -> jnp.dtype:
    """bf16/fp16 statistics are numerically unsafe — promote to at least fp32."""
    return jnp.promote_types(dtype, jnp.float32)


def _bn_stats(y: jax.Array) -> Tuple[jax.Array, jax.Array, float]:
    """(mean, unbiased var, N) over all axes but channel (last), in fp32+.

    Single pass over y (E[y²] − E[y]² instead of a second centered pass):
    one HBM read fewer in the bandwidth-bound train step.  fp32
    accumulation keeps the cancellation benign for BN-scale activations;
    the max(., 0) guards the subtraction's round-off."""
    n = y.size // y.shape[-1]
    y = y.astype(_stats_dtype(y.dtype))
    mean = jnp.mean(y, axis=(0, 1, 2))
    mean_sq = jnp.mean(jnp.square(y), axis=(0, 1, 2))
    # unbiased estimator, matching torch's X.var(unbiased=True) (resnet.py:86)
    var = jnp.maximum(mean_sq - jnp.square(mean), 0.0) * (n / (n - 1))
    return mean, var, n


def _conv_bn_forward(x, w, stride, padding, eps):
    """Shared forward: conv -> batch stats -> normalize.
    Returns (out, y, mean, var) — THE single definition of the numerics."""
    y = conv2d(x, w, stride, padding)
    mean, var, _ = _bn_stats(y)
    out = ((y.astype(mean.dtype) - mean)
           / (jnp.sqrt(var) + eps)).astype(y.dtype)
    return out, y, mean, var


def conv_bn_reference(x: jax.Array, w: jax.Array, stride: int = 1,
                      padding: Padding = 1, eps: float = 1e-3) -> jax.Array:
    """Unfused conv+BN — the autodiff oracle the fused kernel is tested against."""
    return _conv_bn_forward(x, w, stride, padding, eps)[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def fused_conv_bn(x: jax.Array, w: jax.Array, stride: int = 1,
                  padding: Padding = 1, eps: float = 1e-3):
    """Fused conv+BN. Returns ``(out, mean, var)``; ``mean``/``var`` are
    per-channel batch statistics for the caller's running-stat update."""
    out, _, mean, var = _conv_bn_forward(x, w, stride, padding, eps)
    return out, mean, var


def _fused_fwd(x, w, stride, padding, eps):
    out, _, mean, var = _conv_bn_forward(x, w, stride, padding, eps)
    sqrt_var = jnp.sqrt(var)
    # Save only (X, W, mean, sqrt_var) — NOT the conv output y, which is the
    # big NHWC buffer. Backward recomputes it (resnet.py:107-108 parity).
    return (out, mean, var), (x, w, mean, sqrt_var)


def _fused_bwd(stride, padding, eps, res, cts):
    x, w, mean, sqrt_var = res
    g, _, _ = cts  # cotangents for (out, mean, var); stats are stats-only outputs

    # (1) recompute the conv output — the rematerialization step, done through
    # jax.vjp so the same computation also yields the conv transpose closure.
    y, conv_vjp = jax.vjp(lambda x_, w_: conv2d(x_, w_, stride, padding), x, w)

    # (2) hand-derived BatchNorm backward (matches batch_norm_backward,
    # resnet.py:37-69, rewritten vectorized over NHWC), in fp32+:
    #   out_i = (y_i - mu) / s,   s = sqrt(var) + eps,  var unbiased over n.
    n = y.size // y.shape[-1]
    sd = mean.dtype
    y32, g32 = y.astype(sd), g.astype(sd)
    s = sqrt_var + eps
    centered = y32 - mean
    g_sum = jnp.sum(g32, axis=(0, 1, 2))
    # d var: through s = sqrt(var)+eps; note sum_i centered_i = 0 kills the
    # mean-path inside var.
    d_s = -jnp.sum(g32 * centered, axis=(0, 1, 2)) / (s * s)
    # guard: a (near-)constant or cancellation-collapsed channel has
    # sqrt_var == 0; its centered values are ~0 so the d_var term should
    # vanish, not blow up to inf
    d_var = d_s / (2.0 * jnp.maximum(sqrt_var, 1e-12))
    dy = g32 / s + centered * (2.0 * d_var / (n - 1)) - g_sum / (s * n)

    # (3) conv backward through the recomputed vjp.
    dx, dw = conv_vjp(dy.astype(y.dtype))
    return dx, dw


fused_conv_bn.defvjp(_fused_fwd, _fused_bwd)


def conv_bn_train(x: jax.Array, w: jax.Array, stride: int = 1,
                  padding: Padding = 1, eps: float = 1e-3,
                  remat: bool = True):
    """Training-mode fused conv+BN returning ``(out, mean, var)``.

    remat=True (default) uses the custom_vjp kernel above: backward
    recomputes the conv output — the reference's memory trick, which on
    TPU is ALSO the faster path (v5e @ bs=1024: 3650 vs 3443 img/s/chip)
    because the train step is HBM-bandwidth-bound and recomputing the
    activation on the MXU beats re-reading it from HBM.  remat=False
    leaves differentiation to autodiff (saves the conv output).
    Identical forward numerics; gradients agree except at the
    degenerate var==0 clamp edge, where autodiff zeroes the var path
    and the hand-written backward bounds it (tests/test_ops.py)."""
    if remat:
        return fused_conv_bn(x, w, stride, padding, eps)
    out, _, mean, var = _conv_bn_forward(x, w, stride, padding, eps)
    return out, mean, var
