"""Unified configuration surface for every entry point.

The reference duplicates an argparse block per script (resnet50_test.py:46-59,
transformer_test.py:350-361, tuning/resnet50_tuning.py:33-50).  Here there is
ONE flag surface shared by all entries, preserving the reference's flag names
(--bs, --lr, --epoch, --alpha, --workers, --meta_learning, --distributed,
--ngd, --resume) and adding the TPU-specific ones (--device, mesh shape,
precision policy).
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass
class TrainConfig:
    """Everything a training run needs, in one picklable record."""

    # -- workload ---------------------------------------------------------
    model: str = "resnet50"           # resnet18/34/50/101/152 | transformer
    dataset: str = "cifar10"          # cifar10 | agnews | synthetic |
                                      # stream (a sharded on-disk dataset
                                      # under --stream_dir, data/stream/)
    num_classes: int = 10
    task: str = "cls"                 # cls | lm: the training objective.
                                      # "lm" (transformer only) = next-
                                      # token prediction — per-position
                                      # vocab logits (lm_head), shifted-
                                      # target token cross-entropy,
                                      # perplexity metric; no mixup/
                                      # pooler.  The streamed text
                                      # workload's objective (r18)
    tie_lm_head: bool = True          # tie the LM head to token_embedding
                                      # (logits = h @ E^T): ~vocab*d_model
                                      # fewer params, the vocab-sharding
                                      # TP rule serves the head for free.
                                      # --untie_lm_head restores the r18
                                      # separate projection; untied
                                      # checkpoints restore into tied
                                      # models via a warned compat shim
                                      # (train/checkpoint.py)
    lm_causal: bool = False           # --task lm: apply the causal mask
                                      # at TRAINING time so the trained
                                      # conditional matches the mask
                                      # decode serving imposes (closes
                                      # the r21 train/decode mismatch;
                                      # resolve_attention routes it to
                                      # the dense impl — flash is key-
                                      # padding-only)
    pp_microbatches: int = 0          # M on a pp>1 mesh: microbatches
                                      # per step through the staged
                                      # encoder (parallel/pipeline.py).
                                      # 0 = auto (largest divisor of the
                                      # batch in [S, 2S] — 2S halves the
                                      # bubble vs M=S); must divide
                                      # --batch_size when set
    pp_schedule: str = "1f1b"         # 1f1b (contiguous stages) |
                                      # interleaved (round-robin layer
                                      # chunks, v=2; needs L % 2S == 0,
                                      # else contiguous fallback) — the
                                      # tick loop always traverses the
                                      # chunks in DEPTH order, so both
                                      # schedules compute the pp=1
                                      # function (pipeline.py)
    pp_residency: bool = True         # shard stage-owned params (and,
                                      # via the ZeRO overlay, their
                                      # opt-state mirrors) over pp so
                                      # per-chip HBM scales ~1/S with
                                      # pipeline depth (sharding.py
                                      # pp_residency_specs);
                                      # --no_pp_residency restores the
                                      # r22 replicated-over-pp layout

    # -- optimization (reference flag surface) ----------------------------
    lr: float = 0.1
    batch_size: int = 512             # --bs
    epochs: int = 30                  # --epoch
    alpha: float = 0.2                # mixup Beta(alpha, alpha)
    workers: int = 4
    meta_learning: bool = False       # learnable per-sample mixup lambda
    mixup_mode: str = ""              # "" auto | static | intra | meta | attn | none
    use_ngd: bool = False             # --ngd
    resume: bool = False
    distributed: bool = False
    weight_decay: float = 1e-4        # tuning/resnet50_tuning.py:47
    gamma: float = 0.2                # LR decay factor (tuning flag)
    momentum: float = 0.9
    clip_norm: float = 10.0           # resnet50_test.py:546
    label_smoothing: float = 0.0
    optimizer: str = ""               # "" = auto (ngd if use_ngd else madgrad)
    schedule: str = ""                # "" = auto per reference pairing

    # -- NGD hyperparameters (ngd_optimizer.py:9-15 hard-codes these) -----
    ngd_rank: int = -1                # -1 = auto: min((dim+1)//2, 80) per axis
    ngd_update_period: int = 4
    ngd_alpha: float = 4.0
    ngd_eta: float = 0.1
    ngd_max_dim: int = 8192           # skip Fisher preconditioning on axes
                                      # larger than this (vocab-sized
                                      # embedding axes stall training;
                                      # optim/ngd.py NGDHyperParams.max_dim)

    # -- precision --------------------------------------------------------
    precision: str = "bf16"           # bf16 | fp32 | fp16 (fp16 uses loss scaling)
    quant: str = "none"               # none | int8 | fp8: quantized-training
                                      # mode for the transformer's hot GEMMs
                                      # (attention q/k/v/out projections +
                                      # both FFN matmuls): forward GEMMs run
                                      # at int8 (s32 accumulation) or fp8
                                      # E4M3 (fp32 accumulation) with
                                      # per-tensor DELAYED scaling — amax
                                      # histories ride the batch_stats
                                      # collection through the fused-
                                      # dispatch carry/checkpoints, so K-
                                      # dispatch and kill-at-N resume stay
                                      # bitwise (ops/quant.py,
                                      # train.amp.QuantPolicy).  Kill
                                      # switch: FDT_QUANT=0 (plain matmuls,
                                      # same state tree).  tp meshes run
                                      # the quant kernel PER-SHARD on the
                                      # Megatron column/row tiles through
                                      # the r19 shard_map layer (parallel/
                                      # kernel_shard.py); off-TPU backends
                                      # and the FDT_KERNEL_SHARD=0 /
                                      # non-dividing-shape fallbacks use
                                      # the XLA reference path (warned)
    quant_grad: str = "none"          # none | fp8_e5m2: quantize the
                                      # backward cotangents to the wide-
                                      # range E5M2 grid (JIT per-tensor
                                      # scale) and run BOTH gradient GEMMs
                                      # on quantized operands — the FP8-LM
                                      # recipe's gradient half (requires
                                      # --quant int8/fp8; ops/quant.py
                                      # _quant_dot_bwd)

    # -- device / mesh ----------------------------------------------------
    device: str = "auto"              # tpu | cpu | auto
    mesh_shape: Tuple[int, ...] = ()  # () = auto: all devices on the dp axis
    mesh_axes: Tuple[str, ...] = ("dp",)
    fsdp: bool = False                # shard params/opt state over the dp axis
    zero1: bool = False               # shard ONLY optimizer state over the
                                      # data axes (ZeroRedundancyOptimizer
                                      # analog, transformer_test.py:4,221-222)
    host_offload: bool = False        # FSDP param offload to host memory
    zero_opt: bool = True             # ZeRO over tp: shape-aware sharding of
                                      # the FULL optimizer state wherever the
                                      # mesh has a tp axis (sharding.py
                                      # OPT_STATE_RULES); --no_zero_opt
                                      # restores the r15 replicated layout
                                      # (the interchange/twin baseline)
    offload_opt_state: bool = False   # park the big (cold) opt-state leaves
                                      # in pinned host memory and stream them
                                      # through the update — the reference's
                                      # FSDP+CPUOffload row without also
                                      # offloading params (sharding.py
                                      # offload_opt_leaf selects the tier)
    overlap_grad_reduce: bool = False # bucketed gradient reduce-scatter
                                      # expressed inside the K-dispatch scan
                                      # so microbatch i's collective hides
                                      # under i+1's compute.  Value-identity
                                      # reshard; off by default because the
                                      # reduce order may shift float bits
                                      # (the bitwise pins compare flag-off)
    overlap_bucket_mb: int = 4        # bucket size for --overlap_grad_reduce
                                      # (DDP's 25 MB default scaled to TPU
                                      # slice interconnect latency)
    remat: bool = False               # jax.checkpoint the model blocks
    remat_policy: str = "attn_out"    # transformer --remat granularity.
                                      # attn_out (default): whole-layer
                                      # remat but the attention context is
                                      # SAVED so the kernel never re-runs —
                                      # measured bs256/seq512: 941 ex/s @
                                      # 4.9 GB vs layer 560 @ 4.1, ffn
                                      # 1074 @ 10.7, dots 838 @ 8.0, none
                                      # ~1080 @ 15.7.  Also: ffn | layer |
                                      # dots
    donate: bool = True               # donate the train state into the step
                                      # (in-place update; disable on backends
                                      # with donated-buffer dealloc bugs)

    # -- data -------------------------------------------------------------
    data_dir: str = "./data"
    subset_stride: int = 1            # tuning harness uses 10
    seq_len: int = 512                # transformer max length
    seq_buckets: Tuple[int, ...] = (64, 128, 256, 512)
    prefetch_depth: int = 2
    data_path: str = "host"           # host | resident | stream:
                                      # "resident" uploads the train split
                                      # to device once (uint8 images /
                                      # int32 token ids) and gathers each
                                      # batch inside the jitted dispatch
                                      # (data/device_resident.py); works
                                      # single-host (replicated) AND on
                                      # pods (per-host sharded — see
                                      # resident_layout).  "stream" (r18)
                                      # keeps the split ON DISK in the
                                      # sharded stream format (requires
                                      # --dataset stream + --stream_dir)
                                      # and trains through a fixed device
                                      # window refilled by a background
                                      # double-buffered H2D thread — the
                                      # beyond-HBM tier (data/stream/)
    stream_dir: str = ""              # root of a sharded stream dataset
                                      # (train/ + test/ subdirs, each with
                                      # manifest.json + shard_*.npy —
                                      # scripts/shard_dataset.py writes
                                      # one); required by
                                      # --dataset/--data_path stream
    stream_window: int = 8            # batches per stream buffer (two
                                      # buffers double-buffer; a third is
                                      # transiently in flight in the
                                      # refill thread).  Rounded UP to a
                                      # multiple of --steps_per_dispatch
                                      # so buffer boundaries stay
                                      # dispatch-aligned (warned)
    resident_layout: str = "auto"     # auto | replicated | sharded: how the
                                      # resident split is placed.  auto =
                                      # replicated on one host (the r8
                                      # layout, unchanged), per-host sharded
                                      # on pods (each process uploads only
                                      # its row shard; one jitted re-shard
                                      # per epoch builds the batch-major
                                      # view, so steady-state gathers are
                                      # local-HBM dynamic_index reads).
                                      # "sharded" forces the sharded layout
                                      # even single-host (spreads the split
                                      # over local chips); "replicated"
                                      # multi-host falls back to the host
                                      # path with a warning
    steps_per_dispatch: int = 1       # K: train steps fused into one device
                                      # dispatch via lax.scan (steps.py
                                      # make_fused_train_step); 1 = today's
                                      # one-dispatch-per-step loop.
                                      # checkpoint/preemption cadence
                                      # quantizes to dispatch boundaries
                                      # (checkpoint_every rounds UP to a
                                      # multiple of K, warned)

    # -- transformer architecture (reference defaults, transformer.py:12-35)
    n_layers: int = 6
    d_model: int = 512
    d_ff: int = 1024
    n_heads: int = 8
    attention: str = ""               # "" auto | dense | flash | ring | ulysses
    mlp_impl: str = ""                # "" auto (pallas on TPU) | fused | pallas
    ffn_impl: str = "flax"            # flax | pallas: fused LN+FFN+dropout+
                                      # residual sublayer kernel
                                      # (ops/fused_ffn.py) — a capacity
                                      # lever (zero FFN-shaped backward
                                      # residuals); see PARITY for the
                                      # measured time trade
    dropout_impl: str = "hash"        # hash (stateless index-hash masks,
                                      # seed-only backward residual, bit-
                                      # reproducible AND fastest measured —
                                      # ops/dropout.py) | xla (flax
                                      # nn.Dropout) | none (floor probes)
    dropout_rng_impl: str = "threefry"  # PRNG for the xla dropout impl:
                                      # threefry (bit-reproducible masks,
                                      # the default — ADVICE r3 #2) | rbg
                                      # (hardware-RNG path, faster mask
                                      # GENERATION but backend-dependent
                                      # bits; superseded by dropout_impl=
                                      # hash, which is faster than both)

    # -- bag-of-tricks ablation (reference README.md:63: ~2.5x end-to-end
    # from AMP + kernel fusion + prefetch + distributed) -------------------
    tricks: str = "on"                # on | off.  "off" disables EVERY
                                      # speed lever at once: bf16->fp32,
                                      # flash->dense attention, Pallas/
                                      # fused MLP->naive, fused QKV->3
                                      # Linears, conv recompute->autodiff,
                                      # hash dropout->threefry nn.Dropout,
                                      # prefetch/workers->synchronous.
                                      # resolve_tricks() applies it.

    # -- bookkeeping ------------------------------------------------------
    seed: int = 123456                # resnet50_test.py:728
    checkpoint_dir: str = "./checkpoint"
    log_every: int = 50               # live loss/acc/ex-s line every N steps
                                      # (tqdm-descriptor observability,
                                      # resnet50_test.py:560-566, at 1/N the
                                      # sync cost; 0 disables)
    profile: bool = False
    profile_steps: str = ""           # "A:B": start/stop jax.profiler
                                      # around global train steps A..B
                                      # (1-indexed, inclusive) MID-RUN —
                                      # the whole-run --profile is
                                      # unusable past toy scale.  Trace
                                      # lands under the telemetry dir
                                      # (utils/profiling.py
                                      # StepWindowProfiler)
    plot: bool = True

    # -- telemetry (telemetry/ package; on by default, <1% guarded) -------
    telemetry: bool = True            # per-dispatch JSONL records + run
                                      # manifest + span breakdown under
                                      # <checkpoint_dir>/telemetry (or
                                      # --telemetry_dir); process 0 folds
                                      # per-host files into pod p50/p95/
                                      # p99 + straggler flags per epoch.
                                      # Kill switches: --no_telemetry,
                                      # FDT_TELEMETRY=0; overhead guarded
                                      # <1% by bench telemetry_overhead_pct
    telemetry_dir: str = ""           # "" = <checkpoint_dir>/telemetry
                                      # (pods share it like the ckpt fs —
                                      # the aggregation transport needs a
                                      # shared directory)
    straggler_ratio: float = 2.0      # flag a host whose per-step p95
                                      # exceeds this multiple of the pod
                                      # median host-p95 (the [telemetry]
                                      # straggler line)
    aggregate_grace_s: float = 2.0    # how long process 0 waits at an
                                      # epoch boundary for the peers'
                                      # telemetry epoch markers before
                                      # folding without them (was a
                                      # hard-coded 2 s — slow CI hosts
                                      # raced it); skipped hosts are
                                      # recorded in pod_summary.json
                                      # (hosts_missing) either way
    telemetry_every: int = 1          # record every Nth dispatch (compile-
                                      # marked firsts always recorded).  The
                                      # r12 note flags per-dispatch
                                      # time.monotonic pressure under async
                                      # dispatch as the first suspect if
                                      # telemetry_overhead_pct fails on live
                                      # TPU — this knob is the landed
                                      # mitigation (sampled records keep
                                      # their true step numbers)

    # -- failure detection / debugging ------------------------------------
    # The reference has neither (SURVEY.md §5: recovery = manual re-launch
    # with --resume; its NGD NaN guard + never-enabled _self_test are the
    # nearest analogs).  Both are deliberate do-better additions.
    auto_recover: bool = False        # non-finite epoch loss -> restore the
                                      # last good checkpoint and continue
    max_recoveries: int = 2           # consecutive restores before giving up
    debug: bool = False               # per-epoch NGD Fisher invariant checks
                                      # (the reference's debug flag,
                                      # ngd_optimizer.py:46, which it never
                                      # turns on)
    sentinel: str = "none"            # anomaly sentinel
                                      # (resilience/sentinel.py):
                                      # "none" = off (programs stay
                                      # byte-identical to the unguarded
                                      # build); "guard" = in-graph bad-step
                                      # guard only (one fused non-finite
                                      # check over loss + global grad norm;
                                      # a poisoned step leaves params/
                                      # opt-state/RNG bitwise-untouched and
                                      # is counted as skipped_steps);
                                      # "full" = guard + host-side
                                      # loss-spike detector with rollback-
                                      # and-quarantine (needs --supervise
                                      # + --checkpoint_every for the
                                      # rollback half — warned otherwise)
    spike_window: int = 32            # sentinel "full": trailing window of
                                      # per-dispatch losses the median/MAD
                                      # spike statistic is computed over
    spike_threshold: float = 8.0      # sentinel "full": a dispatch loss
                                      # more than this many MADs above the
                                      # window median is a spike (rollback
                                      # + quarantine of the dispatch's
                                      # global-batch indices)

    # -- resilience (resilience/ package; all off by default) --------------
    checkpoint_every: int = 0         # async step-cadence checkpoints every
                                      # N train steps (0 = epoch-level only)
    checkpoint_every_secs: float = 0.0  # ... and/or every S seconds of wall
                                      # clock, whichever fires first
    checkpoint_keep: int = 3          # keep-last-K retention for the
                                      # step-cadence checkpoints
    checkpoint_async: bool = True     # off-critical-path saves (snapshot on
                                      # the step thread, serialize + commit
                                      # in the background); forced sync for
                                      # multi-host runs (collective save)
    supervise: bool = False           # wrap the train loop in the bounded-
                                      # retry supervisor: on a crash, restore
                                      # the newest valid checkpoint and
                                      # continue (resilience/supervisor.py)
    max_restarts: int = 3             # supervisor restart budget
    preempt_sync_every: int = 8       # steps between cross-host preemption
                                      # agreement collectives (multi-host
                                      # only; bounds SIGTERM-to-save latency
                                      # vs per-step allgather cost).  The
                                      # pod coordinator polls peer FAIL
                                      # markers at the same cadence
    peer_timeout_s: float = 60.0      # pod health watchdog: a peer whose
                                      # heartbeat file is older than this is
                                      # presumed dead and the pod restarts
                                      # together (resilience/coordinator.py;
                                      # active with --supervise on a pod)
    step_timeout_s: float = 0.0       # local step watchdog (requires
                                      # --supervise — warned otherwise): no
                                      # completed
                                      # dispatch for this many seconds means
                                      # this host is wedged (hung device
                                      # program / collective blocked on a
                                      # dead peer) — the watchdog thread
                                      # writes its FAIL marker and hard-
                                      # aborts so the pod converges on a
                                      # restart.  0 = off (default: it must
                                      # exceed the worst-case dispatch
                                      # (re)compile, which only the operator
                                      # knows)
    storage_backend: str = "posix"    # durable-write medium for the
                                      # resilience stack (markers, sharded
                                      # checkpoints, retention): "posix"
                                      # (shared fs, today's semantics),
                                      # "fake_object_store" (rename-free
                                      # object semantics under
                                      # <checkpoint_dir>/_objects — the GCS
                                      # stand-in), or "gs://bucket[/prefix]"
                                      # (resilience/storage.py)
    readmit_timeout_s: float = 60.0   # slice-granular elastic recovery
                                      # (multi-slice pods, FDT_SLICE_COUNT):
                                      # how long surviving slices hold at a
                                      # dispatch boundary for a failed
                                      # slice's restart + rejoin before
                                      # falling back to a whole-pod restart.
                                      # 0 = disable re-admission (every
                                      # failure restarts the whole pod, the
                                      # r10 behavior)
    commit_timeout_s: float = 0.0     # sharded-checkpoint commit-barrier
                                      # timeout.  0 = auto: tied to
                                      # O(peer_timeout_s) (max(2x, 10s))
                                      # whenever the pod coordinator is
                                      # armed — a 600s barrier that
                                      # outlives peer detection turns
                                      # every re-admission hold into a
                                      # pod_fallback_restart (r14 follow-
                                      # on) — else the historic 600s.
                                      # User values that invert the
                                      # ordering (below peer_timeout_s, or
                                      # above readmit_timeout_s) warn
    executable_cache: str = ""        # persistent EXECUTABLE cache
                                      # (resilience/executable_cache.py):
                                      # "" = off, "on" =
                                      # <checkpoint_dir>/_exec_cache
                                      # through the storage backend, else
                                      # an explicit directory.  A
                                      # restarted/rejoining process
                                      # deserializes its compiled (train,
                                      # eval, reshard, serve-predict)
                                      # programs instead of recompiling
                                      # (cache_source=deserialized in the
                                      # manifest compile table); keyed by
                                      # HLO fingerprint + jax/jaxlib +
                                      # device kind + mesh; corrupt
                                      # entries degrade to plain compile.
                                      # Env seam: FDT_EXEC_CACHE (0=off)
    warm_spares: int = 0              # launcher-side contract (r17): how
                                      # many STANDBY spare processes to
                                      # launch beside the pod, each with
                                      # FDT_SLICE_SPARE=<id> (and an out-
                                      # of-pod FDT_POD_INDEX).  A spare
                                      # pre-admits — mesh built, programs
                                      # warmed via the executable cache,
                                      # params restored to the last COMMIT
                                      # and refreshed at each new one —
                                      # and claims a failed slice's seat
                                      # at re-admission time (CLAIM
                                      # marker, first writer wins).  The
                                      # training process itself reads
                                      # FDT_SLICE_SPARE, not this flag

    # -- serving (serve/ package; cli.run_serving) -------------------------
    serve_replicas: int = 0           # inference replicas: 0 = auto (one
                                      # per local chip under the
                                      # replicated-per-chip layout; forced
                                      # to 1 model-sharded group when the
                                      # mesh has a model axis — SNIPPETS
                                      # [3]: 1D is essentially always
                                      # faster for inference, so shard the
                                      # model only when it doesn't fit)
    serve_batch_size: int = 8         # compiled batch dimension every
                                      # dispatch cell pads to
    serve_max_delay_ms: float = 20.0  # continuous-batching deadline: how
                                      # long a partial batch waits for
                                      # company before flushing with
                                      # masked pad rows — THE latency/
                                      # throughput trade-off knob (raise
                                      # for fuller batches, lower for
                                      # tail latency)
    serve_heartbeat_timeout_s: float = 5.0  # a replica silent past this
                                      # is detached and its work re-
                                      # dispatched (r10 heartbeat idiom
                                      # at request scope; must exceed the
                                      # worst single predict — engines
                                      # are warmed up so that excludes
                                      # compiles)
    serve_readmit_s: float = 0.0      # auto re-admit a detached replica
                                      # after this many seconds (0 =
                                      # manual readmit() only)
    serve_requests: int = 64          # built-in synthetic request count
                                      # for the CLI serve smoke

    # -- decode serving (serve/decode/; cli.run_decode_serving) ------------
    decode_batch_size: int = 4        # cache SLOTS per replica — the
                                      # decode-step batch dimension a
                                      # mid-stream admission swaps into
    decode_page: int = 16             # KV-cache page size (tokens): the
                                      # attention-window quantum — live
                                      # length picks ceil(len/page)
                                      # pages, so the decode program set
                                      # is one program per page count,
                                      # not per length
    decode_max_pages: int = 0         # cache capacity in pages per slot:
                                      # 0 = auto (largest prompt bucket
                                      # plus one page of generation
                                      # headroom, capped at the position
                                      # table)
    decode_max_new_tokens: int = 32   # per-request generation budget cap
                                      # (a request's own max_new is
                                      # honored up to this)
    decode_sample: str = "greedy"     # "greedy" | "topk" — STATIC, baked
                                      # into the compiled program set
    decode_temperature: float = 1.0   # topk softmax temperature
    decode_top_k: int = 40            # topk truncation (<=0 = full vocab)
    decode_replicas: int = 0          # decode replicas: 0 = auto (one per
                                      # local chip; 1 model-sharded group
                                      # when the mesh has a model axis —
                                      # same SNIPPETS [3] rule as
                                      # serve_replicas)
    decode_requests: int = 16         # built-in synthetic prompt count
                                      # for the CLI decode smoke
    decode_deadline_s: float = 120.0  # decode front door: per-request
                                      # wall deadline (assembly to
                                      # completion, all retries
                                      # included) — a request stranded
                                      # by dying worker processes fails
                                      # with TimeoutError after this
                                      # instead of waiting forever;
                                      # <=0 disables

    def replace(self, **kw) -> "TrainConfig":
        return dataclasses.replace(self, **kw)


def resolve_tricks(cfg: "TrainConfig") -> "TrainConfig":
    """Apply the bag-of-tricks switch: tricks="off" rewrites every
    speed-lever field to its naive setting (the ablation baseline the
    reference's headline ~2.5x figure is measured against,
    /root/reference/README.md:63).  Model-level levers without a config
    field (fused QKV, conv recompute) are read off cfg.tricks by
    cli.build_model."""
    if cfg.tricks != "off":
        return cfg
    return cfg.replace(
        precision="fp32",
        quant="none",
        quant_grad="none",
        attention="dense",
        mlp_impl="naive",
        dropout_impl="xla",
        dropout_rng_impl="threefry",
        prefetch_depth=0,
        workers=0,
    )


def build_parser(prog: str = "fdt",
                 defaults: Optional[TrainConfig] = None
                 ) -> argparse.ArgumentParser:
    """One argparse surface; flag names match the reference CLI.  Flag
    defaults come from `defaults` so each entry point's TrainConfig record
    (e.g. transformer lr=5e-5) survives unless overridden on the CLI."""
    p = argparse.ArgumentParser(prog=prog, description=__doc__)
    d = defaults or TrainConfig()
    p.add_argument("--lr", default=d.lr, type=float, help="learning rate")
    p.add_argument("--resume", "-r", action="store_true", help="resume from checkpoint")
    p.add_argument("--epoch", default=d.epochs, type=int, help="number of epochs")
    p.add_argument("--alpha", default=d.alpha, type=float, help="mixup Beta parameter")
    p.add_argument("--bs", "--batch_size", "-b", dest="bs", default=d.batch_size,
                   type=int, help="global batch size")
    p.add_argument("--workers", default=d.workers, type=int, help="data loader workers")
    p.add_argument("--meta_learning", action="store_true",
                   help="learnable per-sample mixup lambda")
    p.add_argument("--mixup_mode", default=d.mixup_mode,
                   choices=["", "static", "intra", "meta", "attn", "none"],
                   help="mixup variant ('' auto: meta when --meta_learning, "
                        "static when alpha != 0, else none; attn = learnable "
                        "per-pixel map, resnet50_test.py:404-424; intra = "
                        "same-class-only static)")
    p.add_argument("--distributed", action="store_true", help="multi-host run")
    p.add_argument("--ngd", action="store_true", help="natural gradient descent")
    p.add_argument("--weight_decay", default=d.weight_decay, type=float)
    p.add_argument("--gamma", default=d.gamma, type=float, help="LR decay factor")
    p.add_argument("--model", default=None, type=str)
    p.add_argument("--optimizer", default=d.optimizer, type=str,
                   help="override: sgd|madgrad|mirror_madgrad|ngd|adamw")
    p.add_argument("--schedule", default=d.schedule,
                   choices=["", "multistep", "cosine", "onecycle", "step",
                            "constant"],
                   help="LR schedule override ('' = the reference pairing "
                        "for the chosen optimizer)")
    p.add_argument("--ngd_max_dim", default=d.ngd_max_dim, type=int,
                   help="skip NGD Fisher preconditioning on tensor axes "
                        "larger than this (vocab-sized embedding axes "
                        "violate the dense-gradient assumption)")
    p.add_argument("--device", default=d.device, choices=["auto", "tpu", "cpu"])
    p.add_argument("--precision", default=d.precision, choices=["bf16", "fp32", "fp16"])
    p.add_argument("--quant", default=d.quant,
                   choices=["none", "int8", "fp8"],
                   help="quantized-training mode (transformer): forward "
                        "GEMMs of the attention projections + FFN at int8 "
                        "(s32 accumulation) or fp8 E4M3 (fp32 accumulation) "
                        "with per-tensor delayed scaling; scale state rides "
                        "the train-state carry so K-dispatch/resume stay "
                        "bitwise.  FDT_QUANT=0 kills it; tp meshes run "
                        "the kernel per-shard via the shard_map layer "
                        "(parallel/kernel_shard.py); off-TPU and the "
                        "FDT_KERNEL_SHARD=0 / non-dividing fallbacks use "
                        "the XLA reference GEMMs (warned)")
    p.add_argument("--quant_grad", default=d.quant_grad,
                   choices=["none", "fp8_e5m2"],
                   help="gradient quantization (requires --quant int8/"
                        "fp8): quantize the backward cotangents to the "
                        "wide-range fp8-E5M2 grid at a just-in-time "
                        "per-tensor scale and run BOTH gradient GEMMs on "
                        "quantized operands — the FP8-LM recipe's "
                        "gradient half (ops/quant.py)")
    p.add_argument("--mesh", default="", type=str,
                   help="mesh as axis=size pairs, e.g. 'dp=4,tp=2' (a 2D "
                        "(data, model) mesh), 'dp=4,fsdp=2', or "
                        "'dp=2,tp=2,pp=2' (3D: pipeline stages over pp — "
                        "the axis that spans DCN between slices); axis "
                        "aliases: model/mp=tp, seq/context=sp, "
                        "pipe/stage=pp (default: all devices on dp)")
    p.add_argument("--fsdp", action="store_true", help="fully-shard params/opt state")
    p.add_argument("--zero1", action="store_true",
                   help="shard only optimizer state over the data axes "
                        "(ZeRO-1; params stay replicated)")
    p.add_argument("--host_offload", action="store_true")
    p.add_argument("--no_zero_opt", action="store_true",
                   help="keep the optimizer state replicated over tp (the "
                        "r15 layout) instead of the default shape-aware "
                        "ZeRO sharding (sharding.py OPT_STATE_RULES)")
    p.add_argument("--offload_opt_state", action="store_true",
                   help="park the big opt-state leaves in pinned host "
                        "memory and stream them through each update "
                        "(FSDP+CPUOffload analog without offloading "
                        "params; no-op where the backend lacks "
                        "pinned_host)")
    p.add_argument("--overlap_grad_reduce", action="store_true",
                   help="lower the gradient reduction as bucketed "
                        "reduce-scatter inside the K-dispatch scan so "
                        "microbatch i's collective overlaps i+1's compute "
                        "(value-identity; reduce order may shift bits)")
    p.add_argument("--overlap_bucket_mb", default=d.overlap_bucket_mb,
                   type=int,
                   help="bucket size (MB) for --overlap_grad_reduce")
    p.add_argument("--remat", action="store_true")
    p.add_argument("--remat_policy", default=d.remat_policy,
                   choices=["ffn", "layer", "attn_out", "dots"],
                   help="what --remat checkpoints on the transformer: "
                        "ffn = FFN sublayer only, layer = whole encoder "
                        "layer (max savings), attn_out = whole layer but "
                        "the attention context is saved so the kernel "
                        "never re-runs, dots = XLA matmul-saveable policy")
    p.add_argument("--data_dir", default=d.data_dir, type=str)
    p.add_argument("--dataset", default=None, type=str)
    p.add_argument("--subset_stride", default=d.subset_stride, type=int,
                   help="take every Nth sample (tuning harness uses 10)")
    p.add_argument("--seed", default=d.seed, type=int)
    p.add_argument("--checkpoint_dir", default=d.checkpoint_dir, type=str)
    p.add_argument("--profile", action="store_true", help="capture a jax.profiler trace")
    p.add_argument("--profile_steps", default=d.profile_steps, type=str,
                   help="capture a jax.profiler trace around global train "
                        "steps A:B only (1-indexed, inclusive; quantized "
                        "to dispatch boundaries under --steps_per_dispatch)"
                        " — the mid-run window --profile can't give")
    p.add_argument("--no_telemetry", action="store_true",
                   help="disable run telemetry (per-dispatch JSONL + "
                        "manifest + pod straggler aggregation under "
                        "<checkpoint_dir>/telemetry); FDT_TELEMETRY=0 "
                        "is the env equivalent")
    p.add_argument("--telemetry_dir", default=d.telemetry_dir, type=str,
                   help="telemetry output directory (default "
                        "<checkpoint_dir>/telemetry; pods must share it, "
                        "like the checkpoint fs)")
    p.add_argument("--straggler_ratio", default=d.straggler_ratio,
                   type=float,
                   help="flag a host whose per-step p95 exceeds this "
                        "multiple of the pod median host-p95 in the "
                        "epoch [telemetry] line")
    p.add_argument("--aggregate_grace_s", default=d.aggregate_grace_s,
                   type=float,
                   help="epoch-boundary grace for the pod telemetry "
                        "fold: how long process 0 waits for peer epoch "
                        "markers before aggregating without them "
                        "(skipped hosts land in pod_summary.json's "
                        "hosts_missing; raise on slow shared "
                        "filesystems/CI hosts)")
    p.add_argument("--telemetry_every", default=d.telemetry_every,
                   type=int,
                   help="record every Nth dispatch in the telemetry "
                        "stream (default 1 = all; compile-marked first "
                        "dispatches are always recorded) — the mitigation "
                        "for per-dispatch clock pressure under async "
                        "dispatch")
    p.add_argument("--log_every", default=d.log_every, type=int,
                   help="live loss/acc/throughput line every N train steps "
                        "(0 disables; the reference's tqdm descriptors, "
                        "resnet50_test.py:560-566, at 1/N the sync cost)")
    p.add_argument("--no_plot", action="store_true")
    p.add_argument("--auto_recover", action="store_true",
                   help="on a non-finite epoch loss, restore the last good "
                        "checkpoint and keep training")
    p.add_argument("--sentinel", default=d.sentinel,
                   choices=["none", "guard", "full"],
                   help="anomaly sentinel: 'guard' arms the in-graph "
                        "bad-step guard (non-finite loss/grad-norm steps "
                        "leave the state bitwise-untouched and are counted); "
                        "'full' adds the host-side loss-spike detector with "
                        "rollback-and-quarantine (wants --supervise + "
                        "--checkpoint_every); 'none' keeps the programs "
                        "byte-identical to the unguarded build")
    p.add_argument("--spike_window", default=d.spike_window, type=int,
                   help="sentinel full: trailing per-dispatch loss window "
                        "for the median/MAD spike statistic")
    p.add_argument("--spike_threshold", default=d.spike_threshold,
                   type=float,
                   help="sentinel full: MAD multiples above the window "
                        "median that count as a loss spike")
    p.add_argument("--checkpoint_every", default=d.checkpoint_every, type=int,
                   help="async step-cadence checkpoints every N train steps "
                        "(keep-last-K, atomic commit markers, preemption-"
                        "aware; 0 = epoch-level checkpoints only)")
    p.add_argument("--checkpoint_every_secs", default=d.checkpoint_every_secs,
                   type=float,
                   help="wall-clock checkpoint cadence in seconds (combines "
                        "with --checkpoint_every; whichever fires first)")
    p.add_argument("--checkpoint_keep", default=d.checkpoint_keep, type=int,
                   help="how many step-cadence checkpoints to retain")
    p.add_argument("--sync_checkpoint", action="store_true",
                   help="disable the async (off-critical-path) checkpoint "
                        "write; saves block the step loop instead")
    p.add_argument("--supervise", action="store_true",
                   help="self-restarting supervisor: on a crash, restore "
                        "the newest valid checkpoint and continue with "
                        "exponential backoff (bounded by --max_restarts; "
                        "deterministic crashes re-raise immediately)")
    p.add_argument("--max_restarts", default=d.max_restarts, type=int,
                   help="supervisor restart budget")
    p.add_argument("--preempt_sync_every", default=d.preempt_sync_every,
                   type=int,
                   help="steps between cross-host preemption-agreement "
                        "collectives (multi-host; lower = faster SIGTERM-"
                        "to-emergency-save, higher = less sync overhead); "
                        "the pod coordinator polls peer failure markers at "
                        "the same cadence")
    p.add_argument("--peer_timeout_s", default=d.peer_timeout_s, type=float,
                   help="pod health watchdog: a peer heartbeat older than "
                        "this many seconds is a failed host and the pod "
                        "restarts together (with --supervise on a pod)")
    p.add_argument("--step_timeout_s", default=d.step_timeout_s, type=float,
                   help="local step watchdog (requires --supervise): no "
                        "completed dispatch for this many seconds => write "
                        "a FAIL marker and hard-abort so the pod converges "
                        "on a restart (0 = off; must exceed the worst-case "
                        "dispatch (re)compile time)")
    p.add_argument("--storage_backend", default=d.storage_backend,
                   help="durable-write medium for resilience markers / "
                        "sharded checkpoints / retention: posix (default), "
                        "fake_object_store (rename-free object semantics "
                        "under <checkpoint_dir>/_objects), or "
                        "gs://bucket[/prefix]")
    p.add_argument("--readmit_timeout_s", default=d.readmit_timeout_s,
                   type=float,
                   help="multi-slice elastic recovery (FDT_SLICE_COUNT): "
                        "how long surviving slices hold for a failed "
                        "slice's restart + re-admission before falling "
                        "back to a whole-pod restart (0 = always whole-pod)")
    p.add_argument("--commit_timeout_s", default=d.commit_timeout_s,
                   type=float,
                   help="sharded-checkpoint commit-barrier timeout (0 = "
                        "auto: max(2 x peer_timeout_s, 10s) when the pod "
                        "coordinator is armed, else 600s); values that "
                        "invert the detection/hold ordering warn")
    p.add_argument("--executable_cache", default=d.executable_cache,
                   help="persistent executable cache: '' = off, 'on' = "
                        "<checkpoint_dir>/_exec_cache via the storage "
                        "backend, else an explicit directory — a "
                        "restarted process deserializes its compiled "
                        "programs instead of recompiling (restart MTTR "
                        "is compile-dominated on real hardware); "
                        "FDT_EXEC_CACHE overrides (0 = kill)")
    p.add_argument("--warm_spares", default=d.warm_spares, type=int,
                   help="launcher contract: standby spare processes to "
                        "run beside the pod (each sets "
                        "FDT_SLICE_SPARE=<id>); a spare pre-admits and "
                        "claims a failed slice's seat at re-admission "
                        "time instead of waiting out a cold restart")
    p.add_argument("--debug", action="store_true",
                   help="per-epoch NGD Fisher invariant self-tests")
    p.add_argument("--data_path", default=d.data_path,
                   choices=["host", "resident", "stream"],
                   help="input pipeline: host = BatchLoader + prefetch + "
                        "per-batch H2D (default), resident = train split "
                        "uploaded to device once and batches gathered "
                        "inside the jitted dispatch (zero steady-state "
                        "host work; multi-host via per-host sharded "
                        "residency, see --resident_layout), stream = the "
                        "split stays ON DISK (sharded stream format, "
                        "--stream_dir) and trains through a fixed device "
                        "window refilled by a background double-buffered "
                        "H2D thread — the beyond-HBM tier; stall guarded "
                        "<1% by bench stream_stall_pct")
    p.add_argument("--task", default=d.task, choices=["cls", "lm"],
                   help="training objective: cls = classification (the "
                        "reference's), lm = next-token prediction through "
                        "the transformer (per-position vocab logits, "
                        "shifted-target loss, perplexity metric; no "
                        "mixup) — the streamed LM workload")
    p.add_argument("--untie_lm_head", action="store_true",
                   help="--task lm: use the r18 separate lm_head "
                        "projection instead of tying the head to "
                        "token_embedding (logits = h @ E^T, the r19 "
                        "default; untied checkpoints restore into tied "
                        "models via a warned compat shim)")
    p.add_argument("--lm_causal", action="store_true",
                   help="--task lm: apply the causal (next-token) mask "
                        "at TRAINING time, matching the mask decode "
                        "serving imposes — without it the model trains "
                        "bidirectional and decodes causal (the r21 "
                        "mismatch).  Routes attention to the dense impl "
                        "(the only one whose mask path takes a full "
                        "[B,1,L,L] mask)")
    p.add_argument("--pp_microbatches", default=d.pp_microbatches,
                   type=int,
                   help="pipeline microbatches M per step on a pp>1 "
                        "mesh (must divide --batch_size); 0 = auto "
                        "(largest divisor in [S, 2S] — bubble "
                        "(S-1)/(M+S-1))")
    p.add_argument("--pp_schedule", default=d.pp_schedule,
                   choices=["1f1b", "interleaved"],
                   help="pipeline stage assignment: 1f1b = contiguous "
                        "layer blocks; interleaved = round-robin chunks "
                        "(Megatron v=2, requires n_layers %% (2*pp) == "
                        "0, contiguous fallback otherwise) — executed "
                        "in depth order either way, at the price of a "
                        "longer fill/drain (bubble (2S-1)/(M+2S-1))")
    p.add_argument("--no_pp_residency", action="store_true",
                   help="keep params/opt-state replicated over pp (the "
                        "r22 layout) instead of the default per-stage "
                        "residency (sharding.py pp_residency_specs) — "
                        "the interchange/twin baseline, and the right "
                        "call when pp fits one slice anyway")
    p.add_argument("--stream_dir", default=d.stream_dir, type=str,
                   help="sharded stream dataset root (train/ + test/ "
                        "subdirs; scripts/shard_dataset.py writes one) — "
                        "required by --dataset stream / --data_path "
                        "stream")
    p.add_argument("--stream_window", default=d.stream_window, type=int,
                   help="batches per stream buffer (double-buffered; "
                        "rounded up to a multiple of "
                        "--steps_per_dispatch)")
    p.add_argument("--resident_layout", default=d.resident_layout,
                   choices=["auto", "replicated", "sharded"],
                   help="placement of the resident split: auto = "
                        "replicated single-host / per-host sharded on "
                        "pods; sharded = each process holds only its row "
                        "shard (~n/process_count per host) and one jitted "
                        "re-shard per epoch builds the batch-major view "
                        "(steady-state gathers stay in local HBM); "
                        "replicated = the r8 whole-split-per-host layout "
                        "(single-host only)")
    p.add_argument("--steps_per_dispatch", default=d.steps_per_dispatch,
                   type=int,
                   help="K train steps fused into one device dispatch "
                        "(lax.scan); 1 = the classic per-step loop.  K>1 "
                        "amortizes Python dispatch + resilience polling "
                        "K-fold; checkpoint cadence rounds up to a "
                        "multiple of K")
    p.add_argument("--seq_len", default=d.seq_len, type=int,
                   help="transformer max sequence length")
    p.add_argument("--n_layers", default=d.n_layers, type=int)
    p.add_argument("--d_model", default=d.d_model, type=int)
    p.add_argument("--d_ff", default=d.d_ff, type=int)
    p.add_argument("--n_heads", default=d.n_heads, type=int)
    p.add_argument("--attention", default=d.attention,
                   choices=["", "dense", "flash", "ring", "ulysses"],
                   help="attention impl ('' = the measured 4-impl routing "
                        "surface, cli.resolve_attention: sequence-parallel "
                        "ulysses/ring on a model axis (sp always; tp from "
                        "seq 2048 up — ulysses when the axis divides heads "
                        "and seq, else ring), dense/flash per the 2D "
                        "crossover otherwise)")
    p.add_argument("--mlp_impl", default=d.mlp_impl,
                   choices=["", "fused", "pallas"],
                   help="classifier MLP kernel ('' = pallas on TPU, else "
                        "the custom_vjp fused path)")
    p.add_argument("--ffn_impl", default=d.ffn_impl,
                   choices=["flax", "pallas"],
                   help="FFN sublayer impl: flax = Dense/GELU composition "
                        "(default), pallas = fused LN+FFN+dropout+residual "
                        "kernel with recompute backward (capacity lever; "
                        "not valid with a tp-sharded mesh)")
    p.add_argument("--tricks", default=d.tricks, choices=["on", "off"],
                   help="bag-of-tricks switch: off = disable every speed "
                        "lever at once (fp32, dense attention, naive MLP, "
                        "unfused QKV, autodiff conv+BN, threefry "
                        "nn.Dropout, synchronous loading) — the ablation "
                        "baseline for the end-to-end speedup figure")
    p.add_argument("--dropout_impl", default=d.dropout_impl,
                   choices=["hash", "xla", "none"],
                   help="dropout engine: hash = stateless index-hash masks "
                        "(no mask tensor in HBM, bit-reproducible, fastest "
                        "measured), xla = flax nn.Dropout (PRNG per "
                        "--dropout_rng_impl), none = disabled (probes)")
    p.add_argument("--dropout_rng_impl", default=d.dropout_rng_impl,
                   choices=["threefry", "rbg"],
                   help="PRNG for the xla dropout impl: threefry = bit-"
                        "reproducible masks (default), rbg = hardware-RNG "
                        "path (faster generation, backend-dependent bits)")
    p.add_argument("--serve_replicas", default=d.serve_replicas, type=int,
                   help="inference replicas (serve entrypoint): 0 = auto "
                        "(one per local chip; one model-sharded group "
                        "when the mesh has a model axis)")
    p.add_argument("--serve_batch_size", default=d.serve_batch_size,
                   type=int,
                   help="compiled serving batch size every dispatch cell "
                        "pads to")
    p.add_argument("--serve_max_delay_ms", default=d.serve_max_delay_ms,
                   type=float,
                   help="continuous-batching deadline: max wait before a "
                        "partial batch flushes with masked pad rows (the "
                        "latency/throughput trade-off knob)")
    p.add_argument("--serve_heartbeat_timeout_s",
                   default=d.serve_heartbeat_timeout_s, type=float,
                   help="detach a serving replica whose heartbeat is "
                        "silent past this many seconds; its work "
                        "re-dispatches to the survivors")
    p.add_argument("--serve_readmit_s", default=d.serve_readmit_s,
                   type=float,
                   help="auto re-admit a detached serving replica after "
                        "this many seconds (0 = manual only)")
    p.add_argument("--serve_requests", default=d.serve_requests, type=int,
                   help="synthetic request count for the CLI serve smoke")
    p.add_argument("--decode_batch_size", default=d.decode_batch_size,
                   type=int,
                   help="KV-cache slots per decode replica (the decode-"
                        "step batch dimension admissions swap into)")
    p.add_argument("--decode_page", default=d.decode_page, type=int,
                   help="KV-cache page size in tokens: live length picks "
                        "ceil(len/page) pages, so the decode program set "
                        "is one program per page count")
    p.add_argument("--decode_max_pages", default=d.decode_max_pages,
                   type=int,
                   help="cache capacity in pages per slot (0 = auto: "
                        "largest prompt bucket + one page of headroom)")
    p.add_argument("--decode_max_new_tokens",
                   default=d.decode_max_new_tokens, type=int,
                   help="per-request generation budget cap")
    p.add_argument("--decode_sample", default=d.decode_sample,
                   choices=["greedy", "topk"],
                   help="sampling method, baked into the compiled decode "
                        "programs (deterministic per (seed, request) "
                        "either way)")
    p.add_argument("--decode_temperature", default=d.decode_temperature,
                   type=float, help="topk sampling temperature")
    p.add_argument("--decode_top_k", default=d.decode_top_k, type=int,
                   help="topk truncation; <=0 samples the full vocab")
    p.add_argument("--decode_replicas", default=d.decode_replicas,
                   type=int,
                   help="decode replicas: 0 = auto (one per local chip; "
                        "one model-sharded group when the mesh has a "
                        "model axis)")
    p.add_argument("--decode_requests", default=d.decode_requests,
                   type=int,
                   help="synthetic prompt count for the CLI decode smoke")
    p.add_argument("--decode_deadline_s", default=d.decode_deadline_s,
                   type=float,
                   help="decode front door per-request wall deadline in "
                        "seconds (all retries included); a request "
                        "stranded by dying worker processes fails with "
                        "TimeoutError after this instead of waiting "
                        "forever (<=0 disables)")
    return p


def parse_mesh(spec: str) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
    """'dp=4,tp=2' -> (('dp','tp'), (4,2)).  Empty -> ((), ()).

    Axis names are canonicalized through parallel.mesh.AXIS_ALIASES
    ('model'/'mp' -> 'tp', 'seq'/'context' -> 'sp', ...) so every layer
    downstream — TP rules, attention routing, shard_map fallbacks —
    sees one spelling per role."""
    if not spec:
        return (), ()
    from faster_distributed_training_tpu.parallel.mesh import canonical_axes
    axes, sizes = [], []
    for part in spec.split(","):
        name, _, size = part.partition("=")
        name = name.strip()
        if not name or not size:
            raise ValueError(f"bad mesh spec {spec!r}; want 'axis=size,...'")
        axes.append(name)
        sizes.append(int(size))
    return canonical_axes(axes), tuple(sizes)


def config_from_args(args: argparse.Namespace, defaults: Optional[TrainConfig] = None,
                     **overrides) -> TrainConfig:
    base = defaults or TrainConfig()
    axes, shape = parse_mesh(args.mesh)
    cfg = base.replace(
        lr=args.lr, resume=args.resume, epochs=args.epoch, alpha=args.alpha,
        batch_size=args.bs, workers=args.workers,
        meta_learning=args.meta_learning, mixup_mode=args.mixup_mode,
        distributed=args.distributed, use_ngd=args.ngd,
        weight_decay=args.weight_decay, gamma=args.gamma,
        optimizer=args.optimizer, schedule=args.schedule,
        ngd_max_dim=args.ngd_max_dim,
        device=args.device, precision=args.precision, quant=args.quant,
        quant_grad=args.quant_grad,
        tie_lm_head=not args.untie_lm_head,
        lm_causal=args.lm_causal,
        pp_microbatches=args.pp_microbatches,
        pp_schedule=args.pp_schedule,
        pp_residency=not args.no_pp_residency,
        fsdp=args.fsdp, zero1=args.zero1, host_offload=args.host_offload,
        zero_opt=not args.no_zero_opt,
        offload_opt_state=args.offload_opt_state,
        overlap_grad_reduce=args.overlap_grad_reduce,
        overlap_bucket_mb=args.overlap_bucket_mb,
        remat=args.remat, remat_policy=args.remat_policy,
        data_dir=args.data_dir, subset_stride=args.subset_stride, seed=args.seed,
        checkpoint_dir=args.checkpoint_dir, profile=args.profile,
        profile_steps=args.profile_steps,
        telemetry=not args.no_telemetry,
        telemetry_dir=args.telemetry_dir,
        straggler_ratio=args.straggler_ratio,
        aggregate_grace_s=args.aggregate_grace_s,
        telemetry_every=args.telemetry_every,
        log_every=args.log_every,
        plot=not args.no_plot,
        auto_recover=args.auto_recover, debug=args.debug,
        sentinel=args.sentinel,
        spike_window=args.spike_window,
        spike_threshold=args.spike_threshold,
        checkpoint_every=args.checkpoint_every,
        checkpoint_every_secs=args.checkpoint_every_secs,
        checkpoint_keep=args.checkpoint_keep,
        checkpoint_async=not args.sync_checkpoint,
        supervise=args.supervise, max_restarts=args.max_restarts,
        preempt_sync_every=args.preempt_sync_every,
        peer_timeout_s=args.peer_timeout_s,
        step_timeout_s=args.step_timeout_s,
        storage_backend=args.storage_backend,
        readmit_timeout_s=args.readmit_timeout_s,
        commit_timeout_s=args.commit_timeout_s,
        executable_cache=args.executable_cache,
        warm_spares=args.warm_spares,
        data_path=args.data_path,
        task=args.task,
        stream_dir=args.stream_dir,
        stream_window=args.stream_window,
        resident_layout=args.resident_layout,
        steps_per_dispatch=args.steps_per_dispatch,
        seq_len=args.seq_len, n_layers=args.n_layers, d_model=args.d_model,
        d_ff=args.d_ff, n_heads=args.n_heads, attention=args.attention,
        mlp_impl=args.mlp_impl, ffn_impl=args.ffn_impl,
        dropout_impl=args.dropout_impl,
        dropout_rng_impl=args.dropout_rng_impl, tricks=args.tricks,
        serve_replicas=args.serve_replicas,
        serve_batch_size=args.serve_batch_size,
        serve_max_delay_ms=args.serve_max_delay_ms,
        serve_heartbeat_timeout_s=args.serve_heartbeat_timeout_s,
        serve_readmit_s=args.serve_readmit_s,
        serve_requests=args.serve_requests,
        decode_batch_size=args.decode_batch_size,
        decode_page=args.decode_page,
        decode_max_pages=args.decode_max_pages,
        decode_max_new_tokens=args.decode_max_new_tokens,
        decode_sample=args.decode_sample,
        decode_temperature=args.decode_temperature,
        decode_top_k=args.decode_top_k,
        decode_replicas=args.decode_replicas,
        decode_requests=args.decode_requests,
        decode_deadline_s=args.decode_deadline_s,
    )
    cfg = resolve_tricks(cfg)
    if args.model:
        cfg = cfg.replace(model=args.model)
    if args.dataset:
        cfg = cfg.replace(dataset=args.dataset)
    if axes:
        cfg = cfg.replace(mesh_axes=axes, mesh_shape=shape)
    if cfg.fsdp and "fsdp" not in cfg.mesh_axes:
        if cfg.mesh_shape != ():
            raise ValueError(
                f"--fsdp requires an 'fsdp' axis in --mesh, got {cfg.mesh_axes}; "
                f"e.g. --mesh dp=2,fsdp=4")
        # --fsdp with no explicit mesh: put every device on one fsdp axis,
        # which is the ZeRO-3 topology (params sharded where data is sharded).
        cfg = cfg.replace(mesh_axes=("fsdp",))
    return cfg if not overrides else cfg.replace(**overrides)
