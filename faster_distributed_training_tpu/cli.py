"""CLI wiring shared by every entry point.

The reference ships two monolithic scripts (resnet50_test.py,
transformer_test.py) whose __main__ blocks duplicate device probing,
data prep, model build, optimizer selection and the DDP/FSDP launch
(resnet50_test.py:693-740, transformer_test.py:364-424).  Here all of
that is ONE code path parameterized by TrainConfig; the root-level
entry scripts are thin defaults-providers.

Launch model: one process per host, all local chips visible
(`--distributed` triggers jax.distributed.initialize) — replacing
torchrun's process-per-GPU + NCCL rendezvous (run_distributed.sh:2-3).
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Tuple

import numpy as np

from faster_distributed_training_tpu.config import (TrainConfig,
                                                    build_parser,
                                                    config_from_args)


def _host_isa_fingerprint() -> str:
    """Short hash of this host's CPU feature set AND the jaxlib version.
    The persistent cache stores AOT executables; one compiled on a host
    with wider vector extensions (AVX-512) SIGILLs when replayed on a
    host without them (observed in MULTICHIP_r03 gate logs), so the
    cache directory is keyed by the ISA features (VERDICT r3 #6).  The
    jaxlib version is part of the key because XLA bakes version-
    dependent PSEUDO-features (``+prefer-no-gather`` etc., the
    MULTICHIP_r04 cpu_aot_loader warnings) into CPU AOT executables —
    features /proc/cpuinfo cannot see but the loader still compares
    (VERDICT r4 #5)."""
    import hashlib
    import platform

    feat = platform.machine()
    try:
        import jaxlib
        feat += ":" + getattr(jaxlib, "__version__", "?")
    except ImportError:
        pass
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feat += line
                    break
    except OSError:
        feat += platform.processor() or ""
    return hashlib.sha1(feat.encode()).hexdigest()[:8]


def _jaxlib_version() -> str:
    """The installed jaxlib's version string, "" when unavailable — the
    single probe both the donation gate and its log line read, so the
    two can't drift."""
    try:
        import jaxlib
        return str(getattr(jaxlib, "__version__", ""))
    except ImportError:
        return ""


def donation_workaround_needed(version: Optional[str] = None) -> bool:
    """True when the jaxlib CPU client still carries the r7
    restore-then-donate heap-corruption bug (measured+bisected on the
    0.4.x line: glibc "corrupted double-linked list" / SIGSEGV at the
    first post-restore donating step).  The ROADMAP said "retest when
    jax moves past 0.4.x" — this predicate makes the retest automatic:
    ``run_training`` re-enables donation the first time the container's
    jaxlib reports a version past 0.4 (and logs which branch it took).
    Unparseable/unknown versions keep the workaround: correctness over
    a micro-optimization."""
    if version is None:
        version = _jaxlib_version()
    import re as _re
    m = _re.match(r"^\s*(\d+)\.(\d+)", str(version))
    if not m:
        return True
    return (int(m.group(1)), int(m.group(2))) <= (0, 4)


def _configured_platform() -> str:
    """The platform jax WILL use, read without initializing the backend
    (jax.default_backend() would pin the platform before setup_platform's
    --device override runs)."""
    import jax

    p = (getattr(jax.config, "jax_platforms", None)
         or os.environ.get("JAX_PLATFORMS", ""))
    return p.split(",")[0] if p else ""


def quiet_cpu_aot_flags() -> None:
    """Cap the XLA:CPU target ISA at AVX2 (x86 only, before first backend
    use).  Measured root cause of the MULTICHIP_r03/r04 `cpu_aot_loader`
    warnings (VERDICT r4 #5): targeting AVX-512 makes XLA bake the
    PSEUDO-features ``+prefer-no-scatter``/``+prefer-no-gather`` into CPU
    AOT executables, and the loader's replay check compares them against
    the host's /proc/cpuinfo features — where pseudo-features never
    appear — so EVERY persistent-cache replay warns, even same-host
    same-jaxlib (reproduced+measured: write/replay with default flags =
    6 warnings, with ``--xla_cpu_max_isa=AVX2`` = 0).  The CPU backend
    here is the test/gate simulator, never the perf path, so the ISA cap
    costs nothing that matters."""
    import platform

    if platform.machine() not in ("x86_64", "AMD64"):
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_cpu_max_isa" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " --xla_cpu_max_isa=AVX2").strip()


def _default_cache_dir() -> str:
    """Cache directory choice: ISA+jaxlib-keyed unless the configured
    platform is known TPU (see enable_compilation_cache's docstring).
    An UNKNOWN platform ("" — no env, no config) gets the keyed
    directory: correctness over sharing.  On the driver host the outer
    environment pins JAX_PLATFORMS=axon, so bench/auto runs do resolve
    to the shared TPU directory there; a TPU host without that env var
    merely recompiles into the keyed directory once."""
    plat = _configured_platform()
    on_tpu = plat.startswith(("tpu", "axon"))
    suffix = "" if on_tpu else f"-{_host_isa_fingerprint()}"
    return os.path.expanduser(f"~/.cache/fdt_xla_v2{suffix}")


def enable_compilation_cache(path: str = "") -> None:
    """Persistent XLA compilation cache — TPU train-step compiles take
    minutes; cached reloads take seconds (shared across processes, e.g.
    bench.py's subprocess comparison runs).

    The directory is keyed by the host's CPU-feature + jaxlib hash
    UNLESS the configured platform is known to be a TPU: CPU AOT
    executables compiled on a machine with wider vector extensions (or a
    different XLA pseudo-feature set) SIGILL or warn when replayed
    elsewhere (MULTICHIP_r03/r04 gate logs).  The default is INVERTED
    from round 4 (ADVICE r4 #1): under ``--device auto`` — and in
    bench.py, which enables the cache before any platform setup —
    ``_configured_platform()`` reads "", and those CPU executables must
    never land in a shared un-keyed directory.  Suffixing costs only
    cross-host sharing, never correctness; TPU/axon programs keep the
    shared directory so the driver's bench runs stay warm.  The base
    name is version-bumped (``fdt_xla_v2``) so stale pre-fix entries
    from the un-keyed round-4 directory can never load (VERDICT r4 #5).
    """
    import jax

    plat = _configured_platform()
    if not plat.startswith(("tpu", "axon")):
        # single chokepoint for every non-TPU path (INCLUDING --device
        # auto on a CPU-only host and bench.py's early call): cap the CPU
        # target ISA before the first compile so cached AOT executables
        # never carry the warn-on-every-replay AVX-512 pseudo-features.
        # XLA parses XLA_FLAGS when the first module's debug options are
        # built, so setting the env here — before any jit — is in time.
        quiet_cpu_aot_flags()
    if not path and not os.environ.get("FDT_COMPILATION_CACHE"):
        path = _default_cache_dir()
    path = path or os.environ.get("FDT_COMPILATION_CACHE", "")
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass


def setup_platform(cfg: TrainConfig) -> None:
    """Select the JAX platform before first backend use.  `auto` keeps
    whatever the environment provides (TPU when available).  On cpu, a
    mesh larger than the physical device count gets virtual devices
    (the multi-chip simulation used by tests, SURVEY.md §4)."""
    import numpy as np

    import jax

    if cfg.device != "auto":
        want = "tpu" if cfg.device == "tpu" else "cpu"
        if want == "cpu":
            quiet_cpu_aot_flags()
        try:
            jax.config.update("jax_platforms", want)
        except Exception:
            os.environ["JAX_PLATFORMS"] = want
        need = int(np.prod(cfg.mesh_shape)) if cfg.mesh_shape else 1
        if want == "cpu" and need > 1:
            try:
                jax.config.update("jax_num_cpu_devices", need)
            except Exception:
                pass  # backend already initialized; make_mesh will report

    # AFTER the platform override: the cache directory choice reads the
    # configured platform (CPU caches are ISA-keyed, TPU caches shared)
    enable_compilation_cache()


def load_dataset(cfg: TrainConfig, train: bool):
    """Returns a BatchLoader-compatible dataset for cfg.dataset.

    CIFAR-10 falls back to synthetic data when the archive is absent and
    cannot be downloaded (zero-egress environments) — the pipeline code
    paths are identical (data/synthetic.py)."""
    from faster_distributed_training_tpu.data import (load_cifar10,
                                                      synthetic_agnews,
                                                      synthetic_cifar)

    # difficulty overrides for the synthetic fallback (accuracy-evidence
    # convergence runs lower the signal so the curve has a real shape)
    synth_kw = {}
    if os.environ.get("FDT_SYNTH_SIGNAL"):
        synth_kw["signal"] = float(os.environ["FDT_SYNTH_SIGNAL"])
    if os.environ.get("FDT_SYNTH_NOISE"):
        synth_kw["noise_std"] = float(os.environ["FDT_SYNTH_NOISE"])

    if cfg.dataset == "stream":
        # sharded on-disk dataset (data/stream/): the text flavor IS the
        # reader (it speaks encode_batch, so host/resident paths serve
        # it too — the cross-path bitwise tests depend on that); the
        # image flavor returns the (image, label) mmap pair
        from faster_distributed_training_tpu.data.stream import (
            open_stream_split)
        if not cfg.stream_dir:
            raise ValueError("--dataset stream requires --stream_dir "
                             "(scripts/shard_dataset.py writes one)")
        return open_stream_split(cfg.stream_dir, train=train)
    if cfg.dataset == "cifar10":
        try:
            x, y = load_cifar10(cfg.data_dir, train=train)
        except Exception as e:  # download impossible / corrupt archive
            print(f"[data] CIFAR-10 unavailable ({e!r}); using synthetic")
            x, y = synthetic_cifar(n=50000 if train else 10000,
                                   seed=0 if train else 1, **synth_kw)
    elif cfg.dataset == "agnews":
        from faster_distributed_training_tpu.data.agnews import AGNewsDataset
        try:
            return AGNewsDataset(cfg.data_dir, train=train,
                                 buckets=cfg.seq_buckets)
        except Exception as e:
            print(f"[data] AG News unavailable ({e!r}); using synthetic")
            return synthetic_agnews(n=12000 if train else 2000,
                                    seed=0 if train else 1,
                                    max_len=cfg.seq_len)
    elif cfg.dataset == "synthetic":
        if cfg.model == "transformer":
            return synthetic_agnews(n=4096 if train else 1024,
                                    seed=0 if train else 1,
                                    max_len=cfg.seq_len)
        x, y = synthetic_cifar(n=4096 if train else 1024,
                               seed=0 if train else 1, **synth_kw)
    else:
        raise ValueError(f"unknown dataset {cfg.dataset!r}")
    return (x, y)


def apply_subset(ds, stride: int):
    """1/N-stride subset of either dataset kind — applied to BOTH splits,
    matching the reference tuning harness (tuning/resnet50_tuning.py:328,346
    subsets train and test alike)."""
    if stride <= 1:
        return ds
    if isinstance(ds, tuple):
        x, y = ds
        return (x[::stride], y[::stride])

    class _Subset:
        def __init__(self, base):
            self._base = base
            self._idx = np.arange(0, len(base), stride)

        def __len__(self):
            return len(self._idx)

        def num_classes(self):
            return self._base.num_classes()

        def vocab_size(self):
            return self._base.vocab_size()

        def encode_batch(self, indices, max_len=512):
            return self._base.encode_batch(self._idx[np.asarray(indices)],
                                           max_len)

    return _Subset(ds)


# The dense path's backward holds ~3 score-shaped fp32 tensors at peak
# (saved probs residual + ds/dp transients — same accounting as flash's
# _DENSE_BWD_BUDGET_BYTES, validated by the measured +1.6 GB at
# bs256/seq256 ≈ 3 x 537 MB).  The routing budget below caps that
# footprint so auto-routing can never walk a big-batch config into HBM
# exhaustion: the materialized probs scale with B·L².  Override via
# FDT_DENSE_ATTN_BUDGET_MB (0 forces flash everywhere).
_DENSE_ATTN_BUDGET_MB = 4096

# The measured attention routing surface (VERDICT r5 #5; extended to
# the 4-impl {dense, flash, ring, ulysses} surface in r11).  Every cell
# the auto-router serves cites the bench arm that measures it; the arms
# (attn_route_*) land in BENCH_LATEST.json per round under the
# regression guard, so a crossover drift shows up as a flagged move.
#
# Row format: (bs, seq, routed impl, bench arm, mesh condition).
# mesh condition "" = mesh-independent (1D / no model axis); "sp" = the
# mesh has a sequence-capable model axis (a dedicated sp axis, or tp —
# the axis NAME doesn't change the shard_map math, so tp-axis routing
# cites the same arms) whose size divides both heads and seq (ulysses
# eligible); "sp_ragged" = model axis present but heads/seq don't
# divide (ring, which accepts any axis size).
_ATTN_ROUTE_SURFACE = (
    (256, 256, "dense", "transformer_agnews_ex_per_sec_bs256_seq256", ""),
    (512, 128, "dense", "attn_route_bs512_seq128_dense_step_ms", ""),
    (1024, 128, "dense", "attn_route_bs1024_seq128_dense_step_ms", ""),
    (512, 256, "dense", "attn_route_bs512_seq256_dense_step_ms", ""),
    (1024, 256, "flash", "attn_route_bs1024_seq256_flash_step_ms", ""),
    (256, 384, "flash", "attn_route_bs256_seq384_flash_step_ms", ""),
    (64, 512, "flash", "transformer_agnews_ex_per_sec_bs64_seq512", ""),
    # r11 sequence-parallel cells (bench.ATTN_ROUTE_SP_BENCH_CELLS
    # measures flash/ring/ulysses at each; the flash arm is the
    # single-chip-replicated alternative the sp routing must beat):
    (8, 2048, "ulysses", "attn_route_bs8_seq2048_ulysses_step_ms", "sp"),
    (8, 2048, "ring", "attn_route_bs8_seq2048_ring_step_ms", "sp_ragged"),
    (4, 4096, "ulysses", "attn_route_bs4_seq4096_ulysses_step_ms", "sp"),
    (4, 4096, "ring", "attn_route_bs4_seq4096_ring_step_ms", "sp_ragged"),
)

# Sequence length from which a (data, model) mesh's model axis routes
# attention sequence-parallel instead of single-chip dense/flash — the
# boundary sits at the first measured sp cell (bs8/seq2048,
# attn_route_bs8_seq2048_* arms); below it the 1D surface still rules
# (dense/flash are tp-compatible: dense head-shards, flash is rerouted
# by build_model's capability fallback).  Provisional pending the first
# live TPU record — PARITY "r6 A/B follow-up decision" step (f).
_SEQ_PARALLEL_MIN_LEN = 2048


def _dense_attn_fits(bs: int, seq: int, n_heads: int) -> bool:
    """Memory-headroom term of the routing surface: 3 score-shaped fp32
    tensors at the dense backward's peak must fit the routing budget."""
    mb = os.environ.get("FDT_DENSE_ATTN_BUDGET_MB")
    try:
        budget_mb = int(mb) if mb is not None else _DENSE_ATTN_BUDGET_MB
    except ValueError:
        import warnings
        warnings.warn(f"ignoring malformed FDT_DENSE_ATTN_BUDGET_MB={mb!r} "
                      f"(want an integer MB count); using the default "
                      f"{_DENSE_ATTN_BUDGET_MB}", stacklevel=2)
        budget_mb = _DENSE_ATTN_BUDGET_MB
    return 3 * 4 * bs * n_heads * seq * seq <= budget_mb << 20


def _route_model_axis(cfg: TrainConfig, ax_size: int) -> Optional[str]:
    """The sequence-parallel impl a model axis of `ax_size` can serve
    for this shape, or None when it can't: BOTH strategies shard the
    sequence over the axis (shard_map divisibility), so a seq_len the
    axis doesn't divide routes back to the single-chip surface instead
    of an impl that would fail at trace time.  Among the eligible:
    ulysses when the axis also divides the heads (lower interconnect
    volume — O(B·H·L·D/sp) per tensor, collective-free inner kernel;
    the documented trade in ops/ulysses_attention.py), ring otherwise
    (any head count).  Per-cell attn_route_*_{ring,ulysses}_step_ms
    arms measure both sides so the preference stays falsifiable."""
    if cfg.seq_len % ax_size:
        return None
    return "ulysses" if cfg.n_heads % ax_size == 0 else "ring"


def resolve_attention(cfg: TrainConfig, mesh=None) -> str:
    """'' auto-resolves from the measured 4-impl surface
    {dense, flash, ring, ulysses}.  Explicit --attention always wins.

    Mesh-dependent tier first (_ATTN_ROUTE_SURFACE's "sp"/"sp_ragged"
    rows): a dedicated sp axis routes sequence-parallel whenever it can
    serve the shape (_route_model_axis: seq must divide the axis —
    both strategies shard L over it; ulysses when the heads divide too,
    else ring — r6 routed a blanket "ring" here; the split is now
    measured per cell by the attn_route_bs8_seq2048_* / bs4_seq4096_*
    arm triples); a tp axis routes sequence-parallel only from
    _SEQ_PARALLEL_MIN_LEN up (below it the model axis serves tensor
    parallelism and the 1D surface rules).  Shapes the model axis
    can't serve fall through to the mesh-independent 2D dense/flash
    crossover: on TPU, DENSE inside the measured envelope and flash
    beyond; dense off-TPU.

    The 2D surface (r5 + r6 bench arms, v5e, NGD full step):

      * seq<=256, bs<=256 — DENSE: 99.8 ms/step dense vs 111.9 flash @
        bs256/seq256 once dense prob dropout went through the stateless
        hash engine — at L<=256 the monolithic kernel's per-(b,h)-
        instance overhead exceeds XLA's batched GEMM+softmax cost
        (r5 probe; guarded per-round by
        transformer_agnews_ex_per_sec_bs256_seq256).
      * seq<=256, bs in {512, 1024} — DENSE while the probs fit: at
        fixed L the per-example cost of both paths scales ~linearly in
        B, so the L-crossover carries over; pinned per-round by the
        attn_route_bs512_seq128 / bs1024_seq128 / bs512_seq256
        dense-vs-flash step-ms arm pairs in BENCH_LATEST.json.
      * memory-headroom bound (_dense_attn_fits): dense materializes
        ~3 fp32 [B,H,L,L] score tensors at the backward peak (measured
        +1.6 GB at bs256/seq256), so cells past the budget route flash
        regardless — bs1024/seq256 is 3·4·1024·8·256² = 6.4 GB > the
        4 GB default budget (flash side measured by
        attn_route_bs1024_seq256_flash_step_ms; dense deliberately not
        benched, the bound exists to keep it un-runnable configs away).
      * seq >= 384 — FLASH: flash wins from L=512 down (58.6 vs 69.6 ms
        @ bs64/seq512, transformer_agnews_ex_per_sec_bs64_seq512), and
        the seq=384 arm pair (attn_route_bs256_seq384_*_step_ms) pins
        the boundary cell between the measured 256 and 512 points.

    The surface is recorded row-by-row in _ATTN_ROUTE_SURFACE (cell ->
    impl -> measuring arm -> mesh condition) and tests/test_substrate.py
    asserts every routed cell's arm actually exists in bench.py."""
    if cfg.attention:
        return cfg.attention
    if (getattr(cfg, "task", "cls") == "lm"
            and getattr(cfg, "lm_causal", False)):
        # --lm_causal (r22): the model combines a causal [1,1,L,L] (or
        # joint [B,1,L,L]) mask into attention at TRAINING time, and
        # dense is the only impl whose mask path takes a full
        # query-by-key mask — flash accepts key-padding masks only
        # (ops/flash_attention.py flash mask contract) and ring/ulysses
        # shard L.  Routed here so every auto-resolved causal config
        # lands on a mask-capable impl; an explicit --attention above
        # still wins and build_model's capability fallback reroutes it
        # with a warning.
        return "dense"
    from faster_distributed_training_tpu.parallel.mesh import (
        seq_parallel_axis)
    # route against the axis the model will EXECUTE over
    # (seq_parallel_axis prefers a dedicated sp axis over tp — the same
    # policy build_model hands the model as sp_axis), never against a
    # different axis than the one shard_map will shard L on
    ax, ax_size = seq_parallel_axis(mesh)
    if ax is not None and (ax == "sp"
                           or cfg.seq_len >= _SEQ_PARALLEL_MIN_LEN):
        impl = _route_model_axis(cfg, ax_size)
        if impl:
            return impl
        # seq doesn't divide the executing axis: fall through to the
        # single-chip surface rather than crash inside shard_map
    import jax
    if jax.default_backend() != "tpu":
        return "dense"
    return ("dense" if cfg.seq_len <= 256
            and _dense_attn_fits(cfg.batch_size, cfg.seq_len, cfg.n_heads)
            else "flash")


def build_model(cfg: TrainConfig, vocab_size: Optional[int] = None,
                mesh=None, serving: bool = False):
    """``serving=True`` builds the INFERENCE twin of the training model:
    byte-identical param tree (checkpoints interchange), but the r13
    quant scale state is FROZEN — QuantPolicy.frozen_scales makes every
    QuantDense quantize at the scales the restored amax history implies
    and never roll it, so serving is state-free and two identical
    requests return bitwise-identical logits (serve/engine.py)."""
    import jax.numpy as jnp

    from faster_distributed_training_tpu.models import get_model

    import jax

    dtype = jnp.bfloat16 if cfg.precision == "bf16" else jnp.float32
    tricks_off = cfg.tricks == "off"
    if cfg.model == "transformer":
        from faster_distributed_training_tpu.parallel.mesh import (
            seq_parallel_axis, tp_size)
        impl = resolve_attention(cfg, mesh)
        tp = tp_size(mesh)
        sp_axis, sp_ax_size = seq_parallel_axis(mesh)
        causal = (getattr(cfg, "task", "cls") == "lm"
                  and getattr(cfg, "lm_causal", False))
        if causal and impl != "dense":
            # REGISTERED warned fallback: an explicit --attention that
            # can't take the full causal mask (flash = key-padding only;
            # ring/ulysses shard L) reroutes to dense — same policy as
            # the shard_map capability fallbacks below
            import warnings
            warnings.warn(
                f"--lm_causal needs a full [B,1,L,L] attention mask; "
                f"impl {impl!r} only takes key-padding masks — using "
                f"'dense' attention", stacklevel=2)
            impl = "dense"
        from faster_distributed_training_tpu.parallel import kernel_shard
        if impl == "flash" and tp > 1 \
                and not kernel_shard.flash_serviceable(mesh, cfg.n_heads):
            # REGISTERED warned fallback (scripts/check_kernel_routing):
            # the r19 shard_map layer runs the flash kernel per-shard on
            # each device's local heads, so a serviceable tp mesh (heads
            # divide tp, FDT_KERNEL_SHARD armed) keeps flash.  Only the
            # non-dividing / killed cases reroute to the shard_map
            # sequence-parallel strategies (explicit --attention flash
            # included) — validated against the axis the model will
            # execute over (sp_ax_size — seq_parallel_axis prefers sp)
            fallback = _route_model_axis(cfg, sp_ax_size) or "dense"
            import warnings
            warnings.warn(
                f"attention 'flash' cannot run head-sharded on this "
                f"{dict(mesh.shape)} mesh "
                + (f"(n_heads={cfg.n_heads} does not divide tp={tp})"
                   if kernel_shard.enabled() else
                   "(FDT_KERNEL_SHARD=0 disables the shard_map kernel "
                   "layer)")
                + f"; using '{fallback}' "
                + ("sequence-parallel attention over tp"
                   if fallback != "dense" else
                   "attention (seq_len doesn't divide the tp axis, so "
                   "the sequence-parallel strategies can't serve it "
                   "either)"), stacklevel=2)
            impl = fallback
        mlp_impl = cfg.mlp_impl or (
            "pallas" if jax.default_backend() == "tpu" else "fused")
        if mlp_impl == "pallas" and jax.default_backend() != "tpu":
            import warnings
            warnings.warn(
                "--mlp_impl pallas off-TPU runs the kernel in Pallas "
                "INTERPRET mode (orders of magnitude slower) — test-only; "
                "use --mlp_impl fused for real off-TPU runs", stacklevel=2)
        ffn_impl = cfg.ffn_impl
        if ffn_impl == "pallas":
            from faster_distributed_training_tpu.ops.fused_ffn import (
                ffn_kernel_fits_vmem)
            if not ffn_kernel_fits_vmem(cfg.d_model, cfg.d_ff,
                                        jnp.dtype(dtype).itemsize):
                # ADVICE r5 (low): a user-configured large --d_model/
                # --d_ff would die with an opaque Mosaic scoped-VMEM
                # compile error; mirror the tp-mesh fallback instead.
                import warnings
                warnings.warn(
                    f"--ffn_impl pallas: weights+hidden for d_model="
                    f"{cfg.d_model}, d_ff={cfg.d_ff} exceed the kernel's "
                    f"VMEM budget (ops/fused_ffn.py ffn_kernel_fits_vmem)"
                    f"; falling back to the flax FFN composition",
                    stacklevel=2)
                ffn_impl = "flax"
        if ffn_impl == "pallas":
            # sharded meshes run the kernel per-shard via shard_map over
            # the data axes (fused_ffn_sublayer_sharded); tp meshes run
            # the Megatron column-then-row decomposition through the r19
            # shard_map layer (kernel_shard.fused_ffn_sublayer_tp — the
            # tp weight shards are consumed in place, no per-step
            # gather).  The flax composition survives only as the
            # REGISTERED warned fallback: FDT_KERNEL_SHARD=0 or shapes
            # tp doesn't divide.
            if tp > 1 and not kernel_shard.ffn_tp_serviceable(
                    mesh, cfg.d_ff, cfg.seq_len):
                import warnings
                warnings.warn(
                    "--ffn_impl pallas cannot run the Megatron column/"
                    f"row-sharded kernel on this {dict(mesh.shape)} mesh "
                    + (f"(d_ff={cfg.d_ff} or seq_len={cfg.seq_len} does "
                       f"not divide the tp/sp axes)"
                       if kernel_shard.enabled() else
                       "(FDT_KERNEL_SHARD=0 disables the shard_map "
                       "kernel layer)")
                    + "; falling back to the flax FFN composition",
                    stacklevel=2)
                ffn_impl = "flax"
            elif jax.default_backend() != "tpu":
                import warnings
                warnings.warn(
                    "--ffn_impl pallas off-TPU runs the kernel in Pallas "
                    "INTERPRET mode (orders of magnitude slower) — "
                    "test-only; use the default flax FFN for real "
                    "off-TPU runs", stacklevel=2)
        # --quant int8/fp8 (r13): the QuantPolicy handed to the model,
        # with the kernel routing decided HERE where the mesh/backend
        # are known (train.amp.resolve_quant_policy owns the cfg->fmt
        # mapping; ops/quant.py owns the math/kernels).
        quant = None
        from faster_distributed_training_tpu.train.amp import (
            resolve_quant_policy)
        policy = resolve_quant_policy(cfg)
        if policy is not None:
            import warnings

            from faster_distributed_training_tpu.ops.quant import (
                quant_enabled)
            if not quant_enabled():
                # the kill switch leaves the param/state TREE intact
                # (QuantDense computes the plain matmul) so a killed
                # run's checkpoints interchange with quantized ones
                warnings.warn(
                    f"--quant {cfg.quant} requested but FDT_QUANT=0 is "
                    f"set: every quantized site computes the plain "
                    f"full-precision matmul this run (scale state is "
                    f"still allocated, so checkpoints interchange)",
                    stacklevel=2)
            use_pallas = None
            if tp > 1:
                # r19: serviceable sites (their sharded kernel dim
                # divides tp) run the quant kernel PER SHARD on the
                # Megatron column/row tiles through the shard_map layer
                # (QuantDense mesh/tp_dim routing); anything else takes
                # the REGISTERED warned fallback — the XLA reference
                # path is a plain dot_general on int8/fp8 operands,
                # which partitions like any other dot, so quantization
                # itself stays on either way.
                div = (cfg.n_heads % tp == 0 and cfg.d_ff % tp == 0
                       and cfg.d_model % tp == 0)
                if not (kernel_shard.enabled() and div):
                    warnings.warn(
                        f"--quant {cfg.quant}: the quant matmul kernel "
                        f"cannot run column/row-sharded on this "
                        f"{dict(mesh.shape)} mesh "
                        + (f"(n_heads={cfg.n_heads}/d_ff={cfg.d_ff}/"
                           f"d_model={cfg.d_model} must all divide "
                           f"tp={tp})" if kernel_shard.enabled() else
                           "(FDT_KERNEL_SHARD=0 disables the shard_map "
                           "kernel layer)")
                        + "; using the XLA reference quantized GEMMs "
                        "(quantization stays on)", stacklevel=2)
                    use_pallas = False
            elif jax.default_backend() != "tpu":
                # the designed off-TPU path (tests/CPU convergence
                # harness): reference GEMMs, same math, no interpret-
                # mode Pallas on the hot path
                use_pallas = False
            # --ffn_impl pallas composes with --quant since r19: the
            # generalized fused-FFN kernel runs its two GEMMs on the
            # quantized operands in-kernel (models/transformer.py)
            quant = policy._replace(use_pallas=use_pallas,
                                    frozen_scales=bool(serving))
        # the model sees the mesh whenever it has work to do with it:
        # sequence-parallel attention, the sharded fused-FFN kernel, or
        # a model axis to annotate activations over (tp/sp activation
        # constraints, models/transformer.py).  Pure-dp meshes pass
        # None so the 1D program stays byte-identical to r10.
        model_mesh = (mesh if (impl in ("ring", "ulysses")
                               or ffn_impl == "pallas"
                               or tp > 1 or sp_ax_size > 1) else None)
        return get_model("transformer", cfg.num_classes,
                         vocab=vocab_size or 30522, maxlen=cfg.seq_len,
                         n_layers=cfg.n_layers, d_model=cfg.d_model,
                         d_ff=cfg.d_ff, h=cfg.n_heads,
                         attention_impl=impl, mlp_impl=mlp_impl,
                         mesh=model_mesh,
                         sp_axis=sp_axis or "sp",
                         alpha=cfg.alpha if cfg.alpha > 0 else 0.99,
                         dtype=dtype, remat=cfg.remat,
                         remat_policy=cfg.remat_policy,
                         dropout_impl=cfg.dropout_impl, ffn_impl=ffn_impl,
                         fused_qkv=not tricks_off, quant=quant,
                         lm_head=getattr(cfg, "task", "cls") == "lm",
                         tie_lm_head=(getattr(cfg, "task", "cls") == "lm"
                                      and getattr(cfg, "tie_lm_head",
                                                  True)),
                         causal=causal)
    if (getattr(cfg, "quant", "none") or "none") != "none":
        import warnings
        warnings.warn(
            f"--quant {cfg.quant} is only wired for the transformer's "
            f"GEMMs (attention projections + FFN); {cfg.model} runs "
            f"full-precision", stacklevel=2)
    return get_model(cfg.model, cfg.num_classes, dtype=dtype,
                     remat=cfg.remat, conv_remat=not tricks_off)


def make_loaders(cfg: TrainConfig, train_ds, eval_ds, dp: int = 1
                 ) -> Tuple[Callable, Callable, int]:
    """(train_loader(epoch), eval_loader(epoch), steps_per_epoch).

    cfg.batch_size is the GLOBAL batch: each host loads batch_size /
    process_count samples and make_array_from_process_local_data
    assembles the global array (DistributedSampler semantics,
    resnet50_test.py:331)."""
    import jax

    from faster_distributed_training_tpu.data import (BatchLoader,
                                                      PrefetchIterator)
    from faster_distributed_training_tpu.data.loader import (
        ParallelBatchIterator, dataset_len)

    pc = jax.process_count()
    if cfg.batch_size % pc:
        raise ValueError(f"global batch {cfg.batch_size} not divisible by "
                         f"{pc} processes")
    if dp > 1 and cfg.batch_size % dp:
        raise ValueError(f"global batch {cfg.batch_size} not divisible by "
                         f"the data-parallel world size {dp}")
    local_bs = cfg.batch_size // pc

    if cfg.debug:
        # multi-host data contract: local partition algebra + cross-host
        # agreement on the actual sharding inputs (collective)
        from faster_distributed_training_tpu.data import (
            verify_host_shards, verify_host_shards_global)
        n_train = dataset_len(train_ds)
        verify_host_shards(n_train, epoch=0, seed=cfg.seed)
        verify_host_shards_global(n_train, epoch=0, seed=cfg.seed)

    # --workers N > 1: a thread pool materializes batches concurrently
    # (tokenize/gather run in the GIL-releasing C++ core), the reference's
    # DataLoader worker model (resnet50_test.py:52,321-352); otherwise one
    # background prefetch thread.
    def _wrap(loader):
        if cfg.prefetch_depth <= 0:
            # genuinely synchronous iteration (the bag-of-tricks OFF arm):
            # no background thread at all — queue.Queue(maxsize=0) would
            # mean an UNBOUNDED prefetch queue, the opposite of the intent
            return loader
        if cfg.workers > 1:
            return ParallelBatchIterator(loader, cfg.workers,
                                         depth=max(cfg.prefetch_depth,
                                                   cfg.workers))
        return PrefetchIterator(loader, depth=cfg.prefetch_depth)

    def train_loader(epoch: int):
        return _wrap(
            BatchLoader(train_ds, local_bs, epoch=epoch, seed=cfg.seed,
                        shuffle=True, max_len=cfg.seq_len))

    # eval pads the final partial batch with valid=0 samples (BatchLoader
    # pad_last) so the whole split counts toward test accuracy at any
    # --bs — matching the reference's full-split eval
    # (resnet50_test.py:631-659); the padded batch keeps the train batch
    # shape, so dp-sharding constraints are unchanged and eval can never
    # be starved by a small (e.g. subset-strided) split
    def eval_loader(epoch: int):
        return _wrap(
            BatchLoader(eval_ds, local_bs, epoch=0, seed=cfg.seed,
                        shuffle=False, max_len=cfg.seq_len, pad_last=True))

    steps = len(BatchLoader(train_ds, local_bs))
    return train_loader, eval_loader, max(steps, 1)


def _warm_spare_park(trainer, state, res, train_loader, eval_loader,
                     telemetry, log) -> Optional[dict]:
    """Warm-spare pre-admission (r17): warm the steady-state programs
    through the observatory (and its executable cache, when armed) and
    park on the coordinator until a failed slice's seat is claimable.
    Each new COMMIT is restored once as a PROBE — proving the newest
    checkpoint restorable and keeping the storage medium warm before
    the swap depends on it — but the restored tree is deliberately NOT
    retained: holding a second full state resident would double the
    spare's HBM footprint for the whole park, and the post-claim
    attempt path re-restores through the slice-scoped barrier anyway
    (restore is the cheap half of MTTR; the programs are the warm
    part).  Returns the claim dict after ``Resilience.adopt_seat``
    re-keys the bundle (the caller then runs the normal supervised
    attempt path: the coordinator is already rejoining under the
    adopted identity), or None when the pod completed incident-free."""
    from faster_distributed_training_tpu.telemetry import spans

    coord = res.coordinator
    log(f"[spare] warm spare {coord.spare_index} pre-admitting: warming "
        f"programs + restoring to the last COMMIT")
    with spans.span("spare_warm"):
        warmed = trainer.warm_programs(state, train_loader, eval_loader)
    log(f"[spare] {warmed} program(s) warm; parking for incidents "
        f"(claim = first CLAIM marker writer wins)")
    if telemetry is not None:
        telemetry.recorder.record_event("spare", event="parked",
                                        spare=int(coord.spare_index))
    warm = {"step": -1}

    def refresh():
        if res.manager is None:
            return
        newest = res.manager.latest_valid()
        if newest is None or newest[0] <= warm["step"]:
            return
        got = res.manager.peek_latest(state)
        if got is not None:
            _st, meta = got      # restorability probe only — dropped
            warm["step"] = int(meta.get("step", newest[0]))
            log(f"[spare] COMMIT step {warm['step']} verified restorable")

    claim = coord.spare_wait(refresh_fn=refresh)
    if claim is None:
        if telemetry is not None:
            telemetry.recorder.record_event(
                "spare", event="stood_down", spare=int(coord.spare_index))
        return None
    res.adopt_seat(claim["seat"])
    if telemetry is not None:
        fields = {"event": "claimed", "spare": int(coord.spare_index),
                  "seat": int(claim["seat"]),
                  "generation": int(claim["generation"]),
                  "step": int(warm["step"])}
        fields["slice"] = int(claim["slice"])
        telemetry.recorder.record_event("spare", **fields)
    return claim


def run_training(cfg: TrainConfig,
                 log: Callable[[str], None] = print) -> dict:
    """Full training run; returns {'state','history','best_acc','cfg'}."""
    setup_platform(cfg)

    import jax
    import jax.numpy as jnp

    from faster_distributed_training_tpu.data.augment import augment_batch
    from faster_distributed_training_tpu.optim import build_optimizer
    from faster_distributed_training_tpu.parallel import (
        initialize_distributed, make_mesh)
    from faster_distributed_training_tpu.parallel.placement import (
        dp_size, make_put_batch, shard_train_state, train_state_shardings)
    from faster_distributed_training_tpu.train import (Trainer,
                                                       create_train_state,
                                                       init_attn_lambda,
                                                       init_meta_lambda)
    from faster_distributed_training_tpu.train.steps import resolve_mixup_mode
    from faster_distributed_training_tpu.utils.plotting import draw_graph
    from faster_distributed_training_tpu.utils.profiling import trace_profile

    if cfg.distributed:
        initialize_distributed()

    mesh = make_mesh(cfg.mesh_axes, cfg.mesh_shape)
    is_text = cfg.model == "transformer"

    if cfg.data_path == "stream":
        if cfg.dataset != "stream":
            raise ValueError(
                f"--data_path stream reads the sharded on-disk format; "
                f"use --dataset stream --stream_dir <dir> (got dataset="
                f"{cfg.dataset!r}; scripts/shard_dataset.py shards a "
                f"corpus/split into that format)")
        if cfg.subset_stride > 1:
            raise ValueError("--subset_stride is not supported with "
                             "--data_path stream (the window refill "
                             "addresses the full on-disk index space); "
                             "shard a smaller dataset instead")
    if cfg.dataset == "stream":
        # chaos arm FDT_FAULT_CORRUPT_SHARD (resilience/faults.py): flip
        # bytes inside one committed shard file BEFORE the reader opens
        # its mmaps — the manifest sizes still match, so only the CRC
        # screen (data/stream/reader.py) can catch it, which is the point
        from faster_distributed_training_tpu.resilience.faults import (
            apply_corrupt_shard_fault)
        apply_corrupt_shard_fault(cfg.stream_dir, log=log)
    train_ds = apply_subset(load_dataset(cfg, train=True), cfg.subset_stride)
    eval_ds = apply_subset(load_dataset(cfg, train=False), cfg.subset_stride)
    if cfg.dataset == "stream" and is_text:
        if (train_ds.manifest.get("content") == "lm"
                and getattr(cfg, "task", "cls") != "lm"):
            # the packed LM rows carry NO labels — the reader fabricates
            # zero labels purely as shape placeholders, so a cls run
            # would "learn" constant class 0 to 100% accuracy silently
            raise ValueError(
                f"{cfg.stream_dir} is an LM-content corpus (packed token "
                f"rows, no labels) but --task is {cfg.task!r} — train it "
                f"with --task lm")
        # pre-tokenized packed rows have ONE width; the model's maxlen
        # and every bucket decision must agree with it
        sl = int(getattr(train_ds, "seq_len", 0) or 0)
        if sl and sl != cfg.seq_len:
            log(f"[data] stream dataset rows are seq_len={sl}; "
                f"overriding --seq_len {cfg.seq_len}")
            cfg = cfg.replace(seq_len=sl)
    vocab = train_ds.vocab_size() if is_text else None
    model = build_model(cfg, vocab_size=vocab, mesh=mesh)

    # pp>1 (r22): the third parallelism axis — encoder layers staged
    # over pp, microbatched 1F1B inside the K-dispatch scan.  Every
    # routing decision (stage assignment, microbatch count, collective
    # placement) is made HERE, once, in parallel/pipeline.py and dumped
    # as one rule table into manifest.json beside the compile table.
    # None on every pp=1 mesh — those programs stay byte-identical.
    from faster_distributed_training_tpu.parallel.pipeline import (
        build_pipeline_spec, pipeline_rules, stage_idle_ticks)
    pipeline = build_pipeline_spec(
        cfg, mesh,
        attention_impl=getattr(model, "attention_impl", None))
    if pipeline is not None:
        log(f"[pipeline] pp={pipeline.n_stages} stages x "
            f"{pipeline.n_microbatches} microbatches "
            f"({pipeline.schedule}): layers "
            f"{[list(s) for s in pipeline.stage_layers]}, "
            f"bubble {pipeline.bubble_pct:.1f}% "
            f"({pipeline.n_ticks} ticks/step; stage boundary = "
            f"collective-permute over pp, the DCN hop)")

    train_loader, eval_loader, steps_per_epoch = make_loaders(
        cfg, train_ds, eval_ds, dp=dp_size(mesh))

    # xN LR scaling: actual DP world size, not the reference's hard-coded
    # x4 (resnet50_test.py:482-483).
    tx, _ = build_optimizer(cfg, steps_per_epoch,
                            lr_scale=float(dp_size(mesh)))

    rng = jax.random.PRNGKey(cfg.seed)
    if is_text:
        sample = jnp.zeros((cfg.batch_size, cfg.seq_len), jnp.int32)
        extra = None
    else:
        sample = jnp.zeros((cfg.batch_size, 32, 32, 3), jnp.float32)
        # learnable-lambda modes own a trainable leaf beside the model:
        # meta = per-sample scalar, attn = per-pixel NHWC map
        # (resnet50_test.py:388-401, 404-424)
        mode = resolve_mixup_mode(cfg)
        if mode == "meta":
            extra = {"mixup_lambda": init_meta_lambda(rng, cfg.batch_size)}
        elif mode == "attn":
            extra = {"mixup_lambda": init_attn_lambda(rng, cfg.batch_size,
                                                      32, 32, 3)}
        else:
            extra = None
    state = create_train_state(model, tx, sample, rng,
                               init_kwargs={"train": True},
                               extra_params=extra)
    # the explicit sharding tree is needed beyond --host_offload on any
    # mesh with a model axis: the train step pins its OUTPUT state to it
    # (steps.make_train_step) so XLA's partitioner can neither drift the
    # updated tp-sharded params toward replication nor scatter
    # replicated params onto the sp axis between donated steps
    # (measured: an sp mesh without the pin re-sharded pos_embedding
    # over sp after step 1 and the donated recall mismatched)
    from faster_distributed_training_tpu.parallel.mesh import (pp_size,
                                                               sp_size,
                                                               tp_size)
    shardings = (train_state_shardings(state, mesh, cfg,
                                       pipeline=pipeline)
                 if cfg.host_offload or cfg.offload_opt_state
                 or cfg.overlap_grad_reduce or tp_size(mesh) > 1
                 or sp_size(mesh) > 1 or pp_size(mesh) > 1 else None)
    state = shard_train_state(state, mesh, cfg, shardings=shardings)

    # TRAIN augmentation lives inside the train step now (steps.py):
    # uint8 batches are crop/flip/normalized on device with the key
    # derived from the CHECKPOINTED step counter — fold_in(PRNGKey(seed+1),
    # state.step) — so a resumed run's augmentation stream is bitwise-
    # identical to an uninterrupted one (the r7 ROADMAP gap: the old
    # host-side aug_counter restarted at 0 on resume) and the K-step
    # fused dispatch advances it with zero host involvement.  Train
    # staging therefore uploads RAW uint8 (4x less H2D than the old
    # augment-at-put float32); eval still normalizes at staging (no RNG).
    aug_key = jax.random.PRNGKey(cfg.seed + 1)
    aug = jax.jit(augment_batch, static_argnames=("train",))

    def eval_augment(batch):
        if is_text or "image" not in batch:
            return batch
        return {**batch, "image": aug(aug_key, batch["image"], train=False)}

    put_train = make_put_batch(mesh)
    put_stacked = make_put_batch(mesh, stacked=True)
    put_eval = make_put_batch(mesh, eval_augment)

    # --data_path resident: the train split uploads once — replicated on
    # one host, per-host ROW-SHARDED on pods (each process's HBM holds
    # only its ~n/process_count shard; one jitted re-shard per epoch
    # builds the batch-major view the dispatch indexes locally)
    from faster_distributed_training_tpu.data.device_resident import (
        build_device_resident)
    # --data_path stream (r18): the split stays on disk; a fixed device
    # window (2 buffers x stream_window batches) is refilled by a
    # background double-buffered H2D thread (data/stream/window.py)
    from faster_distributed_training_tpu.data.stream import build_stream
    # the text flavor's train_ds IS the open reader — reuse its mmaps
    stream = build_stream(cfg, mesh=mesh, dataset=train_ds)
    if stream is not None:
        log(f"[data] streaming train split from disk: {stream.n} samples "
            f"({stream.dataset.nbytes_on_disk / 1e6:.0f} MB on disk, "
            f"{len(stream.dataset.manifest['shards'])} shard(s)), device "
            f"window 2x{stream.window} batches "
            f"(peak ~{stream.nbytes / 1e6:.1f} MB/host), "
            f"{stream.steps_per_epoch} steps/epoch"
            + (f", seq_len={stream.seq_len}" if stream.is_text else ""))
    resident = build_device_resident(cfg, train_ds, mesh=mesh)
    if resident is not None:
        layout = ("sharded" if getattr(resident, "batch_major", False)
                  else "replicated")
        log(f"[data] device-resident train split ({layout}): "
            f"{resident.n} samples, {resident.nbytes / 1e6:.0f} MB "
            f"{'per-host shard' if layout == 'sharded' else 'in HBM'}, "
            f"{resident.steps_per_epoch} steps/epoch"
            + (f", seq_len={resident.seq_len}" if resident.is_text else ""))

    from faster_distributed_training_tpu.resilience import (Preempted,
                                                            Supervisor,
                                                            build_resilience)
    from faster_distributed_training_tpu.train.metrics import attach_goodput

    if cfg.supervise and not (cfg.checkpoint_every
                              or cfg.checkpoint_every_secs):
        # a supervisor without restore points can only replay from scratch;
        # default to one step-cadence save per epoch
        cfg = cfg.replace(checkpoint_every=steps_per_epoch)
        log(f"[resilience] --supervise without a checkpoint cadence: "
            f"defaulting --checkpoint_every to {steps_per_epoch} "
            f"(one save per epoch)")
    # K-step fused dispatch: the checkpoint/preemption cadence only
    # polls at dispatch boundaries, so the save cadence must quantize to
    # a multiple of K (rounded UP — never save more often than asked)
    k = max(int(cfg.steps_per_dispatch or 1), 1)
    if k > 1 and cfg.checkpoint_every and cfg.checkpoint_every % k:
        rounded = -(-cfg.checkpoint_every // k) * k
        import warnings
        warnings.warn(
            f"--checkpoint_every {cfg.checkpoint_every} is not a multiple "
            f"of --steps_per_dispatch {k}; rounding up to {rounded} "
            f"(checkpoints land on dispatch boundaries)", stacklevel=2)
        log(f"[ckpt] checkpoint_every rounded {cfg.checkpoint_every} -> "
            f"{rounded} (multiple of steps_per_dispatch={k})")
        cfg = cfg.replace(checkpoint_every=rounded)
    res = build_resilience(cfg, log=log)
    # stream-shard CRC quarantine events land in the sentinel's durable
    # ledger + goodput counters (goodput-only when the sentinel is off —
    # the reader warns + remaps regardless, see data/stream/reader.py)
    if res is not None:
        reader = (stream.dataset if stream is not None
                  else train_ds if hasattr(train_ds, "on_quarantine")
                  else None)
        if reader is not None:
            if res.sentinel is not None:
                reader.on_quarantine = res.sentinel.quarantine_shard
            else:
                reader.on_quarantine = (
                    lambda s, p: res.goodput.count("quarantined_shards"))
    if (resident is not None
            and getattr(resident, "upload_checksums", None)
            and getattr(cfg, "sentinel", "none") == "full"):
        # end-to-end upload integrity (--sentinel full): re-read the
        # device-resident split and compare against the host-side
        # checksums taken at encode time — once, before training, off
        # the hot path (raises on mismatch; a corrupt upload must not
        # train silently)
        resident.verify_upload()
        log("[sentinel] device-resident upload verified: post-upload "
            "readback matches the host-side encode checksums")
    if res is not None and cfg.donate and jax.default_backend() == "cpu":
        # Measured (r7): on jaxlib 0.4.x's CPU client, a checkpoint
        # restore followed by donating the state back into the compiled
        # step corrupts the heap (glibc "corrupted double-linked list" /
        # SIGSEGV at the first post-restore step) — the donated-buffer
        # dealloc bug class the `donate` flag exists to route around.
        # Resilient runs make restore-then-continue a NORMAL path rather
        # than a manual --resume rarity, so the CPU backend (the test/
        # gate simulator, never the perf path) trades donation away on
        # affected jaxlibs; TPU keeps both donation and resilience.  The
        # workaround is VERSION-GATED (donation_workaround_needed): once
        # the container's jaxlib moves past 0.4.x the retest is
        # automatic — donation stays on and the log records it.
        _jlv = _jaxlib_version() or "?"  # unparseable -> the predicate
        #                                  keeps the workaround
        if donation_workaround_needed(_jlv):
            cfg = cfg.replace(donate=False)
            log(f"[resilience] CPU backend on jaxlib {_jlv} (0.4.x-class): "
                f"buffer donation disabled for this run (restore-then-"
                f"donate corrupts this CPU client's heap; TPU runs keep "
                f"donation — gate auto-re-enables past 0.4.x)")
        else:
            log(f"[resilience] CPU backend on jaxlib {_jlv} (> 0.4.x): "
                f"r7 restore-then-donate workaround NOT applied — "
                f"donation stays on (ROADMAP retest satisfied; if this "
                f"run segfaults post-restore, re-open the workaround)")

    # -- telemetry (r12): every run emits the structured surface bench.py
    # used to monopolize — per-dispatch JSONL + manifest + span breakdown
    # + (pods) the epoch straggler fold.  build_telemetry returns None
    # under --no_telemetry / FDT_TELEMETRY=0 and the hot loop gets zero
    # new work.
    from faster_distributed_training_tpu.telemetry import (
        build_telemetry, flight, programs, resolve_telemetry_dir, spans,
        write_manifest)
    from faster_distributed_training_tpu.utils.profiling import (
        StepWindowProfiler, parse_profile_steps)

    ckpt_name = "transformer" if is_text else "resnet"
    telemetry = build_telemetry(cfg, log=log)
    prev_span_recorder = None
    prev_observatory = None
    prev_flight = None
    if telemetry is not None:
        prev_span_recorder = spans.set_recorder(telemetry.recorder)
        # the compile observatory doubles as a process-global (the span
        # idiom) so seams outside the Trainer — the device-resident
        # epoch re-shard — observe their compiles through it too
        prev_observatory = programs.set_observatory(telemetry.observatory)
        # crash flight recorder: failure seams (supervisor, watchdog,
        # the unhandled-exception escape below) dump the in-memory ring
        # + open spans + program table durably — through the r14
        # storage backend when resilience has one, so a dead slice
        # leaves forensics where the pod can read them
        prev_flight = flight.configure(
            telemetry.directory,
            backend=res.backend if res is not None else None,
            goodput=res.goodput if res is not None else None, log=log)
        if telemetry.pi == 0:
            write_manifest(telemetry.directory, cfg, mesh,
                           extra={"steps_per_epoch": steps_per_epoch,
                                  "workload": ckpt_name,
                                  # the pp routing/stage rule table —
                                  # one inspectable record of every
                                  # pipeline decision, beside the
                                  # compile table telemetry.close merges
                                  "pipeline": pipeline_rules(pipeline,
                                                             cfg)})
        if pipeline is not None:
            # schedule accounting into the telemetry stream: the
            # analytic bubble (the executed program pays exactly this —
            # fill/drain ticks compute on discarded microbatches) and the
            # per-stage idle/active tick split the pp_stage_idle_ms
            # bench arm scales by measured tick time
            telemetry.recorder.record_event(
                "pp_bubble", n_stages=pipeline.n_stages,
                n_microbatches=pipeline.n_microbatches,
                n_ticks=pipeline.n_ticks, schedule=pipeline.schedule,
                bubble_pct=round(pipeline.bubble_pct, 3))
            for s, idle in enumerate(stage_idle_ticks(pipeline)):
                telemetry.recorder.record_event(
                    "pp_stage", stage=s,
                    layers=[f"layer_{i}"
                            for i in pipeline.stage_layers[s]],
                    idle_ticks=idle,
                    # slot-tick units, matching idle_ticks: M per slot
                    # x V/S slots per stage (== M for 1f1b)
                    active_ticks=pipeline.n_microbatches
                    * (pipeline.n_virtual // pipeline.n_stages))
        if res is not None:
            # restart/preemption/peer-failure counters land in the
            # stream as they happen (goodput.set_event_sink)
            res.goodput.set_event_sink(telemetry.recorder
                                       .goodput_event_sink)
        log(f"[telemetry] recording to {telemetry.directory} "
            f"(host {telemetry.pi}/{telemetry.pc}; disable with "
            f"--no_telemetry or FDT_TELEMETRY=0)")
    if telemetry is not None and telemetry.observatory is not None:
        # r17 instant restart: the persistent executable cache rides the
        # compile observatory (lookup-before-compile / store-after-
        # compile — a restarted process deserializes its programs,
        # cache_source=deserialized in the manifest compile table), and
        # the observatory feeds program-acquisition seconds to goodput
        # so restart MTTR splits into compile vs restore components
        from faster_distributed_training_tpu.resilience.executable_cache \
            import build_executable_cache
        telemetry.observatory.executable_cache = build_executable_cache(
            cfg, backend=res.backend if res is not None else None,
            mesh=mesh, log=log)
        if res is not None:
            telemetry.observatory.goodput = res.goodput
    profiler = None
    window = parse_profile_steps(cfg.profile_steps)
    if window is not None:
        trace_dir = os.path.join(resolve_telemetry_dir(cfg),
                                 f"trace_steps_{window[0]}_{window[1]}")
        profiler = StepWindowProfiler(trace_dir, *window, log=log)
        log(f"[profile] windowed capture armed: global steps "
            f"{window[0]}..{window[1]} -> {trace_dir}")

    preempted = False
    with mesh:
        trainer = Trainer(cfg, put_batch=put_train,
                          put_eval_batch=put_eval, log=log,
                          state_shardings=shardings, resilience=res,
                          put_stacked=put_stacked, resident=resident,
                          telemetry=telemetry, profiler=profiler,
                          stream=stream, pipeline=pipeline)

        # restored states (host numpy) must land back on the run's
        # sharding policy — placement.place_on_shardings, shared with
        # the loop's auto-recover rollback
        from faster_distributed_training_tpu.parallel.placement import (
            place_on_shardings)

        state, start_epoch = trainer.maybe_resume(state, ckpt_name)
        state = place_on_shardings(state, shardings)

        def attempt(restart_index: int):
            """One training attempt: resume from the newest VALID
            step-cadence checkpoint when one exists (crash recovery AND
            process-restart recovery share this path), else from the
            epoch-checkpoint/fresh state.

            Deliberately NOT gated on --resume: after a preemption the
            platform re-runs the same command, and that re-launch must
            pick up the emergency checkpoint unaided (the standard
            production-manager semantic).  Corollary, documented in the
            README: a checkpoint_dir with step checkpoints in it always
            resumes — re-running a COMPLETED run's command is an
            (intentional) idempotent no-op; point --checkpoint_dir at a
            fresh directory for a fresh run."""
            st, ep, sie, restored_step = state, start_epoch, 0, 0
            rejoining = (res is not None and res.coordinator is not None
                         and res.coordinator.rejoining)
            if res is not None and res.manager is not None:
                prev_step = trainer.global_step
                got = res.manager.restore_latest(st)
                if got is not None:
                    st, meta = got
                    st = place_on_shardings(st, shardings)
                    ep = int(meta.get("epoch", 0))
                    sie = int(meta.get("step_in_epoch", 0))
                    trainer.best_acc = float(meta.get("best_acc",
                                                      trainer.best_acc))
                    restored_step = step = int(meta.get("step", 0))
                    log(f"[resume] restored step-cadence checkpoint: "
                        f"step {step} (epoch {ep}, batch {sie})")
                    if restart_index > 0 and prev_step > step:
                        # rollback badput: steps re-run because the newest
                        # checkpoint predates the crash, costed at the
                        # run's observed productive step time
                        s = res.goodput.summary()
                        if s["steps"]:
                            res.goodput.add(
                                "rollback_lost_s",
                                (prev_step - step)
                                * s["productive_s"] / s["steps"])
                elif cfg.supervise and restart_index == 0 and not rejoining:
                    # seed a step-0 restore point so a crash before the
                    # first cadence save is still recoverable (the donated
                    # live state can't serve as one).  Never while
                    # REJOINING: the parked survivors are not taking this
                    # tick, so its commit barrier could only time out.
                    res.manager.save(st, 0, epoch=ep, step_in_epoch=0,
                                     best_acc=trainer.best_acc)
            if rejoining:
                # rejoining slice (r14): agree the catch-up target with
                # the parked survivors now — when the restored step
                # already IS the target, the readiness handshake
                # completes here, before the dispatch loop re-enters
                res.coordinator.rejoin_sync(restored_step)
            return trainer.fit(st, train_loader, eval_loader,
                               ckpt_name=ckpt_name, start_epoch=ep,
                               start_step_in_epoch=sie)

        with trace_profile("./profile" if cfg.profile else None):
            try:
                spare_stood_down = False
                if (res is not None and res.coordinator is not None
                        and res.coordinator.spare_index is not None):
                    # r17 warm spare: pre-admit (programs warmed through
                    # the executable cache, params restored to the last
                    # COMMIT + refreshed) and park until a failed seat
                    # is claimable; on a claim the coordinator is
                    # already in rejoin mode under the adopted identity
                    # and the NORMAL supervised attempt path below runs
                    # the swap (restore through the slice barrier, catch
                    # up, RJREADY, release, then train to completion)
                    claim = _warm_spare_park(trainer, state, res,
                                             train_loader, eval_loader,
                                             telemetry, log)
                    spare_stood_down = claim is None
                if spare_stood_down:
                    log("[spare] pod completed without an incident; "
                        "spare stands down (state untouched)")
                elif res is not None and cfg.supervise:
                    # coordinator (pods / --step_timeout_s): every attempt
                    # enters the shared-fs generation rendezvous and every
                    # failure is published as a FAIL marker BEFORE the
                    # backoff, so all hosts of the pod restart together
                    # (resilience/coordinator.py)
                    sup = Supervisor(max_restarts=cfg.max_restarts,
                                     goodput=res.goodput, log=log,
                                     coordinator=res.coordinator)
                    state = sup.run(attempt,
                                    progress=lambda: trainer.global_step)
                else:
                    state = attempt(0)
            except Preempted as p:
                preempted = True
                if p.state is not None:
                    state = p.state
                log(f"[preempt] training stopped cleanly at step {p.step}; "
                    f"re-launch with the same --checkpoint_dir to resume")
            except BaseException as e:
                # the run is dying for good (supervisor budget exhausted,
                # deterministic crash, an unsupervised fault): leave the
                # flight dump behind before the exception escapes.  The
                # dump is per-exception-deduplicated, so an incident the
                # supervisor already dumped doesn't land twice.
                flight.emergency_dump("unhandled_exception", exc=e,
                                      step=trainer.global_step)
                raise
            finally:
                # even when training dies for good (supervisor budget
                # exhausted, deterministic crash re-raise): drain the
                # in-flight async save and give the SIGTERM/SIGINT
                # handlers back — a long-lived caller must not inherit a
                # swallowed Ctrl-C or a thread still writing checkpoints
                if res is not None:
                    res.close()
                if profiler is not None:
                    profiler.close()   # an open window is still captured
                if telemetry is not None:
                    # flush the tail, refresh pod_summary.json, merge the
                    # program table into the manifest, and give the
                    # process-global sinks back (a crashed run's
                    # telemetry is exactly the telemetry worth keeping)
                    telemetry.close()
                    spans.set_recorder(prev_span_recorder)
                    programs.set_observatory(prev_observatory)
                    flight.restore(prev_flight)

    if cfg.plot and jax.process_index() == 0 and trainer.history["test_acc"]:
        prefix = ckpt_name
        draw_graph(trainer.history["test_acc"], "test accuracy",
                   f"{prefix} test accuracy", f"{prefix}_accuracy.png")
        draw_graph(trainer.history["epoch_time"], "seconds",
                   f"{prefix} epoch time", f"{prefix}_time.png")
    out = {"state": state, "history": trainer.history,
           "best_acc": trainer.best_acc, "cfg": cfg}
    if stream is not None and trainer.stream_stall_pct is not None:
        # the streamed input path's headline: steady-state % of step
        # time blocked on the window refill (<1% target, bench arm
        # stream_stall_pct measures it under the guard)
        out["stream_stall_pct"] = round(trainer.stream_stall_pct, 3)
        log(f"[stream] steady-state stall: {out['stream_stall_pct']}% of "
            f"step time blocked on the data window (target <1%)")
    if telemetry is not None:
        out["telemetry_dir"] = telemetry.directory
    if res is not None:
        out["preempted"] = preempted
        attach_goodput(out, res.goodput)
    return out


def synth_requests(n: int, vocab: int, buckets, seed: int = 0,
                   min_len: int = 4):
    """Ragged synthetic serving request mix: ``n`` token arrays with
    lengths uniform over [min_len, max bucket] — every configured
    bucket gets traffic and partial batches occur naturally.  The
    CLI serve smoke's built-in load; scripts/serve_smoke.py builds a
    nastier mix (spill lengths, over-long truncation) on top."""
    rng = np.random.default_rng(seed)
    top = max(buckets)
    out = []
    for _ in range(int(n)):
        length = int(rng.integers(min_len, top + 1))
        out.append(rng.integers(1, max(int(vocab), 2),
                                size=length).astype(np.int32))
    return out


def run_serving(cfg: TrainConfig, requests=None,
                log: Callable[[str], None] = print) -> dict:
    """The serving entrypoint (the ROADMAP's "millions of users" half):
    load the trained artifact from ``cfg.checkpoint_dir`` through the
    configured StorageBackend, stand up the serve/ stack — AOT-warmed
    per-bucket predict programs, continuous-batching queue, N replicas
    with heartbeat liveness — push ``requests`` (ragged int32 token
    arrays; a synthetic mix of ``cfg.serve_requests`` when None)
    through it, and return results + latency/throughput summary.

    Replica layout (SNIPPETS [3] — 1D partitioning "is essentially
    always faster for inference/decoding"): REPLICATED-per-chip, one
    replica per local device, unless the mesh names a model axis —
    models that needed tp/sp to train don't fit one chip, so that case
    serves ONE model-sharded replica group over the mesh."""
    setup_platform(cfg)

    import jax

    from faster_distributed_training_tpu.parallel import make_mesh
    from faster_distributed_training_tpu.parallel.mesh import (sp_size,
                                                               tp_size)
    from faster_distributed_training_tpu.serve import (BatchScheduler,
                                                       InferenceEngine,
                                                       Replica, ReplicaSet,
                                                       RequestQueue,
                                                       load_serving_state)
    from faster_distributed_training_tpu.telemetry import (
        TelemetryRecorder, resolve_telemetry_dir, spans, update_manifest)

    mesh = make_mesh(cfg.mesh_axes, cfg.mesh_shape)
    sharded = tp_size(mesh) > 1 or sp_size(mesh) > 1
    recorder = None
    prev_rec = None
    obs = None
    prev_obs = None
    if cfg.telemetry and os.environ.get("FDT_TELEMETRY", "1") != "0":
        import dataclasses
        import time as time_mod

        tdir = resolve_telemetry_dir(cfg)
        recorder = TelemetryRecorder(tdir, log=log)
        # MERGE a serve section into the manifest — the documented flow
        # serves from the TRAINING checkpoint dir, whose manifest.json
        # carries the r15 compile/program table; write_manifest would
        # atomically replace it and wipe that evidence
        update_manifest(tdir, {"serve": {
            "unix_time": round(time_mod.time(), 3),
            "config": dataclasses.asdict(cfg)}})
        prev_rec = spans.set_recorder(recorder)
        # r17: serving gets its own compile observatory (run_training's
        # never existed in this process), so the engines' AOT warmups
        # observe through it — and through the persistent executable
        # cache when armed, a restarted serving replica deserializes
        # its serve:predict:L<bucket> programs instead of recompiling
        from faster_distributed_training_tpu.telemetry import (
            ProgramObservatory, programs)
        if programs.observatory_enabled():
            obs = ProgramObservatory(recorder=recorder, log=log)
            from faster_distributed_training_tpu.resilience \
                .executable_cache import build_executable_cache
            from faster_distributed_training_tpu.resilience.storage \
                import build_backend
            # the cache rides the SAME configured backend serving's
            # checkpoint loads do — a posix default here would strand
            # the entries on the local disk while the deployment's
            # durable medium (the one a replica restarted on another
            # machine can reach) is an object store
            obs.executable_cache = build_executable_cache(
                cfg,
                backend=build_backend(
                    getattr(cfg, "storage_backend", "posix"),
                    cfg.checkpoint_dir, log=log),
                mesh=mesh if sharded else None, log=log)
            prev_obs = programs.set_observatory(obs)
        log(f"[serve] telemetry recording to {tdir}")
    try:
        model, sstate, meta = load_serving_state(
            cfg, mesh=mesh if sharded else None, log=log)
        # the queue owns the eligible-bucket set (data.loader
        # .eligible_buckets — one rule); the engines warm exactly it
        q = RequestQueue(cfg.seq_buckets, max_len=cfg.seq_len)
        buckets = q.buckets
        if sharded:
            log(f"[serve] mesh {dict(mesh.shape)} has a model axis: the "
                f"model did not fit one chip — serving ONE model-sharded "
                f"replica group (SNIPPETS [3]: replicate per chip "
                f"whenever it fits; it doesn't here)")
            engines = [InferenceEngine(model.apply, sstate,
                                       cfg.serve_batch_size, buckets,
                                       mesh=mesh, name="replica0",
                                       log=log)]
            chips_serving = mesh.size
        else:
            devs = jax.local_devices()
            n_rep = int(cfg.serve_replicas) or len(devs)
            engines = [InferenceEngine(model.apply, sstate,
                                       cfg.serve_batch_size, buckets,
                                       device=devs[i % len(devs)],
                                       name=f"replica{i}", log=log)
                       for i in range(n_rep)]
            # replicas round-robin over local devices; fewer replicas
            # than chips occupy only min(n, devices) of them — the
            # per-chip headline divides by chips actually SERVING, not
            # the host's total (a 2-replica bench on an 8-chip host
            # would otherwise understate qps/chip 4x)
            chips_serving = min(n_rep, len(devs))
        with spans.span("serve_warmup"):
            warm_s = sum(e.warmup() for e in engines)
        log(f"[serve] {len(engines)} replica(s) x {len(buckets)} bucket "
            f"programs AOT-warmed in {warm_s:.1f}s "
            f"(buckets {list(buckets)}, batch {cfg.serve_batch_size})")
        replicas = [Replica(e.name, e, log=log) for e in engines]
        rset = ReplicaSet(
            replicas, heartbeat_timeout_s=cfg.serve_heartbeat_timeout_s,
            readmit_after_s=cfg.serve_readmit_s, log=log)
        sched = BatchScheduler(q, rset, batch_size=cfg.serve_batch_size,
                               max_delay_ms=cfg.serve_max_delay_ms,
                               recorder=recorder, log=log)
        sched.start()
        try:
            if requests is None:
                requests = synth_requests(cfg.serve_requests,
                                          meta.get("vocab") or 30522,
                                          buckets, seed=cfg.seed)
            handles = [q.submit(t) for t in requests]
            results = [h.wait(timeout=300.0) for h in handles]
        finally:
            sched.close()
        summary = sched.summary()
        out = {"results": results, "meta": meta, "cfg": cfg,
               "state": sstate, "replicas": rset.stats(), **summary,
               "chips_serving": chips_serving,
               "qps_per_chip": round(summary["qps"]
                                     / max(chips_serving, 1), 2)}
        log(f"[serve] served {summary['requests']} requests in "
            f"{summary['batches']} batches ({summary['padded_rows']} pad "
            f"rows): p50 {summary['p50_ms']} ms, p99 {summary['p99_ms']} "
            f"ms, {summary['qps']} qps ({out['qps_per_chip']}/chip)")
        return out
    finally:
        if recorder is not None:
            if obs is not None:
                from faster_distributed_training_tpu.telemetry import (
                    programs, update_manifest as _upd)
                programs.set_observatory(prev_obs)
                # the serve compile story under its OWN manifest key —
                # merging into "compile" would clobber the training
                # run's program table (the r16 lesson, kept)
                _upd(recorder.directory,
                     {"serve_compile": obs.summary()})
            spans.set_recorder(prev_rec)
            recorder.close()


def run_decode_serving(cfg: TrainConfig, prompts=None,
                       log: Callable[[str], None] = print) -> dict:
    """The AUTOREGRESSIVE serving entrypoint (ROADMAP item #1's online
    half): load the trained LM artifact from ``cfg.checkpoint_dir``,
    stand up the serve/decode stack — paged KV cache, AOT prefill +
    decode-step program families, token-granular continuous batching —
    push ``prompts`` (ragged int32 token arrays; a synthetic mix of
    ``cfg.decode_requests`` when None) through it with a
    ``cfg.decode_max_new_tokens`` budget each, and return the generated
    token arrays + TTFT/throughput summary.

    Replica layout is run_serving's SNIPPETS [3] decision verbatim:
    REPLICATED per chip (one DecodeEngine + DecodeScheduler per local
    device, all draining ONE queue) unless the mesh names a model axis,
    in which case ONE model-sharded replica serves over the mesh.  The
    multi-PROCESS front door (serve/decode/frontend.FrontDoor) stacks
    on top of this entrypoint — each worker process runs exactly this
    single-replica wiring."""
    setup_platform(cfg)

    import jax

    from faster_distributed_training_tpu.models.decode import SamplingCfg
    from faster_distributed_training_tpu.parallel import make_mesh
    from faster_distributed_training_tpu.parallel.mesh import (sp_size,
                                                               tp_size)
    from faster_distributed_training_tpu.serve import (RequestQueue,
                                                       load_serving_state)
    from faster_distributed_training_tpu.serve.decode import (
        DecodeEngine, DecodeScheduler)
    from faster_distributed_training_tpu.telemetry import (
        TelemetryRecorder, resolve_telemetry_dir, spans, update_manifest)
    from faster_distributed_training_tpu.train.metrics import percentiles

    mesh = make_mesh(cfg.mesh_axes, cfg.mesh_shape)
    sharded = tp_size(mesh) > 1 or sp_size(mesh) > 1
    recorder = None
    prev_rec = None
    obs = None
    prev_obs = None
    if cfg.telemetry and os.environ.get("FDT_TELEMETRY", "1") != "0":
        import dataclasses
        import time as time_mod

        tdir = resolve_telemetry_dir(cfg)
        recorder = TelemetryRecorder(tdir, log=log)
        # MERGE (never write_manifest): the training checkpoint dir's
        # manifest carries the r15 program table this run must not wipe
        update_manifest(tdir, {"decode_serve": {
            "unix_time": round(time_mod.time(), 3),
            "config": dataclasses.asdict(cfg)}})
        prev_rec = spans.set_recorder(recorder)
        from faster_distributed_training_tpu.telemetry import (
            ProgramObservatory, programs)
        if programs.observatory_enabled():
            obs = ProgramObservatory(recorder=recorder, log=log)
            from faster_distributed_training_tpu.resilience \
                .executable_cache import build_executable_cache
            from faster_distributed_training_tpu.resilience.storage \
                import build_backend
            # same durable backend as the checkpoint loads — a restarted
            # decode replica on another machine must reach the cached
            # executables too
            obs.executable_cache = build_executable_cache(
                cfg,
                backend=build_backend(
                    getattr(cfg, "storage_backend", "posix"),
                    cfg.checkpoint_dir, log=log),
                mesh=mesh if sharded else None, log=log)
            prev_obs = programs.set_observatory(obs)
        log(f"[decode] telemetry recording to {tdir}")
    try:
        model, sstate, meta = load_serving_state(
            cfg, mesh=mesh if sharded else None, log=log)
        q = RequestQueue(cfg.seq_buckets, max_len=cfg.seq_len)
        buckets = q.buckets
        sampling = SamplingCfg(method=cfg.decode_sample,
                               temperature=cfg.decode_temperature,
                               top_k=cfg.decode_top_k, seed=cfg.seed)
        if sharded:
            log(f"[decode] mesh {dict(mesh.shape)} has a model axis: the "
                f"model did not fit one chip — serving ONE model-sharded "
                f"decode replica (SNIPPETS [3]: replicate per chip "
                f"whenever it fits; it doesn't here)")
            engines = [DecodeEngine(model, sstate, buckets,
                                    batch_size=cfg.decode_batch_size,
                                    page=cfg.decode_page,
                                    max_pages=cfg.decode_max_pages,
                                    sampling=sampling, mesh=mesh,
                                    name="decode0", log=log)]
            chips_serving = mesh.size
        else:
            devs = jax.local_devices()
            n_rep = int(cfg.decode_replicas) or len(devs)
            engines = [DecodeEngine(model, sstate, buckets,
                                    batch_size=cfg.decode_batch_size,
                                    page=cfg.decode_page,
                                    max_pages=cfg.decode_max_pages,
                                    sampling=sampling,
                                    device=devs[i % len(devs)],
                                    name=f"decode{i}", log=log)
                       for i in range(n_rep)]
            chips_serving = min(n_rep, len(devs))
        with spans.span("decode_warmup"):
            warm_s = sum(e.warmup() for e in engines)
        log(f"[decode] {len(engines)} replica(s) x ({len(buckets)} "
            f"prefill + {engines[0].max_pages} decode-step) programs "
            f"AOT-warmed in {warm_s:.1f}s (buckets {list(buckets)}, "
            f"page {cfg.decode_page}, {cfg.decode_batch_size} slots)")
        scheds = [DecodeScheduler(q, e,
                                  max_delay_ms=cfg.serve_max_delay_ms,
                                  max_new_tokens=cfg.decode_max_new_tokens,
                                  recorder=recorder, name=e.name, log=log)
                  for e in engines]
        for s in scheds:
            s.start()
        try:
            if prompts is None:
                prompts = synth_requests(cfg.decode_requests,
                                         meta.get("vocab") or 30522,
                                         buckets, seed=cfg.seed)
            handles = [q.submit(t,
                                max_new_tokens=cfg.decode_max_new_tokens)
                       for t in prompts]
            results = [h.wait(timeout=300.0) for h in handles]
        finally:
            q.close()
            for s in scheds:
                s.close()
        # aggregate across schedulers: one summary over the union of
        # their per-request samples (percentiles are over the combined
        # population, not an average of per-replica percentiles)
        ttft, total = [], []
        n_req = toks = steps = prefills = 0
        t_first, t_last = None, None
        for s in scheds:
            ttft += [t for t in s.ttft_ms if t is not None]
            total += [t for t in s.total_ms if t is not None]
            n_req += s.completed_requests
            toks += s.generated_tokens
            steps += s.engine.steps
            prefills += s.engine.prefills
            if s._t_first is not None:
                t_first = s._t_first if t_first is None \
                    else min(t_first, s._t_first)
            if s._t_last is not None:
                t_last = s._t_last if t_last is None \
                    else max(t_last, s._t_last)
        wall = ((t_last - t_first)
                if (t_first is not None and t_last is not None
                    and t_last > t_first) else 0.0)
        pt = percentiles(ttft, qs=(50, 99))
        pl = percentiles(total, qs=(50, 99))
        tps = round(toks / wall, 2) if wall else 0.0
        out = {"results": results, "meta": meta, "cfg": cfg,
               "state": sstate,
               "requests": n_req, "tokens": toks, "steps": steps,
               "prefills": prefills,
               "ttft_p50_ms": pt.get(50, 0.0),
               "ttft_p99_ms": pt.get(99, 0.0),
               "latency_p50_ms": pl.get(50, 0.0),
               "latency_p99_ms": pl.get(99, 0.0),
               "tokens_per_sec": tps,
               "chips_serving": chips_serving,
               "tokens_per_sec_per_chip": round(
                   tps / max(chips_serving, 1), 2)}
        log(f"[decode] generated {toks} tokens for {n_req} requests in "
            f"{steps} steps ({prefills} prefills): TTFT p50 "
            f"{out['ttft_p50_ms']} ms / p99 {out['ttft_p99_ms']} ms, "
            f"{tps} tok/s ({out['tokens_per_sec_per_chip']}/chip)")
        return out
    finally:
        if recorder is not None:
            if obs is not None:
                from faster_distributed_training_tpu.telemetry import (
                    programs, update_manifest as _upd)
                programs.set_observatory(prev_obs)
                # decode's compile story under its OWN manifest key —
                # "serve_compile" belongs to the classifier tier
                _upd(recorder.directory,
                     {"decode_compile": obs.summary()})
            spans.set_recorder(prev_rec)
            recorder.close()


def main(argv=None, defaults: Optional[TrainConfig] = None,
         prog: str = "fdt") -> dict:
    parser = build_parser(prog=prog, defaults=defaults)
    args = parser.parse_args(argv)
    cfg = config_from_args(args, defaults=defaults)
    return run_training(cfg)


def main_serve(argv=None, defaults: Optional[TrainConfig] = None,
               prog: str = "fdt-serve") -> dict:
    """The ``serve`` CLI twin of :func:`main`: same flag surface, but
    the checkpoint_dir is READ (never written) and the run pushes a
    synthetic ragged request mix through the serving stack instead of
    training.  ``python -m faster_distributed_training_tpu.serve.run``
    / scripts/serve_smoke.py are the script-level entries."""
    parser = build_parser(prog=prog, defaults=defaults)
    args = parser.parse_args(argv)
    cfg = config_from_args(args, defaults=defaults)
    out = run_serving(cfg)
    # CLI use: the numbers, not the tensors — drop the logits, the live
    # param bundle and the config object (meta/summary/replica stats
    # are plain scalars)
    for heavy in ("results", "state", "cfg"):
        out.pop(heavy, None)
    return out


def main_decode(argv=None, defaults: Optional[TrainConfig] = None,
                prog: str = "fdt-decode") -> dict:
    """The ``decode`` CLI twin of :func:`main_serve`: same flag surface,
    checkpoint_dir READ only, a synthetic ragged prompt mix generated
    to ``cfg.decode_max_new_tokens`` each.  scripts/decode_smoke.py is
    the script-level entry (with the multi-process front door on top)."""
    parser = build_parser(prog=prog, defaults=defaults)
    args = parser.parse_args(argv)
    cfg = config_from_args(args, defaults=defaults)
    out = run_decode_serving(cfg)
    for heavy in ("results", "state", "cfg"):
        out.pop(heavy, None)
    return out
