"""Serving request queue: ragged requests binned into training buckets.

A request arrives as a ragged 1-D int32 token array.  ``submit`` bins
it by :func:`data.loader.select_bucket` — the ONE bucket-selection rule
the training text pipeline already compiled programs for, which is what
keeps an arbitrary request mix from ever retracing an inference
program: a 65-token request on (64, 128) buckets SPILLS to the 128
bucket, and a request longer than the largest eligible bucket runs
truncated at it (``bucket_length``'s last-bucket-truncates rule,
data/agnews.py — same behavior a too-long training sample gets).

The queue holds one FIFO per bucket.  :meth:`take_cell` is the
continuous-batching drain the scheduler loop calls: a bucket whose
oldest request has crossed the latency deadline dispatches FIRST (as a
partial batch if under-full — the scheduler pads it with masked rows;
deadline beats batch-fullness so no bucket can starve behind another's
sustained full-batch traffic), then any bucket holding a full batch
dispatches immediately.  Requests keep arriving while replicas compute
— nothing here ever blocks a submitter on a dispatch.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from faster_distributed_training_tpu.data.loader import (eligible_buckets,
                                                         select_bucket)


class ServeRequest:
    """One in-flight request: token ids in, a logits row out.

    ``wait`` blocks the SUBMITTER (never the serving threads) until the
    scheduler fulfills or fails the request.  ``raw_len`` keeps the
    pre-truncation length so telemetry can see over-long requests."""

    _ids = itertools.count()

    def __init__(self, tokens: np.ndarray, bucket: int, raw_len: int,
                 t_submit: float):
        self.id = next(self._ids)
        self.tokens = tokens          # 1-D int32, already <= bucket long
        self.bucket = int(bucket)
        self.raw_len = int(raw_len)
        self.t_submit = float(t_submit)
        self.t_done: Optional[float] = None
        self.replica: str = ""
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self._event = threading.Event()

    def fulfill(self, logits_row: np.ndarray, replica: str,
                t_done: float) -> None:
        self.result = logits_row
        self.replica = replica
        self.t_done = t_done
        self._event.set()

    def fail(self, exc: BaseException) -> None:
        self.error = exc
        self.t_done = time.monotonic()
        self._event.set()

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError(f"request {self.id} not served within "
                               f"{timeout}s")
        if self.error is not None:
            raise self.error
        return self.result

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def latency_ms(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return (self.t_done - self.t_submit) * 1e3


class GenRequest(ServeRequest):
    """A GENERATION request (r21 decode tier): prompt token ids in,
    ``max_new`` generated token ids out.

    Same lifecycle as :class:`ServeRequest` (``wait`` blocks the
    submitter; ``result`` is the generated int32 token array — the
    first entry is the token sampled off the prefill logits), plus the
    token-granular bookkeeping the decode scheduler needs: ``out``
    accumulates tokens as steps complete and ``t_first`` stamps the
    first token for TTFT accounting."""

    def __init__(self, tokens: np.ndarray, bucket: int, raw_len: int,
                 t_submit: float, max_new: int):
        super().__init__(tokens, bucket, raw_len, t_submit)
        self.max_new = int(max_new)
        self.out: List[int] = []
        self.t_first: Optional[float] = None

    def push_token(self, token: int, now: float) -> None:
        if self.t_first is None:
            self.t_first = now
        self.out.append(int(token))

    def ttft_ms(self) -> Optional[float]:
        if self.t_first is None:
            return None
        return (self.t_first - self.t_submit) * 1e3


class RequestQueue:
    """Thread-safe bucket-binned request queue (one FIFO per bucket)."""

    def __init__(self, buckets: Sequence[int],
                 max_len: Optional[int] = None,
                 clock=time.monotonic):
        self.buckets: Tuple[int, ...] = eligible_buckets(buckets, max_len)
        self._clock = clock
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._fifos: Dict[int, List[ServeRequest]] = {
            b: [] for b in self.buckets}
        self._closed = False
        self.submitted = 0

    def submit(self, tokens,
               max_new_tokens: Optional[int] = None,
               req_id: Optional[int] = None) -> ServeRequest:
        """Bin a ragged token array into its bucket FIFO; returns the
        request handle the submitter waits on.  Over-long requests run
        truncated at the largest bucket (logged on the request via
        raw_len, never rejected — the production semantic).

        ``max_new_tokens`` switches the request to GENERATION (r21): a
        :class:`GenRequest` whose result is the generated token array
        instead of a logits row.  Both kinds share the one queue and
        the one bucket-selection rule.  ``req_id`` overrides the
        auto-assigned id — the decode front door threads the PARENT
        request id through the wire so a generation retried on a
        different worker process samples with the same fold_in key and
        returns the same tokens."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        raw_len = len(tokens)
        bucket = select_bucket(max(raw_len, 1), self.buckets)
        if max_new_tokens is not None:
            req: ServeRequest = GenRequest(tokens[:bucket], bucket,
                                           raw_len, self._clock(),
                                           max_new=max_new_tokens)
        else:
            req = ServeRequest(tokens[:bucket], bucket, raw_len,
                               self._clock())
        if req_id is not None:
            req.id = int(req_id)
        with self._cond:
            if self._closed:
                raise RuntimeError("RequestQueue is closed")
            self._fifos[bucket].append(req)
            self.submitted += 1
            self._cond.notify_all()
        return req

    def pending(self) -> int:
        with self._lock:
            return sum(len(f) for f in self._fifos.values())

    def take_cell(self, batch_size: int, max_delay_s: float,
                  timeout_s: float = 0.05
                  ) -> Optional[Tuple[int, List[ServeRequest]]]:
        """One (bucket, requests) dispatch cell, or None after
        ``timeout_s`` with nothing dispatchable.

        Policy (continuous batching):
          1. any bucket whose OLDEST request has waited past
             ``max_delay_s`` dispatches first (oldest head first, up to
             batch_size — a full expired bucket is just a full batch).
             Deadline beats batch-fullness: under sustained full-batch
             traffic on one bucket, a lone request in another bucket
             would otherwise starve unboundedly behind rule 2 and the
             ``max_delay`` latency bound would be fiction;
          2. else any bucket holding >= batch_size requests dispatches
             a full FIFO batch immediately (smallest such bucket first
             — short requests are the latency-sensitive ones);
          3. else wait (bounded by ``timeout_s`` and by the earliest
             upcoming deadline) and re-check.
        """
        deadline = self._clock() + max(timeout_s, 0.0)
        with self._cond:
            while True:
                cell = self._pick_locked(batch_size, max_delay_s)
                if cell is not None:
                    return cell
                if self._closed:
                    return None
                now = self._clock()
                wait = deadline - now
                oldest = self._oldest_locked()
                if oldest is not None:
                    # wake exactly when the oldest request's deadline
                    # fires, even if that is sooner than the poll bound
                    wait = min(wait, oldest + max_delay_s - now)
                if wait <= 0:
                    return None
                self._cond.wait(wait)

    def take_one(self, max_delay_s: float, timeout_s: float = 0.05
                 ) -> Optional[Tuple[int, ServeRequest]]:
        """SLOT-granular drain (r21 decode tier): one (bucket, request),
        or None.  Exactly take_cell's policy at batch size 1 — with
        every non-empty bucket "full", rule 1 still runs first, so a
        deadline-expired bucket's head beats rule 2's smallest-bucket
        preference: the r16 deadline-first admission rule, preserved at
        token granularity."""
        cell = self.take_cell(1, max_delay_s, timeout_s=timeout_s)
        if cell is None:
            return None
        bucket, reqs = cell
        return bucket, reqs[0]

    def _oldest_locked(self) -> Optional[float]:
        ts = [f[0].t_submit for f in self._fifos.values() if f]
        return min(ts) if ts else None

    def _pick_locked(self, batch_size: int, max_delay_s: float
                     ) -> Optional[Tuple[int, List[ServeRequest]]]:
        now = self._clock()
        expired = [(self._fifos[b][0].t_submit, b)
                   for b in self.buckets
                   if self._fifos[b]
                   and now - self._fifos[b][0].t_submit >= max_delay_s]
        if expired:                                  # rule 1: deadline
            _, b = min(expired)
            fifo = self._fifos[b]
            cell, self._fifos[b] = fifo[:batch_size], fifo[batch_size:]
            return b, cell
        for b in self.buckets:                       # rule 2: full batch
            if len(self._fifos[b]) >= batch_size:
                fifo = self._fifos[b]
                cell, self._fifos[b] = fifo[:batch_size], fifo[batch_size:]
                return b, cell
        return None

    def close(self) -> None:
        """No further submits; blocked take_cell callers wake and drain
        what remains (the scheduler keeps calling until pending()==0)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
