"""serve/ — the batched-inference serving tier above the trained artifact.

Everything below this package optimizes *training*; the north star is a
production system serving heavy traffic, and this subsystem is that
missing half: a request queue with continuous/dynamic batching into the
SAME bucket-padded lengths the training text pipeline compiled for
(data/loader.select_bucket — no request mix can retrace), an
AOT-compiled, donation-enabled predict step with params frozen (no
optimizer state resident, int8/fp8 weights served at the r13 QuantDense
scale state with the amax history frozen at load), and multi-replica
dispatch with heartbeat liveness so a dead replica is detached and
re-admitted without draining the others (the r10/r14 resilience idioms
at request scope).

Partitioning rule (SNIPPETS [3]): 1D partitioning "is essentially
always faster for inference/decoding" — serve REPLICATED-per-chip when
the model fits one chip's HBM, and fall back to a single model-sharded
replica group only when a model axis says it doesn't
(cli.run_serving owns the decision; the engine serves either).

Layout:
  * :mod:`queue_` (``serve.queue``)   — ServeRequest + RequestQueue
    (bucket-binned FIFO cells, deadline bookkeeping);
  * :mod:`engine`     — InferenceEngine (per-bucket AOT programs,
    batch-buffer donation, frozen params) + checkpoint loading through
    any r14 StorageBackend;
  * :mod:`scheduler`  — BatchScheduler (drains the queue into
    (bucket, batch) cells under a max-latency deadline, pads partial
    batches with masked rows whose outputs are dropped);
  * :mod:`replicas`   — Replica / ReplicaSet (least-loaded dispatch,
    heartbeat staleness detach, re-admission);
  * :mod:`decode`     — the r21 autoregressive tier (paged KV cache,
    AOT prefill/decode program families, token-granular continuous
    batching, multi-process front door) — imported lazily by its
    users, not re-exported here, so the classifier serve path never
    pays the decode imports.
"""

from faster_distributed_training_tpu.serve.engine import (  # noqa: F401
    InferenceEngine, ServingState, load_serving_state, pad_batch)
from faster_distributed_training_tpu.serve.queue import (  # noqa: F401
    GenRequest, RequestQueue, ServeRequest)
from faster_distributed_training_tpu.serve.replicas import (  # noqa: F401
    Replica, ReplicaSet)
from faster_distributed_training_tpu.serve.scheduler import (  # noqa: F401
    BatchScheduler)

__all__ = ["InferenceEngine", "ServingState", "load_serving_state",
           "pad_batch", "RequestQueue", "ServeRequest", "GenRequest",
           "Replica", "ReplicaSet", "BatchScheduler"]
