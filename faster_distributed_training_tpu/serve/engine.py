"""Inference engine: AOT-compiled, donation-enabled predict programs
with params frozen.

The serving step deliberately does NOT reuse the training step family:

  * **params are frozen** — no optimizer state exists at all
    (:class:`ServingState` carries params + batch_stats and an EMPTY
    opt_state group, so the r15 memory attribution
    (``telemetry.programs.state_bytes_table``) reads serving HBM =
    params (+ quant scales) only; pinned by tests/test_serve.py);
  * **no mutable collections** — the model applies with
    ``train=False`` and immutable ``batch_stats``; under ``--quant``
    the r13 ``QuantDense`` scale state is additionally FROZEN at load
    (``QuantPolicy.frozen_scales`` via ``cli.build_model(serving=
    True)``), so serving N requests is state-free and two identical
    requests return bitwise-identical logits;
  * **the batch is donated, not the state** — the training step donates
    the train state (its carry); a serving step's only dead buffer is
    the REQUEST batch it just consumed, so the predict program donates
    exactly that (``donate_argnums`` on the batch argument) and the
    params buffers are never at risk.  The scheduler always hands the
    engine fresh host (numpy) arrays, so donation can never invalidate
    a buffer a retry still needs;
  * **AOT-compiled per (bucket, batch) cell** — one explicit
    ``lower()``/``compile()`` per bucket length at warmup, routed
    through the r15 program observatory when one is active (program
    name ``serve:predict:L<bucket>``), so serving compiles are
    accounted like every other program and steady-state calls go
    straight to the executable.

Checkpoint loading (:func:`load_serving_state`) routes through the r14
``StorageBackend`` + checkpoint manager walk, so the serving tier
restores from exactly the artifacts training wrote — step-cadence
(sharded or single-file) checkpoints first, the epoch checkpoint as the
fallback — on posix, the fake object store, or GCS alike.
"""

from __future__ import annotations

import os
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

# jax warns once per compiled program when a donated buffer cannot be
# aliased into an output (a logits output never matches the token
# buffer's shape/dtype).  Donation here is about FREEING the consumed
# request batch early, not aliasing — the warning is expected, so the
# engine filters exactly it at compile time.
_DONATION_WARNING = "Some donated buffers were not usable"


class ServingState:
    """The serving-side state bundle: params + batch_stats, NO optimizer
    state.  The ``opt_state`` attribute exists (empty) so the r15
    ``state_bytes_table`` attribution applies unchanged — its
    ``opt_state_bytes_per_chip`` reading 0 for a serving process is the
    pinned memory contract."""

    def __init__(self, params: Any, batch_stats: Any, step: int = 0):
        self.params = params
        self.batch_stats = batch_stats
        self.opt_state: dict = {}
        self.step = int(step)

    def variables(self) -> Dict[str, Any]:
        return {"params": self.params, "batch_stats": self.batch_stats}


def make_predict_fn(apply_fn: Callable) -> Callable:
    """The pure serving step: variables + batch -> logits.  Mirrors
    steps.make_eval_step's forward (deterministic, running stats) but
    returns RAW logits — response shaping (masked-row drop, argmax,
    softmax) is the caller's business, and the bitwise batched-vs-single
    contract is stated on logits."""

    def predict(variables: Dict[str, Any],
                batch: Dict[str, Any]):
        return apply_fn({"params": variables["params"]["model"],
                         "batch_stats": variables["batch_stats"]},
                        batch["tokens"],
                        token_types=batch.get("token_types"),
                        mask=batch.get("mask"), train=False)

    return predict


def pad_batch(requests: Sequence, bucket: int, batch_size: int,
              pad_id: int = 0) -> Tuple[Dict[str, np.ndarray], int]:
    """Assemble a (batch_size, bucket) batch from <= batch_size
    requests; returns (batch, n_real).  Rows past n_real are PAD rows:
    copies of row 0 (a real request — the same any-real-sample padding
    BatchLoader's pad_last uses, so the model only ever sees
    in-distribution rows) whose outputs the scheduler DROPS.  Per-row
    independence of the transformer forward (no cross-example op; quant
    scales are per-tensor constants under frozen_scales) is what makes
    the pad content unobservable in the real rows — pinned bitwise by
    scripts/serve_smoke.py."""
    if not requests:
        raise ValueError("pad_batch needs at least one request")
    if len(requests) > batch_size:
        raise ValueError(f"{len(requests)} requests > batch_size "
                         f"{batch_size}")
    tokens = np.full((batch_size, bucket), pad_id, np.int32)
    mask = np.zeros((batch_size, bucket), np.int32)
    for i, req in enumerate(requests):
        t = np.asarray(req.tokens, np.int32)[:bucket]
        tokens[i, :len(t)] = t
        mask[i, :len(t)] = 1
    n_real = len(requests)
    for i in range(n_real, batch_size):
        tokens[i] = tokens[0]
        mask[i] = mask[0]
    return {"tokens": tokens, "token_types": np.zeros_like(tokens),
            "mask": mask}, n_real


class InferenceEngine:
    """Per-bucket AOT predict programs over one frozen variable bundle.

    ``device``: pin this engine's params (and every call's batch) to one
    chip — the replicated-per-chip layout (SNIPPETS [3]).  ``mesh``: the
    model-sharded fallback — compiles/executes under the mesh context
    with the variables wherever the caller placed them.

    ``donate``: None = auto (donate the batch argument unless the
    backend is a jaxlib-0.4.x CPU client, the r7 allocator caveat —
    ``cli.donation_workaround_needed``); True/False force.  Donated or
    not, callers passing device arrays must treat them as CONSUMED.
    """

    def __init__(self, apply_fn: Callable, state: ServingState,
                 batch_size: int, buckets: Sequence[int],
                 donate: Optional[bool] = None, device=None, mesh=None,
                 name: str = "serve",
                 log: Callable[[str], None] = print):
        import jax

        self.batch_size = int(batch_size)
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        self.name = name
        self.device = device
        self.mesh = mesh
        self._log = log
        if donate is None:
            from faster_distributed_training_tpu.cli import (
                donation_workaround_needed)
            donate = not (jax.default_backend() == "cpu"
                          and donation_workaround_needed())
        self.donate = bool(donate)
        variables = state.variables()
        if device is not None:
            variables = jax.device_put(variables, device)
        self._variables = variables
        self._jit = jax.jit(make_predict_fn(apply_fn),
                            donate_argnums=(1,) if self.donate else ())
        self._compiled: Dict[int, Any] = {}
        self.calls = 0

    # -- compilation -------------------------------------------------------

    def _dummy_batch(self, bucket: int) -> Dict[str, np.ndarray]:
        z = np.zeros((self.batch_size, bucket), np.int32)
        return {"tokens": z, "token_types": z,
                "mask": np.ones_like(z)}

    def compile_bucket(self, bucket: int) -> None:
        """Explicit AOT lower+compile of the (bucket, batch_size) cell,
        observed by the process-global program observatory when one is
        active; any observe failure falls back to a plain
        lower/compile (and any AOT failure to plain jit dispatch)."""
        if bucket in self._compiled:
            return
        from faster_distributed_training_tpu.telemetry import programs
        args = (self._variables, self._dummy_batch(bucket))
        pname = f"{self.name}:predict:L{bucket}"
        compiled = None
        with warnings.catch_warnings():
            warnings.filterwarnings("ignore", message=_DONATION_WARNING)
            with self._mesh_ctx():
                obs = programs.get_observatory()
                if obs is not None:
                    sig = programs.args_signature(args, (1,))
                    compiled = obs.observe_compile(pname, self._jit, args,
                                                   sig=sig)
                if compiled is None:
                    try:
                        compiled = self._jit.lower(*args).compile()
                    except Exception as e:
                        self._log(f"[serve] AOT compile of {pname} failed "
                                  f"({e!r}); plain jit dispatch serves it")
                        compiled = self._jit
        self._compiled[bucket] = compiled

    def warmup(self, buckets: Optional[Sequence[int]] = None) -> float:
        """Compile every (bucket, batch) cell BEFORE the queue opens —
        steady-state serving never pays a compile (and the replica
        heartbeat timeout never has to cover one).  Returns wall
        seconds."""
        t0 = time.monotonic()
        for b in (buckets if buckets is not None else self.buckets):
            self.compile_bucket(int(b))
        return time.monotonic() - t0

    def _mesh_ctx(self):
        import contextlib
        return self.mesh if self.mesh is not None \
            else contextlib.nullcontext()

    # -- the hot path ------------------------------------------------------

    def predict_batch(self, batch: Dict[str, Any]) -> np.ndarray:
        """Logits [batch_size, n_class] for one assembled batch.  The
        batch arrays are CONSUMED when donation is on (the scheduler
        always hands fresh host arrays, so a re-dispatch after a
        replica death re-uploads from the same numpy)."""
        import jax

        tokens = batch["tokens"]
        bs, bucket = tokens.shape
        if bs != self.batch_size:
            raise ValueError(f"batch rows {bs} != engine batch_size "
                             f"{self.batch_size} (the scheduler pads)")
        if bucket not in self._compiled:
            self.compile_bucket(bucket)
        if self.device is not None:
            batch = jax.device_put(batch, self.device)
        with self._mesh_ctx():
            logits = self._compiled[bucket](self._variables, batch)
        self.calls += 1
        return np.asarray(logits)


# -- checkpoint loading ----------------------------------------------------

def load_serving_state(cfg, mesh=None, log: Callable[[str], None] = print,
                       ckpt_name: Optional[str] = None
                       ) -> Tuple[Any, ServingState, dict]:
    """(model, ServingState, meta) from ``cfg.checkpoint_dir`` through
    the configured r14 StorageBackend.

    Walk order = the training side's own restore preference: newest
    VALID step-cadence checkpoint (sharded or single-file, via the
    manager's committed-entry walk) first, the epoch checkpoint
    (``<dir>/<workload>``) as the fallback.  The restored train state's
    opt_state/loss_scale/rng are DROPPED — serving holds params +
    batch_stats only.  The model is built with
    ``cli.build_model(serving=True)``: identical param tree to
    training (checkpoints interchange), quant scale state frozen at the
    restored amax history."""
    import jax
    import jax.numpy as jnp

    from faster_distributed_training_tpu.cli import (build_model,
                                                     load_dataset)
    from faster_distributed_training_tpu.optim import build_optimizer
    from faster_distributed_training_tpu.resilience.manager import (
        AsyncCheckpointManager)
    from faster_distributed_training_tpu.resilience.storage import (
        build_backend)
    from faster_distributed_training_tpu.train import create_train_state
    from faster_distributed_training_tpu.train.checkpoint import (
        has_checkpoint, read_checkpoint_meta, restore_checkpoint)

    if cfg.model != "transformer":
        raise ValueError(f"serving is wired for the transformer text "
                         f"workload; got model={cfg.model!r}")
    ckpt_name = ckpt_name or "transformer"
    ds = load_dataset(cfg, train=False)
    vocab = ds.vocab_size() if hasattr(ds, "vocab_size") else None
    model = build_model(cfg, vocab_size=vocab, mesh=mesh, serving=True)
    # the template the checkpoint restores into: same creation path as
    # training (param tree identity is the interchange contract); the
    # throwaway optimizer state is dropped right after the restore
    tx, _ = build_optimizer(cfg, steps_per_epoch=1)
    sample = jnp.zeros((max(cfg.batch_size, 1), cfg.seq_len), jnp.int32)
    template = create_train_state(model, tx, sample,
                                  jax.random.PRNGKey(cfg.seed),
                                  init_kwargs={"train": True})
    backend = build_backend(getattr(cfg, "storage_backend", "posix"),
                            cfg.checkpoint_dir, log=log)
    # same prefix the training side's build_resilience used — its
    # step-cadence dirs are <dir>/<workload>_step_<N>
    mgr = AsyncCheckpointManager(cfg.checkpoint_dir, prefix=ckpt_name,
                                 backend=backend, log=log)
    try:
        got = mgr.restore_latest(template)
    finally:
        mgr.close()
    meta: dict
    if got is not None:
        restored, meta = got
        log(f"[serve] restored step-cadence checkpoint: step "
            f"{int(meta.get('step', 0))}")
    elif has_checkpoint(cfg.checkpoint_dir, ckpt_name, backend=backend):
        # the orbax ARRAY read is posix by design (the documented
        # single-file exception — non-posix backends force the sharded
        # step-cadence path above), but the meta markers routed through
        # the backend, so read them back the same way instead of
        # restore_checkpoint's posix-default read
        restored, epoch, best = restore_checkpoint(cfg.checkpoint_dir,
                                                   ckpt_name, template)
        bmeta = read_checkpoint_meta(cfg.checkpoint_dir, ckpt_name,
                                     backend=backend)
        meta = {"epoch": int(bmeta.get("epoch", epoch)),
                "best_acc": float(bmeta.get("best_acc", best)),
                "step": int(np.asarray(restored.step))}
        log(f"[serve] restored epoch checkpoint {ckpt_name!r} "
            f"(epoch {meta['epoch']})")
    else:
        raise FileNotFoundError(
            f"no serveable checkpoint under {cfg.checkpoint_dir!r} "
            f"(neither a committed step-cadence checkpoint nor "
            f"{ckpt_name!r})")
    meta = dict(meta)
    meta["vocab"] = vocab
    state = ServingState(params=restored.params,
                         batch_stats=restored.batch_stats,
                         step=int(np.asarray(restored.step)))
    if mesh is not None:
        # model-sharded serving: place params/batch_stats on the same
        # overlay training used (train_state_shardings), so the tp/sp
        # program contracts local shards instead of gathered copies
        from faster_distributed_training_tpu.parallel.placement import (
            train_state_shardings)
        sh = train_state_shardings(restored, mesh, cfg)
        state.params = jax.tree.map(jax.device_put, state.params,
                                    sh.params)
        state.batch_stats = jax.tree.map(jax.device_put,
                                         state.batch_stats,
                                         sh.batch_stats)
    return model, state, meta
