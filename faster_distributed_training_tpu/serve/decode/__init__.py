"""KV-cache autoregressive decode serving (r21, ROADMAP item #1).

Layered like the classifier serve/ stack it extends:

  cache.py     — the paged KV cache: device K/V buffers sized
                 pages*page_size plus the host-side slot table
                 (lengths, tokens, request ids, free list);
  engine.py    — DecodeEngine: AOT prefill-per-bucket +
                 decode-step-per-page-count program families through
                 the r15 observatory and the r17 executable cache;
  scheduler.py — DecodeScheduler: the slot-granular continuous-
                 batching loop (admit between steps, reclaim on
                 finish);
  frontend.py  — the multi-process front door: one worker PROCESS per
                 replica behind a length-framed JSON socket protocol,
                 ReplicaSet detach/readmit semantics across process
                 death.
"""

from faster_distributed_training_tpu.serve.decode.cache import (  # noqa: F401
    PagedKVCache)
from faster_distributed_training_tpu.serve.decode.engine import (  # noqa: F401
    DecodeEngine)
from faster_distributed_training_tpu.serve.decode.frontend import (  # noqa: F401
    FrontDoor, GenScheduler, ProcReplica, WorkerClient)
from faster_distributed_training_tpu.serve.decode.scheduler import (  # noqa: F401
    DecodeScheduler)

__all__ = ["PagedKVCache", "DecodeEngine", "DecodeScheduler",
           "FrontDoor", "GenScheduler", "ProcReplica", "WorkerClient"]
