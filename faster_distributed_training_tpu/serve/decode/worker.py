"""Decode worker-process entry point.

``python -m faster_distributed_training_tpu.serve.decode.worker --cfg
<json> --port <p> --name <n> --hb_dir <d>`` — a module the package
``__init__`` does NOT import, so runpy executes it without the
"already in sys.modules" double-import hazard.  All the logic lives in
:func:`frontend.worker_main`.
"""

import sys

from faster_distributed_training_tpu.serve.decode.frontend import worker_main

if __name__ == "__main__":
    sys.exit(worker_main(sys.argv[1:]))
