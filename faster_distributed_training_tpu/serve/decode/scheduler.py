"""DecodeScheduler: token-granular continuous batching over one engine.

The r16 classifier scheduler batches at REQUEST granularity — a cell
is assembled, dispatched, and the batch's composition is frozen until
its logits return.  Generation inverts the shape of the work: a batch
lives for hundreds of steps and its members finish at different times.
This loop therefore schedules at SLOT granularity, interleaving three
phases between every decode step:

  1. ADMIT — while a cache slot is free and the queue has work, drain
     ONE request (queue.take_one: the take_cell policy at batch 1, so
     deadline-expired buckets still beat fuller ones — the r16
     admission rule preserved verbatim), prefill it, and swap its K/V
     into the RUNNING batch;
  2. STEP — one decode-step program over the whole slot batch (the
     engine picks the page-count program covering the longest live
     slot);
  3. RECLAIM — requests that hit their token budget (or the cache/
     position ceiling) are fulfilled and their slot freed for the next
     admission.

Telemetry (append-only r21 kinds): ``decode_admit`` per admission,
``decode_step`` per step, ``slot_evict`` per reclaim.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from faster_distributed_training_tpu.serve.decode.engine import DecodeEngine
from faster_distributed_training_tpu.serve.queue import (GenRequest,
                                                         RequestQueue)


class DecodeScheduler:
    """One engine + one queue -> a slot-granular generation loop."""

    def __init__(self, queue: RequestQueue, engine: DecodeEngine,
                 max_delay_ms: float = 20.0, max_new_tokens: int = 32,
                 recorder=None, name: str = "decode0",
                 log: Callable[[str], None] = print):
        self.queue = queue
        self.engine = engine
        self.max_delay_s = float(max_delay_ms) / 1e3
        self.max_new_tokens = int(max_new_tokens)
        self.recorder = recorder
        self.name = name
        self._log = log
        self._slots: Dict[int, GenRequest] = {}
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # bookkeeping for summary()
        self.completed_requests = 0
        self.generated_tokens = 0
        self.ttft_ms: List[float] = []
        self.total_ms: List[float] = []
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"fdt-{self.name}")
        self._thread.start()

    def close(self, drain_s: float = 30.0) -> None:
        """Stop admitting new work once the queue is closed (by the
        caller), finish what is in flight (bounded), stop the loop."""
        deadline = time.monotonic() + drain_s
        while time.monotonic() < deadline:
            with self._lock:
                busy = bool(self._slots) or self.queue.pending()
            if not busy:
                break
            time.sleep(0.01)
        self._closed = True
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        # anything still holding a slot past the drain bound fails loud
        with self._lock:
            stranded = list(self._slots.items())
            self._slots.clear()
        for slot, req in stranded:
            self.engine.cache.evict(slot)
            req.fail(RuntimeError(f"decode drain timed out with request "
                                  f"{req.id} still in slot {slot}"))

    # -- the loop ----------------------------------------------------------

    def _loop(self) -> None:
        while not self._closed:
            running = self.engine.active_count() > 0
            self._admit(block=not running)
            if self.engine.active_count() == 0:
                continue
            t0 = time.monotonic()
            tokens, pages = self.engine.step()
            step_ms = (time.monotonic() - t0) * 1e3
            if self.recorder is not None:
                self.recorder.record_event(
                    "decode_step", replica=self.name, pages=pages,
                    active=len(self._slots),
                    batch=self.engine.batch_size,
                    step_ms=round(step_ms, 3))
            self._reclaim(tokens)

    def _admit(self, block: bool) -> None:
        """Fill free slots from the queue.  With a running batch the
        drain must not stall the step loop, so the queue poll is
        non-blocking; an idle engine waits the usual take_cell bound."""
        first = True
        while self.engine.cache.free_slot() is not None:
            timeout = 0.05 if (block and first) else 0.0
            first = False
            got = self.queue.take_one(self.max_delay_s, timeout_s=timeout)
            if got is None:
                return
            bucket, req = got
            if not isinstance(req, GenRequest):
                req.fail(TypeError(
                    "DecodeScheduler serves GenRequests (queue."
                    "submit(tokens, max_new_tokens=...)); got a plain "
                    "logits request"))
                continue
            now = time.monotonic()
            slot, f_tok = self.engine.admit(req.tokens, bucket, req.id)
            req.push_token(f_tok, time.monotonic())
            with self._lock:
                self._slots[slot] = req
                if self._t_first is None:
                    self._t_first = now
            if self.recorder is not None:
                self.recorder.record_event(
                    "decode_admit", replica=self.name, slot=slot,
                    bucket=bucket, len=req.raw_len,
                    queue_ms=round((now - req.t_submit) * 1e3, 3))
            # a 1-token budget is satisfied by the prefill sample alone
            self._maybe_finish(slot, req)

    def _reclaim(self, tokens: np.ndarray) -> None:
        now = time.monotonic()
        for slot, req in list(self._slots.items()):
            if not self.engine.cache.active[slot]:
                continue
            req.push_token(int(tokens[slot]), now)
            self._maybe_finish(slot, req)

    def _maybe_finish(self, slot: int, req: GenRequest) -> None:
        budget = min(req.max_new, self.max_new_tokens)
        done = len(req.out) >= budget
        reason = "budget"
        if not done and self.engine.cache.headroom(slot) <= 0:
            done = True
            reason = "capacity"
        if not done:
            return
        n = len(req.out)
        self.engine.cache.evict(slot)
        with self._lock:
            self._slots.pop(slot, None)
            self.completed_requests += 1
            self.generated_tokens += n
            self.ttft_ms.append(req.ttft_ms())
            self._t_last = time.monotonic()
        req.fulfill(np.asarray(req.out, np.int32), self.name,
                    time.monotonic())
        with self._lock:
            self.total_ms.append(req.latency_ms())
        if self.recorder is not None:
            self.recorder.record_event(
                "slot_evict", replica=self.name, slot=slot, tokens=n,
                reason=reason)

    # -- reporting ---------------------------------------------------------

    def summary(self) -> dict:
        """TTFT p50/p99 + generation throughput (nearest-rank
        percentiles, train.metrics.percentiles — the stack's one
        definition)."""
        from faster_distributed_training_tpu.train.metrics import (
            percentiles)
        with self._lock:
            ttft = list(self.ttft_ms)
            total = list(self.total_ms)
            n = self.completed_requests
            toks = self.generated_tokens
            wall = ((self._t_last - self._t_first)
                    if (self._t_first is not None
                        and self._t_last is not None
                        and self._t_last > self._t_first) else 0.0)
        pt = percentiles(ttft, qs=(50, 99))
        pl = percentiles(total, qs=(50, 99))
        return {"requests": n, "tokens": toks,
                "steps": self.engine.steps,
                "prefills": self.engine.prefills,
                "ttft_p50_ms": pt.get(50, 0.0),
                "ttft_p99_ms": pt.get(99, 0.0),
                "latency_p50_ms": pl.get(50, 0.0),
                "latency_p99_ms": pl.get(99, 0.0),
                "tokens_per_sec": round(toks / wall, 2) if wall else 0.0}
