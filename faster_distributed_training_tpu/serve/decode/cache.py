"""Paged KV cache: fixed-size device buffers + host-side slot table.

One cache serves one decode batch of ``batch_size`` SLOTS.  The device
arrays are allocated ONCE at the maximum window (``max_pages * page``
columns) so every decode-step program — one per page count,
serve/decode/engine.py — shares a single buffer identity and donation
round-trips it; "paging" here is about the ATTENTION WINDOW, not the
allocation: each step only reads the first ``pages * page`` columns,
where ``pages`` is the smallest page count covering the longest active
slot, so per-step cost tracks the live sequences while the program set
stays the enumerated ``max_pages`` cells (never a per-length retrace).

The slot table is plain host numpy — lengths, current tokens, request
ids, active flags.  The scheduler mutates it between steps (admit /
evict), the engine reads it to assemble each step's traced operands.
A freed slot's device columns are NOT zeroed: the length mask in
ops/cached_attention.py makes stale columns unobservable, and the next
admission's prefill insert overwrites the prefix it needs (pinned by
the mid-stream admission parity test, tests/test_decode.py).
"""

from __future__ import annotations

import math
from typing import List, Optional

import numpy as np


class PagedKVCache:
    """Device K/V buffers (n_layers, batch, heads, max_pages*page, d_k)
    plus the host slot table."""

    def __init__(self, spec, batch_size: int, page: int, max_pages: int):
        import jax.numpy as jnp

        if page < 1 or max_pages < 1:
            raise ValueError(f"page {page} / max_pages {max_pages} must "
                             f"be >= 1")
        self.spec = spec
        self.batch_size = int(batch_size)
        self.page = int(page)
        self.max_pages = int(max_pages)
        self.capacity = self.page * self.max_pages
        shape = (spec.n_layers, self.batch_size, spec.h, self.capacity,
                 spec.d_k)
        self.k = jnp.zeros(shape, spec.dtype)
        self.v = jnp.zeros(shape, spec.dtype)
        B = self.batch_size
        self.lengths = np.zeros((B,), np.int32)    # valid cache columns
        self.tokens = np.zeros((B,), np.int32)     # token AT lengths-1
        self.req_ids = np.zeros((B,), np.int32)
        self.active = np.zeros((B,), bool)

    # -- slot management (host side, between steps) ------------------------

    def free_slot(self) -> Optional[int]:
        idle = np.flatnonzero(~self.active)
        return int(idle[0]) if len(idle) else None

    def active_slots(self) -> List[int]:
        return [int(i) for i in np.flatnonzero(self.active)]

    def admit(self, slot: int, req_id: int, prompt_len: int,
              first_token: int) -> None:
        """Claim ``slot`` for a prefilled request: ``prompt_len`` cache
        columns are valid and ``first_token`` (sampled off the prefill
        logits) is the token the next decode step consumes."""
        if self.active[slot]:
            raise RuntimeError(f"slot {slot} is already active")
        if prompt_len > self.capacity:
            raise ValueError(f"prompt of {prompt_len} exceeds cache "
                             f"capacity {self.capacity}")
        self.lengths[slot] = int(prompt_len)
        self.tokens[slot] = int(first_token)
        self.req_ids[slot] = int(req_id)
        self.active[slot] = True

    def evict(self, slot: int) -> None:
        self.active[slot] = False
        self.lengths[slot] = 0
        self.tokens[slot] = 0
        self.req_ids[slot] = 0

    def advance(self, next_tokens: np.ndarray) -> None:
        """Commit one decode step: every active slot consumed its token
        (cache column ``lengths`` was written) and sampled the next."""
        act = self.active
        self.lengths[act] += 1
        self.tokens[act] = next_tokens[act]

    # -- window accounting -------------------------------------------------

    def window_pages(self) -> int:
        """Smallest page count whose window covers every active slot
        through the NEXT step's write (column ``lengths``, 0-based —
        hence lengths + 1 columns must be visible)."""
        if not self.active.any():
            return 1
        need = int(self.lengths[self.active].max()) + 1
        return min(self.max_pages,
                   max(1, math.ceil(need / self.page)))

    def slot_pages(self, slot: int) -> int:
        """Pages the slot's live prefix occupies (telemetry)."""
        return max(1, math.ceil(int(self.lengths[slot]) / self.page))

    def headroom(self, slot: int) -> int:
        """Generated tokens the slot can still take before the cache
        (or the model's position table) runs out."""
        cap = min(self.capacity, self.spec.maxlen)
        return cap - int(self.lengths[slot])
