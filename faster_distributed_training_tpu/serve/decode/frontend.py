"""The decode front door: replicas as PROCESSES behind a socket RPC.

The r16 serving tier runs its replicas as threads of one process — a
"replica death" there is a fault seam, not a process.  This module
promotes each decode replica to its own OS process behind a localhost
TCP socket with a length-framed JSON protocol:

  frame   := 4-byte big-endian length || utf-8 JSON object
  request := {"op": "generate", "id": int, "tokens": [int],
              "max_new": int}
           | {"op": "ping"} | {"op": "stop"}
  reply   := {"id": int, "tokens": [int], "ttft_ms": float}
           | {"ok": 1, ...} | {"error": str}

Liveness is the r14/r10 pair of idioms at process scope: every worker
process touches an ``HB_<name>`` marker file from a daemon thread (the
coordinator's marker heartbeat, verbatim), and the parent's
:class:`ProcReplica` folds marker staleness into the ``Replica.stale``
predicate the ReplicaSet watchdog already polls — so a SIGKILLed or
wedged process is DETACHED exactly like a wedged thread, its in-flight
generations re-dispatched to the survivors (deterministic per (seed,
request) sampling makes the re-run return the same tokens), and
re-admission RESPAWNS the process, whose warmup rides the executable
cache instead of a cold compile.

The parent-side control loop is :class:`GenScheduler` — the r16
``BatchScheduler`` with its assembly seam overridden to the identity
wire payload (batch size 1: the front door dispatches REQUESTS;
token-granular batching happens inside each worker's
DecodeScheduler).  Dispatch, parking, the bounded attempt budget, and
replica rescue are untouched inheritance.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional

import numpy as np

from faster_distributed_training_tpu.serve.queue import (GenRequest,
                                                         RequestQueue)
from faster_distributed_training_tpu.serve.replicas import (Replica,
                                                            ReplicaSet)
from faster_distributed_training_tpu.serve.scheduler import BatchScheduler

_HB_PERIOD_S = 0.3


# -- wire protocol ---------------------------------------------------------

def send_msg(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode("utf-8")
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def recv_msg(sock: socket.socket) -> Optional[dict]:
    head = _recv_exact(sock, 4)
    if head is None:
        return None
    (length,) = struct.unpack(">I", head)
    body = _recv_exact(sock, length)
    if body is None:
        return None
    return json.loads(body.decode("utf-8"))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def load_cfg(path: str):
    """TrainConfig back from the JSON the parent wrote
    (dataclasses.asdict round-trip; JSON turned the tuple fields into
    lists, so coerce them back)."""
    from faster_distributed_training_tpu.config import TrainConfig
    with open(path) as f:
        d = json.load(f)
    names = {f.name for f in dataclasses.fields(TrainConfig)}
    kw = {}
    for k, v in d.items():
        if k in names:
            kw[k] = tuple(v) if isinstance(v, list) else v
    return TrainConfig(**kw)


# -- the worker process ----------------------------------------------------

def _touch_forever(path: str, stop: threading.Event) -> None:
    while not stop.is_set():
        try:
            with open(path, "w") as f:
                f.write(str(time.time()))
        except OSError:
            pass
        stop.wait(_HB_PERIOD_S)


def worker_main(argv: Optional[List[str]] = None) -> int:
    """Entry point of one decode worker process: restore the
    checkpoint, warm the decode program set (through the observatory +
    executable cache when armed — the restart-MTTR path), then serve
    generate/ping frames until "stop" or parent death."""
    import argparse
    p = argparse.ArgumentParser(prog="fdt-decode-worker")
    p.add_argument("--cfg", required=True)
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--name", default="worker0")
    p.add_argument("--hb_dir", default="")
    args = p.parse_args(argv)

    cfg = load_cfg(args.cfg)
    from faster_distributed_training_tpu.cli import setup_platform
    setup_platform(cfg)

    from faster_distributed_training_tpu.models.decode import SamplingCfg
    from faster_distributed_training_tpu.serve.decode.engine import (
        DecodeEngine)
    from faster_distributed_training_tpu.serve.decode.scheduler import (
        DecodeScheduler)
    from faster_distributed_training_tpu.serve.engine import (
        load_serving_state)
    from faster_distributed_training_tpu.telemetry import (
        TelemetryRecorder, programs, resolve_telemetry_dir,
        update_manifest)

    name = args.name
    log = lambda m: print(f"[{name}] {m}", flush=True)   # noqa: E731

    recorder = None
    obs = None
    prev_obs = None
    if cfg.telemetry and os.environ.get("FDT_TELEMETRY", "1") != "0":
        tdir = resolve_telemetry_dir(cfg)
        recorder = TelemetryRecorder(tdir, log=log)
        update_manifest(tdir, {"decode_worker": {
            "name": name, "port": args.port,
            "config": dataclasses.asdict(cfg)}})
        if programs.observatory_enabled():
            from faster_distributed_training_tpu.resilience \
                .executable_cache import build_executable_cache
            from faster_distributed_training_tpu.resilience.storage import (
                build_backend)
            from faster_distributed_training_tpu.telemetry import (
                ProgramObservatory)
            obs = ProgramObservatory(recorder=recorder, log=log)
            obs.executable_cache = build_executable_cache(
                cfg, backend=build_backend(
                    getattr(cfg, "storage_backend", "posix"),
                    cfg.checkpoint_dir, log=log),
                mesh=None, log=log)
            prev_obs = programs.set_observatory(obs)

    hb_stop = threading.Event()
    if args.hb_dir:
        os.makedirs(args.hb_dir, exist_ok=True)
        threading.Thread(
            target=_touch_forever,
            args=(os.path.join(args.hb_dir, f"HB_{name}"), hb_stop),
            daemon=True).start()

    model, sstate, _meta = load_serving_state(cfg, log=log)
    q = RequestQueue(cfg.seq_buckets, max_len=cfg.seq_len)
    engine = DecodeEngine(
        model, sstate, q.buckets,
        batch_size=cfg.decode_batch_size, page=cfg.decode_page,
        max_pages=cfg.decode_max_pages,
        sampling=SamplingCfg(method=cfg.decode_sample,
                             temperature=cfg.decode_temperature,
                             top_k=cfg.decode_top_k, seed=cfg.seed),
        name=name, log=log)
    warm_s = engine.warmup()
    log(f"decode program set warmed in {warm_s:.2f}s "
        f"({len(engine.buckets)} prefill + {engine.max_pages} decode "
        f"programs)")
    sched = DecodeScheduler(q, engine,
                            max_delay_ms=cfg.serve_max_delay_ms,
                            max_new_tokens=cfg.decode_max_new_tokens,
                            recorder=recorder, name=name, log=log)
    sched.start()

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", args.port))
    srv.listen(16)
    log(f"serving on 127.0.0.1:{args.port}")
    stopping = threading.Event()

    def handle(conn: socket.socket) -> None:
        try:
            while True:
                msg = recv_msg(conn)
                if msg is None:
                    return
                op = msg.get("op")
                if op == "ping":
                    send_msg(conn, {"ok": 1, "name": name})
                elif op == "stop":
                    send_msg(conn, {"ok": 1})
                    stopping.set()
                    return
                elif op == "generate":
                    try:
                        # the PARENT's request id rides the wire into
                        # the sampling fold_in key, so a generation
                        # retried on another worker (replica death)
                        # returns the same tokens
                        req = q.submit(
                            np.asarray(msg["tokens"], np.int32),
                            max_new_tokens=int(msg["max_new"]),
                            req_id=msg.get("id"))
                        out = req.wait(timeout=300.0)
                        send_msg(conn, {
                            "id": msg.get("id"),
                            "tokens": np.asarray(out).tolist(),
                            "ttft_ms": req.ttft_ms()})
                    except BaseException as e:
                        send_msg(conn, {"id": msg.get("id"),
                                        "error": repr(e)})
                else:
                    send_msg(conn, {"error": f"unknown op {op!r}"})
        except OSError:
            pass
        finally:
            conn.close()

    srv.settimeout(0.2)
    try:
        while not stopping.is_set():
            try:
                conn, _addr = srv.accept()
            except socket.timeout:
                continue
            threading.Thread(target=handle, args=(conn,),
                             daemon=True).start()
    finally:
        srv.close()
        q.close()
        sched.close(drain_s=5.0)
        hb_stop.set()
        if recorder is not None:
            if obs is not None:
                programs.set_observatory(prev_obs)
                update_manifest(recorder.directory,
                                {"decode_compile": obs.summary()})
            recorder.close()
        log("worker stopped")
    return 0


# -- the parent side -------------------------------------------------------

class WorkerClient:
    """Engine-shaped socket client: ``predict_batch(payload) ->
    np.int32 tokens``.  One persistent connection, reconnect with
    bounded retry on demand (a freshly respawned worker may still be
    warming; the retry window is the readiness budget).  Any socket
    error mid-call raises — the Replica worker converts that into
    detach + re-dispatch, which is the whole point."""

    def __init__(self, port: int, connect_timeout_s: float = 120.0,
                 call_timeout_s: float = 300.0):
        self.port = int(port)
        self.connect_timeout_s = float(connect_timeout_s)
        self.call_timeout_s = float(call_timeout_s)
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        deadline = time.monotonic() + self.connect_timeout_s
        last: Optional[BaseException] = None
        while time.monotonic() < deadline:
            try:
                s = socket.create_connection(("127.0.0.1", self.port),
                                             timeout=2.0)
                s.settimeout(self.call_timeout_s)
                return s
            except OSError as e:
                last = e
                time.sleep(0.2)
        raise ConnectionError(
            f"worker on port {self.port} not reachable within "
            f"{self.connect_timeout_s}s") from last

    def _call(self, msg: dict) -> dict:
        with self._lock:
            if self._sock is None:
                self._sock = self._connect()
            try:
                send_msg(self._sock, msg)
                reply = recv_msg(self._sock)
            except OSError:
                self.drop()
                raise
            if reply is None:
                self.drop()
                raise ConnectionError(
                    f"worker on port {self.port} closed the connection")
            return reply

    def drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def ping(self) -> dict:
        return self._call({"op": "ping"})

    def stop(self) -> None:
        try:
            self._call({"op": "stop"})
        except (OSError, ConnectionError):
            pass

    def predict_batch(self, payload: dict) -> np.ndarray:
        reply = self._call({"op": "generate", **payload})
        if "error" in reply:
            raise RuntimeError(f"worker generate failed: "
                               f"{reply['error']}")
        return np.asarray(reply["tokens"], np.int32)


class ProcReplica(Replica):
    """A Replica whose engine lives in another PROCESS.  ``start``
    (first admission and every re-admission) ensures the process is
    running and READY (ping) before the worker thread spins up — a
    respawn after process death warms from the executable cache, which
    is what keeps re-admission near ``restart_cached_mttr_s`` instead
    of a cold compile.  ``stale`` adds the r14 marker check: a process
    whose HB_<name> file stops moving is presumed dead/wedged even if
    the parent-side worker thread is idle and beating."""

    def __init__(self, name: str, spawn: Callable[[], subprocess.Popen],
                 client: WorkerClient, hb_path: str,
                 marker_timeout_s: float = 5.0,
                 log: Callable[[str], None] = print):
        super().__init__(name, client, log=log)
        self._spawn = spawn
        self.client = client
        self.hb_path = hb_path
        self.marker_timeout_s = float(marker_timeout_s)
        self.proc: Optional[subprocess.Popen] = None
        self.respawns = 0

    def proc_alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def ensure_proc(self) -> None:
        if not self.proc_alive():
            if self.proc is not None:
                self.respawns += 1
            self.proc = self._spawn()

    def start(self) -> None:
        """Called under the ReplicaSet lock (first admission and every
        re-admission).  A readiness failure must NOT raise — the caller
        is the watchdog loop — so a worker that never answers its ping
        stays detached with a fresh ``detached_at`` and the auto-
        readmit timer simply tries again."""
        try:
            self.ensure_proc()
            self.client.drop()
            self.client.ping()      # blocks (bounded) until ready
        except (OSError, ConnectionError, RuntimeError) as e:
            self._log(f"[serve] replica {self.name} respawn not ready: "
                      f"{e!r}; will retry")
            self.alive = False
            self.detached_at = time.monotonic()
            return
        super().start()

    def stale(self, now: float, timeout_s: float) -> bool:
        if super().stale(now, timeout_s):
            return True
        if not self.alive:
            return False
        if not self.proc_alive():
            return True
        try:
            age = time.time() - os.path.getmtime(self.hb_path)
        except OSError:
            return False            # not written yet (still starting)
        return age > self.marker_timeout_s

    def kill(self) -> None:
        """Fault seam for smokes/tests: SIGKILL the worker process —
        the process-scope analog of the in-process ``hang_s``."""
        if self.proc is not None:
            self.proc.kill()


class GenScheduler(BatchScheduler):
    """BatchScheduler at slot granularity: cells of ONE request, the
    wire payload as the work's batch, the generated token array as its
    result.  Everything between — least-loaded dispatch, parking when
    no replica is live, the bounded attempt budget, rescue from a
    detached replica — is the inherited r16 machinery."""

    def __init__(self, queue: RequestQueue, replicas: ReplicaSet,
                 max_delay_ms: float = 20.0, recorder=None,
                 request_deadline_s: Optional[float] = None,
                 log: Callable[[str], None] = print):
        super().__init__(queue, replicas, batch_size=1,
                         max_delay_ms=max_delay_ms, recorder=recorder,
                         request_deadline_s=request_deadline_s,
                         log=log)

    def summary(self) -> dict:
        """The front door's robustness counters under their README
        names: a generation re-dispatched because its worker PROCESS
        died/errored mid-request is a decode_request_retry; one that
        blew its per-request deadline is a decode_request_timeout."""
        out = super().summary()
        out["decode_request_retries"] = out.pop("request_retries", 0)
        out["decode_request_timeouts"] = out.pop("request_timeouts", 0)
        return out

    def _assemble(self, bucket: int, requests):
        req = requests[0]
        if not isinstance(req, GenRequest):
            raise TypeError("the decode front door serves GenRequests "
                            "(queue.submit(tokens, max_new_tokens=...))")
        return {"id": req.id, "tokens": np.asarray(req.tokens).tolist(),
                "max_new": req.max_new}, 1

    def _on_done(self, work, tokens: np.ndarray, replica) -> None:
        now = time.monotonic()
        req = work.requests[0]
        req.fulfill(np.asarray(tokens, np.int32), replica.name, now)
        with self._lock:
            self.completed_batches += 1
            self.completed_requests += 1
            self.latencies_ms.append(req.latency_ms())
            t0 = req.t_submit
            self._t_first = t0 if self._t_first is None \
                else min(self._t_first, t0)
            self._t_last = now if self._t_last is None \
                else max(self._t_last, now)
        if self.recorder is not None and self.request_events:
            self.recorder.record_event(
                "serve_request", bucket=req.bucket, len=req.raw_len,
                queue_ms=round((work.t_created - req.t_submit) * 1e3, 3),
                total_ms=round(req.latency_ms(), 3),
                replica=replica.name)


class FrontDoor:
    """Parent-side assembly: N worker processes + queue + GenScheduler.

    ``cfg`` is the serving TrainConfig (checkpoint_dir names the
    artifact to serve); each worker gets its own telemetry directory
    (``telemetry_dir=<run_dir>/telemetry_<name>``) so the r12 one-file-
    per-process contract holds across the process boundary."""

    def __init__(self, cfg, n_workers: int = 2, run_dir: str = "",
                 heartbeat_timeout_s: float = 60.0,
                 marker_timeout_s: float = 5.0,
                 readmit_after_s: float = 1.0,
                 recorder=None, log: Callable[[str], None] = print):
        self.cfg = cfg
        self.run_dir = run_dir or os.path.join(cfg.checkpoint_dir,
                                               "frontdoor")
        os.makedirs(self.run_dir, exist_ok=True)
        self._log = log
        self.queue = RequestQueue(cfg.seq_buckets, max_len=cfg.seq_len)
        self.replicas: List[ProcReplica] = []
        hb_dir = os.path.join(self.run_dir, "hb")
        for i in range(int(n_workers)):
            name = f"decode{i}"
            port = free_port()
            cfg_path = os.path.join(self.run_dir, f"cfg_{name}.json")
            worker_cfg = cfg.replace(telemetry_dir=os.path.join(
                self.run_dir, f"telemetry_{name}"))
            with open(cfg_path, "w") as f:
                json.dump(dataclasses.asdict(worker_cfg), f)
            cmd = [sys.executable, "-m",
                   "faster_distributed_training_tpu.serve.decode"
                   ".worker",
                   "--cfg", cfg_path, "--port", str(port),
                   "--name", name, "--hb_dir", hb_dir]

            log_path = os.path.join(self.run_dir, f"{name}.log")
            # the package root on the child's PYTHONPATH: `-m` resolves
            # from sys.path, and the parent may be running from any cwd
            pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))))
            env = dict(os.environ)
            env["PYTHONPATH"] = pkg_root + (
                os.pathsep + env["PYTHONPATH"]
                if env.get("PYTHONPATH") else "")

            def spawn(_cmd=tuple(cmd), _log=log_path,
                      _env=env) -> subprocess.Popen:
                # own log file, not the parent's stdout: worker output
                # survives the parent and a child can never hold a
                # parent-side pipe open
                logf = open(_log, "ab")
                try:
                    return subprocess.Popen(list(_cmd), stdout=logf,
                                            stderr=subprocess.STDOUT,
                                            env=_env)
                finally:
                    logf.close()

            self.replicas.append(ProcReplica(
                name, spawn, WorkerClient(port),
                hb_path=os.path.join(hb_dir, f"HB_{name}"),
                marker_timeout_s=marker_timeout_s, log=log))
        self.rset = ReplicaSet(self.replicas,
                               heartbeat_timeout_s=heartbeat_timeout_s,
                               readmit_after_s=readmit_after_s, log=log)
        self.sched = GenScheduler(
            self.queue, self.rset,
            max_delay_ms=cfg.serve_max_delay_ms, recorder=recorder,
            request_deadline_s=float(
                getattr(cfg, "decode_deadline_s", 0.0) or 0.0) or None,
            log=log)

    def start(self) -> None:
        # spawn every process first so their warmups overlap, then let
        # each start() block on its own readiness ping
        for r in self.replicas:
            r.ensure_proc()
        self.sched.start()

    def submit(self, tokens, max_new: int) -> GenRequest:
        req = self.queue.submit(tokens, max_new_tokens=max_new)
        assert isinstance(req, GenRequest)
        return req

    def close(self) -> None:
        self.sched.close()
        for r in self.replicas:
            if r.proc_alive():
                r.client.stop()
        deadline = time.monotonic() + 5.0
        for r in self.replicas:
            if r.proc is None:
                continue
            while r.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if r.proc.poll() is None:
                r.proc.kill()


if __name__ == "__main__":
    sys.exit(worker_main(sys.argv[1:]))
