"""DecodeEngine: the AOT program families behind KV-cache generation.

Two observed program families, both enumerated at warmup and FIXED —
the decode twin of the classifier engine's per-bucket predict cells:

  ``<name>:prefill:L<bucket>``  one per prompt bucket, batch 1: full
      causal forward over the padded prompt, returning per-layer K/V,
      the last-real-position logits and the first sampled token;
  ``<name>:decode:P<pages>``    one per page count: a single decode
      step over the WHOLE slot batch with the attention window
      statically sliced to pages*page columns.

Every program routes through the r15 observatory (retrace detector +
compile telemetry) and rides the r17 executable cache when armed, so a
restarted decode replica deserializes its programs in
~``restart_cached_mttr_s`` instead of recompiling.  Ragged request
traffic can therefore never retrace: request length picks a bucket
(data.loader.select_bucket, the training pipeline's one rule), live
sequence length picks a page count, and both domains are finite —
pinned by tests/test_decode.py's program-set test.

The per-bucket cache INSERT programs (scattering prefill K/V into a
slot) are jitted but deliberately NOT observed: they are trivial
scatters whose set is bounded by the bucket list, not a model program
family worth a pin.

The step is synchronous (``np.asarray`` on the sampled tokens) — on
CPU simulation the dispatch is the cost anyway; a TPU deployment would
pipeline host admission against the device step, which changes none of
the program shapes.
"""

from __future__ import annotations

import time
import warnings
from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from faster_distributed_training_tpu.models.decode import (SamplingCfg,
                                                           decode_spec,
                                                           decode_step,
                                                           prefill)
from faster_distributed_training_tpu.serve.decode.cache import PagedKVCache
from faster_distributed_training_tpu.serve.engine import (_DONATION_WARNING,
                                                          ServingState)


class DecodeEngine:
    """Paged KV-cache generation over one frozen LM variable bundle.

    ``device`` pins the replica to one chip (the SNIPPETS [3] 1D
    replicated layout decode defaults to); ``mesh`` is the model-
    sharded exception for checkpoints that don't fit a chip.  ``donate``
    None = auto: the cache buffers round-trip through every step/insert
    program unless the backend is a jaxlib-0.4.x CPU client (the r7
    allocator caveat, same gate as the classifier engine)."""

    def __init__(self, model, state: ServingState, buckets: Sequence[int],
                 batch_size: int = 4, page: int = 16, max_pages: int = 0,
                 sampling: Optional[SamplingCfg] = None,
                 donate: Optional[bool] = None, device=None, mesh=None,
                 name: str = "serve",
                 log: Callable[[str], None] = print):
        import jax

        self.spec = decode_spec(model)
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        self.batch_size = int(batch_size)
        self.page = int(page)
        if max_pages <= 0:
            # auto: room for the longest prompt bucket plus one page of
            # generation headroom, capped by the position table
            import math
            max_pages = math.ceil(
                min(max(self.buckets) + page, self.spec.maxlen) / page)
        self.max_pages = int(max_pages)
        if max(self.buckets) > self.page * self.max_pages:
            raise ValueError(
                f"largest bucket {max(self.buckets)} exceeds the cache "
                f"capacity {self.page * self.max_pages} "
                f"(= page {self.page} x max_pages {self.max_pages})")
        self.sampling = sampling or SamplingCfg()
        self.name = name
        self.device = device
        self.mesh = mesh
        self._log = log
        if donate is None:
            from faster_distributed_training_tpu.cli import (
                donation_workaround_needed)
            donate = not (jax.default_backend() == "cpu"
                          and donation_workaround_needed())
        self.donate = bool(donate)
        params = state.params["model"]
        if device is not None:
            params = jax.device_put(params, device)
        self._params = params
        self.cache = PagedKVCache(self.spec, self.batch_size, self.page,
                                  self.max_pages)
        if device is not None:
            self.cache.k = jax.device_put(self.cache.k, device)
            self.cache.v = jax.device_put(self.cache.v, device)

        spec, samp = self.spec, self.sampling

        def _prefill(p, tokens, length, req_ids):
            return prefill(spec, samp, p, tokens, length, req_ids)

        self._prefill_jit = jax.jit(_prefill)
        self._decode_jits: Dict[int, object] = {}
        dkw = dict(donate_argnums=(1, 2)) if self.donate else {}
        for pages in range(1, self.max_pages + 1):
            window = pages * self.page

            def _step(p, k, v, token, pos, active, req_ids, _w=window):
                return decode_step(spec, samp, _w, p, k, v, token, pos,
                                   active, req_ids)

            self._decode_jits[pages] = jax.jit(_step, **dkw)
        self._insert_jits: Dict[int, object] = {}
        ikw = dict(donate_argnums=(0, 1)) if self.donate else {}
        for b in self.buckets:

            def _insert(k, v, pk, pv, slot, _L=b):
                k = k.at[:, slot, :, :_L, :].set(pk[:, 0])
                v = v.at[:, slot, :, :_L, :].set(pv[:, 0])
                return k, v

            self._insert_jits[b] = jax.jit(_insert, **ikw)
        self._prefill_compiled: Dict[int, object] = {}
        self._decode_compiled: Dict[int, object] = {}
        self._insert_compiled: Dict[int, object] = {}
        self.steps = 0
        self.prefills = 0

    # -- compilation -------------------------------------------------------

    def _mesh_ctx(self):
        import contextlib
        return self.mesh if self.mesh is not None \
            else contextlib.nullcontext()

    def _observe(self, pname: str, jitted, args, sig_argnums) -> object:
        """engine.InferenceEngine.compile_bucket's observe-else-AOT-else-
        plain-jit ladder, shared by all three program families."""
        from faster_distributed_training_tpu.telemetry import programs
        compiled = None
        with warnings.catch_warnings():
            warnings.filterwarnings("ignore", message=_DONATION_WARNING)
            with self._mesh_ctx():
                obs = programs.get_observatory() if pname else None
                if obs is not None:
                    sig = programs.args_signature(args, sig_argnums)
                    compiled = obs.observe_compile(pname, jitted, args,
                                                   sig=sig)
                if compiled is None:
                    try:
                        compiled = jitted.lower(*args).compile()
                    except Exception as e:
                        if pname:
                            self._log(f"[decode] AOT compile of {pname} "
                                      f"failed ({e!r}); plain jit "
                                      f"dispatch serves it")
                        compiled = jitted
        return compiled

    def _compile_prefill(self, bucket: int) -> None:
        if bucket in self._prefill_compiled:
            return
        args = (self._params,
                np.zeros((1, bucket), np.int32),
                np.ones((1,), np.int32),
                np.zeros((1,), np.int32))
        self._prefill_compiled[bucket] = self._observe(
            f"{self.name}:prefill:L{bucket}", self._prefill_jit, args,
            (1, 2, 3))

    def _compile_decode(self, pages: int) -> None:
        if pages in self._decode_compiled:
            return
        B = self.batch_size
        args = (self._params, self.cache.k, self.cache.v,
                np.zeros((B,), np.int32), np.zeros((B,), np.int32),
                np.zeros((B,), bool), np.zeros((B,), np.int32))
        self._decode_compiled[pages] = self._observe(
            f"{self.name}:decode:P{pages}", self._decode_jits[pages],
            args, (3, 4, 5, 6))

    def _compile_insert(self, bucket: int) -> None:
        if bucket in self._insert_compiled:
            return
        pk = np.zeros((self.spec.n_layers, 1, self.spec.h, bucket,
                       self.spec.d_k), np.dtype(self.cache.k.dtype))
        args = (self.cache.k, self.cache.v, pk, pk,
                np.int32(0))
        self._insert_compiled[bucket] = self._observe(
            "", self._insert_jits[bucket], args, ())

    def warmup(self) -> float:
        """Compile the ENTIRE program set before any request arrives —
        the decode heartbeat timeout never has to cover a compile, and
        with the executable cache armed a restarted replica is serving
        in deserialize time.  Returns wall seconds."""
        t0 = time.monotonic()
        for b in self.buckets:
            self._compile_prefill(b)
            self._compile_insert(b)
        for p in range(1, self.max_pages + 1):
            self._compile_decode(p)
        return time.monotonic() - t0

    # -- the hot path ------------------------------------------------------

    def admit(self, tokens: np.ndarray, bucket: int,
              req_id: int) -> Tuple[int, int]:
        """Prefill one prompt and swap its K/V into a free slot.
        Returns (slot, first_token).  Caller guarantees a free slot
        exists (scheduler admission gate)."""
        import jax

        slot = self.cache.free_slot()
        if slot is None:
            raise RuntimeError("admit called with no free slot")
        t = np.asarray(tokens, np.int32).reshape(-1)[:bucket]
        length = max(len(t), 1)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(t)] = t
        self._compile_prefill(bucket)
        self._compile_insert(bucket)
        args = (padded, np.asarray([length], np.int32),
                np.asarray([req_id], np.int32))
        if self.device is not None:
            args = jax.device_put(args, self.device)
        with self._mesh_ctx():
            pk, pv, _logits, first = self._prefill_compiled[bucket](
                self._params, *args)
            self.cache.k, self.cache.v = self._insert_compiled[bucket](
                self.cache.k, self.cache.v, pk, pv, np.int32(slot))
        first_token = int(np.asarray(first)[0])
        self.cache.admit(slot, req_id, length, first_token)
        self.prefills += 1
        return slot, first_token

    def prefill_logits(self, tokens: np.ndarray,
                       bucket: int) -> np.ndarray:
        """The (vocab,) fp32 logits at the prompt's last position —
        the parity probe tests compare against ``model.apply`` under
        the causal mask (no cache mutation)."""
        t = np.asarray(tokens, np.int32).reshape(-1)[:bucket]
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(t)] = t
        self._compile_prefill(bucket)
        with self._mesh_ctx():
            _pk, _pv, logits, _first = self._prefill_compiled[bucket](
                self._params, padded,
                np.asarray([max(len(t), 1)], np.int32),
                np.zeros((1,), np.int32))
        return np.asarray(logits)[0]

    def step(self) -> Tuple[np.ndarray, int]:
        """One decode step over every active slot.  Returns
        (next_tokens[batch], pages) — callers read next_tokens only at
        active slots.  The cache's slot table is advanced."""
        cache = self.cache
        pages = cache.window_pages()
        self._compile_decode(pages)
        token = cache.tokens.copy()
        pos = cache.lengths.copy()          # the column this step writes
        pos[~cache.active] = 0
        with self._mesh_ctx():
            cache.k, cache.v, nxt = self._decode_compiled[pages](
                self._params, cache.k, cache.v, token,
                pos, cache.active.copy(), cache.req_ids.copy())
        nxt = np.asarray(nxt)
        cache.advance(nxt)
        self.steps += 1
        return nxt, pages

    def active_count(self) -> int:
        return int(self.cache.active.sum())
