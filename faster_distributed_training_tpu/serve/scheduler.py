"""Batch scheduler: queue cells -> padded batches -> replica dispatch.

The scheduler thread is the serving tier's control loop.  Each tick it
(1) runs the replica heartbeat monitor, (2) retries work parked while
no replica was live, and (3) drains one (bucket, requests) cell from
the queue — deadline-expired buckets first (partial if under-full),
then full batches (queue.take_cell's policy; ``max_delay_ms`` is the
latency/throughput trade-off knob: raise it and partial batches fill
further before flushing, lower it and tail latency shrinks at lower
chip utilization).  Partial cells
pad to the engine batch size with masked rows (serve/engine.pad_batch)
whose output rows are DROPPED here — a pad row can never leak into a
response (pinned by tests/test_serve.py).

Completion runs on the REPLICA worker thread (one callback: scatter
logits rows to requests, stamp latency, emit telemetry); the scheduler
thread never blocks on a device.  Work rescued from a detached replica
re-enters through :meth:`_redispatch` with a bounded attempt budget —
a batch that fails on every replica fails its requests with the last
error instead of cycling forever.

Telemetry (append-only r12 schema additions): one ``serve_batch`` event
per dispatched batch and one ``serve_request`` event per request when a
recorder is attached.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

import numpy as np

from faster_distributed_training_tpu.serve.engine import pad_batch
from faster_distributed_training_tpu.serve.queue import (RequestQueue,
                                                         ServeRequest)
from faster_distributed_training_tpu.serve.replicas import ReplicaSet


class _Work:
    """One assembled batch in flight.  ``claim`` is the ONE-SHOT
    completion gate: a batch re-dispatched off a presumed-hung replica
    may race its original — whichever finishes first claims, the loser
    drops (identical logits either way)."""

    def __init__(self, bucket: int, requests: List[ServeRequest],
                 batch: dict, n_real: int, on_done: Callable,
                 max_attempts: int):
        self.bucket = int(bucket)
        self.requests = requests
        self.batch = batch             # fresh numpy — safe to re-upload
        self.n_real = int(n_real)
        self.t_created = time.monotonic()
        self.attempts = 0
        self.max_attempts = int(max_attempts)
        # retry backoff gate: a rescued batch parks until this clock
        # (monotonic) instead of hammering a replica set mid-respawn
        self.not_before = 0.0
        self.last_error: Optional[BaseException] = None
        self._on_done = on_done
        self._claim_lock = threading.Lock()
        self._claimed = False

    @property
    def claimed(self) -> bool:
        return self._claimed

    def claim(self) -> bool:
        with self._claim_lock:
            if self._claimed:
                return False
            self._claimed = True
            return True

    def complete(self, logits, replica) -> None:
        self._on_done(self, np.asarray(logits), replica)

    def note_failure(self, exc: BaseException) -> None:
        self.last_error = exc

    def fail_all(self, exc: BaseException) -> bool:
        """Fail every request (first claimer only); True when this call
        won the claim — failure counters key off that so a rescue/expiry
        race can never double-count."""
        if not self.claim():
            return False
        for req in self.requests:
            req.fail(exc)
        return True


class BatchScheduler:
    """Continuous-batching control loop over one queue + one replica
    set.  ``batch_size`` is the compiled batch dimension every cell
    pads to; ``max_delay_ms`` bounds how long a partial batch may wait
    for company."""

    def __init__(self, queue: RequestQueue, replicas: ReplicaSet,
                 batch_size: int, max_delay_ms: float = 20.0,
                 recorder=None, request_events: bool = True,
                 request_deadline_s: Optional[float] = None,
                 retry_backoff_s: float = 0.05,
                 retry_backoff_cap_s: float = 2.0,
                 log: Callable[[str], None] = print):
        self.queue = queue
        self.replicas = replicas
        self.batch_size = int(batch_size)
        self.max_delay_s = float(max_delay_ms) / 1e3
        self.recorder = recorder
        self.request_events = bool(request_events)
        # per-request deadline (None = wait forever, the pre-r24
        # behavior): work whose oldest request has been in the system
        # longer than this fails with TimeoutError at its next dispatch
        # or parked-retry tick — a dead/respawning engine process makes
        # callers wait a BOUNDED time, never forever
        self.request_deadline_s = (None if request_deadline_s is None
                                   or request_deadline_s <= 0
                                   else float(request_deadline_s))
        # rescue backoff: attempt k re-enters dispatch after
        # backoff·2^(k-1) (capped) parked seconds, so a batch bounced
        # off a replica set mid-respawn gives the respawn room to warm
        self.retry_backoff_s = float(retry_backoff_s)
        self.retry_backoff_cap_s = float(retry_backoff_cap_s)
        self._log = log
        self._lock = threading.Lock()
        self._parked: List[_Work] = []   # work with no live replica yet
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        # latency/throughput bookkeeping (summary())
        self.latencies_ms: List[float] = []
        self.completed_requests = 0
        self.completed_batches = 0
        self.padded_rows = 0
        self.request_retries = 0     # re-dispatches after replica loss
        self.request_timeouts = 0    # requests failed by the deadline
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self.replicas.requeue = self._redispatch
        self.replicas.start_all()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fdt-serve-scheduler")
        self._thread.start()

    def close(self, drain_s: float = 5.0) -> None:
        """Stop accepting, drain what is pending (bounded), stop the
        loop and the replicas."""
        self.queue.close()
        deadline = time.monotonic() + drain_s
        while time.monotonic() < deadline:
            busy = (self.queue.pending() or self._parked
                    or any(r.load() for r in self.replicas.replicas))
            if not busy:
                break
            time.sleep(0.01)
        self._closed = True
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        self.replicas.close()

    # -- the control loop --------------------------------------------------

    def _loop(self) -> None:
        while not self._closed:
            self.replicas.monitor()
            self._retry_parked()
            cell = self.queue.take_cell(self.batch_size, self.max_delay_s,
                                        timeout_s=0.05)
            if cell is None:
                continue
            bucket, requests = cell
            batch, n_real = self._assemble(bucket, requests)
            work = _Work(bucket, requests, batch, n_real,
                         on_done=self._on_done,
                         max_attempts=max(len(self.replicas.replicas),
                                          1) + 1)
            self._dispatch(work)

    def _assemble(self, bucket: int, requests: List[ServeRequest]):
        """Cell -> engine payload (batch, n_real).  The classifier tier
        pads to the compiled batch dimension; the decode front door
        (serve/decode/frontend.py) overrides this seam with the
        identity wire payload — everything else (dispatch, parking,
        attempt budget, replica rescue) is shared."""
        return pad_batch(requests, bucket, self.batch_size)

    def _expire(self, work: _Work) -> bool:
        """Deadline check: True when the work was failed for age.  The
        clock starts at batch assembly (``t_created``) — queue wait
        before assembly is bounded separately by ``max_delay_ms``."""
        if self.request_deadline_s is None:
            return False
        age = time.monotonic() - work.t_created
        if age <= self.request_deadline_s:
            return False
        err: BaseException = TimeoutError(
            f"request deadline exceeded ({age:.1f}s > "
            f"{self.request_deadline_s:.1f}s, {work.attempts} dispatch "
            f"attempt(s), last error: {work.last_error!r})")
        if work.fail_all(err):
            with self._lock:
                self.request_timeouts += work.n_real
            self._log(f"[serve] batch (bucket {work.bucket}, "
                      f"{work.n_real} requests) TIMED OUT: {err}")
        return True

    def _dispatch(self, work: _Work) -> None:
        if self._expire(work):
            return
        work.attempts += 1
        if work.attempts > work.max_attempts:
            err = work.last_error or RuntimeError(
                "batch exhausted its dispatch attempts")
            self._log(f"[serve] batch (bucket {work.bucket}, "
                      f"{work.n_real} requests) FAILED after "
                      f"{work.attempts - 1} attempts: {err!r}")
            work.fail_all(err)
            return
        if not self.replicas.dispatch(work):
            with self._lock:
                self._parked.append(work)

    def _redispatch(self, work: _Work) -> None:
        """Requeue sink for the replica set: rescued / failed work
        re-enters dispatch (unless something already completed it).
        A RETRY (attempt >= 1, i.e. an engine died or errored
        mid-request) is counted and parks through the bounded
        exponential backoff instead of re-entering immediately."""
        if work.claimed:
            return
        if work.attempts >= 1:
            with self._lock:
                self.request_retries += 1
            if self.retry_backoff_s > 0:
                work.not_before = time.monotonic() + min(
                    self.retry_backoff_s * 2.0 ** (work.attempts - 1),
                    self.retry_backoff_cap_s)
                with self._lock:
                    self._parked.append(work)
                return
        self._dispatch(work)

    def _retry_parked(self) -> None:
        now = time.monotonic()
        with self._lock:
            parked, self._parked = self._parked, []
        for work in parked:
            if work.claimed:
                continue
            if self._expire(work):
                continue
            if work.not_before > now:
                with self._lock:
                    self._parked.append(work)
                continue
            if work.not_before:
                # backoff elapsed: this re-entry is a true dispatch
                # attempt (budget-counted), not a no-replica park loop
                work.not_before = 0.0
                self._dispatch(work)
                continue
            if not self.replicas.dispatch(work):
                with self._lock:
                    self._parked.append(work)

    # -- completion (replica worker thread) --------------------------------

    def _on_done(self, work: _Work, logits: np.ndarray, replica) -> None:
        now = time.monotonic()
        # pad rows [n_real:] are DROPPED here — the only consumer of the
        # logits is this scatter, so a masked pad row cannot reach any
        # response
        for i, req in enumerate(work.requests):
            req.fulfill(logits[i], replica.name, now)
        dispatch_ms = (now - work.t_created) * 1e3
        with self._lock:
            self.completed_batches += 1
            self.completed_requests += work.n_real
            self.padded_rows += self.batch_size - work.n_real
            for req in work.requests:
                self.latencies_ms.append(req.latency_ms())
                t0 = req.t_submit
                self._t_first = t0 if self._t_first is None \
                    else min(self._t_first, t0)
            self._t_last = now if self._t_last is None \
                else max(self._t_last, now)
        if self.recorder is not None:
            self.recorder.record_event(
                "serve_batch", bucket=work.bucket, size=self.batch_size,
                real=work.n_real, pad=self.batch_size - work.n_real,
                replica=replica.name,
                dispatch_ms=round(dispatch_ms, 3),
                attempts=work.attempts)
            if self.request_events:
                for req in work.requests:
                    self.recorder.record_event(
                        "serve_request", bucket=req.bucket,
                        len=req.raw_len,
                        queue_ms=round((work.t_created - req.t_submit)
                                       * 1e3, 3),
                        total_ms=round(req.latency_ms(), 3),
                        replica=replica.name)

    # -- reporting ---------------------------------------------------------

    def summary(self) -> dict:
        """p50/p99 request latency + throughput over everything served
        so far (nearest-rank percentiles — train.metrics.percentiles,
        the one definition the telemetry stack already uses)."""
        from faster_distributed_training_tpu.train.metrics import (
            percentiles)
        with self._lock:
            lats = list(self.latencies_ms)
            n = self.completed_requests
            wall = ((self._t_last - self._t_first)
                    if (self._t_first is not None
                        and self._t_last is not None
                        and self._t_last > self._t_first) else 0.0)
            out = {"requests": n, "batches": self.completed_batches,
                   "padded_rows": self.padded_rows,
                   "request_retries": self.request_retries,
                   "request_timeouts": self.request_timeouts}
        pct = percentiles(lats, qs=(50, 99))
        out["p50_ms"] = pct.get(50, 0.0)
        out["p99_ms"] = pct.get(99, 0.0)
        out["qps"] = round(n / wall, 2) if wall else 0.0
        return out
