"""Replica-level resilience: N independent predict workers, one queue.

Each :class:`Replica` owns an :class:`~serve.engine.InferenceEngine`
(its own chip under the replicated-per-chip layout; the shared mesh
under the model-sharded fallback) and a worker thread draining a
private inbox.  The :class:`ReplicaSet` dispatches assembled batches to
the least-loaded LIVE replica and watches liveness the r10 way: every
worker-loop tick touches a heartbeat timestamp (the in-process
equivalent of the coordinator's ``HB_<pi>`` marker files — same
semantic, request-scope), and a replica silent past
``heartbeat_timeout_s`` is presumed wedged (hung device program, dead
thread) and DETACHED: its queued and in-flight work is re-dispatched to
the survivors, so one dead replica never stalls the queue.  A detached
replica re-admits (``readmit``) without draining the others — the r14
re-admission semantic, one replica instead of one slice.

Failure seams for tests/smokes (the FDT_FAULT idiom, in-process):
``Replica.fail_next`` raises inside the worker on its next batch;
``Replica.hang_s`` blocks the worker mid-batch so only the heartbeat
monitor can act.

The heartbeat timeout must exceed the worst-case single predict call —
which is why the engines are warmed up (AOT-compiled) BEFORE the queue
opens: steady-state predicts are milliseconds, compiles would be
seconds and indistinguishable from a hang (the --step_timeout_s caveat,
config.py, at request scope).
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Callable, List, Optional

_POLL_S = 0.05


class Replica:
    """One serving worker: engine + inbox + heartbeat."""

    def __init__(self, name: str, engine,
                 log: Callable[[str], None] = print):
        self.name = name
        self.engine = engine
        self._log = log
        self.inbox: "queue_mod.Queue" = queue_mod.Queue()
        self.alive = False
        self.last_beat = time.monotonic()
        self.busy_with = None          # the work item mid-predict
        self.served_batches = 0
        self.served_requests = 0
        self.failures = 0
        self.detached_at: Optional[float] = None
        # fault seams (tests/smoke): an exception to raise on the next
        # batch, and/or seconds to hang mid-batch
        self.fail_next: Optional[BaseException] = None
        self.hang_s: float = 0.0
        self._set: Optional["ReplicaSet"] = None
        self._token = 0                # bumped on detach: stale workers exit
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._token += 1
        self.alive = True
        self.detached_at = None
        # a fresh worker starts with no in-flight work — the previous
        # incarnation's marker was rescued at detach and a stale thread
        # is no longer allowed to clear the new worker's (token guard)
        self.busy_with = None
        self.last_beat = time.monotonic()
        self._thread = threading.Thread(target=self._worker,
                                        args=(self._token,), daemon=True,
                                        name=f"fdt-serve-{self.name}")
        self._thread.start()

    def stop(self) -> None:
        self.alive = False
        self._token += 1

    def load(self) -> int:
        return self.inbox.qsize() + (1 if self.busy_with is not None else 0)

    def stale(self, now: float, timeout_s: float) -> bool:
        return self.alive and (now - self.last_beat) > timeout_s

    def submit(self, work) -> None:
        self.inbox.put(work)

    # -- the worker --------------------------------------------------------

    def _worker(self, token: int) -> None:
        while token == self._token:
            self.last_beat = time.monotonic()
            try:
                work = self.inbox.get(timeout=_POLL_S)
            except queue_mod.Empty:
                continue
            if token != self._token:
                # detached between get() and here: hand the work back
                if self._set is not None:
                    self._set.requeue(work)
                return
            self.busy_with = work
            self.last_beat = time.monotonic()
            try:
                if self.hang_s:
                    # hang seam: the worker wedges mid-batch; only the
                    # heartbeat monitor can detach it
                    time.sleep(self.hang_s)
                if self.fail_next is not None:
                    exc, self.fail_next = self.fail_next, None
                    raise exc
                logits = self.engine.predict_batch(work.batch)
            except BaseException as e:
                self.failures += 1
                # token-guarded: a STALE thread (detached + re-admitted
                # while it was wedged in predict) must neither clear the
                # new worker's in-flight marker — a later detach would
                # then find nothing to rescue and that batch would
                # strand — nor report a replica failure that would
                # detach the healthy new incarnation; its own work was
                # already rescued at detach time
                if token == self._token:
                    self.busy_with = None
                    if self._set is not None:
                        self._set.replica_failed(self, work, e)
                    else:
                        work.fail_all(e)
                return
            if token == self._token:
                self.busy_with = None
            self.last_beat = time.monotonic()
            if work.claim():
                self.served_batches += 1
                self.served_requests += work.n_real
                work.complete(logits, self)
            # an unclaimable work means a monitor already re-dispatched
            # it (this worker was presumed hung) — the late result drops

    def stats(self) -> dict:
        return {"name": self.name, "alive": self.alive,
                "served_batches": self.served_batches,
                "served_requests": self.served_requests,
                "failures": self.failures, "load": self.load()}


class ReplicaSet:
    """Least-loaded dispatch + heartbeat watchdog + re-admission over N
    replicas.  ``requeue`` (set by the scheduler at start) receives
    every work item rescued from a detached replica."""

    def __init__(self, replicas: List[Replica],
                 heartbeat_timeout_s: float = 5.0,
                 readmit_after_s: float = 0.0,
                 log: Callable[[str], None] = print):
        self.replicas = list(replicas)
        for r in self.replicas:
            r._set = self
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        # 0 = manual re-admission only; > 0 = a detached replica is
        # automatically re-admitted after this many seconds (the
        # restarted-process stand-in for tests/smokes)
        self.readmit_after_s = float(readmit_after_s)
        self._log = log
        self._lock = threading.Lock()
        self.requeue: Callable = lambda work: work.fail_all(
            RuntimeError("no requeue sink attached"))
        self.replica_failures = 0
        self.replica_readmissions = 0

    # -- lifecycle ---------------------------------------------------------

    def start_all(self) -> None:
        for r in self.replicas:
            if not r.alive:
                r.start()

    def close(self) -> None:
        for r in self.replicas:
            r.stop()

    def live(self) -> List[Replica]:
        return [r for r in self.replicas if r.alive]

    # -- dispatch ----------------------------------------------------------

    def dispatch(self, work) -> bool:
        """Hand ``work`` to the least-loaded live replica; False when
        none is live (the scheduler parks and retries — requests WAIT
        for a re-admission rather than failing)."""
        with self._lock:
            live = self.live()
            if not live:
                return False
            r = min(live, key=lambda r: r.load())
            r.submit(work)
            return True

    # -- liveness ----------------------------------------------------------

    def monitor(self, now: Optional[float] = None) -> None:
        """One watchdog tick (the scheduler loop calls this every
        iteration): detach heartbeat-stale replicas, auto-readmit timed
        detached ones."""
        now = time.monotonic() if now is None else now
        for r in self.replicas:
            if r.stale(now, self.heartbeat_timeout_s):
                self.detach(r, reason=f"heartbeat silent "
                            f"{now - r.last_beat:.1f}s > "
                            f"{self.heartbeat_timeout_s}s")
            elif (not r.alive and self.readmit_after_s
                    and r.detached_at is not None
                    and now - r.detached_at >= self.readmit_after_s):
                self.readmit(r)

    def detach(self, r: Replica, reason: str = "") -> None:
        """Mark ``r`` dead, bump its worker token (a late-returning
        thread exits instead of completing), and rescue its queued +
        in-flight work onto the survivors.  Never blocks on the wedged
        thread itself."""
        with self._lock:
            if not r.alive:
                return
            r.stop()
            r.detached_at = time.monotonic()
            self.replica_failures += 1
            rescued = []
            inflight = r.busy_with
            if inflight is not None and not inflight.claimed:
                # re-dispatch without claiming: completion is one-shot
                # (work.claim()), so whichever of {the hung call, the
                # retry} finishes FIRST fulfills the requests and the
                # loser's result drops — both compute identical logits
                # (same program, same batch), so the race is benign
                rescued.append(inflight)
            while True:
                try:
                    rescued.append(r.inbox.get_nowait())
                except queue_mod.Empty:
                    break
        self._log(f"[serve] replica {r.name} DETACHED ({reason}); "
                  f"{len(rescued)} batch(es) re-dispatched to "
                  f"{len(self.live())} survivor(s)")
        for work in rescued:
            self.requeue(work)

    def replica_failed(self, r: Replica, work, exc: BaseException) -> None:
        """Worker-thread error path: the replica is detached, the failed
        work re-dispatched (bounded by the work's own attempt budget,
        scheduler.py), and everything still queued in its inbox rescued
        onto the survivors — the worker thread is gone, nothing else
        would ever drain it."""
        self._log(f"[serve] replica {r.name} worker error: {exc!r}")
        with self._lock:
            was_alive = r.alive
            if was_alive:
                r.stop()
                r.detached_at = time.monotonic()
                self.replica_failures += 1
            rescued = []
            while True:
                try:
                    rescued.append(r.inbox.get_nowait())
                except queue_mod.Empty:
                    break
        if was_alive:
            self._log(f"[serve] replica {r.name} DETACHED (worker error); "
                      f"{len(rescued)} queued batch(es) re-dispatched")
        work.note_failure(exc)
        self.requeue(work)
        for w in rescued:
            self.requeue(w)

    def readmit(self, r: Replica) -> None:
        """Re-admit a detached replica: fresh worker thread, fresh
        heartbeat — the others were never drained (the r14 semantic)."""
        with self._lock:
            if r.alive:
                return
            r.start()
            if not r.alive:
                # a process-backed replica whose respawn wasn't ready
                # (serve/decode/frontend.ProcReplica): start() re-armed
                # its detach timer instead of raising — not a
                # re-admission, the monitor will try again
                return
            self.replica_readmissions += 1
        self._log(f"[serve] replica {r.name} RE-ADMITTED "
                  f"({len(self.live())} live)")

    def stats(self) -> dict:
        return {"replicas": [r.stats() for r in self.replicas],
                "replica_failures": self.replica_failures,
                "replica_readmissions": self.replica_readmissions}
