"""ResNet family for 32x32 inputs, TPU-native (NHWC, bf16-friendly).

Re-design of the reference's resnet.py with identical architecture:
  * CIFAR stem — 3x3 conv, stride 1; conv2_x stride 1 (resnet.py:241-243);
  * CELU(alpha=0.075) in the stem and BasicBlock (resnet.py:166,173,190,240),
    ReLU in BottleNeck (resnet.py:204-227);
  * FusedConvBN (no affine, eps added to std) for every stride-1 conv,
    plain Conv+BatchNorm (affine, running stats) for strided convs and
    shortcuts — exactly the reference's split (resnet.py:157-227);
  * torch-style uniform(-1/sqrt(fan_in), 1/sqrt(fan_in)) weight init
    (resnet.py:137-144 and torch's Conv2d/Linear defaults).

Deliberate fixes over the reference (SURVEY.md §7):
  * FusedConvBN keeps running statistics so eval is deterministic
    (reference normalizes with batch stats even at eval, resnet.py:83-100);
  * under pjit with a sharded batch all BN statistics are global —
    cross-replica SyncBN for free;
  * optional `remat` wraps each residual block in jax.checkpoint,
    extending the kernels' recompute-in-backward trick to whole blocks.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from faster_distributed_training_tpu.ops.conv_bn import (conv2d,
                                                         conv_bn_train)

Dtype = Any


def torch_uniform_init(fan_in: int) -> Callable:
    """U(-1/sqrt(fan_in), 1/sqrt(fan_in)) — torch Conv2d/Linear default and
    the reference's FusedConvBN.reset_parameters (resnet.py:137-144)."""
    bound = 1.0 / (fan_in ** 0.5)

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return init


def celu(x: jax.Array, alpha: float = 0.075) -> jax.Array:
    return nn.celu(x, alpha=alpha)


class FusedConvBNLayer(nn.Module):
    """Conv + BN fused via ops.fused_conv_bn; running stats in `batch_stats`."""
    features: int
    kernel: int
    stride: int = 1
    padding: int = 0
    eps: float = 1e-3            # added to std, resnet.py:94
    momentum: float = 0.1        # torch exp_avg_factor (resnet.py:117)
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    conv_remat: bool = True      # backward recomputes the conv output
                                 # (reference parity, resnet.py:107-108).
                                 # Measured FASTER than the autodiff path on
                                 # v5e (3650 vs 3443 img/s/chip @ bs=1024):
                                 # the step is HBM-bound, so recomputing the
                                 # activation beats re-reading it.  Distinct
                                 # from ResNet.remat (block checkpointing);
                                 # not plumbed through the model factories —
                                 # it is a measured default, togglable on
                                 # the layer for experiments

    @nn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        cin = x.shape[-1]
        w = self.param("kernel",
                       torch_uniform_init(cin * self.kernel * self.kernel),
                       (self.kernel, self.kernel, cin, self.features),
                       self.param_dtype)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((self.features,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((self.features,), jnp.float32))
        xc, wc = x.astype(self.dtype), w.astype(self.dtype)
        if train:
            out, mean, var = conv_bn_train(xc, wc, self.stride, self.padding,
                                           self.eps, remat=self.conv_remat)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = (1 - m) * ra_mean.value + m * mean
                ra_var.value = (1 - m) * ra_var.value + m * var
            return out
        y = conv2d(xc, wc, self.stride, self.padding)
        out = ((y.astype(jnp.float32) - ra_mean.value)
               / (jnp.sqrt(ra_var.value) + self.eps))
        return out.astype(self.dtype)


class ConvBN(nn.Module):
    """Plain conv (no bias) + standard affine BatchNorm — the reference's
    nn.Conv2d + nn.BatchNorm2d pairing for strided convs/shortcuts."""
    features: int
    kernel: int
    stride: int = 1
    padding: int = 0
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        cin = x.shape[-1]
        w = self.param("kernel",
                       torch_uniform_init(cin * self.kernel * self.kernel),
                       (self.kernel, self.kernel, cin, self.features),
                       self.param_dtype)
        y = conv2d(x.astype(self.dtype), w.astype(self.dtype),
                   self.stride, self.padding)
        # torch BatchNorm2d defaults: eps=1e-5, exp_avg_factor=0.1
        return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                            epsilon=1e-5, dtype=self.dtype,
                            param_dtype=self.param_dtype)(y)


class BasicBlock(nn.Module):
    """resnet.py:147-190 — expansion 1, CELU activations."""
    features: int
    stride: int = 1
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    conv_remat: bool = True
    expansion = 1

    @nn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)
        fkw = dict(kw, conv_remat=self.conv_remat)
        f = self.features
        if self.stride != 1:
            h = ConvBN(f, 3, self.stride, 1, **kw)(x, train)
            h = celu(h)
            h = FusedConvBNLayer(f * self.expansion, 3, 1, 1, **fkw)(h, train)
        else:
            h = FusedConvBNLayer(f, 3, 1, 1, **fkw)(x, train)
            h = celu(h)
            h = FusedConvBNLayer(f * self.expansion, 3, 1, 1, **fkw)(h, train)
        if self.stride != 1 or x.shape[-1] != f * self.expansion:
            x = ConvBN(f * self.expansion, 1, self.stride, 0, **kw)(x, train)
        return celu(h + x)


class BottleNeck(nn.Module):
    """resnet.py:193-227 — expansion 4, ReLU activations."""
    features: int
    stride: int = 1
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    conv_remat: bool = True
    expansion = 4

    @nn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)
        fkw = dict(kw, conv_remat=self.conv_remat)
        f = self.features
        h = FusedConvBNLayer(f, 1, 1, 0, **fkw)(x, train)
        h = nn.relu(h)
        if self.stride != 1:
            h = ConvBN(f, 3, self.stride, 1, **kw)(h, train)
        else:
            h = FusedConvBNLayer(f, 3, 1, 1, **fkw)(h, train)
        h = nn.relu(h)
        h = FusedConvBNLayer(f * self.expansion, 1, 1, 0, **fkw)(h, train)
        if self.stride != 1 or x.shape[-1] != f * self.expansion:
            x = ConvBN(f * self.expansion, 1, self.stride, 0, **kw)(x, train)
        return nn.relu(h + x)


class ResNet(nn.Module):
    """resnet.py:230-283 — stem + 4 stages + global avg pool + fc."""
    block: Any
    stage_sizes: Sequence[int]
    num_classes: int = 10
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    conv_remat: bool = True   # FusedConvBN recompute-in-backward (the
                          # measured-faster default); False = plain
                          # autodiff conv+BN (bag-of-tricks ablation arm)
    remat: bool = False   # checkpoint every residual block.  Measured on
                          # v5e @ bs=1024 bf16 NGD: 3196 vs 3858 img/s/chip
                          # — the step is HBM-bound and block-recompute adds
                          # more traffic than it saves, so this stays OFF by
                          # default; it is a memory lever for bigger batches,
                          # not a speed lever (cf. conv_bn.py's per-conv
                          # recompute, which IS the faster path).

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)
        x = FusedConvBNLayer(64, 3, 1, 1, **kw,
                             conv_remat=self.conv_remat)(x, train)
        x = celu(x)
        block_cls = self.block
        if self.remat:
            block_cls = nn.remat(block_cls, static_argnums=(2,))
        for stage, (n_blocks, features, stride) in enumerate(
                zip(self.stage_sizes, (64, 128, 256, 512), (1, 2, 2, 2))):
            for i in range(n_blocks):
                x = block_cls(features, stride if i == 0 else 1, **kw,
                              conv_remat=self.conv_remat)(x, train)
        x = jnp.mean(x, axis=(1, 2))  # AdaptiveAvgPool2d((1,1)) on NHWC
        fan_in = x.shape[-1]
        w = self.param("fc_kernel", torch_uniform_init(fan_in),
                       (fan_in, self.num_classes), self.param_dtype)
        b = self.param("fc_bias", torch_uniform_init(fan_in),
                       (self.num_classes,), self.param_dtype)
        x = x.astype(self.dtype) @ w.astype(self.dtype) + b.astype(self.dtype)
        return x.astype(jnp.float32)  # logits in fp32 for a stable softmax


def _factory(block, sizes):
    def make(num_classes: int = 10, **kw) -> ResNet:
        return ResNet(block=block, stage_sizes=sizes, num_classes=num_classes,
                      **kw)
    return make


resnet18 = _factory(BasicBlock, (2, 2, 2, 2))    # resnet.py:286
resnet34 = _factory(BasicBlock, (3, 4, 6, 3))    # resnet.py:292
resnet50 = _factory(BottleNeck, (3, 4, 6, 3))    # resnet.py:298
resnet101 = _factory(BottleNeck, (3, 4, 23, 3))  # resnet.py:304
resnet152 = _factory(BottleNeck, (3, 8, 36, 3))  # resnet.py:310
