"""Decode-mode transformer: the eval forward as pure functions over the
checkpointed param tree, split into PREFILL (full prompt, K/V out) and
DECODE-STEP (one position against the KV cache).

Why not ``model.apply`` with a flax mutable cache collection: the
serving tier needs full control over program signatures — the decode
step's cache is donated, its window is a STATIC slice (one AOT program
per page count, serve/decode/engine.py), and per-slot positions/ids are
traced vectors — none of which the module-tree plumbing expresses
cleanly.  So this file mirrors ``models.transformer``'s eval-time math
op-for-op, reading the exact param leaves training checkpoints carry
(``Embeddings_0/*``, ``layer_i/{ln_attn,attn/{qkv,out},ln_ffn,ffn/
{Dense_0,Dense_1}}``, ``ln_final``, tied ``token_embedding`` or untied
``lm_head``).  tests/test_decode.py pins prefill logits against
``model.apply`` and greedy tokens against the cacheless forward, so a
drift between the mirror and the module is a test failure, not a silent
skew.

The serving-contract caveat (documented in README "Decode serving"):
the r18 LM task trains a BIDIRECTIONAL encoder — packed stream rows
apply no attention mask, every position sees the whole row while the
loss shifts targets by one.  Autoregressive generation requires
causality, so decode serving IMPOSES a causal mask at serving time:
prefill runs the prompt under ``causal_mask`` and the cache only ever
exposes positions <= the query's.  Generation is therefore
self-consistent (greedy cache-vs-cacheless parity holds exactly —
both sides causal) but is NOT the training-time conditional: the model
was trained seeing bidirectional context it no longer gets.

Supported envelope (checked by :func:`decode_spec`): ``lm_head=True``
(an LM checkpoint — tied r19 or untied r18), fused QKV (the default
param layout; the unfused bag-of-tricks ablation arm has a different
tree), no quantization.  ``attention_impl``/``ffn_impl`` don't gate
anything: all impls share the same eval math and param tree; the
mirror computes the dense/flax composition.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from faster_distributed_training_tpu import prng
from faster_distributed_training_tpu.models.transformer import (
    dense_attention, sinusoidal_table)
from faster_distributed_training_tpu.ops.cached_attention import (
    cached_attention)
from faster_distributed_training_tpu.ops.layernorm import torch_layernorm


@dataclasses.dataclass(frozen=True)
class DecodeSpec:
    """Static model geometry the decode programs close over."""
    n_layers: int
    h: int
    d_model: int
    d_ff: int
    vocab: int
    maxlen: int
    tied: bool
    dtype: Any = jnp.float32

    @property
    def d_k(self) -> int:
        return self.d_model // self.h


@dataclasses.dataclass(frozen=True)
class SamplingCfg:
    """Static sampling config — baked into the AOT programs (a runtime
    temperature knob would be one more traced operand for no measured
    need; the program set stays the enumerated families).

    method "greedy" ignores the rest.  "topk" draws from the
    temperature-scaled top-``top_k`` logits with the r8 fold_in key
    chain key = fold(fold(stream(root_key(seed), "decode"), request_id),
    position), so generation is deterministic per (seed, request) and
    independent of batch placement, admission order, or replica."""
    method: str = "greedy"
    temperature: float = 1.0
    top_k: int = 40
    seed: int = 0


def decode_spec(model) -> DecodeSpec:
    """Extract the decode geometry from a built Transformer module,
    rejecting checkpoints outside the decode envelope with actionable
    errors (the stub-or-gate rule: unsupported is loud, not wrong)."""
    if not getattr(model, "lm_head", False):
        raise ValueError(
            "decode serving needs an LM checkpoint (--task lm); this "
            "model has the classifier head — there is nothing to "
            "generate from")
    if getattr(model, "quant", None) is not None:
        raise ValueError(
            "decode serving supports unquantized checkpoints only "
            "(the QuantDense decode mirror is not implemented); "
            "serve with --quant none")
    if not getattr(model, "fused_qkv", True):
        raise ValueError(
            "decode serving reads the fused-QKV param layout; the "
            "unfused ablation arm's query/key/value tree is not "
            "mirrored")
    return DecodeSpec(n_layers=int(model.n_layers), h=int(model.h),
                      d_model=int(model.d_model), d_ff=int(model.d_ff),
                      vocab=int(model.vocab), maxlen=int(model.maxlen),
                      tied=bool(getattr(model, "tie_lm_head", False)),
                      dtype=model.dtype)


def causal_mask(L: int) -> jax.Array:
    """The 4-D causal mask decode serving imposes (see the module
    docstring).  Shape (1, 1, L, L): Transformer.__call__ broadcasts
    only 2-D masks, 4-D passes through to the attention untouched — so
    the SAME array drives both the prefill mirror and the cacheless
    ``model.apply`` reference the parity tests compare against."""
    return jnp.tril(jnp.ones((L, L), jnp.int32))[None, None]


# -- param-leaf math (each helper mirrors one flax module's eval path) ----

def _ln(x, leaf, dtype):
    y = torch_layernorm(x.astype(jnp.float32),
                        leaf["scale"].astype(jnp.float32),
                        leaf["bias"].astype(jnp.float32), 1e-6)
    return y.astype(dtype)


def _dense(x, leaf, dtype):
    return (x.astype(dtype) @ leaf["kernel"].astype(dtype)
            + leaf["bias"].astype(dtype))


def _qkv_proj(x, leaf, dtype):
    """nn.DenseGeneral((3, h, d_k)) — (B, L, d) -> (B, L, 3, h, d_k)."""
    y = jnp.einsum("bld,dthk->blthk", x.astype(dtype),
                   leaf["kernel"].astype(dtype))
    return y + leaf["bias"].astype(dtype)


def _ffn(x, leaf, dtype):
    hmid = _dense(x, leaf["Dense_0"], dtype)
    hmid = jax.nn.gelu(hmid, approximate=False)
    return _dense(hmid, leaf["Dense_1"], dtype)


def _embed(params, tokens, positions, spec: DecodeSpec, pe_table):
    """Embeddings + the reference's PE quirk at eval: the model feeds
    the embeddings through dropout(emb + pe) and ADDS the result back
    (transformer.py h = emb + encodings), so eval h0 = 2*emb + pe.
    token_types are all zero on the serving path (pad_batch does the
    same), so the segment term is row 0 broadcast."""
    e = params["Embeddings_0"]
    tok = jnp.take(e["token_embedding"], tokens,
                   axis=0).astype(jnp.float32)
    pos = jnp.take(e["pos_embedding"], positions,
                   axis=0).astype(jnp.float32)
    seg = e["segment_embedding"][0].astype(jnp.float32)
    emb = (tok + pos + seg) * math.sqrt(spec.d_model)
    pe = jnp.take(pe_table, positions, axis=0)
    return (2.0 * emb + pe).astype(spec.dtype)


def _head(h, params, spec: DecodeSpec):
    """LM head on (..., d_model) -> fp32 (..., vocab); tied r19 (raw
    token table transposed, fp32 accumulation) or untied r18 Dense."""
    if spec.tied:
        table = params["Embeddings_0"]["token_embedding"]
        logits = jnp.dot(h.astype(spec.dtype),
                         table.astype(spec.dtype).T,
                         preferred_element_type=jnp.float32)
    else:
        logits = _dense(h, params["lm_head"], spec.dtype)
    return logits.astype(jnp.float32)


def _sample_keys(seed: int, req_ids, positions):
    base = prng.stream(prng.root_key(seed), "decode")

    def one(rid, pos):
        k = jax.random.fold_in(base, jnp.asarray(rid, jnp.uint32))
        return jax.random.fold_in(k, jnp.asarray(pos, jnp.uint32))

    return jax.vmap(one)(req_ids, positions)


def sample_tokens(logits, sampling: SamplingCfg, req_ids,
                  positions) -> jax.Array:
    """(B, V) fp32 logits -> (B,) int32 token ids.  ``positions`` is
    the absolute position of the token being GENERATED (prefill: the
    prompt length; decode step: pos + 1), which is what makes a
    request's sample stream invariant to when it was admitted."""
    if sampling.method == "greedy":
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if sampling.method != "topk":
        raise ValueError(f"unknown sampling method {sampling.method!r} "
                         f"(greedy | topk)")
    V = logits.shape[-1]
    k = V if sampling.top_k <= 0 else min(int(sampling.top_k), V)
    keys = _sample_keys(sampling.seed, req_ids, positions)
    vals, idx = jax.lax.top_k(logits, k)

    def one(key, v, i):
        g = jax.random.categorical(
            key, v.astype(jnp.float32) / float(sampling.temperature))
        return i[g]

    return jax.vmap(one)(keys, vals, idx).astype(jnp.int32)


# -- the two program bodies ------------------------------------------------

def prefill(spec: DecodeSpec, sampling: SamplingCfg,
            params: Dict[str, Any], tokens: jax.Array,
            length: jax.Array, req_ids: jax.Array
            ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Full causal forward over a (B, L) prompt bucket.

    Returns (k, v, logits, first_token): per-layer keys/values stacked
    (n_layers, B, h, L, d_k) — columns >= length[b] are computed from
    pad tokens and carry garbage the cache's length mask never exposes
    (causality already makes real positions independent of the pad
    suffix) — plus the fp32 logits AT the last real position and the
    token sampled from them (the request's first generated token, at
    absolute position ``length``)."""
    B, L = tokens.shape
    pe = jnp.asarray(sinusoidal_table(spec.maxlen, spec.d_model))
    positions = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[None, :],
                                 (B, L))
    h = _embed(params, tokens, positions, spec, pe)
    mask = causal_mask(L)
    ks, vs = [], []
    for i in range(spec.n_layers):
        lp = params[f"layer_{i}"]
        a = _ln(h, lp["ln_attn"], spec.dtype)
        qkv = _qkv_proj(a, lp["attn"]["qkv"], spec.dtype)
        q = qkv[:, :, 0].transpose(0, 2, 1, 3)     # (B, h, L, d_k)
        kk = qkv[:, :, 1].transpose(0, 2, 1, 3)
        vv = qkv[:, :, 2].transpose(0, 2, 1, 3)
        ks.append(kk)
        vs.append(vv)
        ctx = dense_attention(q, kk, vv, mask, 0.0, True, None)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, L, spec.d_model)
        h = h + _dense(ctx, lp["attn"]["out"], spec.dtype)
        f = _ln(h, lp["ln_ffn"], spec.dtype)
        h = h + _ffn(f, lp["ffn"], spec.dtype)
    h = _ln(h, params["ln_final"], spec.dtype)
    h_last = h[jnp.arange(B), length - 1]          # (B, d_model)
    logits = _head(h_last, params, spec)
    first = sample_tokens(logits, sampling, req_ids, length)
    return jnp.stack(ks), jnp.stack(vs), logits, first


def decode_step(spec: DecodeSpec, sampling: SamplingCfg, window: int,
                params: Dict[str, Any], kcache: jax.Array,
                vcache: jax.Array, token: jax.Array, pos: jax.Array,
                active: jax.Array, req_ids: jax.Array
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step over the whole slot batch.

    kcache/vcache: (n_layers, B, h, C_max, d_k) — passed (and donated)
    WHOLE so every page-count program shares one buffer identity; only
    the first ``window`` columns (static, = pages * page_size) enter
    the attention, which is what bounds the per-step cost by the
    longest ACTIVE sequence rather than the allocation.
    token: (B,) the token AT position ``pos`` (sampled last step);
    pos:   (B,) its absolute position — the cache column written;
    active:(B,) bool; inactive (free) slots run the same math on
    dummy inputs and their outputs are dropped host-side (same
    pad-row semantic the classifier scheduler pins).

    Returns (kcache, vcache, next_token) with next_token sampled at
    absolute position pos + 1."""
    B = token.shape[0]
    pe = jnp.asarray(sinusoidal_table(spec.maxlen, spec.d_model))
    h = _embed(params, token, pos, spec, pe)[:, None, :]   # (B, 1, D)
    rows = jnp.arange(B)
    lengths = pos.astype(jnp.int32) + 1
    for i in range(spec.n_layers):
        lp = params[f"layer_{i}"]
        a = _ln(h, lp["ln_attn"], spec.dtype)
        qkv = _qkv_proj(a, lp["attn"]["qkv"], spec.dtype)
        q = qkv[:, :, 0].transpose(0, 2, 1, 3)     # (B, h, 1, d_k)
        k_new = qkv[:, 0, 1]                       # (B, h, d_k)
        v_new = qkv[:, 0, 2]
        kcache = kcache.at[i, rows, :, pos, :].set(k_new)
        vcache = vcache.at[i, rows, :, pos, :].set(v_new)
        ctx = cached_attention(q, kcache[i, :, :, :window],
                               vcache[i, :, :, :window], lengths)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, 1, spec.d_model)
        h = h + _dense(ctx, lp["attn"]["out"], spec.dtype)
        f = _ln(h, lp["ln_ffn"], spec.dtype)
        h = h + _ffn(f, lp["ffn"], spec.dtype)
    h = _ln(h[:, 0], params["ln_final"], spec.dtype)
    logits = _head(h, params, spec)
    nxt = sample_tokens(logits, sampling, req_ids, pos + 1)
    nxt = jnp.where(active, nxt, 0).astype(jnp.int32)
    return kcache, vcache, nxt
