"""Model zoo: ResNet family (CIFAR variant) and Transformer encoder.

Flax re-designs of the reference's model zoo (resnet.py, transformer.py):
same architectures and hyperparameters, NHWC/TPU-native layouts, proper
train/eval semantics (running BN statistics, mixup gated on `train`).
"""

from faster_distributed_training_tpu.models.resnet import (  # noqa: F401
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152)
from faster_distributed_training_tpu.models.transformer import (  # noqa: F401
    Transformer)

_RESNETS = {
    "resnet18": resnet18, "resnet34": resnet34, "resnet50": resnet50,
    "resnet101": resnet101, "resnet152": resnet152,
}


def get_model(name: str, num_classes: int, **kw):
    """Factory matching the reference's get_model (resnet50_test.py:460-468)."""
    if name in _RESNETS:
        return _RESNETS[name](num_classes=num_classes, **kw)
    if name == "transformer":
        return Transformer(n_class=num_classes, **kw)
    raise ValueError(f"unknown model {name!r}; "
                     f"have {sorted(_RESNETS) + ['transformer']}")
