"""Transformer encoder for text classification, TPU-native.

Re-design of the reference's transformer.py with the same architecture:
6-layer pre-LN encoder, h=8, d_model=512, d_ff=1024 (GELU), maxlen=512,
BERT-style 3-way embeddings (token+position+segment, transformer.py:150-156)
*plus* an additive sinusoidal encoding (the reference adds both,
transformer.py:61-64: ``x = embeddings + dropout(embeddings + pe)`` — a
quirk we preserve), CLS pooler (transformer.py:94-101), sentence-embedding
mixup inside forward (transformer.py:71-84), FusedMLP classifier
(transformer.py:278-289), Xavier-uniform init for every >1-dim param
(transformer.py:86-91).

Deliberate fixes over the reference (SURVEY.md §7 "bugs to fix"):
  * mixup only runs when ``train=True`` — the reference mixes at eval
    too and its eval path then mis-unpacks the tuple
    (transformer_test.py:321);
  * the attention mask fills with a genuinely large negative number —
    the reference's ``-1e-9`` (transformer.py:189) is ~0 and masks
    nothing;
  * the token-embedding fp32 island (transformer.py:154-155) is kept:
    embedding tables live and are summed in fp32, then cast to the
    compute dtype;
  * attention can route through a Pallas flash-attention kernel
    (``attention_impl='flash'``) instead of the O(L^2) dense softmax.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.ad_checkpoint import checkpoint_name

from faster_distributed_training_tpu.ops.dropout import FastDropout
from faster_distributed_training_tpu.ops.fused_mlp import (fused_mlp,
                                                           fused_mlp_pallas,
                                                           mlp_reference)
from faster_distributed_training_tpu.ops.quant import QuantDense
from faster_distributed_training_tpu.parallel.mesh import (seq_parallel_axis,
                                                           tp_size)
from faster_distributed_training_tpu.parallel.sharding import (
    mesh_data_axes, shard_activation)

Dtype = Any
NEG_INF = -1e9  # proper masking constant (reference bug: -1e-9)


def xavier_uniform(key, shape, dtype=jnp.float32):
    return nn.initializers.xavier_uniform()(key, shape, dtype)


def qkv_xavier(key, shape, dtype=jnp.float32):
    """Xavier bound for the fused (d_model, 3, h, d_k) QKV kernel computed
    per projection: the fused kernel is three (d_model, d_model) Xavier
    matrices laid side by side, so the bound is sqrt(6/(2*d_model)) — the
    same number the reference's per-matrix init produces
    (transformer.py:86-91), not the smaller bound flax's variance_scaling
    would derive from the 4-d shape."""
    d_model = shape[0]
    bound = math.sqrt(3.0 / d_model)
    return jax.random.uniform(key, shape, dtype, -bound, bound)


class TorchLayerNorm(nn.Module):
    """The reference's hand-rolled LayerNorm (transformer.py:230-242):
    (x - mean) / (std + eps) with *unbiased* std and eps added to std."""
    eps: float = 1e-6
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from faster_distributed_training_tpu.ops.layernorm import (
            torch_layernorm)

        d = x.shape[-1]
        a = self.param("scale", nn.initializers.ones, (d,), self.param_dtype)
        b = self.param("bias", nn.initializers.zeros, (d,), self.param_dtype)
        # fp32 core shared with the fused FFN kernel (ops/layernorm.py):
        # unbiased std (torch x.std default), eps added to std not var.
        # torch_layernorm is the saved-(mean, rstd) custom_vjp form — the
        # backward rebuilds x-hat from the input instead of storing the
        # centered/normalized intermediates (the r5-measured ~7.5 ms of
        # LN HBM round-trips across the 13 sites; FDT_LN_SAVED_STATS=0
        # restores default autodiff for probes).
        y = torch_layernorm(x.astype(jnp.float32),
                            a.astype(jnp.float32),
                            b.astype(jnp.float32), self.eps)
        return y.astype(self.dtype)


def sinusoidal_table(max_len: int, d_model: int) -> np.ndarray:
    """transformer.py:116-121 — static sin/cos table, built host-side once."""
    pe = np.zeros((max_len, d_model), dtype=np.float32)
    position = np.arange(max_len)[:, None]
    scale = np.exp(np.arange(0, d_model, 2) * -(math.log(10000.0) / d_model))
    pe[:, 0::2] = np.sin(position * scale)
    pe[:, 1::2] = np.cos(position * scale)
    return pe


class Embeddings(nn.Module):
    """token + learned-position + segment embeddings, scaled by sqrt(d_model)
    (transformer.py:132-156). Tables and the sum stay fp32 (the reference's
    autocast-disabled island), cast to compute dtype by the caller.
    Returns (embeddings, token_table) — the raw token table feeds the
    tied LM head (Transformer.tie_lm_head: logits = h @ E^T) without
    moving the param out of its checkpointed location."""
    d_model: int
    vocab: int
    maxlen: int
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, token_types: jax.Array
                 ) -> Tuple[jax.Array, jax.Array]:
        tok = self.param("token_embedding", xavier_uniform,
                         (self.vocab, self.d_model), self.param_dtype)
        pos = self.param("pos_embedding", xavier_uniform,
                         (self.maxlen, self.d_model), self.param_dtype)
        seg = self.param("segment_embedding", xavier_uniform,
                         (3, self.d_model), self.param_dtype)
        L = x.shape[1]
        tokens = jnp.take(tok, x, axis=0).astype(jnp.float32)
        positions = pos[None, :L, :].astype(jnp.float32)
        segments = jnp.take(seg, token_types[:, :L], axis=0).astype(jnp.float32)
        return (tokens + positions + segments) * math.sqrt(self.d_model), tok


def dense_attention(q, k, v, mask, dropout_rate, deterministic, dropout_rng):
    """ScaledDotProduct (transformer.py:180-193) with a fixed mask constant."""
    d_k = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d_k)
    if mask is not None:
        scores = jnp.where(mask == 0, jnp.asarray(NEG_INF, scores.dtype), scores)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_rate > 0.0 and not deterministic:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


class MultiheadAttention(nn.Module):
    """transformer.py:196-227 — QKV projection + output proj.

    The reference runs Q, K, V as three separate full-width nn.Linear
    calls; here they are ONE fused (d_model → 3·d_model) matmul
    (`qkv` DenseGeneral): one MXU dispatch and one HBM read of the
    activations instead of three, with identical math and parameter
    count.  The kernel is laid out (d_model, 3, h, d_k) so tensor
    parallelism can shard the head axis (parallel/sharding._TP_RULES).

    attention_impl selects the context computation:
      dense — O(L²) ScaledDotProduct with prob dropout (the reference);
      flash — Pallas TPU kernel / blockwise fallback (ops/flash_attention);
      ring  — sequence-parallel ring attention over `sp_axis` of `mesh`
              (ops/ring_attention);
      ulysses — sequence-parallel all-to-all head/sequence swap over
              `sp_axis` (ops/ulysses_attention; needs h % sp == 0).
    EVERY impl applies attention-prob dropout in training
    (transformer.py:190-192): flash/ring/ulysses use the stateless
    index-hash dropout (ops.attention.dropout_keep) computed inside the
    kernel/scan, so the probability tensor never touches HBM; dense
    follows `dropout_impl` — hash (the default engine,
    dense_attention_reference's in-place hash keep on the materialized
    probs) or the reference's jax.random.bernoulli threefry mask when
    dropout_impl != "hash" (the bag-of-tricks OFF arm sets
    dropout_impl="xla" precisely to keep that reference-naive cost in
    the ablation baseline).
    """
    h: int
    d_model: int
    dropout: float = 0.1
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    attention_impl: str = "dense"     # dense | flash | ring | ulysses
    mesh: Optional[Any] = None        # required for ring
    sp_axis: str = "sp"
    fused_qkv: bool = True            # ONE (d_model -> 3·d_model) matmul;
                                      # False = the reference's three
                                      # separate Linears (transformer.py:
                                      # 196-227) — the bag-of-tricks
                                      # ablation's unfused arm (different
                                      # param layout, ablation-only)
    dropout_impl: str = "hash"        # prob-dropout engine for dense
    flash_save_stats: bool = True     # False inside rematted regions:
                                      # out/lse residuals would force the
                                      # flash forward to re-run in the
                                      # remat replay (flash_attention
                                      # docstring)
    quant: Optional[Any] = None       # train.amp.QuantPolicy: int8/fp8
                                      # forward GEMMs for qkv + out with
                                      # delayed per-tensor scaling
                                      # (ops/quant.py); None = bf16/fp32
    pp_ctx: Optional[Any] = None      # parallel.pipeline.PipelineTickCtx
                                      # on a pp>1 mesh (r23): per-site
                                      # stable dropout seeds + global
                                      # (b,h) stream offsets so the
                                      # microbatched attention dropout
                                      # equals pp=1's mask slice, and
                                      # the QuantDense amax cadence.
                                      # None (pp=1) leaves every trace
                                      # byte-identical

    @nn.compact
    def __call__(self, x: jax.Array, mask: Optional[jax.Array],
                 train: bool) -> jax.Array:
        B, L, _ = x.shape
        d_k = self.d_model // self.h
        # quantized projections share nn.Dense's exact param tree
        # ("kernel"/"bias" under the same module names), so checkpoints
        # interchange between --quant modes; only the GEMM math and the
        # batch_stats-resident amax state differ (ops/quant.QuantDense)
        quant_kw = (dict(fmt=self.quant.fmt,
                         amax_history_len=self.quant.amax_history_len,
                         margin=self.quant.margin,
                         use_pallas=self.quant.use_pallas,
                         frozen_scales=getattr(self.quant,
                                               "frozen_scales", False),
                         grad_fmt=getattr(self.quant, "grad_fmt", None),
                         mesh=self.mesh,
                         amax_cadence=self.pp_ctx,
                         dtype=self.dtype, param_dtype=self.param_dtype)
                    if self.quant is not None else None)
        # projection-boundary annotations for a (data, model) mesh
        # (SNIPPETS [3]): heads over tp through the dense attention
        # math, the out-proj input sharded on its contiguous-head
        # d_model grouping so the tp-sharded `out` kernel contracts
        # locally and XLA inserts exactly one psum.  flash on a
        # serviceable tp mesh (r19, heads divide tp) keeps the same
        # head-over-tp layout — the annotations line up with the
        # shard_map boundary of kernel_shard.flash_attention_sharded so
        # no resharding happens at entry/exit; ring/ulysses re-shard
        # inside their own shard_map and stay un-annotated.
        from faster_distributed_training_tpu.parallel import kernel_shard
        dat = mesh_data_axes(self.mesh)
        # the SAME predicate the flash dispatch below uses (incl. the
        # FDT_KERNEL_SHARD kill switch): annotating head-over-tp while
        # dispatching the unsharded kernel would make XLA all-gather
        # q/k/v around the custom call — the exact failure r19 closes
        head_tp = (tp_size(self.mesh) > 1
                   and (self.attention_impl == "dense"
                        or (self.attention_impl == "flash"
                            and kernel_shard.flash_serviceable(
                                self.mesh, self.h))))
        if self.fused_qkv:
            if quant_kw is not None:
                # tp_dim names the Megatron role of each site's kernel
                # for the r19 shard_map quant layer (parallel/
                # kernel_shard.py): qkv shards the head axis (column-
                # parallel), q/k/v their output features, `out` its
                # input rows (row-parallel, one psum)
                qkv = QuantDense((3, self.h, d_k), kernel_init=qkv_xavier,
                                 name="qkv", tp_dim=2, **quant_kw)(x)
            else:
                qkv = nn.DenseGeneral((3, self.h, d_k), axis=-1,
                                      kernel_init=qkv_xavier,
                                      dtype=self.dtype,
                                      param_dtype=self.param_dtype,
                                      name="qkv")(x)  # (B, L, 3, h, d_k)
            q = qkv[:, :, 0].transpose(0, 2, 1, 3)  # (B, h, L, d_k)
            k = qkv[:, :, 1].transpose(0, 2, 1, 3)
            v = qkv[:, :, 2].transpose(0, 2, 1, 3)
        else:
            def proj(name):
                if quant_kw is not None:
                    y = QuantDense(self.d_model, kernel_init=xavier_uniform,
                                   name=name, tp_dim=1, **quant_kw)(x)
                else:
                    y = nn.Dense(self.d_model, kernel_init=xavier_uniform,
                                 dtype=self.dtype,
                                 param_dtype=self.param_dtype,
                                 name=name)(x)
                return y.reshape(B, L, self.h, d_k).transpose(0, 2, 1, 3)
            q, k, v = proj("query"), proj("key"), proj("value")
        if head_tp:
            q = shard_activation(q, self.mesh, (dat, "tp", None, None))
            k = shard_activation(k, self.mesh, (dat, "tp", None, None))
            v = shard_activation(v, self.mesh, (dat, "tp", None, None))
        # training-path prob dropout for the never-materialized impls:
        # one fresh u32 hash seed per step from the dropout rng stream
        # dropout_impl "none" disables the attention-prob regularizer on
        # EVERY impl (it is the all-dropout-off floor switch, not just
        # the FastDropout sites' engine)
        drop_rate = (self.dropout
                     if (self.dropout > 0 and train
                         and self.dropout_impl != "none") else 0.0)
        use_hash = (self.attention_impl != "dense"
                    or self.dropout_impl == "hash")
        if drop_rate > 0 and use_hash:
            draw = lambda: jax.random.bits(     # noqa: E731
                self.make_rng("dropout"), dtype=jnp.uint32)
            if self.pp_ctx is not None:
                # r23 pipeline parity: ONE seed per site per step (the
                # first draw — make_rng fold count 0, pp=1's key), every
                # tick; the microbatch's position enters via the global
                # (b, h) stream offset below instead
                site = "/".join(str(p) for p in self.scope.path)
                drop_seed = self.pp_ctx.site_seed(site + ":attn", draw)
            else:
                drop_seed = draw()
        else:
            drop_seed = None
        if self.attention_impl == "flash":
            from faster_distributed_training_tpu.ops.flash_attention import (
                flash_attention)
            from faster_distributed_training_tpu.parallel import kernel_shard
            # flash_save_stats=True defers to the FDT_FLASH_SAVE_STATS
            # env default (None) so the A/B kill switch still works;
            # False (rematted attention) is a hard override
            save = None if self.flash_save_stats else False
            if kernel_shard.flash_serviceable(self.mesh, self.h):
                # r19: heads divide tp — the flash kernel runs PER SHARD
                # on each device's local heads under shard_map (parallel/
                # kernel_shard.py) instead of falling back to the slower
                # sequence-parallel strategies; dropout masks address
                # GLOBAL (b, h) stream indices, so they are placement-
                # invariant vs the unsharded kernel
                ctx = kernel_shard.flash_attention_sharded(
                    q, k, v, mask, self.mesh,
                    dropout_rate=drop_rate, dropout_seed=drop_seed,
                    save_stats=save)
            else:
                ctx = flash_attention(q, k, v, mask=mask,
                                      dropout_rate=drop_rate,
                                      dropout_seed=drop_seed,
                                      save_stats=save)
        elif self.attention_impl in ("ring", "ulysses"):
            if self.mesh is None:
                raise ValueError(
                    f"attention_impl={self.attention_impl!r} needs a mesh "
                    f"with an {self.sp_axis!r} axis")
            if self.attention_impl == "ring":
                from faster_distributed_training_tpu.ops.ring_attention import (
                    ring_self_attention as sp_attention)
            else:
                from faster_distributed_training_tpu.ops.ulysses_attention import (
                    ulysses_self_attention as sp_attention)
            ctx = sp_attention(q, k, v, mask, self.mesh,
                               sp_axis=self.sp_axis,
                               dropout_rate=drop_rate,
                               dropout_seed=drop_seed)
        elif use_hash and drop_rate > 0:
            # dense with the hash engine: same softmax-then-hash-keep
            # semantics as every kernel path, no threefry mask tensor
            from faster_distributed_training_tpu.ops.attention import (
                bh_index, dense_attention_reference)
            bh = None
            if self.pp_ctx is not None:
                # address the GLOBAL (b, h) stream: this microbatch's
                # batch rows start at row0, so its (b, h) indices are
                # pp=1's shifted by row0*h — the mask equals pp=1's
                # slice for these rows (r23)
                bh = bh_index(B, self.h) + jnp.int32(
                    self.pp_ctx.row0 * self.h)
            ctx = dense_attention_reference(q, k, v, mask, drop_rate,
                                            dropout_seed=drop_seed,
                                            dropout_bh=bh)
        else:
            # dropout inactive (eval / rate 0): ONE dense path for every
            # engine, so a training-only flag cannot shift inference
            # numerics; with dropout active this is the reference-naive
            # arm (dropout_impl == "xla", e.g. --tricks off):
            # materialized threefry bernoulli mask on the probs
            rng = (self.make_rng("dropout") if drop_rate > 0 else None)
            ctx = dense_attention(q, k, v, mask, drop_rate,
                                  deterministic=not train, dropout_rng=rng)
        if head_tp:
            ctx = shard_activation(ctx, self.mesh, (dat, "tp", None, None))
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, L, self.d_model)
        if head_tp:
            # d_model here is h contiguous head groups: sharding it on
            # tp keeps the tp-row-sharded `out` kernel's contraction
            # local (one psum after, no activation gather before)
            ctx = shard_activation(ctx, self.mesh, (dat, None, "tp"))
        # Name the attention context so the "attn_out" remat policy can
        # SAVE it: backward under that policy replays the cheap layer
        # matmuls (qkv/out-proj/FFN) but never re-runs the attention
        # kernel itself (whose Pallas backward already recomputes its
        # scores in-kernel — re-running the forward too would pay
        # attention twice, VERDICT r3 #3).
        ctx = checkpoint_name(ctx, "attn_out")
        if quant_kw is not None:
            # tp_dim=0: the out-proj is the attention block's Megatron
            # ROW-parallel site — its kernel's input dim is tp-sharded
            # (the contiguous-head d_model grouping annotated above), so
            # the per-shard GEMM contracts locally and psums once
            return QuantDense(self.d_model, kernel_init=xavier_uniform,
                              name="out", tp_dim=0, **quant_kw)(ctx)
        return nn.Dense(self.d_model, kernel_init=xavier_uniform,
                        dtype=self.dtype, param_dtype=self.param_dtype,
                        name="out")(ctx)


class PositionalWiseFFN(nn.Module):
    """transformer.py:159-177 — Linear → GELU → dropout → Linear.

    On a (data, model) mesh the [B, L, d_ff] hidden is annotated sharded
    on tp right at the first-matmul boundary, matching the tp-sharded
    kernels (_TP_RULES: dense_0 column- / dense_1 row-sharded) so XLA
    never gathers the full hidden activation — GELU + dropout run on
    1/tp of it per device and the single psum lands after dense_1."""
    d_model: int
    d_ff: int
    dropout: float = 0.1
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    dropout_impl: str = "hash"
    mesh: Optional[Any] = None
    quant: Optional[Any] = None   # QuantPolicy: int8/fp8 FFN GEMMs
    pp_ctx: Optional[Any] = None  # PipelineTickCtx on pp>1 (r23): stable
                                  # per-site dropout seed + microbatch
                                  # stream offset, QuantDense amax
                                  # cadence; None = unchanged trace

    @nn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        kw = dict(kernel_init=xavier_uniform, dtype=self.dtype,
                  param_dtype=self.param_dtype)
        if self.quant is not None:
            # quantized twins of the two Dense layers, explicitly named
            # Dense_0/Dense_1 so the param tree (and therefore
            # checkpoints, TP rules and _FFNParamMirror) is byte-
            # identical to the flax composition's auto-naming
            qkw = dict(fmt=self.quant.fmt,
                       amax_history_len=self.quant.amax_history_len,
                       margin=self.quant.margin,
                       use_pallas=self.quant.use_pallas,
                       frozen_scales=getattr(self.quant,
                                             "frozen_scales", False),
                       grad_fmt=getattr(self.quant, "grad_fmt", None),
                       mesh=self.mesh, amax_cadence=self.pp_ctx, **kw)
            # Megatron roles for the r19 shard_map quant layer: Dense_0
            # column-parallel (d_ff out), Dense_1 row-parallel (d_ff in,
            # one psum) — the _TP_RULES layout
            dense_0 = QuantDense(self.d_ff, name="Dense_0", tp_dim=1, **qkw)
            dense_1 = QuantDense(self.d_model, name="Dense_1", tp_dim=0,
                                 **qkw)
        else:
            dense_0 = nn.Dense(self.d_ff, **kw)
            dense_1 = nn.Dense(self.d_model, **kw)
        h = dense_0(x)
        if tp_size(self.mesh) > 1:
            h = shard_activation(h, self.mesh,
                                 (mesh_data_axes(self.mesh), None, "tp"))
        h = nn.gelu(h, approximate=False)
        h = FastDropout(self.dropout, self.dropout_impl,
                        pp_ctx=self.pp_ctx)(h, deterministic=not train)
        return dense_1(h)


# Remat policies for --remat (VERDICT r3 #3).  "layer" checkpoints the
# whole EncoderLayer — maximum memory savings, but it re-runs flash
# attention's forward in the backward replay even though the flash
# BACKWARD already recomputes its own scores in-kernel
# (ops/flash_attention.py): attention ends up computed twice per
# backward.  "ffn" checkpoints ONLY the FFN sublayer (the two big
# matmul activations, [B,L,d_ff] gelu in/out — the bulk of the per-layer
# residual footprint) and leaves attention alone.  "attn_out"
# checkpoints the whole layer under save_only_these_names("attn_out"):
# the attention context is SAVED (the kernel never re-runs) while every
# other residual — qkv, FFN hidden, LN stats — is replayed from cheap
# matmuls; the best memory/throughput trade measured.  "dots" applies
# XLA's dots_with_no_batch_dims_saveable policy to the whole layer:
# matmul outputs are saved, elementwise chains recomputed.
REMAT_POLICIES = ("layer", "ffn", "attn_out", "dots")


class _QuantDenseMirror(nn.Module):
    """QuantDense's exact param + batch_stats trees (kernel/bias under
    the module name, amax_history_x/amax_history_w in batch_stats)
    WITHOUT its compute — the quantized fused-FFN path reads the leaves
    and runs the math in the generalized kernel, so checkpoints (params
    AND scale state) interchange with the Flax QuantDense composition."""
    features: int
    amax_history_len: int = 16
    kernel_init: object = xavier_uniform
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, probe: jax.Array):
        from faster_distributed_training_tpu.ops.quant import (
            fresh_amax_history)

        kernel = self.param("kernel", self.kernel_init,
                            (probe.shape[-1], self.features),
                            self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), self.param_dtype)
        hx = self.variable("batch_stats", "amax_history_x",
                           fresh_amax_history, self.amax_history_len)
        hw = self.variable("batch_stats", "amax_history_w",
                           fresh_amax_history, self.amax_history_len)
        return kernel, bias, hx, hw


class _FFNParamMirror(nn.Module):
    """Declares PositionalWiseFFN's exact param tree (Dense_0 -> d_ff,
    Dense_1 -> d_model, same auto-naming order) WITHOUT its compute —
    the fused-FFN kernel path (`ffn_impl="pallas"`) reads the leaves and
    runs the math in `ops.fused_ffn`, keeping checkpoints interchangeable
    between the Flax and kernel implementations.  The probe call is
    (1, d_model) — parameter creation only, negligible compute.

    With ``quant`` set (a QuantPolicy) the mirror declares QuantDense's
    tree instead — same params plus the four amax histories in
    batch_stats — and returns them after the weights, so the quantized
    fused kernel (r19) rolls the exact state the Flax quantized
    composition would."""
    d_model: int
    d_ff: int
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    quant: Optional[Any] = None

    @nn.compact
    def __call__(self, probe: jax.Array):
        if self.quant is not None:
            qm = dict(amax_history_len=self.quant.amax_history_len,
                      kernel_init=xavier_uniform,
                      param_dtype=self.param_dtype)
            w1, b1, hx1, hw1 = _QuantDenseMirror(
                self.d_ff, name="Dense_0", **qm)(probe)
            w2, b2, hx2, hw2 = _QuantDenseMirror(
                self.d_model, name="Dense_1", **qm)(
                    jnp.zeros(probe.shape[:-1] + (self.d_ff,),
                              probe.dtype))
            return w1, b1, w2, b2, (hx1, hw1, hx2, hw2)
        kw = dict(kernel_init=xavier_uniform, dtype=self.dtype,
                  param_dtype=self.param_dtype)
        d0 = nn.Dense(self.d_ff, **kw)
        d1 = nn.Dense(self.d_model, **kw)
        d1(d0(probe))
        return (d0.variables["params"]["kernel"],
                d0.variables["params"]["bias"],
                d1.variables["params"]["kernel"],
                d1.variables["params"]["bias"], None)


class EncoderLayer(nn.Module):
    """One pre-LN attention sublayer + one pre-LN FFN sublayer
    (transformer.py:245-275).  Factored into its own module so
    ``Transformer.remat`` can wrap it in ``nn.remat`` — backward then
    recomputes the layer's activations instead of keeping them in HBM,
    the capacity lever long sequences need."""
    h: int
    d_model: int
    d_ff: int
    dropout_connection_attention: float = 0.1
    dropout_connection_ffn: float = 0.1
    dropout_attention: float = 0.1
    dropout_ffn: float = 0.1
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    attention_impl: str = "dense"
    mesh: Optional[Any] = None
    sp_axis: str = "sp"
    dropout_impl: str = "hash"
    remat_ffn: bool = False   # checkpoint the FFN sublayer only ("ffn")
    fused_qkv: bool = True
    ffn_impl: str = "flax"    # flax | pallas (ops/fused_ffn.py mega-kernel)
    flash_save_stats: bool = True   # False under attention-wrapping remat
    quant: Optional[Any] = None     # QuantPolicy threaded to attention +
                                    # FFN projections; with ffn_impl
                                    # "pallas" the generalized fused
                                    # kernel runs its two GEMMs on the
                                    # quantized operands in-kernel (r19
                                    # — the bf16-only caveat is gone)
    pp_ctx: Optional[Any] = None    # parallel.pipeline.PipelineTickCtx
                                    # on a pp>1 mesh (r23), threaded to
                                    # every dropout site (stable seeds +
                                    # microbatch stream offsets) and
                                    # every QuantDense (one amax roll
                                    # per step).  None on pp=1: all
                                    # traces byte-identical to r22

    @nn.compact
    def __call__(self, h: jax.Array, mask: Optional[jax.Array],
                 train: bool) -> jax.Array:
        ln = lambda name: TorchLayerNorm(   # noqa: E731
            dtype=self.dtype, param_dtype=self.param_dtype, name=name)
        # sequence-parallel LN/dropout regions (Korthikanti et al.;
        # ops/sequence_parallel.py owns the kernel-side analog): between
        # the parallel blocks the residual stream is annotated sharded
        # on the model axis ALONG THE SEQUENCE — LayerNorm (per-token
        # over D) and the connection dropouts (position-hashed) run on
        # L/ax tokens per device and the per-device activation residing
        # between TP regions shrinks by 1/ax.  XLA inserts the gather
        # exactly at the qkv/FFN entry (or hands the already-sequence-
        # sharded tensor straight to ring/ulysses' shard_map).  Identity
        # on 1D meshes (shard_activation filters absent axes).
        seq_ax, _ = seq_parallel_axis(self.mesh)
        dat = mesh_data_axes(self.mesh)
        seq_shard = (
            (lambda x: shard_activation(x, self.mesh, (dat, seq_ax, None)))
            if seq_ax is not None else (lambda x: x))
        h = seq_shard(h)
        a = ln("ln_attn")(h)
        a = MultiheadAttention(self.h, self.d_model, self.dropout_attention,
                               self.dtype, self.param_dtype,
                               self.attention_impl, self.mesh,
                               self.sp_axis, self.fused_qkv,
                               dropout_impl=self.dropout_impl,
                               flash_save_stats=self.flash_save_stats,
                               quant=self.quant, pp_ctx=self.pp_ctx,
                               name="attn")(a, mask, train)
        a = FastDropout(self.dropout_connection_attention,
                        self.dropout_impl,
                        pp_ctx=self.pp_ctx)(seq_shard(a),
                                            deterministic=not train)
        h = seq_shard(h + a)
        # ADVICE r5 (medium): the kernel's in-VMEM dropout IS the hash
        # engine — it must follow dropout_impl like every other site.
        # "none" (the all-dropout-off floor switch) runs the kernel with
        # rates 0; "xla" (the --tricks off reference-naive arm) needs the
        # threefry nn.Dropout masks, which only the Flax composition can
        # apply, so active-dropout + non-hash engines fall back to it.
        ffn_dropout_active = (train and self.dropout_impl != "none"
                              and (self.dropout_ffn > 0
                                   or self.dropout_connection_ffn > 0))
        if (self.ffn_impl == "pallas"
                and (not ffn_dropout_active
                     or self.dropout_impl == "hash")):
            # fused sublayer (ops/fused_ffn.py): LN + FFN + both dropout
            # sites + residual in one Pallas kernel, recompute backward —
            # zero FFN-shaped residuals (a capacity lever; see PARITY for
            # the measured time trade).  Param trees mirror the Flax path
            # exactly.  On sharded meshes the kernel runs PER SHARD via
            # fused_ffn_sublayer_sharded (shard_map over the data axes;
            # each shard addresses the GLOBAL dropout index space, so
            # masks are placement-invariant); tp meshes run the Megatron
            # column-then-row decomposition through the r19 shard_map
            # kernel layer (parallel/kernel_shard.py — w1/w2 consumed as
            # their tp shards in place, ONE psum per sublayer) when
            # d_ff/seq divide, with the Flax composition as the
            # registered warned fallback (build_model).  --quant rides
            # the same kernels (the generalized core quantizes the GEMMs
            # in-kernel at the delayed scales and emits the step amaxes).
            from faster_distributed_training_tpu.ops.fused_ffn import (
                ffn_core_generalized, fused_ffn_sublayer,
                fused_ffn_sublayer_sharded)
            from faster_distributed_training_tpu.parallel import kernel_shard
            lnf = ln("ln_ffn")
            lnf(h[..., :1, :])      # param creation only (probe row)
            ln_scale = lnf.variables["params"]["scale"]
            ln_bias = lnf.variables["params"]["bias"]
            w1, b1, w2, b2, qstate = _FFNParamMirror(
                self.d_model, self.d_ff, self.dtype, self.param_dtype,
                quant=self.quant, name="ffn")(h[..., :1, :])
            if ffn_dropout_active:
                draw = lambda: jax.random.bits(     # noqa: E731
                    self.make_rng("dropout"), (2,), dtype=jnp.uint32)
                if self.pp_ctx is not None:
                    # stable per-step seeds (first draw) — NOTE this is
                    # determinism only, not pp=1 parity: the fused
                    # kernel's masks address per-invocation row indices,
                    # so build_pipeline_spec keeps the warning for
                    # pallas FFN + dropout under pp
                    site = "/".join(str(p) for p in self.scope.path)
                    seeds = self.pp_ctx.site_seed(site + ":ffn", draw)
                else:
                    seeds = draw()
                hid_seed, out_seed = seeds[0], seeds[1]
                r_h, r_c = self.dropout_ffn, self.dropout_connection_ffn
            else:
                hid_seed = out_seed = jnp.uint32(0)
                r_h = r_c = 0.0
            fmt = None
            if self.quant is not None:
                from faster_distributed_training_tpu.ops.quant import (
                    quant_enabled, scale_from_history, tensor_amax,
                    update_amax_history)
                hx1, hw1, hx2, hw2 = qstate
                # FDT_QUANT=0 keeps the state tree allocated but runs
                # the plain bf16/fp32 kernel (the QuantDense contract)
                fmt = self.quant.fmt if quant_enabled() else None
            w1c, b1c = w1.astype(self.dtype), b1.astype(self.dtype)
            w2c, b2c = w2.astype(self.dtype), b2.astype(self.dtype)
            kernel_args = (h, ln_scale, ln_bias, w1c, b1c, w2c, b2c,
                           hid_seed, out_seed)
            gfmt = (getattr(self.quant, "grad_fmt", None)
                    if fmt is not None else None)
            if fmt is not None:
                mg = self.quant.margin
                if self.pp_ctx is not None:
                    # pipeline amax cadence (r23): every tick quantizes
                    # at the PRE-step scales (what pp=1 uses all step)
                    qsite = "/".join(str(p) for p in self.scope.path)
                    hists = (
                        self.pp_ctx.amax_pre(qsite + ":hx1", hx1.value),
                        self.pp_ctx.amax_pre(qsite + ":hw1", hw1.value),
                        self.pp_ctx.amax_pre(qsite + ":hx2", hx2.value),
                        self.pp_ctx.amax_pre(qsite + ":hw2", hw2.value))
                else:
                    hists = (hx1.value, hw1.value, hx2.value, hw2.value)
                scales = tuple(scale_from_history(hh, fmt, mg)
                               for hh in hists)
            else:
                scales = None
            if tp_size(self.mesh) > 1:
                res = kernel_shard.fused_ffn_sublayer_tp(
                    *kernel_args, mesh=self.mesh,
                    rate_hidden=r_h, rate_conn=r_c,
                    quant_fmt=fmt, quant_scales=scales, grad_fmt=gfmt)
            elif self.mesh is not None and any(
                    self.mesh.shape[ax] > 1 for ax in self.mesh.axis_names):
                # SPMD: per-shard kernels over the data axes, masks
                # addressed in the GLOBAL index space (ops/fused_ffn.py)
                res = fused_ffn_sublayer_sharded(
                    *kernel_args, mesh=self.mesh,
                    rate_hidden=r_h, rate_conn=r_c,
                    quant_fmt=fmt, quant_scales=scales, grad_fmt=gfmt)
            elif fmt is not None:
                res = ffn_core_generalized(
                    h, ln_scale, ln_bias, w1c, b1c, w2c, b2c,
                    hid_seed, out_seed, 0, 0, 0, r_h, r_c, 1e-6, 1, 1,
                    dff_glob=self.d_ff, quant_fmt=fmt,
                    quant_scales=scales, grad_fmt=gfmt)
            else:
                return fused_ffn_sublayer(*kernel_args, r_h, r_c)
            if fmt is None:
                return res
            out, amax2 = res
            # roll the delayed-scaling histories exactly as QuantDense
            # would: x-side amaxes from the kernel (LN output / post-
            # dropout activation), w-side from the cast weights
            if (not getattr(self.quant, "frozen_scales", False)
                    and self.is_mutable_collection("batch_stats")):
                if self.pp_ctx is not None:
                    # one roll per optimizer step: first real push
                    # rolls, later ticks max-reduce into slot 0,
                    # bubble ticks skipped (PipelineTickCtx.amax_push)
                    cad, qs = self.pp_ctx, qsite
                    hx1.value = cad.amax_push(qs + ":hx1", hx1.value,
                                              amax2[0])
                    hx2.value = cad.amax_push(qs + ":hx2", hx2.value,
                                              amax2[1])
                    hw1.value = cad.amax_push(qs + ":hw1", hw1.value,
                                              tensor_amax(w1c))
                    hw2.value = cad.amax_push(qs + ":hw2", hw2.value,
                                              tensor_amax(w2c))
                else:
                    hx1.value = update_amax_history(hx1.value, amax2[0])
                    hx2.value = update_amax_history(hx2.value, amax2[1])
                    hw1.value = update_amax_history(hw1.value,
                                                    tensor_amax(w1c))
                    hw2.value = update_amax_history(hw2.value,
                                                    tensor_amax(w2c))
            return out
        f = ln("ln_ffn")(h)
        ffn_cls = (nn.remat(PositionalWiseFFN, static_argnums=(2,))
                   if self.remat_ffn else PositionalWiseFFN)
        f = ffn_cls(self.d_model, self.d_ff, self.dropout_ffn,
                    self.dtype, self.param_dtype,
                    self.dropout_impl, self.mesh, self.quant,
                    self.pp_ctx, name="ffn")(f, train)
        f = FastDropout(self.dropout_connection_ffn,
                        self.dropout_impl,
                        pp_ctx=self.pp_ctx)(seq_shard(f),
                                            deterministic=not train)
        return seq_shard(h + f)


class Transformer(nn.Module):
    """transformer.py:12-91 — returns (logits, perm_index, lam) in train mode
    (mixup on the pooled sentence embedding), plain logits in eval mode."""
    n_class: int
    vocab: int = 30522            # bert-base-uncased vocab size
    n_layers: int = 6
    h: int = 8
    d_model: int = 512
    d_ff: int = 1024
    d_hidden: int = 1024
    maxlen: int = 512
    dropout_encodings: float = 0.1
    dropout_connection_attention: float = 0.1
    dropout_connection_ffn: float = 0.1
    dropout_attention: float = 0.1
    dropout_ffn: float = 0.1
    alpha: float = 0.99           # in-forward mixup Beta parameter
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    attention_impl: str = "dense"  # dense | flash | ring | ulysses
    mlp_impl: str = "fused"        # fused (custom_vjp) | pallas
    mesh: Optional[Any] = None     # required for ring/ulysses
    sp_axis: str = "sp"
    remat: bool = False
    remat_policy: str = "attn_out"  # layer | ffn | attn_out | dots
                                   # (see REMAT_POLICIES)
    dropout_impl: str = "hash"     # hash | xla | none (ops/dropout.py)
    ffn_impl: str = "flax"         # flax | pallas (fused FFN sublayer)
    fused_qkv: bool = True         # False = reference's 3 separate QKV
                                   # Linears (bag-of-tricks ablation arm)
    quant: Optional[Any] = None    # train.amp.QuantPolicy: int8/fp8
                                   # forward GEMMs for the attention
                                   # projections + FFN with delayed
                                   # per-tensor scaling; scale state
                                   # rides the batch_stats collection
    lm_head: bool = False          # --task lm (r18): per-position vocab
                                   # logits for next-token prediction
                                   # instead of the CLS pooler/classifier
                                   # — the streamed LM workload's head.
                                   # No mixup: sentence-embedding mixup
                                   # is a classification regularizer with
                                   # no analog on a dense token objective
    tie_lm_head: bool = False      # r19 (ROADMAP r18 follow-on (c)):
                                   # logits = h @ token_embedding^T — no
                                   # separate lm_head projection
                                   # (~vocab*d_model fewer params), and
                                   # the token_embedding vocab-sharding
                                   # TP rule serves the head for free.
                                   # False = the r18 untied nn.Dense
                                   # head (checkpoint-compatible via the
                                   # train/checkpoint.py compat shim)
    causal: bool = False           # --lm_causal (r22): apply the causal
                                   # mask at TRAINING time so the
                                   # trained conditional matches the
                                   # mask decode imposes at serving
                                   # (closes the r21 train/decode
                                   # mismatch).  Combined with any
                                   # padding mask below; routed to the
                                   # dense impl by resolve_attention —
                                   # flash only accepts key-padding
                                   # masks (ops/flash_attention.py) and
                                   # ring/ulysses shard L.

    @nn.compact
    def __call__(self, x: jax.Array, token_types: Optional[jax.Array] = None,
                 mask: Optional[jax.Array] = None, train: bool = True,
                 pp_spec: Optional[Any] = None):
        B, L = x.shape
        if token_types is None:
            token_types = jnp.zeros_like(x)
        embeddings, tok_table = Embeddings(self.d_model, self.vocab,
                                           self.maxlen,
                                           self.param_dtype)(x, token_types)
        # x = embeddings + dropout(embeddings + pe): the reference feeds the
        # PositionalEncoding module the embeddings and then ADDS its output to
        # the embeddings again (transformer.py:61-64) — preserved verbatim.
        pe = jnp.asarray(sinusoidal_table(self.maxlen, self.d_model))
        encodings = FastDropout(self.dropout_encodings, self.dropout_impl)(
            embeddings + pe[None, :L, :], deterministic=not train)
        h = (embeddings + encodings).astype(self.dtype)

        if mask is not None and mask.ndim == 2:   # (B, L) padding mask
            mask = mask[:, None, None, :]          # broadcast over heads+query
        if self.causal:
            # causal (next-token) mask, combined with any padding mask:
            # (1,1,L,L) alone broadcasts over batch+heads; against a
            # (B,1,1,L) padding mask the product is the (B,1,L,L) joint
            # mask every query row honors
            cm = jnp.tril(jnp.ones((L, L), jnp.int32))[None, None, :, :]
            mask = cm if mask is None else mask * cm

        # Each encoder layer is one EncoderLayer module; with remat=True the
        # selected policy (remat_policy) decides WHAT backward recomputes:
        #   layer — nn.remat the whole layer (max memory savings; pays
        #           flash attention's forward twice in backward, VERDICT
        #           r3 #3);
        #   ffn   — checkpoint only the FFN sublayer (the [B,L,d_ff]
        #           activations, the bulk of the residual footprint,
        #           while attention — whose Pallas backward already
        #           recomputes in-kernel — is left alone;
        #   dots  — whole-layer remat under XLA's
        #           dots_with_no_batch_dims_saveable (matmul outputs
        #           saved, elementwise chains recomputed).
        layer_cls = EncoderLayer
        remat_ffn = False
        if self.remat:
            if self.remat_policy == "ffn":
                remat_ffn = True
            elif self.remat_policy == "attn_out":
                layer_cls = nn.remat(
                    EncoderLayer, static_argnums=(3,),
                    policy=jax.checkpoint_policies.save_only_these_names(
                        "attn_out"))
            elif self.remat_policy == "dots":
                layer_cls = nn.remat(
                    EncoderLayer, static_argnums=(3,),
                    policy=jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable)
            else:   # "layer" (round-3 behavior)
                layer_cls = nn.remat(EncoderLayer, static_argnums=(3,))
        # remat policies that wrap ATTENTION ("layer"/"attn_out"/"dots")
        # recompute custom_vjp residuals in the backward replay: flash
        # must keep its residuals input-only there, or the saved
        # (out, lse) would force the forward kernel to re-run in the
        # replay (flash_attention docstring).  "ffn" checkpoints only
        # the FFN sublayer, so attention keeps the saved-stats backward.
        flash_save_stats = not (self.remat and self.remat_policy != "ffn")
        if pp_spec is None:
            for i in range(self.n_layers):
                h = layer_cls(self.h, self.d_model, self.d_ff,
                              self.dropout_connection_attention,
                              self.dropout_connection_ffn,
                              self.dropout_attention, self.dropout_ffn,
                              self.dtype, self.param_dtype,
                              self.attention_impl, self.mesh, self.sp_axis,
                              self.dropout_impl, remat_ffn, self.fused_qkv,
                              self.ffn_impl, flash_save_stats, self.quant,
                              name=f"layer_{i}")(h, mask, train)
        else:
            # Pipelined encoder (parallel/pipeline.py — the module
            # docstring there is the spec).  Selected by python
            # branching on pp_spec BEFORE trace, so pp=1 programs (the
            # branch above) stay byte-identical to r21.  Same modules,
            # same names, same param tree: only the execution order of
            # the layer applications changes — the batch runs as M
            # microbatches through V rotating virtual-stage slots, and
            # jax.grad through the rotation yields the reversed (1F1B)
            # backward pipeline.  PipelineTickCtx (r23) restores pp ≡
            # pp=1 with dropout LIVE on the hash engine (stable
            # per-site seeds + global microbatch stream offsets) and
            # with --quant (one amax roll per optimizer step) —
            # build_pipeline_spec still warns for the non-parity
            # engine combos (pipeline.py docstring).
            from faster_distributed_training_tpu.parallel.pipeline import (
                PipelineTickCtx, constrain_stage_buffer, virtual_chunks)
            spec = pp_spec
            # the tick loop runs the depth-ordered VIRTUAL chunks, not
            # a stage's concatenated layer list: slot j applies chunk j
            # (chunks ordered by first layer, pipeline.virtual_chunks),
            # so a microbatch traverses layer 0..L-1 in order under
            # EVERY schedule — 1f1b (V == S, one chunk per stage) and
            # v=2 interleaved (V == 2S, stage j % S hosts slot j) alike.
            chunks = virtual_chunks(spec)
            M, V = spec.n_microbatches, len(chunks)
            if B % M:
                raise ValueError(f"batch {B} not divisible by "
                                 f"{M} pipeline microbatches")
            # ONE mutable trace-time context shared by every layer: the
            # tick loop below sets (microbatch, bubble) before each slot
            # invocation and the dropout/quant sites read them at trace
            # time (the loop is python-unrolled, so each invocation
            # bakes its own values into the jaxpr).  Under --remat each
            # tick's layer call is its OWN checkpoint trace, so the
            # ctx's cross-tick stashes (seeds, amax histories) would
            # leak tracers between traces — no ctx there (r22 per-tick
            # behavior; build_pipeline_spec warns/refuses accordingly)
            ctx = None if self.remat else PipelineTickCtx(M, B // M)
            layers = [layer_cls(self.h, self.d_model, self.d_ff,
                                self.dropout_connection_attention,
                                self.dropout_connection_ffn,
                                self.dropout_attention, self.dropout_ffn,
                                self.dtype, self.param_dtype,
                                self.attention_impl, self.mesh,
                                self.sp_axis, self.dropout_impl,
                                remat_ffn, self.fused_qkv, self.ffn_impl,
                                flash_save_stats, self.quant,
                                pp_ctx=ctx,
                                name=f"layer_{i}")
                      for i in range(self.n_layers)]
            hs = h.reshape((M, B // M) + h.shape[1:])
            # per-microbatch view of a batch-carrying mask; a batch-free
            # causal mask (1,1,L,L) broadcasts into every slot as-is
            bmask = (mask.reshape((M, B // M) + mask.shape[1:])
                     if mask is not None and mask.shape[0] == B else None)
            # fill/drain slots recycle real microbatch data rather than
            # zeros: their outputs are never selected into the loss
            # (zero cotangents either way), but an all-zero constant
            # block lets XLA:CPU constant-fold the slot's attention
            # backward into 0*inf NaN constants at x64 — recycled data
            # keeps every slot on the generic (finite) compute path.
            buf = jnp.broadcast_to(hs[0], (V,) + hs.shape[1:])
            outs = []
            for t in range(spec.n_ticks):
                # rotate: slot j consumes what slot j-1 emitted last
                # tick (slot 0 takes the next microbatch; drain ticks
                # recycle microbatch t % M — discarded, see above).
                # Under GSPMD the pp-sharded slot-dim shift is the
                # stage-boundary collective-permute — the DCN hop.
                inp = hs[t % M]
                buf = jnp.concatenate([inp[None], buf[:-1]], axis=0)
                buf = constrain_stage_buffer(buf, spec)
                slots = []
                for j in range(V):
                    z = buf[j]
                    m_ = mask
                    if bmask is not None:
                        # the mask of the microbatch in this slot
                        # (clamped for bubble slots — their output is
                        # discarded, any finite mask will do)
                        m_ = bmask[min(max(t - j, 0), M - 1)]
                    # which microbatch this slot is processing (same
                    # clamp as the mask) and whether it's a fill/drain
                    # bubble — read at trace time by the r23 dropout
                    # offsets and the quant amax cadence
                    if ctx is not None:
                        ctx.microbatch = min(max(t - j, 0), M - 1)
                        ctx.bubble = not (0 <= t - j < M)
                    for i in chunks[j]:
                        z = layers[i](z, m_, train)
                    slots.append(z)
                buf = jnp.stack(slots)
                buf = constrain_stage_buffer(buf, spec)
                if t >= V - 1:
                    # positive static index: the negative-index gather's
                    # transpose emits a mixed s64/s32 dynamic_update_slice
                    # under x64 that the SPMD partitioner rejects
                    outs.append(buf[V - 1])
            h = jnp.stack(outs).reshape((B,) + h.shape[1:])

        ln = lambda name: TorchLayerNorm(   # noqa: E731
            dtype=self.dtype, param_dtype=self.param_dtype, name=name)

        # Final LayerNorm before the pooler.  The reference carries this
        # layer as dead code — both its definition and its application
        # are commented out (transformer.py:45,68):
        # without it, six pre-LN residual blocks leave h unnormalized,
        # the pooler's tanh pre-activation reaches |x|~3.6 at d_model=512
        # (measured), tanh saturates, and gradients into the entire
        # encoder attenuate ~300x — the d512/6L model cannot learn even
        # on an overfit batch.  Applying the norm is the standard pre-LN
        # closing step and a deliberate, documented fix (same category
        # as the eval-mixup and -1e-9 mask fixes above).
        h = ln("ln_final")(h)

        if self.lm_head:
            # next-token LM head: fp32 logits over the vocab at every
            # position (the loss shifts targets, train/steps.py).  Same
            # return shape train and eval — the mixup triplet below is
            # classification-only.
            if self.tie_lm_head:
                # tied head: logits = h @ E^T on the RAW (unscaled)
                # token table, no bias — the table stays fp32 (the
                # embedding island) and contracts against the compute-
                # dtype h with fp32 accumulation
                logits = jnp.dot(h.astype(self.dtype),
                                 tok_table.astype(self.dtype).T,
                                 preferred_element_type=jnp.float32)
            else:
                logits = nn.Dense(self.vocab, kernel_init=xavier_uniform,
                                  dtype=self.dtype,
                                  param_dtype=self.param_dtype,
                                  name="lm_head")(h)
            return logits.astype(jnp.float32)

        # Pooler: tanh(dense(CLS)) (transformer.py:94-101)
        pooled = nn.tanh(nn.Dense(self.d_model, kernel_init=xavier_uniform,
                                  dtype=self.dtype,
                                  param_dtype=self.param_dtype,
                                  name="pooler")(h[:, 0, :]))
        pooled = FastDropout(0.1, self.dropout_impl)(
            pooled, deterministic=not train)

        # FusedMLP classifier (transformer.py:278-289): d_model→d_hidden→n_class
        w1 = self.param("cls_w1", xavier_uniform,
                        (self.d_hidden, self.d_model), self.param_dtype)
        b1 = self.param("cls_b1", nn.initializers.zeros,
                        (1, self.d_hidden), self.param_dtype)
        w2 = self.param("cls_w2", xavier_uniform,
                        (self.n_class, self.d_hidden), self.param_dtype)
        b2 = self.param("cls_b2", nn.initializers.zeros,
                        (1, self.n_class), self.param_dtype)

        # pallas = VMEM-resident kernel; fused = custom_vjp recompute
        # backward; naive = plain ops under default AD (stores the hidden
        # activations — the bag-of-tricks ablation arm, matching the
        # reference's un-fused MLPScratch semantics)
        mlp_fn = {"pallas": fused_mlp_pallas,
                  "naive": lambda *a: mlp_reference(*a[:5])}.get(
            self.mlp_impl, fused_mlp)

        def classify(z):
            logits = mlp_fn(z.astype(self.dtype), w1.astype(self.dtype),
                            b1.astype(self.dtype), w2.astype(self.dtype),
                            b2.astype(self.dtype))
            return logits.astype(jnp.float32)

        if not train:
            return classify(pooled)

        # in-forward sentence-embedding mixup (transformer.py:71-84),
        # gated on train — fixing the reference's always-on mixup at eval.
        key = self.make_rng("mixup")
        k_lam, k_perm = jax.random.split(key)
        if self.alpha > 0:
            lam = jax.random.beta(k_lam, self.alpha, self.alpha)
        else:
            lam = jnp.asarray(self.alpha, jnp.float32)
        index = jax.random.permutation(k_perm, B)
        mixed = (lam.astype(pooled.dtype) * pooled
                 + (1 - lam).astype(pooled.dtype) * pooled[index])
        return classify(mixed), index, lam
