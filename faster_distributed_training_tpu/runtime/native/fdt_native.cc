// fdt_native — native runtime core for the host data path.
//
// TPU-native counterpart of the reference's per-batch host work, which is
// its documented CPU hot spot (transformer_test.py:93-104: HTML/URL strip +
// stopword removal + tokenization inside the DataLoader collate; SURVEY.md
// §3.3).  The Python implementations in data/agnews.py remain the semantic
// reference; this library must produce byte-identical results (enforced by
// tests/test_runtime.py) and is loaded opportunistically via ctypes
// (runtime/native_lib.py) with graceful Python fallback.
//
// Exposed C ABI:
//   fdt_clean_text     — tag/url strip + lowercase + [a-z0-9']+
//                        tokenization + stopword filter over already
//                        html-unescaped text (== data/agnews.py
//                        clean_text after html.unescape)
//   fdt_encode_batch   — cleaned text -> CLS + crc32-bucket ids + SEP,
//                        padded to max_len (== HashTokenizer.encode)
//   fdt_gather_u8      — index-gather of uint8 rows into a contiguous
//                        batch buffer (the BatchLoader image collate)
//   fdt_crc32          — zlib-compatible CRC32 (dataset integrity checks)

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_set>
#include <vector>

namespace {

// ---------------------------------------------------------------- crc32
uint32_t crc_table[256];
bool crc_init_done = false;

void crc_init() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_init_done = true;
}

uint32_t crc32_of(const uint8_t* data, size_t len) {
  crc_init();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; ++i)
    c = crc_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ------------------------------------------------------------- stopwords
// Must equal data/agnews.py STOPWORDS.
const char* kStopwords[] = {
    "a", "about", "above", "after", "again", "against", "all", "am", "an",
    "and", "any", "are", "as", "at", "be", "because", "been", "before",
    "being", "below", "between", "both", "but", "by", "can", "did", "do",
    "does", "doing", "down", "during", "each", "few", "for", "from",
    "further", "had", "has", "have", "having", "he", "her", "here", "hers",
    "him", "his", "how", "i", "if", "in", "into", "is", "it", "its", "just",
    "me", "more", "most", "my", "no", "nor", "not", "now", "of", "off", "on",
    "once", "only", "or", "other", "our", "out", "over", "own", "s", "same",
    "she", "should", "so", "some", "such", "t", "than", "that", "the",
    "their", "them", "then", "there", "these", "they", "this", "those",
    "through", "to", "too", "under", "until", "up", "very", "was", "we",
    "were", "what", "when", "where", "which", "while", "who", "whom", "why",
    "will", "with", "you", "your"};

const std::unordered_set<std::string>& stopword_set() {
  static const std::unordered_set<std::string> set(
      std::begin(kStopwords), std::end(kStopwords));
  return set;
}

// -------------------------------------------------- tag / url stripping
// HTML entity unescaping stays on the Python side (html.unescape's full
// HTML5 table cannot be reproduced partially without diverging) — this
// library receives ALREADY-UNESCAPED text (data/agnews.py clean_text).
std::string strip_tags(const std::string& in) {   // <[^>]+> -> ' '
  std::string out;
  out.reserve(in.size());
  size_t i = 0;
  while (i < in.size()) {
    if (in[i] == '<') {
      size_t close = in.find('>', i + 1);
      if (close != std::string::npos && close > i + 1) {
        out += ' ';
        i = close + 1;
        continue;
      }
    }
    out += in[i++];
  }
  return out;
}

bool starts_with(const std::string& s, size_t i, const char* pre) {
  size_t n = std::strlen(pre);
  return s.compare(i, n, pre) == 0;
}

bool is_space(char c) {
  // must match Python's \s for ASCII: [ \t\n\r\f\v]
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f'
         || c == '\v';
}

std::string strip_urls(const std::string& in) {
  // https?://\S+ | www\.\S+  (case-sensitive, pre-lowercase — matching
  // the Python regex exactly, data/agnews.py:33).  The \S+ requires at
  // least ONE non-space character after the prefix: a bare "http:// "
  // or trailing "www." does NOT match (and so survives into tokens).
  std::string out;
  out.reserve(in.size());
  size_t i = 0;
  while (i < in.size()) {
    size_t pre = 0;
    if (starts_with(in, i, "https://")) pre = 8;
    else if (starts_with(in, i, "http://")) pre = 7;
    else if (starts_with(in, i, "www.")) pre = 4;
    if (pre && i + pre < in.size() && !is_space(in[i + pre])) {
      out += ' ';
      i += pre;
      while (i < in.size() && !is_space(in[i])) ++i;
      continue;
    }
    out += in[i++];
  }
  return out;
}

bool is_token_char(char c) {
  return (c >= 'a' && c <= 'z') || (c >= '0' && c <= '9') || c == '\'';
}

std::string clean_impl(const std::string& raw) {
  std::string text = strip_urls(strip_tags(raw));
  // lowercase (ASCII; non-ASCII bytes never match the token class)
  for (auto& c : text)
    if (c >= 'A' && c <= 'Z') c += 32;
  const auto& stop = stopword_set();
  std::string out, word;
  for (size_t i = 0; i <= text.size(); ++i) {
    char c = i < text.size() ? text[i] : ' ';
    if (is_token_char(c)) {
      word += c;
    } else if (!word.empty()) {
      if (!stop.count(word)) {
        if (!out.empty()) out += ' ';
        out += word;
      }
      word.clear();
    }
  }
  return out;
}

}  // namespace

extern "C" {

uint32_t fdt_crc32(const uint8_t* data, int64_t len) {
  return crc32_of(data, static_cast<size_t>(len));
}

// Clean `in` into `out` (NUL-terminated).  Returns the cleaned length, or
// -(needed+1) if out_cap is too small.
int64_t fdt_clean_text(const char* in, char* out, int64_t out_cap) {
  std::string cleaned = clean_impl(in);
  int64_t need = static_cast<int64_t>(cleaned.size());
  if (need + 1 > out_cap) return -(need + 1);
  std::memcpy(out, cleaned.data(), cleaned.size());
  out[need] = '\0';
  return need;
}

// HashTokenizer.encode over a batch of ALREADY-CLEANED texts:
// ids = [CLS] + [crc32(word) % (vocab-999) + 999, ...][:max_len-2] + [SEP],
// right-padded with pad_id to max_len.  out_tokens: [n, max_len] int32,
// out_lens: [n] int32 (unpadded length incl. CLS/SEP).
int32_t fdt_encode_batch(const char** texts, int32_t n, int32_t max_len,
                         int32_t vocab_size, int32_t pad_id, int32_t cls_id,
                         int32_t sep_id, int32_t reserved,
                         int32_t* out_tokens, int32_t* out_lens) {
  if (max_len < 2 || vocab_size <= reserved) return -1;
  for (int32_t b = 0; b < n; ++b) {
    int32_t* row = out_tokens + static_cast<int64_t>(b) * max_len;
    int32_t pos = 0;
    row[pos++] = cls_id;
    const char* t = texts[b];
    size_t i = 0, len = std::strlen(t);
    while (i < len && pos < max_len - 1) {
      while (i < len && t[i] == ' ') ++i;
      size_t start = i;
      while (i < len && t[i] != ' ') ++i;
      if (i > start) {
        uint32_t h = crc32_of(reinterpret_cast<const uint8_t*>(t + start),
                              i - start) %
                     static_cast<uint32_t>(vocab_size - reserved);
        row[pos++] = static_cast<int32_t>(h) + reserved;
      }
    }
    row[pos++] = sep_id;
    out_lens[b] = pos;
    for (; pos < max_len; ++pos) row[pos] = pad_id;
  }
  return 0;
}

// Gather `n` rows of `row_bytes` each from `src` at `indices` into `dst`
// (the image-batch collate: dst[i] = src[indices[i]]).
int32_t fdt_gather_u8(const uint8_t* src, const int64_t* indices, int32_t n,
                      int64_t row_bytes, uint8_t* dst) {
  for (int32_t i = 0; i < n; ++i)
    std::memcpy(dst + static_cast<int64_t>(i) * row_bytes,
                src + indices[i] * row_bytes, row_bytes);
  return 0;
}

}  // extern "C"
