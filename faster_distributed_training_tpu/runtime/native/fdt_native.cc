// fdt_native — native runtime core for the host data path.
//
// TPU-native counterpart of the reference's per-batch host work, which is
// its documented CPU hot spot (transformer_test.py:93-104: HTML/URL strip +
// stopword removal + tokenization inside the DataLoader collate; SURVEY.md
// §3.3).  The Python implementations in data/agnews.py remain the semantic
// reference; this library must produce byte-identical results (enforced by
// tests/test_runtime.py) and is loaded opportunistically via ctypes
// (runtime/native_lib.py) with graceful Python fallback.
//
// Exposed C ABI:
//   fdt_clean_text     — tag/url strip + lowercase + [a-z0-9']+
//                        tokenization + stopword filter over already
//                        html-unescaped text (== data/agnews.py
//                        clean_text after html.unescape)
//   fdt_encode_batch   — cleaned text -> CLS + crc32-bucket ids + SEP,
//                        padded to max_len (== HashTokenizer.encode)
//   fdt_gather_u8      — index-gather of uint8 rows into a contiguous
//                        batch buffer (the BatchLoader image collate)
//   fdt_crc32          — zlib-compatible CRC32 (dataset integrity checks)
//   fdt_wp_load        — register a WordPiece vocabulary (newline-joined
//                        tokens, id = line index) -> handle
//   fdt_wp_encode_batch— greedy longest-match WordPiece over CLEANED
//                        ASCII text (== data/wordpiece.py
//                        WordPieceTokenizer.encode on the clean_text
//                        output); returns a fallback code on any byte
//                        outside the cleaned alphabet so the Python
//                        reference handles general Unicode

#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

// ---------------------------------------------------------------- crc32
uint32_t crc_table[256];
bool crc_init_done = false;

void crc_init() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_init_done = true;
}

uint32_t crc32_of(const uint8_t* data, size_t len) {
  crc_init();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; ++i)
    c = crc_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ------------------------------------------------------------- stopwords
// gensim's 337-word STOPWORDS, vendored verbatim (the reference filters
// with gensim.parsing.remove_stopwords, transformer_test.py:95).
// Must equal data/agnews.py STOPWORDS (parity: tests/test_runtime.py).
const char* kStopwords[] = {
    "a", "about", "above", "across", "after", "afterwards", "again",
    "against", "all", "almost", "alone", "along", "already", "also",
    "although", "always", "am", "among", "amongst", "amoungst", "amount",
    "an", "and", "another", "any", "anyhow", "anyone", "anything", "anyway",
    "anywhere", "are", "around", "as", "at", "back", "be", "became",
    "because", "become", "becomes", "becoming", "been", "before",
    "beforehand", "behind", "being", "below", "beside", "besides", "between",
    "beyond", "bill", "both", "bottom", "but", "by", "call", "can", "cannot",
    "cant", "co", "computer", "con", "could", "couldnt", "cry", "de",
    "describe", "detail", "did", "didn", "do", "does", "doesn", "doing",
    "don", "done", "down", "due", "during", "each", "eg", "eight", "either",
    "eleven", "else", "elsewhere", "empty", "enough", "etc", "even", "ever",
    "every", "everyone", "everything", "everywhere", "except", "few",
    "fifteen", "fifty", "fill", "find", "fire", "first", "five", "for",
    "former", "formerly", "forty", "found", "four", "from", "front", "full",
    "further", "get", "give", "go", "had", "has", "hasnt", "have", "he",
    "hence", "her", "here", "hereafter", "hereby", "herein", "hereupon",
    "hers", "herself", "him", "himself", "his", "how", "however", "hundred",
    "i", "ie", "if", "in", "inc", "indeed", "interest", "into", "is", "it",
    "its", "itself", "just", "keep", "kg", "km", "last", "latter", "latterly",
    "least", "less", "ltd", "made", "make", "many", "may", "me", "meanwhile",
    "might", "mill", "mine", "more", "moreover", "most", "mostly", "move",
    "much", "must", "my", "myself", "name", "namely", "neither", "never",
    "nevertheless", "next", "nine", "no", "nobody", "none", "noone", "nor",
    "not", "nothing", "now", "nowhere", "of", "off", "often", "on", "once",
    "one", "only", "onto", "or", "other", "others", "otherwise", "our",
    "ours", "ourselves", "out", "over", "own", "part", "per", "perhaps",
    "please", "put", "quite", "rather", "re", "really", "regarding", "same",
    "say", "see", "seem", "seemed", "seeming", "seems", "serious", "several",
    "she", "should", "show", "side", "since", "sincere", "six", "sixty", "so",
    "some", "somehow", "someone", "something", "sometime", "sometimes",
    "somewhere", "still", "such", "system", "take", "ten", "than", "that",
    "the", "their", "them", "themselves", "then", "thence", "there",
    "thereafter", "thereby", "therefore", "therein", "thereupon", "these",
    "they", "thick", "thin", "third", "this", "those", "though", "three",
    "through", "throughout", "thru", "thus", "to", "together", "too", "top",
    "toward", "towards", "twelve", "twenty", "two", "un", "under", "unless",
    "until", "up", "upon", "us", "used", "using", "various", "very", "via",
    "was", "we", "well", "were", "what", "whatever", "when", "whence",
    "whenever", "where", "whereafter", "whereas", "whereby", "wherein",
    "whereupon", "wherever", "whether", "which", "while", "whither", "who",
    "whoever", "whole", "whom", "whose", "why", "will", "with", "within",
    "without", "would", "yet", "you", "your", "yours", "yourself",
    "yourselves"};

const std::unordered_set<std::string>& stopword_set() {
  static const std::unordered_set<std::string> set(
      std::begin(kStopwords), std::end(kStopwords));
  return set;
}

// -------------------------------------------------- tag / url stripping
// HTML entity unescaping stays on the Python side (html.unescape's full
// HTML5 table cannot be reproduced partially without diverging) — this
// library receives ALREADY-UNESCAPED text (data/agnews.py clean_text).
std::string strip_tags(const std::string& in) {   // <[^>]+> -> ' '
  std::string out;
  out.reserve(in.size());
  size_t i = 0;
  while (i < in.size()) {
    if (in[i] == '<') {
      size_t close = in.find('>', i + 1);
      if (close != std::string::npos && close > i + 1) {
        out += ' ';
        i = close + 1;
        continue;
      }
    }
    out += in[i++];
  }
  return out;
}

bool starts_with(const std::string& s, size_t i, const char* pre) {
  size_t n = std::strlen(pre);
  return s.compare(i, n, pre) == 0;
}

bool is_space(char c) {
  // must match Python's \s for ASCII: [ \t\n\r\f\v]
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f'
         || c == '\v';
}

std::string strip_urls(const std::string& in) {
  // https?://\S+ | www\.\S+  (case-sensitive, pre-lowercase — matching
  // the Python regex exactly, data/agnews.py:33).  The \S+ requires at
  // least ONE non-space character after the prefix: a bare "http:// "
  // or trailing "www." does NOT match (and so survives into tokens).
  std::string out;
  out.reserve(in.size());
  size_t i = 0;
  while (i < in.size()) {
    size_t pre = 0;
    if (starts_with(in, i, "https://")) pre = 8;
    else if (starts_with(in, i, "http://")) pre = 7;
    else if (starts_with(in, i, "www.")) pre = 4;
    if (pre && i + pre < in.size() && !is_space(in[i + pre])) {
      out += ' ';
      i += pre;
      while (i < in.size() && !is_space(in[i])) ++i;
      continue;
    }
    out += in[i++];
  }
  return out;
}

bool is_token_char(char c) {
  return (c >= 'a' && c <= 'z') || (c >= '0' && c <= '9') || c == '\'';
}

std::string clean_impl(const std::string& raw) {
  std::string text = strip_urls(strip_tags(raw));
  // lowercase (ASCII; non-ASCII bytes never match the token class)
  for (auto& c : text)
    if (c >= 'A' && c <= 'Z') c += 32;
  const auto& stop = stopword_set();
  std::string out, word;
  for (size_t i = 0; i <= text.size(); ++i) {
    char c = i < text.size() ? text[i] : ' ';
    if (is_token_char(c)) {
      word += c;
    } else if (!word.empty()) {
      if (!stop.count(word)) {
        if (!out.empty()) out += ' ';
        out += word;
      }
      word.clear();
    }
  }
  return out;
}

// ------------------------------------------------------------- wordpiece
struct WpVocab {
  std::unordered_map<std::string, int32_t> map;
};

// Registration and handle lookup are mutex-guarded: two tokenizer
// instances (e.g. train memoized + test from cache file) may register /
// encode concurrently under --workers, and push_back can reallocate the
// vector's element storage out from under a concurrent reader.  The
// unique_ptr indirection keeps each WpVocab itself at a stable address,
// so encode only needs the lock long enough to copy the pointer out.
std::vector<std::unique_ptr<WpVocab>>& wp_registry() {
  static std::vector<std::unique_ptr<WpVocab>> reg;
  return reg;
}

std::mutex& wp_mutex() {
  static std::mutex m;
  return m;
}

constexpr int kWpMaxCharsPerWord = 100;  // HF WordpieceTokenizer default

// Greedy longest-match-first segmentation of one word; appends piece ids
// (unk_id for an unsegmentable word).  Mirrors data/wordpiece.py
// wordpiece_word.
void wp_segment(const WpVocab& v, const std::string& word, int32_t unk_id,
                std::vector<int32_t>* out) {
  if (word.size() > kWpMaxCharsPerWord) {
    out->push_back(unk_id);
    return;
  }
  std::vector<int32_t> pieces;
  size_t start = 0;
  while (start < word.size()) {
    size_t end = word.size();
    int32_t cur = -1;
    while (start < end) {
      std::string piece = word.substr(start, end - start);
      if (start > 0) piece = "##" + piece;
      auto it = v.map.find(piece);
      if (it != v.map.end()) {
        cur = it->second;
        break;
      }
      --end;
    }
    if (cur < 0) {
      out->push_back(unk_id);
      return;
    }
    pieces.push_back(cur);
    start = end;
  }
  out->insert(out->end(), pieces.begin(), pieces.end());
}

}  // namespace

extern "C" {

uint32_t fdt_crc32(const uint8_t* data, int64_t len) {
  return crc32_of(data, static_cast<size_t>(len));
}

// Clean `in` into `out` (NUL-terminated).  Returns the cleaned length, or
// -(needed+1) if out_cap is too small.
int64_t fdt_clean_text(const char* in, char* out, int64_t out_cap) {
  std::string cleaned = clean_impl(in);
  int64_t need = static_cast<int64_t>(cleaned.size());
  if (need + 1 > out_cap) return -(need + 1);
  std::memcpy(out, cleaned.data(), cleaned.size());
  out[need] = '\0';
  return need;
}

// HashTokenizer.encode over a batch of ALREADY-CLEANED texts:
// ids = [CLS] + [crc32(word) % (vocab-999) + 999, ...][:max_len-2] + [SEP],
// right-padded with pad_id to max_len.  out_tokens: [n, max_len] int32,
// out_lens: [n] int32 (unpadded length incl. CLS/SEP).
int32_t fdt_encode_batch(const char** texts, int32_t n, int32_t max_len,
                         int32_t vocab_size, int32_t pad_id, int32_t cls_id,
                         int32_t sep_id, int32_t reserved,
                         int32_t* out_tokens, int32_t* out_lens) {
  if (max_len < 2 || vocab_size <= reserved) return -1;
  for (int32_t b = 0; b < n; ++b) {
    int32_t* row = out_tokens + static_cast<int64_t>(b) * max_len;
    int32_t pos = 0;
    row[pos++] = cls_id;
    const char* t = texts[b];
    size_t i = 0, len = std::strlen(t);
    while (i < len && pos < max_len - 1) {
      while (i < len && t[i] == ' ') ++i;
      size_t start = i;
      while (i < len && t[i] != ' ') ++i;
      if (i > start) {
        uint32_t h = crc32_of(reinterpret_cast<const uint8_t*>(t + start),
                              i - start) %
                     static_cast<uint32_t>(vocab_size - reserved);
        row[pos++] = static_cast<int32_t>(h) + reserved;
      }
    }
    row[pos++] = sep_id;
    out_lens[b] = pos;
    for (; pos < max_len; ++pos) row[pos] = pad_id;
  }
  return 0;
}

// Register a WordPiece vocabulary: `data[0:len)` is newline-joined tokens,
// id = line index (HF vocab.txt format).  Returns a handle >= 0.
int32_t fdt_wp_load(const char* data, int64_t len) {
  auto v = std::make_unique<WpVocab>();
  int32_t id = 0;
  int64_t start = 0;
  for (int64_t i = 0; i <= len; ++i) {
    if (i == len || data[i] == '\n') {
      if (i > start)
        v->map.emplace(std::string(data + start, i - start), id);
      ++id;
      start = i + 1;
    }
  }
  std::lock_guard<std::mutex> lock(wp_mutex());
  wp_registry().push_back(std::move(v));
  return static_cast<int32_t>(wp_registry().size()) - 1;
}

// WordPiece-encode a batch of CLEANED texts ([a-z0-9' ] alphabet, the
// clean_text output): per word, apostrophes split off as punctuation
// tokens (HF BasicTokenizer._run_split_on_punc restricted to the cleaned
// alphabet), then greedy longest-match.  Frame per row:
// [CLS] + pieces[:max_len-2] + [SEP], right-padded with pad_id.
// Returns 0 ok, -1 bad args, -2 when a text contains a byte outside the
// cleaned alphabet (caller must fall back to the Python reference, which
// handles full Unicode).
int32_t fdt_wp_encode_batch(int32_t handle, const char** texts, int32_t n,
                            int32_t max_len, int32_t cls_id, int32_t sep_id,
                            int32_t unk_id, int32_t pad_id,
                            int32_t* out_tokens, int32_t* out_lens) {
  if (handle < 0 || max_len < 2) return -1;
  const WpVocab* vp = nullptr;
  {
    std::lock_guard<std::mutex> lock(wp_mutex());
    if (handle >= static_cast<int32_t>(wp_registry().size())) return -1;
    vp = wp_registry()[handle].get();  // stable address past the lock
  }
  const WpVocab& v = *vp;
  std::vector<int32_t> ids;
  std::string word;
  for (int32_t b = 0; b < n; ++b) {
    ids.clear();
    const char* t = texts[b];
    size_t len = std::strlen(t);
    for (size_t i = 0; i < len; ++i) {
      char c = t[i];
      bool ok = (c >= 'a' && c <= 'z') || (c >= '0' && c <= '9') ||
                c == '\'' || c == ' ';
      if (!ok) return -2;
    }
    word.clear();
    for (size_t i = 0; i <= len; ++i) {
      char c = i < len ? t[i] : ' ';
      if (c == ' ' || c == '\'') {
        if (!word.empty()) {
          wp_segment(v, word, unk_id, &ids);
          word.clear();
        }
        if (c == '\'') wp_segment(v, "'", unk_id, &ids);
      } else {
        word += c;
      }
    }
    int32_t* row = out_tokens + static_cast<int64_t>(b) * max_len;
    int32_t body = static_cast<int32_t>(ids.size());
    if (body > max_len - 2) body = max_len - 2;
    int32_t pos = 0;
    row[pos++] = cls_id;
    for (int32_t i = 0; i < body; ++i) row[pos++] = ids[i];
    row[pos++] = sep_id;
    out_lens[b] = pos;
    for (; pos < max_len; ++pos) row[pos] = pad_id;
  }
  return 0;
}

// Dump the vendored stopword list, newline-joined, into `out`
// (NUL-terminated).  Returns the written length, or -(needed+1) when
// out_cap is too small.  Exists so tests can assert exact set equality
// between kStopwords and data/agnews.py STOPWORDS instead of inferring
// it from cleaner behavior.
int64_t fdt_stopwords(char* out, int64_t out_cap) {
  std::string joined;
  for (const char* w : kStopwords) {
    if (!joined.empty()) joined += '\n';
    joined += w;
  }
  int64_t need = static_cast<int64_t>(joined.size());
  if (need + 1 > out_cap) return -(need + 1);
  std::memcpy(out, joined.data(), joined.size());
  out[need] = '\0';
  return need;
}

// Gather `n` rows of `row_bytes` each from `src` at `indices` into `dst`
// (the image-batch collate: dst[i] = src[indices[i]]).
int32_t fdt_gather_u8(const uint8_t* src, const int64_t* indices, int32_t n,
                      int64_t row_bytes, uint8_t* dst) {
  for (int32_t i = 0; i < n; ++i)
    std::memcpy(dst + static_cast<int64_t>(i) * row_bytes,
                src + indices[i] * row_bytes, row_bytes);
  return 0;
}

}  // extern "C"
