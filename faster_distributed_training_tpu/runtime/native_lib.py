"""Build + bind the native runtime core (runtime/native/fdt_native.cc).

The library is compiled on demand with g++ (cached by source mtime) and
bound through ctypes — no pybind11 dependency in this environment.  Every
entry point has a pure-Python fallback in data/, so the framework works
even without a toolchain; when the library IS available the data path
uses it (see data/agnews.py / data/loader.py call sites).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "native", "fdt_native.cc")
_BUILD_DIR = os.path.join(_HERE, "_build")
_LIB = os.path.join(_BUILD_DIR, "libfdt_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", _SRC, "-o", _LIB]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def load() -> Optional[ctypes.CDLL]:
    """The bound library, building it if stale/absent; None on failure."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            stale = (not os.path.exists(_LIB)
                     or os.path.getmtime(_LIB) < os.path.getmtime(_SRC))
            if stale and not _build():
                _load_failed = True
                return None
            lib = ctypes.CDLL(_LIB)
            lib.fdt_crc32.restype = ctypes.c_uint32
            lib.fdt_crc32.argtypes = [ctypes.c_char_p, ctypes.c_int64]
            lib.fdt_clean_text.restype = ctypes.c_int64
            lib.fdt_clean_text.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                           ctypes.c_int64]
            lib.fdt_encode_batch.restype = ctypes.c_int32
            lib.fdt_encode_batch.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32)]
            lib.fdt_gather_u8.restype = ctypes.c_int32
            lib.fdt_gather_u8.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int32, ctypes.c_int64, ctypes.c_char_p]
            lib.fdt_wp_load.restype = ctypes.c_int32
            lib.fdt_wp_load.argtypes = [ctypes.c_char_p, ctypes.c_int64]
            lib.fdt_stopwords.restype = ctypes.c_int64
            lib.fdt_stopwords.argtypes = [ctypes.c_char_p, ctypes.c_int64]
            lib.fdt_wp_encode_batch.restype = ctypes.c_int32
            lib.fdt_wp_encode_batch.argtypes = [
                ctypes.c_int32, ctypes.POINTER(ctypes.c_char_p),
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32)]
            _lib = lib
        except Exception:
            _load_failed = True
    return _lib


def available() -> bool:
    return load() is not None


def crc32(data: bytes) -> int:
    lib = load()
    if lib is None:
        import zlib
        return zlib.crc32(data)
    return lib.fdt_crc32(data, len(data))


def clean_text(text: str) -> Optional[str]:
    """Native clean_text; None when the library is unavailable (caller
    falls back to the Python implementation)."""
    lib = load()
    if lib is None:
        return None
    raw = text.encode("utf-8", "ignore")
    cap = max(len(raw) + 16, 64)
    buf = ctypes.create_string_buffer(cap)
    n = lib.fdt_clean_text(raw, buf, cap)
    if n < 0:                       # shouldn't happen: cleaning only shrinks
        cap = -int(n)
        buf = ctypes.create_string_buffer(cap)
        n = lib.fdt_clean_text(raw, buf, cap)
        if n < 0:
            return None
    return buf.raw[:n].decode("utf-8", "ignore")


def stopwords() -> Optional[frozenset]:
    """The native core's vendored stopword list; None when the library is
    unavailable.  Used by tests to pin byte-parity with data/agnews.py."""
    lib = load()
    if lib is None:
        return None
    cap = 4096
    buf = ctypes.create_string_buffer(cap)
    n = lib.fdt_stopwords(buf, cap)
    if n < 0:
        cap = -int(n)
        buf = ctypes.create_string_buffer(cap)
        n = lib.fdt_stopwords(buf, cap)
        if n < 0:
            return None
    return frozenset(buf.raw[:n].decode("utf-8").split("\n"))


def encode_batch(texts: List[str], max_len: int, vocab_size: int,
                 pad_id: int = 0, cls_id: int = 101, sep_id: int = 102,
                 reserved: int = 999
                 ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Native HashTokenizer batch encode of CLEANED texts.
    Returns (tokens [n, max_len] int32, lens [n] int32) or None."""
    lib = load()
    if lib is None:
        return None
    n = len(texts)
    tokens = np.empty((n, max_len), np.int32)
    lens = np.empty((n,), np.int32)
    arr = (ctypes.c_char_p * n)(*[t.encode("utf-8", "ignore") for t in texts])
    rc = lib.fdt_encode_batch(
        arr, n, max_len, vocab_size, pad_id, cls_id, sep_id, reserved,
        tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc != 0:
        return None
    return tokens, lens


def wp_load(vocab_lines: List[str]) -> Optional[int]:
    """Register a WordPiece vocab (id = list index) with the native core;
    returns a handle, or None when the library is unavailable.  The caller
    owns the handle (register once per tokenizer, not per batch)."""
    lib = load()
    if lib is None:
        return None
    blob = "\n".join(vocab_lines).encode("utf-8")
    h = lib.fdt_wp_load(blob, len(blob))
    return None if h < 0 else h


def wp_encode_batch(handle: int, texts: List[str], max_len: int,
                    cls_id: int, sep_id: int, unk_id: int, pad_id: int
                    ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Native WordPiece batch encode of CLEANED ([a-z0-9' ]) texts.
    Returns (tokens [n, max_len] int32, lens [n] int32), or None when the
    library is unavailable or a text needs the full-Unicode Python path."""
    lib = load()
    if lib is None:
        return None
    n = len(texts)
    tokens = np.empty((n, max_len), np.int32)
    lens = np.empty((n,), np.int32)
    try:
        arr = (ctypes.c_char_p * n)(*[t.encode("ascii") for t in texts])
    except UnicodeEncodeError:
        return None
    rc = lib.fdt_wp_encode_batch(
        handle, arr, n, max_len, cls_id, sep_id, unk_id, pad_id,
        tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc != 0:
        return None
    return tokens, lens


def gather_u8(src: np.ndarray, indices: np.ndarray) -> Optional[np.ndarray]:
    """dst[i] = src[indices[i]] for a C-contiguous uint8 array; None when
    the library is unavailable."""
    lib = load()
    if lib is None or src.dtype != np.uint8 or not src.flags.c_contiguous:
        return None
    idx = np.ascontiguousarray(indices, np.int64)
    row_bytes = int(np.prod(src.shape[1:])) * src.itemsize
    dst = np.empty((len(idx),) + src.shape[1:], np.uint8)
    lib.fdt_gather_u8(
        src.ctypes.data_as(ctypes.c_char_p),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(idx), row_bytes, dst.ctypes.data_as(ctypes.c_char_p))
    return dst
