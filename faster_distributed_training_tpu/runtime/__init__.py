"""Native runtime layer: C++ host-data-path core with ctypes bindings.

The reference's runtime-adjacent native surface is NCCL/cuDNN/Apex plus
the Python-level collate hot spot (SURVEY.md §2); compute-side native
code here is XLA/Pallas, and this package covers the HOST side: text
cleaning/tokenization and batch gather in C++ (runtime/native/), built
on demand and always backed by pure-Python fallbacks."""

from faster_distributed_training_tpu.runtime import native_lib  # noqa: F401
from faster_distributed_training_tpu.runtime.native_lib import (  # noqa: F401
    available, clean_text, crc32, encode_batch, gather_u8)
