"""Cross-entropy losses (the reference uses nn.CrossEntropyLoss throughout)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def per_sample_cross_entropy(logits: jax.Array, labels: jax.Array,
                             label_smoothing: float = 0.0) -> jax.Array:
    """(batch,) losses — the reduction='none' path (resnet50_test.py:456)."""
    logits = logits.astype(jnp.float32)
    if label_smoothing:
        n = logits.shape[-1]
        targets = optax.smooth_labels(jax.nn.one_hot(labels, n),
                                      label_smoothing)
        return optax.softmax_cross_entropy(logits, targets)
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


def cross_entropy(logits: jax.Array, labels: jax.Array,
                  label_smoothing: float = 0.0) -> jax.Array:
    """Mean-reduced CE, matching torch's default reduction."""
    return jnp.mean(per_sample_cross_entropy(logits, labels, label_smoothing))
