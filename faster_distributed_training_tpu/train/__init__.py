"""Training layer: mixup family, losses, precision policy, steps, loop,
checkpointing — the TPU re-design of the reference's train()/test() loops
(resnet50_test.py:506-677, transformer_test.py:205-347)."""

from faster_distributed_training_tpu.train.mixup import (  # noqa: F401
    mixup_data, mixup_criterion, mixup_criterion_meta, meta_mixup_apply,
    attn_mixup_apply, init_meta_lambda, init_attn_lambda, sample_lam)
from faster_distributed_training_tpu.train.losses import (  # noqa: F401
    cross_entropy, per_sample_cross_entropy)
from faster_distributed_training_tpu.train.amp import (  # noqa: F401
    LossScaleState, fresh_loss_scale, scale_loss, unscale_and_check,
    update_loss_scale)
from faster_distributed_training_tpu.train.state import (  # noqa: F401
    TrainState, create_train_state)
from faster_distributed_training_tpu.train.steps import (  # noqa: F401
    make_eval_step, make_fused_train_step, make_train_step)
from faster_distributed_training_tpu.train.loop import (  # noqa: F401
    Trainer)
