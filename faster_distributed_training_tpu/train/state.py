"""TrainState: the full training pytree — params, BN statistics, optimizer
state (including NGD Fisher factors), loss scale, step, RNG root.

Unlike the reference's checkpoint (net/acc/epoch only,
resnet50_test.py:663-675 — optimizer, scheduler, scaler and Fisher state
are all lost on resume, SURVEY.md §5), everything needed to continue a
run bit-exactly lives in this one structure and is what
train/checkpoint.py serializes."""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct

from faster_distributed_training_tpu.train.amp import (LossScaleState,
                                                       fresh_loss_scale)


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    loss_scale: LossScaleState
    rng: jax.Array
    # static (not traced / not checkpointed):
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads, extra_params=None):
        updates, new_opt_state = self.tx.update(grads, self.opt_state,
                                                self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(step=self.step + 1, params=new_params,
                            opt_state=new_opt_state)


def create_train_state(model, tx: optax.GradientTransformation,
                       sample_input, rng: jax.Array,
                       init_kwargs: Optional[dict] = None,
                       extra_params: Optional[dict] = None) -> TrainState:
    """Initialize model variables + optimizer state.

    `extra_params` lets callers add trainable leaves outside the model —
    the meta-mixup lambda lives at params['mixup'] so it is genuinely
    optimized (fixing resnet50_test.py:525's never-trained lambda)."""
    init_kwargs = dict(init_kwargs or {})
    rngs = {"params": rng, "dropout": jax.random.fold_in(rng, 1),
            "mixup": jax.random.fold_in(rng, 2)}
    variables = model.init(rngs, sample_input, **init_kwargs)
    # model params live under "model"; extra trainable leaves (e.g. the
    # meta-mixup lambda as params["mixup_lambda"]) sit beside it.
    params = {"model": variables["params"], **(extra_params or {})}
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        step=jnp.asarray(0, jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        loss_scale=fresh_loss_scale(),
        rng=rng,
        apply_fn=model.apply,
        tx=tx,
    )
