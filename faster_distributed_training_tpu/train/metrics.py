"""Metric accumulation.

The reference accumulates loss/correct/total on device and all-reduces
at epoch end (resnet50_test.py:550-558,616-619).  Here per-step metrics
are already global (jit over the sharded batch psums them), so the
accumulator only sums device scalars and converts once per epoch —
one host sync per epoch, not per batch."""

from __future__ import annotations

import math
from typing import Dict, Iterable, List

import jax
import numpy as np

Metrics = Dict[str, jax.Array]


def percentiles(values: Iterable[float],
                qs: Iterable[int] = (50, 95, 99)) -> Dict[int, float]:
    """Nearest-rank percentiles of host floats — {q: value}, {} when
    empty.  Shared by the telemetry aggregation (per-step p50/p95/p99,
    telemetry/aggregate.py) and scripts/telemetry_report.py so the two
    can never disagree on the definition.  Nearest-rank (not
    interpolated): a reported p99 is a step time that actually
    happened, which is what straggler forensics wants."""
    vals = sorted(float(v) for v in values)
    if not vals:
        return {}
    out = {}
    for q in qs:
        idx = max(0, min(len(vals) - 1,
                         math.ceil(q / 100.0 * len(vals)) - 1))
        out[int(q)] = round(vals[idx], 3)
    return out


def perplexity(loss: float, cap: float = 30.0) -> float:
    """exp of a per-token cross-entropy — the LM workload's headline
    metric (--task lm).  The exponent is capped so an early-training /
    diverged loss reports a large finite ppl instead of overflowing to
    inf (exp(30) ~ 1e13 — unambiguous, still orderable)."""
    return float(math.exp(min(float(loss), cap)))


class MetricAccumulator:
    def __init__(self):
        self._sums: Dict[str, List[jax.Array]] = {}

    def add(self, metrics: Metrics) -> None:
        for k, v in metrics.items():
            self._sums.setdefault(k, []).append(v)

    def summary(self) -> Dict[str, float]:
        """One device->host sync for the whole epoch."""
        out = {}
        vals = {k: np.asarray(jax.device_get(v)) for k, v in self._sums.items()}
        n_steps = max(len(v) for v in vals.values()) if vals else 0
        for k, arr in vals.items():
            out[k + "_sum"] = float(arr.sum())
        if "loss_total" in vals and "total" in vals and vals["total"].sum():
            # exact sample-weighted loss — correct even when the final
            # (padded) eval batch holds fewer valid samples than the rest
            out["loss"] = float(vals["loss_total"].sum()
                                / vals["total"].sum())
        elif "loss" in vals and n_steps:
            out["loss"] = float(vals["loss"].mean())
        if "correct" in vals and "total" in vals:
            total = float(vals["total"].sum())
            out["accuracy"] = (float(vals["correct"].sum()) / total
                               if total else 0.0)
        return out

    def last(self) -> Metrics:
        return {k: v[-1] for k, v in self._sums.items()}

    def reset(self) -> None:
        self._sums.clear()


def attach_goodput(summary: Dict[str, float], tracker) -> Dict[str, float]:
    """Merge a GoodputTracker snapshot into an epoch/run summary dict
    under ``goodput_``-prefixed keys (resilience/goodput.py) — the
    resilience subsystem's metrics ride the same summary surface as
    loss/accuracy instead of a side channel.  No-op on tracker=None."""
    if tracker is None:
        return summary
    for k, v in tracker.summary().items():
        summary[f"goodput_{k}" if not k.startswith("goodput") else k] = v
    return summary


def format_goodput(tracker) -> str:
    """One log line: `96.2% goodput (ckpt 0.8s block, 2 saves, 1 restore)`
    — the Trainer's per-epoch [goodput] observability."""
    s = tracker.summary()
    bits = [f"{s['goodput_pct']:.1f}% goodput over {s['wall_s']:.1f}s"]
    if s.get("checkpoint_blocking_s"):
        bits.append(f"ckpt block {s['checkpoint_blocking_s']:.2f}s")
    if s.get("emergency_save_s"):
        bits.append(f"emergency save {s['emergency_save_s']:.2f}s")
    if s.get("restore_s"):
        bits.append(f"restore {s['restore_s']:.2f}s")
    if s.get("restart_backoff_s"):
        bits.append(f"backoff {s['restart_backoff_s']:.2f}s")
    if s.get("detect_s"):
        bits.append(f"detect {s['detect_s']:.2f}s")
    if s.get("restart_mttr_s"):
        # detect + backoff + restore per restart — the pod-coordinated
        # recovery headline (resilience/coordinator.py, bench
        # restart_mttr_s arm)
        bits.append(f"mttr {s['restart_mttr_s']:.2f}s/restart")
    if s.get("readmission_hold_s"):
        # r14 elastic recovery: survivor parked time while a failed
        # slice restarted and rejoined (the hold component of the
        # restart_slice_mttr_s bench arm)
        bits.append(f"readmit hold {s['readmission_hold_s']:.2f}s")
    counts = ", ".join(f"{int(s[k])} {k.rstrip('s') if s[k] == 1 else k}"
                       for k in ("saves", "skipped_saves", "restores",
                                 "restarts", "preemptions", "peer_failures",
                                 "step_timeouts", "restart_generations",
                                 "slice_readmissions",
                                 "pod_fallback_restarts",
                                 "skipped_steps", "rollbacks",
                                 "quarantined_batches",
                                 "quarantined_shards")
                       if s.get(k))
    if counts:
        bits.append(counts)
    return "; ".join(bits)
