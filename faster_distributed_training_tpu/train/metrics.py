"""Metric accumulation.

The reference accumulates loss/correct/total on device and all-reduces
at epoch end (resnet50_test.py:550-558,616-619).  Here per-step metrics
are already global (jit over the sharded batch psums them), so the
accumulator only sums device scalars and converts once per epoch —
one host sync per epoch, not per batch."""

from __future__ import annotations

from typing import Dict, List

import jax
import numpy as np

Metrics = Dict[str, jax.Array]


class MetricAccumulator:
    def __init__(self):
        self._sums: Dict[str, List[jax.Array]] = {}

    def add(self, metrics: Metrics) -> None:
        for k, v in metrics.items():
            self._sums.setdefault(k, []).append(v)

    def summary(self) -> Dict[str, float]:
        """One device->host sync for the whole epoch."""
        out = {}
        vals = {k: np.asarray(jax.device_get(v)) for k, v in self._sums.items()}
        n_steps = max(len(v) for v in vals.values()) if vals else 0
        for k, arr in vals.items():
            out[k + "_sum"] = float(arr.sum())
        if "loss_total" in vals and "total" in vals and vals["total"].sum():
            # exact sample-weighted loss — correct even when the final
            # (padded) eval batch holds fewer valid samples than the rest
            out["loss"] = float(vals["loss_total"].sum()
                                / vals["total"].sum())
        elif "loss" in vals and n_steps:
            out["loss"] = float(vals["loss"].mean())
        if "correct" in vals and "total" in vals:
            total = float(vals["total"].sum())
            out["accuracy"] = (float(vals["correct"].sum()) / total
                               if total else 0.0)
        return out

    def last(self) -> Metrics:
        return {k: v[-1] for k, v in self._sums.items()}

    def reset(self) -> None:
        self._sums.clear()
