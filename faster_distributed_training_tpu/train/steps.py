"""Jitted train/eval step factories.

One compiled function per workload replaces the reference's per-batch
Python orchestration (resnet50_test.py:521-566,
transformer_test.py:241-271): mixup, forward, loss, backward, gradient
clipping (inside the optax chain), optimizer update, BN-stat update,
loss-scale bookkeeping and the metric accumulation all trace into a
single XLA program — zero host round-trips per step.

Under a Mesh with the batch sharded on the data axes, XLA inserts the
gradient psums automatically (DDP's bucketed all-reduce,
resnet50_test.py:716, becomes a compiler decision); with params sharded
on an ``fsdp`` axis the same code becomes ZeRO-3
(reduce-scatter + all-gather), matching transformer_test.py:387-392.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from faster_distributed_training_tpu.config import TrainConfig
from faster_distributed_training_tpu.train import mixup as mx
from faster_distributed_training_tpu.train.amp import (
    scale_loss, unscale_and_check, update_loss_scale)
from faster_distributed_training_tpu.train.losses import (
    cross_entropy, per_sample_cross_entropy)
from faster_distributed_training_tpu.train.state import TrainState

Metrics = Dict[str, jax.Array]


def resolve_mixup_mode(cfg: TrainConfig) -> str:
    if cfg.mixup_mode:
        return cfg.mixup_mode
    if cfg.meta_learning:
        return "meta"               # --meta_learning (resnet50_test.py:525)
    return "static" if cfg.alpha != 0 else "none"


def _tree_where(pred, a, b):
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def _sentinel_ok(loss, grads, finite) -> jax.Array:
    """The sentinel's fused bad-step verdict: ONE bit over the
    unscaled per-step loss, the global grad norm, and the loss-scale
    overflow check it rides on (amp.unscale_and_check — already True
    outside fp16).  Both operands are global scalars inside the jitted
    program (the loss is psum-reduced by GSPMD, the norm spans the
    whole grad tree), so the bit is identical on every (dp, tp, pp)
    host BY CONSTRUCTION — no host round-trip, no agreement protocol.
    An fp32-overflowing grad norm reports inf -> not finite, which is
    the right verdict for a gradient that large."""
    import optax
    gnorm = optax.global_norm(grads)
    return (jnp.isfinite(loss) & jnp.isfinite(gnorm)
            & jnp.asarray(finite, bool))


def _sentinel_metrics(metrics: Metrics, ok: jax.Array) -> Metrics:
    """Mask a guarded step's contribution out of the epoch sums via
    ``where`` (NOT multiplication: 0 * NaN is NaN, and the whole point
    is that the bad step's loss may be NaN).  ``loss_total`` is
    materialized first so the accumulator's exact-weighted epoch loss
    (loss_total/total) spans only the steps that actually updated;
    gauges (loss_scale) pass through unmasked.  ``bad_steps`` is the
    counted verdict — summed by the scan reduction and the epoch
    accumulator into ``bad_steps_sum``, which the Trainer forwards to
    the ``skipped_steps`` goodput counter with NO extra device sync
    (it rides the one summary fetch per epoch)."""
    out = dict(metrics)
    if "loss_total" not in out:
        out["loss_total"] = out["loss"] * out["total"]
    for kk in ("loss", "loss_total", "correct", "total"):
        out[kk] = jnp.where(ok, out[kk], jnp.zeros_like(out[kk]))
    out["bad_steps"] = 1.0 - ok.astype(jnp.float32)
    return out


def lm_shift_metrics(logits: jax.Array, tokens: jax.Array,
                     tok_mask: Optional[jax.Array] = None,
                     sample_valid: Optional[jax.Array] = None
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Shifted next-token objective over ``logits [B, L, V]`` /
    ``tokens [B, L]``: position t predicts token t+1.  Returns
    ``(loss_total, correct, total)`` where ``total`` counts VALID target
    positions — a target is valid when both its context position and the
    target token itself are real (``tok_mask`` row-wise; packed LM rows
    carry all-ones masks so every position counts), optionally crossed
    with the per-SAMPLE ``valid`` mask of a padded final eval batch.
    Per-token fp32 cross-entropy; the epoch summary recovers the exact
    token-weighted loss from loss_total/total (MetricAccumulator), and
    perplexity = exp(loss) rides on top (train/metrics.perplexity)."""
    lg = logits[:, :-1].astype(jnp.float32)
    tgt = tokens[:, 1:]
    if tok_mask is not None:
        valid = (tok_mask[:, :-1] * tok_mask[:, 1:]).astype(jnp.float32)
    else:
        valid = jnp.ones(tgt.shape, jnp.float32)
    if sample_valid is not None:
        valid = valid * sample_valid.astype(jnp.float32)[:, None]
    import optax
    losses = optax.softmax_cross_entropy_with_integer_labels(lg, tgt)
    loss_total = jnp.sum(losses * valid)
    correct = jnp.sum((jnp.argmax(lg, axis=-1) == tgt) * valid)
    total = jnp.sum(valid)
    return (loss_total.astype(jnp.float32), correct.astype(jnp.float32),
            total.astype(jnp.float32))


def _offload_transfers(state_shardings):
    """(fetch, stash) for --host_offload: params/optimizer state live in
    pinned_host between steps (CPUOffload(offload_params=True) analog,
    transformer_test.py:46-48); XLA cannot compute on host-placed operands
    directly, so the step fetches the state into device memory on entry
    and stashes the update back to host before returning — both transfers
    are in-graph (jax.device_put under jit), so XLA schedules/overlaps
    them."""
    if state_shardings is None:
        return (lambda s: s), (lambda s: s)

    def device_kind(sh):
        # only host-pinned leaves transfer; the rest keep their sharding
        # (the partial --offload_opt_state tier, and backends like CPU
        # whose only memory kind IS the host) — device_put on an
        # unchanged sharding is a cheap placement pin
        if getattr(sh, "memory_kind", None) == "pinned_host":
            return sh.with_memory_kind("device")
        return sh

    to_dev = jax.tree.map(device_kind, state_shardings)

    def fetch(state):
        return jax.tree.map(jax.device_put, state, to_dev)

    def stash(state):
        return jax.tree.map(jax.device_put, state, state_shardings)

    return fetch, stash


def make_train_step(cfg: TrainConfig, state_shardings=None, pipeline=None
                    ) -> Callable[[TrainState, Any],
                                  Tuple[TrainState, Metrics]]:
    """Build the jitted train step for cfg.model ('resnet*' or 'transformer').

    state_shardings: pass the TrainState-shaped sharding tree when
    cfg.host_offload is on — the step then round-trips the state
    host->device->host per _offload_transfers.

    Image augmentation happens IN-STEP when the batch carries raw uint8
    images (the loaders' native dtype): the crop/flip/normalize key is
    derived from the CHECKPOINTED device step counter
    (``fold_in(PRNGKey(seed+1), state.step)``) instead of a host-side
    counter, so (a) a resumed run's augmentation stream is bitwise-
    identical to an uninterrupted one (ROADMAP "augmentation-stream
    resume"), and (b) the fused K-step dispatch can advance the stream
    on device with zero host involvement.  Pre-normalized float batches
    (bench/synthetic probes, the eval staging path) pass through
    untouched.

    pipeline: a parallel.pipeline.PipelineSpec on a pp>1 mesh — the
    transformer forward then runs the staged 1F1B microbatch rotation
    (models/transformer.py).  None (every pp=1 config) adds NOTHING to
    the apply call, so those programs stay byte-identical to r21."""
    fp16 = cfg.precision == "fp16"
    # --sentinel guard|full: arm the in-graph bad-step guard.  A
    # TRACE-time Python flag, so --sentinel none programs stay
    # byte-identical to the unguarded build (pinned by
    # tests/test_sentinel.py); when armed, the fp16 GradScaler skip
    # below generalizes to every precision with the fused verdict.
    sentinel_on = getattr(cfg, "sentinel", "none") not in ("none", None)
    # FDT_FAULT_NAN_AT_STEP: the poison multiplier is baked into the
    # program at trace time (lazy import — faults.py pulls in the
    # resilience package, which train.steps must not need at import)
    from faster_distributed_training_tpu.resilience.faults import (
        graph_nan_at)
    nan_at = graph_nan_at()
    is_text = cfg.model == "transformer"
    lm = getattr(cfg, "task", "cls") == "lm"
    if lm and not is_text:
        raise ValueError(f"--task lm needs the transformer (next-token "
                         f"prediction over token ids); got model="
                         f"{cfg.model!r}")
    mode = resolve_mixup_mode(cfg)
    # non-offload shardings (a tp/2D mesh): pin the UPDATED state to the
    # placement policy — without the constraint XLA's propagation is
    # free to replicate the optimizer update's outputs, silently undoing
    # the 1/tp per-param footprint the sharding exists for.  Offload
    # runs pin through stash() instead (different memory kinds).
    offload = cfg.host_offload or getattr(cfg, "offload_opt_state", False)
    if offload and state_shardings is None:
        # the placement layer pins params/opt state to pinned_host for this
        # cfg; a step without the fetch would compile against host-placed
        # operands (TPU: compile error; worse, a silent contract violation)
        raise ValueError("cfg.host_offload/offload_opt_state requires "
                         "state_shardings (see parallel.placement."
                         "train_state_shardings)")
    if offload and not any(
            getattr(s, "memory_kind", None) == "pinned_host"
            for s in jax.tree.leaves(
                state_shardings, is_leaf=lambda x: hasattr(x, "mesh"))):
        # backend without a pinned_host tier (CPU): the placement layer
        # already degraded every pin to plain device sharding, so the
        # fetch/stash round-trip would be pure no-op plumbing — but
        # flipping constrain_out still changes GSPMD's partitioning and
        # with it fp32 reduction order.  Treat the flag as fully off so
        # --offload_opt_state on a host-only backend is BITWISE inert
        # (pinned by test_offload_opt_state_degrades_bitwise_on_cpu).
        offload = False
    # constrain_out is also what makes r23 per-stage residency STICK:
    # the updated state is pinned to the train_state_shardings tree
    # (which carries the pp specs from sharding.pp_residency_specs), so
    # the partitioner cannot drift a stage-owned leaf back to
    # replicated between donated steps — the same pin that already
    # protects the tp/sp layouts below.
    constrain_out = state_shardings is not None and not offload
    fetch, stash = _offload_transfers(
        state_shardings if offload else None)
    # --overlap_grad_reduce: reshard grads through byte-bounded 1-D
    # buckets constrained to the zero axis, so GSPMD lowers the gradient
    # psum as bucketed reduce-scatter it can overlap with the next
    # microbatch's compute inside the K-dispatch scan.  Value-identity.
    reduce_grads = lambda g: g                                 # noqa: E731
    if getattr(cfg, "overlap_grad_reduce", False) \
            and state_shardings is not None:
        from faster_distributed_training_tpu.parallel.sharding import (
            bucketed_grad_reduce)
        _mesh = jax.tree.leaves(
            state_shardings,
            is_leaf=lambda x: hasattr(x, "mesh"))[0].mesh
        _bucket = int(getattr(cfg, "overlap_bucket_mb", 4)) << 20
        reduce_grads = lambda g: bucketed_grad_reduce(      # noqa: E731
            g, _mesh, bucket_bytes=_bucket)
    # the augmentation stream root — the same seed+1 derivation
    # cli.run_training used for the host-counter stream it replaces
    aug_root = jax.random.PRNGKey(cfg.seed + 1)
    # pp>1 only: the staged-encoder selector, absent (not None-valued —
    # ABSENT) from every pp=1 apply call so those traces don't change
    pp_kwargs = {} if pipeline is None else {"pp_spec": pipeline}

    def step(state: TrainState, batch: Dict[str, jax.Array]
             ) -> Tuple[TrainState, Metrics]:
        state = fetch(state)
        if (not is_text and "image" in batch
                and batch["image"].dtype == jnp.uint8):
            from faster_distributed_training_tpu.data.augment import (
                augment_batch)
            k_aug = jax.random.fold_in(aug_root, state.step)
            batch = dict(batch, image=augment_batch(
                k_aug, batch["image"], train=True))
        step_key = jax.random.fold_in(state.rng, state.step)
        k_mix, k_drop = jax.random.split(step_key)
        if cfg.dropout_rng_impl == "rbg" and cfg.dropout_impl == "xla":
            # Opt-in: dropout masks through the rbg PRNG (XLA
            # RngBitGenerator — the TPU's hardware-RNG path) instead of
            # threefry, which costs ~100 vector ops per draw and was
            # measured to eat 34% of the transformer step in round 3.
            # Only meaningful with cfg.dropout_impl == "xla": the
            # default hash dropout (ops/dropout.py) never draws mask
            # bits from this key at all (it derives one u32 seed per
            # site), is faster than the rbg path AND bit-reproducible,
            # which is why threefry is back as the rng default
            # (ADVICE r3 #2).  Only the DROPOUT stream switches:
            # mixup/init stay threefry, and the attention-prob dropout
            # keeps its placement-independent index hash
            # (ops.attention.dropout_keep).
            k_drop = jax.random.wrap_key_data(
                jax.random.bits(k_drop, (4,), jnp.uint32), impl="rbg")
        if lm:
            # next-token LM objective (--task lm, r18): per-position
            # vocab logits, targets = tokens shifted left.  mask=None to
            # the model — the streamed LM rows are PACKED (format.
            # pack_lm_rows: no padding), so there is nothing to mask in
            # attention and the one program serves every data path
            # identically; padded-target validity is handled in the LOSS
            # (lm_shift_metrics' tok_mask term) for datasets that do pad.
            # No mixup: a dense token objective has no sentence-embedding
            # to mix (the k_mix rng is threaded for stream parity but
            # the lm model path never draws from it).
            def loss_fn(params):
                variables = {"params": params["model"],
                             "batch_stats": state.batch_stats}
                logits, mutated = state.apply_fn(
                    variables, batch["tokens"],
                    token_types=batch.get("token_types"),
                    mask=None, train=True,
                    rngs={"dropout": k_drop, "mixup": k_mix},
                    mutable=["batch_stats"], **pp_kwargs)
                loss_total, correct, total = lm_shift_metrics(
                    logits, batch["tokens"], batch.get("mask"))
                loss = loss_total / jnp.maximum(total, 1.0)
                scaled = scale_loss(loss, state.loss_scale, fp16)
                if nan_at is not None:
                    # multiplicative poison: the NaN flows through the
                    # backward pass, so every gradient leaf is NaN too —
                    # exactly the shape of a real overflow/bad batch
                    scaled = scaled * jnp.where(state.step == nan_at,
                                                jnp.nan, 1.0)
                new_stats = mutated.get("batch_stats", state.batch_stats)
                return scaled, (loss, loss_total, correct, total, new_stats)

            grads, (loss, loss_total, correct, total, new_stats) = jax.grad(
                loss_fn, has_aux=True)(state.params)
            grads = reduce_grads(grads)
            grads, finite = unscale_and_check(grads, state.loss_scale, fp16)
            ok = _sentinel_ok(loss, grads, finite) if sentinel_on \
                else finite
            updated = state.apply_gradients(grads).replace(
                batch_stats=new_stats,
                loss_scale=update_loss_scale(state.loss_scale, finite,
                                             fp16))
            if fp16 or sentinel_on:
                # the loss-scale ladder keys off the overflow bit
                # (finite), the sentinel skip off the fused verdict (ok)
                skipped = state.replace(
                    step=state.step + 1,
                    loss_scale=update_loss_scale(state.loss_scale, finite,
                                                 fp16))
                updated = _tree_where(ok, updated, skipped)
            # loss = per-TOKEN mean (perplexity's log); total counts
            # target tokens, so the accumulator's loss_total/total is
            # the exact token-weighted epoch loss and "accuracy" is
            # next-token accuracy
            metrics = {"loss": loss.astype(jnp.float32),
                       "loss_total": loss_total,
                       "correct": correct, "total": total}
            if fp16:
                metrics["loss_scale"] = updated.loss_scale.scale
            if sentinel_on:
                metrics = _sentinel_metrics(metrics, ok)
            if constrain_out:
                updated = jax.tree.map(jax.lax.with_sharding_constraint,
                                       updated, state_shardings)
            return stash(updated), metrics
        y = batch["label"]

        def loss_fn(params):
            model_params = params["model"]
            variables = {"params": model_params,
                         "batch_stats": state.batch_stats}
            if is_text:
                out, mutated = state.apply_fn(
                    variables, batch["tokens"],
                    token_types=batch.get("token_types"),
                    mask=batch.get("mask"), train=True,
                    rngs={"dropout": k_drop, "mixup": k_mix},
                    mutable=["batch_stats"], **pp_kwargs)
                logits, index, lam = out       # in-forward mixup triplet
                y_a, y_b = y, y[index]
                loss = mx.mixup_criterion(cross_entropy, logits, y_a, y_b,
                                          lam)
            else:
                x = batch["image"]
                if mode == "meta":
                    x, y_a, y_b, lam = mx.meta_mixup_apply(
                        params["mixup_lambda"], k_mix, x, y)
                elif mode == "attn":
                    x, y_a, y_b, lam = mx.attn_mixup_apply(
                        params["mixup_lambda"], k_mix, x, y)
                elif mode == "static":
                    x, y_a, y_b, lam = mx.mixup_data(k_mix, x, y, cfg.alpha)
                elif mode == "intra":
                    x, y_a, y_b, lam = mx.mixup_data(k_mix, x, y, cfg.alpha,
                                                     intra_only=True)
                else:
                    x, y_a, y_b, lam = x, y, y, jnp.asarray(1.0)
                logits, mutated = state.apply_fn(
                    variables, x, train=True,
                    rngs={"dropout": k_drop, "mixup": k_mix},
                    mutable=["batch_stats"])
                if mode in ("meta", "attn"):
                    loss = mx.mixup_criterion_meta(
                        per_sample_cross_entropy, logits, y_a, y_b, lam)
                else:
                    loss = mx.mixup_criterion(cross_entropy, logits, y_a,
                                              y_b, lam)
            scaled = scale_loss(loss, state.loss_scale, fp16)
            if nan_at is not None:
                scaled = scaled * jnp.where(state.step == nan_at,
                                            jnp.nan, 1.0)
            new_stats = mutated.get("batch_stats", state.batch_stats)
            return scaled, (loss, logits, y_a, y_b, lam, new_stats)

        grads, (loss, logits, y_a, y_b, lam, new_stats) = jax.grad(
            loss_fn, has_aux=True)(state.params)
        grads = reduce_grads(grads)
        grads, finite = unscale_and_check(grads, state.loss_scale, fp16)
        ok = _sentinel_ok(loss, grads, finite) if sentinel_on else finite

        updated = state.apply_gradients(grads).replace(
            batch_stats=new_stats,
            loss_scale=update_loss_scale(state.loss_scale, finite, fp16))
        if fp16 or sentinel_on:
            # skip the whole update on non-finite grads (GradScaler policy,
            # resnet50_test.py:547-548) — but still advance step & scale
            skipped = state.replace(
                step=state.step + 1,
                loss_scale=update_loss_scale(state.loss_scale, finite, fp16))
            updated = _tree_where(ok, updated, skipped)

        # mixup-weighted train accuracy (resnet50_test.py:550-558)
        pred = jnp.argmax(logits, axis=-1)
        if lam.ndim == 0:
            correct = (lam * jnp.sum(pred == y_a)
                       + (1.0 - lam) * jnp.sum(pred == y_b))
        else:
            correct = jnp.sum(lam * (pred == y_a)
                              + (1.0 - lam) * (pred == y_b))
        metrics = {"loss": loss.astype(jnp.float32),
                   "correct": correct.astype(jnp.float32),
                   "total": jnp.asarray(y.shape[0], jnp.float32)}
        if fp16:
            metrics["loss_scale"] = updated.loss_scale.scale
        if sentinel_on:
            metrics = _sentinel_metrics(metrics, ok)
        if constrain_out:
            updated = jax.tree.map(jax.lax.with_sharding_constraint,
                                   updated, state_shardings)
        return stash(updated), metrics

    return step


def _reduce_scanned_metrics(ms: Metrics) -> Metrics:
    """Per-step metrics stacked [K] by lax.scan -> one on-device dict.

    ``loss_total``/``total`` let MetricAccumulator.summary() recover the
    EXACT sample-weighted epoch loss (identical to K=1's mean over equal-
    sized steps); ``loss`` (mean over the dispatch) feeds the live
    log-line and the non-finite epoch check — any non-finite step
    poisons the mean, so divergence detection keeps per-step acuity."""
    out = {"loss": jnp.mean(ms["loss"]),
           # the LM step emits an exact loss_total (token-weighted sum);
           # reduce it directly instead of re-deriving loss*total, so a
           # K>1 LM dispatch's epoch loss is the same float the K=1
           # path accumulates
           "loss_total": (jnp.sum(ms["loss_total"]) if "loss_total" in ms
                          else jnp.sum(ms["loss"] * ms["total"])),
           "correct": jnp.sum(ms["correct"]),
           "total": jnp.sum(ms["total"])}
    if "bad_steps" in ms:
        # the sentinel's counted verdicts (one 0/1 per scanned step) —
        # summed here and again by the epoch accumulator into
        # bad_steps_sum, the Trainer's skipped_steps feed
        out["bad_steps"] = jnp.sum(ms["bad_steps"])
    if "loss_scale" in ms:
        out["loss_scale"] = jax.tree.map(lambda x: x[-1], ms["loss_scale"])
    return out


def make_fused_train_step(cfg: TrainConfig, k: int, state_shardings=None,
                          resident=None, mesh=None,
                          pipeline=None) -> Callable:
    """K steps in ONE device dispatch: ``lax.scan`` over the single-step
    body (Kumar et al. 2021's loop-inside-the-program fix for dispatch-
    bound small-model training).  The scan compiles the body ONCE and
    calls it K times, so each iteration runs the same XLA program as the
    standalone jitted step — which is what makes a K=4 run bitwise-equal
    to a K=1 run at the same global step (pinned by
    tests/test_fused_dispatch.py).  State is donated across the carry;
    loss-scale/NGD/mixup state threads through unchanged (it all lives
    in the carry); metrics are stacked by the scan and reduced on device
    (_reduce_scanned_metrics).

    Two batch sources:
      * host (``resident=None``): ``step_k(state, batches)`` where every
        batch leaf carries a leading K axis (the Trainer stacks K host
        batches and stages them with ONE transfer);
      * device-resident (``resident=DeviceResidentData``):
        ``step_k(state, data, order, start)`` — batch ``start + i`` is
        gathered from the resident split *inside* the scan body
        (``order`` is the epoch's index array, ``start`` the dispatch's
        first step-in-epoch), so the steady-state loop moves no batch
        bytes from the host at all.  A ``resident`` with
        ``batch_major=True`` (per-host sharded residency,
        ``data.device_resident.ShardedDeviceResidentData``) hands the
        dispatch this epoch's ``[steps, batch, ...]`` view instead: the
        permutation was applied by the once-per-epoch re-shard, so the
        in-graph "gather" is a ``dynamic_index`` on the UNsharded
        leading axis — every device reads only its own rows of batch
        ``start + i`` from local HBM (``order`` is carried for
        signature uniformity but never indexed through).

    k == 1 is valid (one-step scan) but the Trainer keeps the plain
    ``make_train_step`` path for it — the default behavior stays
    byte-for-byte today's.

    pipeline (r22): on a pp>1 mesh the scan BODY is the staged
    1F1B-microbatched step, so the pipeline's tick loop nests inside
    the K-dispatch scan — the pipeline bubble and the K-ladder share
    one dispatch accounting (the donated carry, the exact stacked-
    metric reduction and the loss-scale/NGD/mixup threading are the
    scan's, unchanged)."""
    base = make_train_step(cfg, state_shardings, pipeline=pipeline)
    k = int(k)
    if k < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")

    if resident is None:
        def step_k(state: TrainState, batches: Dict[str, jax.Array]
                   ) -> Tuple[TrainState, Metrics]:
            state, ms = lax.scan(base, state, batches, length=k)
            return state, _reduce_scanned_metrics(ms)
        return step_k

    bs = resident.batch_size
    constraint = None
    if mesh is not None:
        from jax.sharding import NamedSharding
        from faster_distributed_training_tpu.parallel.sharding import (
            batch_spec)
        constraint = NamedSharding(mesh, batch_spec(mesh))

    batch_major = getattr(resident, "batch_major", False)

    def gather_batch(data: Dict[str, jax.Array], order: jax.Array,
                     step_in_epoch: jax.Array) -> Dict[str, jax.Array]:
        if batch_major:
            # order was pre-applied by the per-epoch re-shard: just
            # index the unsharded leading step axis (local-HBM reads)
            batch = {kk: lax.dynamic_index_in_dim(v, step_in_epoch, 0,
                                                  keepdims=False)
                     for kk, v in data.items()}
        else:
            idx = lax.dynamic_slice_in_dim(order, step_in_epoch * bs, bs)
            # indices come from a host-built permutation of [0, n) —
            # always in bounds, so skip jnp.take's clamp/fill index
            # normalization
            batch = {kk: v.at[idx].get(mode="promise_in_bounds")
                     for kk, v in data.items()}
        if constraint is not None:
            batch = {kk: lax.with_sharding_constraint(v, constraint)
                     for kk, v in batch.items()}
        return batch

    def step_k(state: TrainState, data: Dict[str, jax.Array],
               order: jax.Array, start: jax.Array
               ) -> Tuple[TrainState, Metrics]:
        def body(s, i):
            return base(s, gather_batch(data, order, start + i))
        state, ms = lax.scan(body, state, jnp.arange(k))
        return state, _reduce_scanned_metrics(ms)

    return step_k


def make_eval_step(cfg: TrainConfig) -> Callable[[TrainState, Any],
                                                 Metrics]:
    """Eval: deterministic forward (running BN stats, no dropout, no mixup —
    fixing the reference's always-on eval mixup, transformer_test.py:321).

    No offload fetch here: under --host_offload the Trainer transfers the
    state to device ONCE per eval epoch (Trainer.evaluate), not per batch —
    the state never changes inside an eval loop."""
    is_text = cfg.model == "transformer"
    lm = getattr(cfg, "task", "cls") == "lm"

    def step(state: TrainState, batch: Dict[str, jax.Array]) -> Metrics:
        variables = {"params": state.params["model"],
                     "batch_stats": state.batch_stats}
        if lm:
            # next-token eval: same shifted objective as training, with
            # the padded-final-batch per-sample `valid` mask crossed in
            # (pad rows contribute zero target tokens — full-split
            # perplexity is exact at any batch size)
            logits = state.apply_fn(variables, batch["tokens"],
                                    token_types=batch.get("token_types"),
                                    mask=None, train=False)
            loss_total, correct, total = lm_shift_metrics(
                logits, batch["tokens"], batch.get("mask"),
                batch.get("valid"))
            return {"loss": (loss_total / jnp.maximum(total, 1.0)
                             ).astype(jnp.float32),
                    "loss_total": loss_total, "correct": correct,
                    "total": total}
        if is_text:
            logits = state.apply_fn(variables, batch["tokens"],
                                    token_types=batch.get("token_types"),
                                    mask=batch.get("mask"), train=False)
        else:
            logits = state.apply_fn(variables, batch["image"], train=False)
        y = batch["label"]
        hit = (jnp.argmax(logits, axis=-1) == y).astype(jnp.float32)
        losses = per_sample_cross_entropy(logits, y)
        valid = batch.get("valid")
        if valid is None:
            loss_total = jnp.sum(losses)
            correct = jnp.sum(hit)
            total = jnp.asarray(y.shape[0], jnp.float32)
        else:
            # padded final batch (BatchLoader pad_last): padding samples
            # carry valid=0 and contribute to nothing
            loss_total = jnp.sum(losses * valid)
            correct = jnp.sum(hit * valid)
            total = jnp.sum(valid)
        return {"loss": (loss_total / jnp.maximum(total, 1.0)
                         ).astype(jnp.float32),
                "loss_total": loss_total.astype(jnp.float32),
                "correct": correct.astype(jnp.float32),
                "total": total}

    return step
