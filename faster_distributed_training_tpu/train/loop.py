"""The training loop: epochs, eval, best-acc checkpointing, timing.

Re-design of train()/test() (resnet50_test.py:506-677,
transformer_test.py:205-347).  Differences by design:
  * one jitted step (steps.py) instead of per-batch Python;
  * loaders are *functions of the epoch* so every epoch reshuffles —
    fixing the missing DistributedSampler.set_epoch in the reference's
    ResNet DDP loop (SURVEY.md §5);
  * per-epoch wall time is fenced with block_until_ready (the
    reference's time.monotonic() pairs measured async CUDA dispatch);
  * checkpoints capture full state (train/checkpoint.py).
"""

from __future__ import annotations

import contextlib
import itertools
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

import jax
import numpy as np

from faster_distributed_training_tpu.config import TrainConfig
from faster_distributed_training_tpu.data.loader import device_prefetch
# host_finite is THE repo-wide host-side finiteness definition (one
# non-finite vocabulary shared with the in-graph sentinel guard); it
# deliberately operates on ALREADY-FETCHED Python floats — using
# jax.numpy.isfinite here would accept a still-on-device scalar and add
# a blocking round-trip at the epoch boundary
from faster_distributed_training_tpu.resilience.sentinel import host_finite
from faster_distributed_training_tpu.telemetry import spans
from faster_distributed_training_tpu.train import checkpoint as ckpt
from faster_distributed_training_tpu.train.metrics import (MetricAccumulator,
                                                           format_goodput)
from faster_distributed_training_tpu.train.state import TrainState
from faster_distributed_training_tpu.train.steps import (
    make_eval_step, make_fused_train_step, make_train_step)
from faster_distributed_training_tpu.utils.profiling import (
    memory_watermarks, peak_memory_bytes)

LoaderFn = Callable[[int], Iterable[Dict[str, Any]]]


def _stack_host_batches(group: List[Dict[str, Any]]) -> Dict[str, Any]:
    """K host batches -> one dict with a leading K axis per leaf, ready
    for a single staged transfer into the fused dispatch.  Text batches
    bucketed to different widths within the group are right-padded to
    the group max (tokens/token_types/mask all pad with 0 = ignore)."""
    out = {}
    for key in group[0]:
        arrs = [np.asarray(b[key]) for b in group]
        if any(a.shape != arrs[0].shape for a in arrs):
            tgt = tuple(max(a.shape[d] for a in arrs)
                        for d in range(arrs[0].ndim))
            arrs = [np.pad(a, [(0, t - s) for s, t in zip(a.shape, tgt)])
                    for a in arrs]
        out[key] = np.stack(arrs)
    return out


class Trainer:
    """Owns the compiled steps and the epoch loop."""

    def __init__(self, cfg: TrainConfig, put_batch: Optional[Callable] = None,
                 put_eval_batch: Optional[Callable] = None,
                 log: Callable[[str], None] = print,
                 state_shardings=None, resilience=None,
                 put_stacked: Optional[Callable] = None, resident=None,
                 telemetry=None, profiler=None, stream=None,
                 pipeline=None):
        self.cfg = cfg
        # parallel.pipeline.PipelineSpec on a pp>1 mesh (None everywhere
        # else): threads into every train-step build so the forward runs
        # the staged 1F1B microbatch rotation; eval stays unstaged (the
        # params are identical, pp only reorders the encoder's work)
        self.pipeline = pipeline
        # telemetry.RunTelemetry bundle (or None = zero hot-path
        # overhead): per-dispatch JSONL records, span breakdown, epoch
        # pod aggregation + straggler flags — telemetry/__init__.py
        self.telemetry = telemetry
        # utils.profiling.StepWindowProfiler (or None): --profile_steps
        # A:B windowed jax.profiler capture, driven at dispatch
        # boundaries by the epoch loops below
        self.profiler = profiler
        # resilience.Resilience bundle (or None = zero hot-path overhead):
        # step-cadence async checkpoints, preemption handling, fault
        # injection, goodput accounting — resilience/__init__.py
        self.resilience = resilience
        self.put_batch = put_batch or (lambda b: b)
        # eval staging may differ (e.g. normalize-only augmentation);
        # defaults to the train staging function
        self.put_eval_batch = put_eval_batch or self.put_batch
        # staging for K-stacked host batches (leading K axis kept on-host;
        # the batch dim below it is the sharded one) — placement.
        # make_put_batch(..., stacked=True)
        self.put_stacked = put_stacked or (lambda b: b)
        # device-resident train split (data/device_resident.py) — when
        # set, run_epoch never touches a host loader: batches are
        # gathered inside the fused dispatch.  Eval stays on the host
        # path (once per epoch, off the hot loop).
        self.resident = resident
        # beyond-HBM streaming source (data/stream/window.py
        # DiskStreamSource, or None): the split lives on disk; each
        # epoch trains through a double-buffered device window refilled
        # by a background thread.  Steady-state stall accounting
        # (fraction of step time blocked on data — bench's
        # stream_stall_pct) accumulates here across epochs, excluding
        # each program's compile-marked first dispatch.
        self.stream = stream
        self._stream_stall_s = 0.0
        self._stream_wall_s = 0.0
        # K train steps per device dispatch (the fused lax.scan program);
        # 1 keeps the classic one-jit-call-per-step loop bit-for-bit.
        self.k = max(int(getattr(cfg, "steps_per_dispatch", 1) or 1), 1)
        self.log = log if jax.process_index() == 0 else (lambda *_: None)
        donate = {"donate_argnums": 0} if getattr(cfg, "donate", True) else {}
        self._donate = donate
        self._state_shardings = state_shardings
        # state_shardings is only needed for --host_offload (the train step
        # fetch/stashes the state across memory kinds per batch,
        # steps._offload_transfers; evaluate() fetches once per epoch)
        self._offload_shardings = (state_shardings if cfg.host_offload
                                   else None)
        # compile observatory (telemetry/programs.py): every program this
        # Trainer builds goes through an observed explicit lower/compile
        # on its first call (compile ms, HLO fingerprint, cache verdict,
        # memory_analysis — all at compile boundaries, nothing
        # per-dispatch) and dispatches through the AOT executable after.
        # None (telemetry off / FDT_PROGRAM_OBS=0) keeps plain jit
        # dispatch, byte-identical to r14.
        self._observatory = (getattr(telemetry, "observatory", None)
                             if telemetry is not None else None)
        self.train_step = self._observe(
            "train:host:k1",
            jax.jit(make_train_step(cfg, state_shardings,
                                    pipeline=pipeline), **donate),
            sig_argnums=(1,))
        self._fused_cache: Dict[tuple, Callable] = {}
        # sig_argnums=(1,): eval batches legally vary (text bucket
        # widths) — each width is a counted VARIANT of the one "eval"
        # program, not a retrace
        self.eval_step = self._observe("eval", jax.jit(make_eval_step(cfg)),
                                       sig_argnums=(1,))
        # sharding-drift guard state (telemetry/programs.py): the live
        # state's sharding fingerprint captured after the run's first
        # dispatch, re-checked at epoch boundaries (_check_sharding_drift)
        self._sharding_expect: Optional[str] = None
        self._sharding_detail: Optional[Dict[str, str]] = None
        self.history: Dict[str, List[float]] = {
            "train_acc": [], "test_acc": [], "train_loss": [],
            "test_loss": [], "epoch_time": [], "peak_mem_bytes": []}
        if getattr(cfg, "task", "cls") == "lm":
            # the LM workload's headline metric rides the same history
            # surface (train/metrics.perplexity of the token-weighted
            # epoch loss); "accuracy" already IS next-token accuracy
            self.history["train_ppl"] = []
            self.history["test_ppl"] = []
        self.best_acc = 0.0
        self.recoveries = 0
        # host-side mirror of state.step: reading the device scalar per
        # step would force a sync, so the loop counts steps itself
        # (re-anchored to the real value at every fit()/restore)
        self.global_step = 0
        # blocked (checkpoint/resilience-hook) seconds accumulated since
        # the last live log line — _log_dispatch subtracts them so the
        # printed ex/s is actual step throughput, not wall throughput
        # diluted by a save that happened to land in the window
        self._blocked_since_log = 0.0
        # programs that have already executed once: the FIRST dispatch of
        # each (path, kk) program carries its compile and is recorded as
        # compile=True + a first_dispatch_compile span, so step-time
        # percentiles stay clean of compilation
        self._dispatched: set = set()
        # batches run by the most recent run_epoch call (epoch telemetry)
        self._last_epoch_steps = 0

    def _observe(self, name: str, jitted, sig_argnums=()) -> Callable:
        """Route a jitted program through the compile observatory when
        one is active (telemetry/programs.py); identity otherwise."""
        if self._observatory is None:
            return jitted
        return self._observatory.wrap(name, jitted,
                                      sig_argnums=sig_argnums)

    def _fused_step(self, kk: int, resident=None) -> Callable:
        """Jitted K-step fused dispatch, cached per (path, kk) — an
        epoch tail shorter than K compiles its own (one-off) program.
        The stream source duck-types the resident interface
        (batch_major=True) and names its path via ``program_key`` so
        the observatory's program table distinguishes
        train:stream:kN from train:resident:kN."""
        key = (getattr(resident, "program_key", "resident")
               if resident is not None else "host", kk)
        fn = self._fused_cache.get(key)
        if fn is None:
            mesh = getattr(resident, "mesh", None)
            fn = jax.jit(
                make_fused_train_step(self.cfg, kk, self._state_shardings,
                                      resident=resident, mesh=mesh,
                                      pipeline=self.pipeline),
                **self._donate)
            # resident signature args: the per-epoch data/order arrays
            # and the start scalar (a regression to a python-int start
            # would surface as a dtype-leak retrace, the r8 bug class)
            fn = self._observe(f"train:{key[0]}:k{kk}", fn,
                               sig_argnums=(1,) if resident is None
                               else (1, 2, 3))
            self._fused_cache[key] = fn
        return fn

    def warm_programs(self, state: TrainState, train_loader: LoaderFn,
                      eval_loader: LoaderFn) -> int:
        """Build the run's steady-state programs — compile, or
        deserialize from the persistent executable cache when one is
        installed on the observatory — WITHOUT advancing the training
        state (r17 warm spares: the pre-admission warm, so a claimed
        seat swaps in at restore+catch-up speed instead of paying the
        compile-dominated cold MTTR).  One throwaway dispatch per
        program: the train step may donate its input, so it runs on a
        same-sharding copy of the state and the outputs are discarded.
        Host data path only — the device-resident programs take
        per-epoch data/order arrays and warm naturally at catch-up
        (logged, not guessed around).  Returns how many programs were
        warmed."""
        if self.resident is not None or self.stream is not None:
            self.log("[spare] --data_path resident/stream: the in-graph-"
                     "gather train programs take per-epoch/window data "
                     "arrays and warm at catch-up; only the eval program "
                     "warms now")
        donate = bool(self._donate)

        def _copy(st):
            if not donate:
                return st      # nothing will be donated; no copy needed
            return jax.tree.map(
                lambda x: x.copy() if hasattr(x, "copy") else x, st)

        warmed = 0
        if self.resident is None and self.stream is None:
            loader = train_loader(0)
            it = iter(loader)
            try:
                raw = next(it)
            except StopIteration:
                raw = None
            closer = getattr(loader, "close", None)
            if closer is not None:
                closer()
            if raw is not None:
                if self.k > 1:
                    batch = self.put_stacked(
                        _stack_host_batches([raw] * self.k))
                    self._fused_step(self.k)(_copy(state), batch)
                else:
                    self.train_step(_copy(state), self.put_batch(raw))
                warmed += 1
        ev_loader = eval_loader(0)
        it = iter(ev_loader)
        try:
            raw = next(it)
        except StopIteration:
            raw = None
        closer = getattr(ev_loader, "close", None)
        if closer is not None:
            closer()
        if raw is not None:
            self.eval_step(state, self.put_eval_batch(raw))
            warmed += 1
        return warmed

    def _record_dispatch(self, epoch: int, n: int, kk: int, wall_s: float,
                         dispatch_s: float, data_s: float, block_s: float,
                         program_key: tuple) -> None:
        """Per-dispatch telemetry: one small host-side record into the
        recorder's ring buffer (nothing on the device, no sync).  The
        first execution of each compiled program is marked compile=True
        (and mirrored as a first_dispatch_compile span) so aggregation
        can exclude compilation from step-time percentiles."""
        first = program_key not in self._dispatched
        if first:
            self._dispatched.add(program_key)
        tel = self.telemetry
        if tel is None:
            return
        rec = tel.recorder
        rec.record_step(self.global_step, epoch, n, kk, wall_s * 1e3,
                        dispatch_s * 1e3, kk * self.cfg.batch_size,
                        data_ms=data_s * 1e3, block_ms=block_s * 1e3,
                        compile_=first)
        if first:
            rec.record_span("first_dispatch_compile", dispatch_s * 1e3,
                            step=self.global_step)

    def _keep_dispatch_times(self, program_key: tuple) -> bool:
        """Whether this dispatch's telemetry-ONLY clock reads (data
        wait + wall/dispatch decomposition) should be taken at all:
        True when the step record will actually be kept — always for a
        program's first (compile-marked) dispatch, else per the
        --telemetry_every cadence (recorder.next_step_kept).  Sampling
        at this layer is what removes the per-dispatch time.monotonic
        pressure the r12 note flagged; the t_done/t_end reads stay
        unconditional (the live-line blocked accounting needs them
        regardless of telemetry)."""
        tel = self.telemetry
        if tel is None:
            return False
        return (program_key not in self._dispatched
                or tel.recorder.next_step_kept())

    def _prof_before(self, kk: int) -> None:
        prof = self.profiler
        if prof is not None and not prof.done:
            prof.before_dispatch(self.global_step, kk)

    def _prof_after(self, metrics) -> None:
        prof = self.profiler
        if prof is not None and prof.active:
            # the fence (one loss readback) runs only when the window is
            # actually closing — steady-state dispatches never sync
            prof.after_dispatch(self.global_step,
                                fence=lambda: float(metrics["loss"]))

    def _observe_state_placement(self, state: TrainState) -> None:
        """After the run's first dispatch (the epoch loops call this
        exactly once — a per-dispatch ``is None`` check guards it): emit
        the per-chip state byte table (kind "memory", scope "state" —
        ``opt_state_bytes_per_chip`` is ROADMAP's ZeRO-sizing number)
        and fingerprint the live shardings for the epoch-boundary drift
        guard.  The fingerprint is of the POST-step state, i.e. what the
        compiled program's output constraint actually produced — the
        thing r11 measured drifting."""
        from faster_distributed_training_tpu.telemetry import programs
        self._sharding_expect = programs.sharding_fingerprint(state)
        self._sharding_detail = (programs.sharding_table(state)
                                 if self.cfg.debug else None)
        tiers = programs.state_bytes_table(state).get(
            "opt_state_tiers") or {}
        if set(tiers) - {"replicated"}:
            # the ZeRO layout is live: say where the opt-state bytes
            # went (sharded over tp / parked in pinned host memory)
            self.log("[memory] opt state per chip: " + ", ".join(
                f"{t}={v['bytes_per_chip'] / 1e6:.1f}MB"
                f"/{v['leaves']} leaves"
                for t, v in sorted(tiers.items())))
        if self.telemetry is not None:
            # the splat must stay a DIRECT state_bytes_table call —
            # scripts/check_telemetry_schema.py resolves its field
            # vocabulary through _SPLAT_SOURCES by callable name
            self.telemetry.recorder.record_event(
                "memory", **programs.state_bytes_table(state))

    def _check_sharding_drift(self, state: TrainState, epoch: int) -> None:
        """Epoch-boundary re-check of the step-1 sharding fingerprint
        (always-on cheap hash; ``--debug`` keeps the per-leaf table so a
        drift names the leaves that moved).  The r11 bug class: XLA
        re-placed donated params between steps until the output pin
        landed — this guard turns a silent re-placement into a loud
        WARNING + ``memory``/``sharding_drift`` event."""
        if self._sharding_expect is None:
            return
        from faster_distributed_training_tpu.telemetry import programs
        got = programs.sharding_fingerprint(state)
        if got == self._sharding_expect:
            return
        changed: list = []
        if self._sharding_detail is not None:
            now = programs.sharding_table(state)
            before = self._sharding_detail
            changed = sorted(p for p in set(now) | set(before)
                             if now.get(p) != before.get(p))[:8]
        import warnings
        msg = (f"train-state sharding DRIFT at epoch {epoch}: "
               f"fingerprint {self._sharding_expect} -> {got}"
               + (f"; changed leaves (first 8): {changed}" if changed
                  else " (re-run with --debug for the per-leaf diff)")
               + " — something re-placed the state between donated "
                 "steps (the r11 params-drift class; check the train "
                 "step's output sharding pin)")
        warnings.warn(msg, stacklevel=2)
        self.log("[memory] WARNING: " + msg)
        if self.telemetry is not None:
            self.telemetry.recorder.record_event(
                "memory", scope="sharding_drift", epoch=epoch,
                expected=self._sharding_expect, got=got,
                changed_leaves=changed)
        # re-anchor on the drifted placement: ONE incident, one warning
        # (not one per remaining epoch), and the next drift is measured
        # against what the state actually is now
        self._observe_state_placement(state)

    def run_epoch(self, state: TrainState, loader: Optional[Iterable],
                  epoch: int = 0, start_step: int = 0) -> tuple:
        if self.stream is not None:
            return self._run_epoch_stream(state, epoch, start_step)
        if self.resident is not None:
            return self._run_epoch_resident(state, epoch, start_step)
        if self.k > 1:
            return self._run_epoch_fused_host(state, loader, epoch,
                                              start_step)
        acc = MetricAccumulator()
        t0 = time.monotonic()
        metrics = None
        res = self.resilience
        # anomaly sentinel (resilience/sentinel.py): quarantined batch
        # positions — pure (epoch, position) set agreed across hosts via
        # the durable ledger — are consumed-and-skipped below, so a
        # post-rollback replay deterministically excludes the batches a
        # loss spike indicted.  None = zero hot-path overhead.
        sent = getattr(res, "sentinel", None) if res is not None else None
        # keep a handle to the prefetch thread's cancel path BEFORE any
        # wrapping: an abnormal loop exit (preemption, injected fault)
        # must not strand the worker blocked on a full queue
        closer = getattr(loader, "close", None)
        if res is not None and res.faults is not None:
            loader = res.faults.wrap_data(loader)
        if start_step:
            # mid-epoch resume: the checkpoint landed after `start_step`
            # batches of this epoch; the loader's order is a pure function
            # of (seed, epoch), so skipping that many batches replays the
            # remainder exactly.  Batches are materialized to be skipped
            # (the loader API yields, it doesn't seek) — host-side work
            # only, no device steps.
            it = iter(loader)
            for _ in itertools.islice(it, start_step):
                pass
            loader = it
            self.log(f"[resume] epoch {epoch}: skipped {start_step} "
                     f"already-trained batches")
        n = start_step
        last = (t0, start_step)
        self._blocked_since_log = 0.0
        # --log_every N: a live loss/accuracy/throughput line every N
        # steps — the reference's tqdm descriptor observability
        # (resnet50_test.py:560-566) at 1/N its sync cost (tqdm's
        # .item() reads synced EVERY batch; here one device->host
        # readback per N steps, 0 disables).  Emission shares
        # _log_dispatch with the fused paths (kk=1: same line as ever).
        #
        # device_prefetch stages put_batch (H2D transfer ahead of the
        # consuming step — the pin_memory + non_blocking overlap,
        # resnet50_test.py:522, TPU style); uint8 image augmentation runs
        # inside the step itself, keyed by the checkpointed step counter.
        # The while/next form (vs `for batch in ...`) exists so the data
        # wait is observable: time spent blocked on the prefetch queue is
        # a distinct telemetry field from the dispatch itself.
        it = iter(device_prefetch(loader, self.put_batch,
                                  depth=self.cfg.prefetch_depth))
        try:
            while True:
                want = self._keep_dispatch_times(("host", 1))
                t_rec = time.monotonic() if want else 0.0
                try:
                    batch = next(it)
                except StopIteration:
                    break
                if sent is not None and sent.quarantined(epoch, n):
                    # consume-and-skip: the batch is materialized (the
                    # loader API yields, it doesn't seek) but never
                    # dispatched — params/opt-state/step untouched, so
                    # the replayed epoch is bitwise the epoch that never
                    # saw this batch
                    n += 1
                    continue
                t_disp = time.monotonic() if want else 0.0
                self._prof_before(1)
                state, metrics = self.train_step(state, batch)
                t_done = time.monotonic()
                acc.add(metrics)
                n += 1
                self.global_step += 1
                if self._sharding_expect is None:
                    self._observe_state_placement(state)
                self._prof_after(metrics)
                if res is not None:
                    state = self._resilience_hooks(state, epoch, n,
                                                   metrics=metrics,
                                                   group=(n - 1, 1))
                t_end = time.monotonic()
                self._blocked_since_log += t_end - t_done
                self._record_dispatch(
                    epoch, n, 1, t_end - t_rec if want else 0.0,
                    t_done - t_disp if want else 0.0,
                    t_disp - t_rec if want else 0.0,
                    t_end - t_done, ("host", 1))
                last = self._log_dispatch(epoch, n, 1, metrics, last)
        except BaseException:
            # stranded prefetch worker cleanup (Preempted, injected
            # faults, Ctrl-C): cancel + join the loader's thread so an
            # abandoned iterator can never block on a full queue forever
            if closer is not None:
                closer()
            raise
        if metrics is not None:
            # fence with a device->host readback: on some PJRT backends
            # block_until_ready returns at dispatch, not completion
            # (.claude/skills/verify/SKILL.md), which would make the
            # reference-parity epoch timing (resnet50_test.py:519,614)
            # meaninglessly small.
            float(metrics["loss"])
        self._last_epoch_steps = n
        elapsed = time.monotonic() - t0
        return state, acc.summary(), elapsed

    def _log_dispatch(self, epoch: int, n: int, kk: int, metrics,
                      last) -> tuple:
        """log_every at dispatch granularity: emit the live line whenever
        this dispatch crossed a log_every boundary.  `last` is (t, n) of
        the previous emission; returns the updated pair.

        The printed ex/s is STEP throughput, not raw wall throughput:
        checkpoint-blocking and resilience-hook seconds measured by the
        dispatch loop since the last line (_blocked_since_log) are
        subtracted from the window, so a cadence save landing mid-window
        no longer reads as a throughput dip (r12 satellite — the raw
        wall number made every save look like a regression in the live
        log while the epoch summary said otherwise)."""
        log_every = int(self.cfg.log_every or 0)
        if not log_every or (n // log_every) <= ((n - kk) // log_every):
            return last
        last_t, last_n = last
        loss = float(metrics["loss"])
        now = time.monotonic()
        window = max(now - last_t, 1e-9)
        blocked = min(max(self._blocked_since_log, 0.0), window)
        self._blocked_since_log = 0.0
        exs = (n - last_n) * self.cfg.batch_size / max(window - blocked,
                                                       1e-9)
        line = f"[epoch {epoch}] step {n}: loss={loss:.4f}"
        total = metrics.get("total")
        correct = metrics.get("correct")
        if correct is not None and total is not None and float(total):
            line += f" acc={float(correct) / float(total):.4f}"
        line += f" {exs:.0f} ex/s"
        if blocked >= 0.001:
            line += f" (+{blocked:.2f}s blocked)"
        if kk > 1:
            line += f" (K={kk} fused)"
        self.log(line)
        return now, n

    def _run_epoch_fused_host(self, state: TrainState, loader: Iterable,
                              epoch: int, start_step: int = 0) -> tuple:
        """K>1 on the host data path: group K host batches, stack them
        into one leading-K transfer, advance K steps in one dispatch.
        Kept mainly as the CPU-testable/bitwise-comparable twin of the
        device-resident path (and for datasets that outgrow HBM) — the
        zero-host-work pairing is --data_path resident."""
        acc = MetricAccumulator()
        t0 = time.monotonic()
        metrics = None
        res = self.resilience
        sent = getattr(res, "sentinel", None) if res is not None else None
        closer = getattr(loader, "close", None)
        if res is not None and res.faults is not None:
            loader = res.faults.wrap_data(loader)
        it = iter(loader)
        if start_step:
            # mid-epoch resume: saves land on dispatch boundaries, so
            # start_step is a whole number of dispatches; the skipped
            # batches are materialized host-side only (loader API yields)
            for _ in itertools.islice(it, start_step):
                pass
            self.log(f"[resume] epoch {epoch}: skipped {start_step} "
                     f"already-trained batches")
        n = start_step
        last = (t0, start_step)
        self._blocked_since_log = 0.0
        try:
            while True:
                # t_rec unconditional here: the program key (and so the
                # compile-marking decision) needs the group's length,
                # which is only known after the islice this clock read
                # brackets — one read per K steps is already amortized
                t_rec = time.monotonic()
                group = list(itertools.islice(it, self.k))
                if not group:
                    break
                kk_full = len(group)
                if sent is not None:
                    # quarantined positions drop out of the stacked group
                    # (the order cursor still advances by the FULL group,
                    # so the surviving batches are the identical content
                    # at their identical positions); a shorter group
                    # compiles its own kk program like any epoch tail
                    group = [b for j, b in enumerate(group)
                             if not sent.quarantined(epoch, n + j)]
                    if not group:
                        n += kk_full
                        continue
                kk = len(group)
                want = self._keep_dispatch_times(("host", kk))
                batch = self.put_stacked(_stack_host_batches(group))
                t_disp = time.monotonic() if want else 0.0
                self._prof_before(kk)
                state, metrics = self._fused_step(kk)(state, batch)
                t_done = time.monotonic()
                acc.add(metrics)
                n += kk_full
                self.global_step += kk
                if self._sharding_expect is None:
                    self._observe_state_placement(state)
                self._prof_after(metrics)
                if res is not None:
                    state = self._resilience_hooks(
                        state, epoch, n, n_steps=kk, metrics=metrics,
                        group=(n - kk_full, kk_full))
                t_end = time.monotonic()
                self._blocked_since_log += t_end - t_done
                self._record_dispatch(
                    epoch, n, kk, t_end - t_rec if want else 0.0,
                    t_done - t_disp if want else 0.0,
                    t_disp - t_rec if want else 0.0,
                    t_end - t_done, ("host", kk))
                last = self._log_dispatch(epoch, n, kk, metrics, last)
        except BaseException:
            if closer is not None:
                closer()
            raise
        if metrics is not None:
            float(metrics["loss"])     # fence (see run_epoch)
        self._last_epoch_steps = n
        return state, acc.summary(), time.monotonic() - t0

    def _run_epoch_resident(self, state: TrainState, epoch: int,
                            start_step: int = 0) -> tuple:
        """The host-free inner loop: the train split lives on device
        (data/device_resident.py), the epoch order is uploaded once, and
        each iteration is ONE jitted dispatch that gathers, augments and
        trains K consecutive batches.  Steady-state per-dispatch host
        work: a Python loop tick, one scalar arg, and the resilience
        flag poll — no batch bytes, no permutation, no staging.

        Data-iterator fault injection (FDT_FAULT_DATA_AT_BATCH) does not
        apply here — there is no host iterator to wrap; step faults and
        preemption inject exactly as on the host path."""
        resident = self.resident
        acc = MetricAccumulator()
        t0 = time.monotonic()
        metrics = None
        res = self.resilience
        sent = getattr(res, "sentinel", None) if res is not None else None
        # sharded residency re-shards into this epoch's batch-major view
        # here (ONE collective per epoch); the replicated layout returns
        # its static arrays and the order drives the in-graph gather
        data = resident.epoch_arrays(epoch)
        order = resident.epoch_order(epoch)
        n_steps = resident.steps_per_epoch
        if start_step:
            # device-resident resume is a pure SEEK: no host batches are
            # materialized to skip — the next dispatch just starts at
            # start_step's offset into the epoch order
            self.log(f"[resume] epoch {epoch}: seeking to batch "
                     f"{start_step} (device-resident order, no host "
                     f"replay)")
        n = start_step
        last = (t0, start_step)
        self._blocked_since_log = 0.0
        while n < n_steps:
            kk = min(self.k, n_steps - n)
            # quarantine-aware dispatch plan: the common case is the
            # single full segment [(n, kk)] (sent.plan's fast path);
            # after a spike rollback the window splits around the
            # quarantined positions — one fused dispatch per surviving
            # contiguous run, each seeking its own in-graph start, so
            # the epoch-order cursor algebra stays pure
            segs = (sent.plan(epoch, n, kk) if sent is not None
                    else [(n, kk)])
            if not segs:
                n += kk
                continue
            run = sum(l for _, l in segs)
            key = ("resident", segs[-1][1])
            want = self._keep_dispatch_times(key)
            t_rec = time.monotonic() if want else 0.0
            for s, l in segs[:-1]:
                self._prof_before(l)
                state, m = self._fused_step(l, resident)(
                    state, data, order,
                    jax.numpy.asarray(s, jax.numpy.int32))
                acc.add(m)
            s0, l0 = segs[-1]
            self._prof_before(l0)
            state, metrics = self._fused_step(l0, resident)(
                state, data, order,
                jax.numpy.asarray(s0, jax.numpy.int32))
            t_done = time.monotonic()
            acc.add(metrics)
            n += kk
            self.global_step += run
            if self._sharding_expect is None:
                self._observe_state_placement(state)
            self._prof_after(metrics)
            if res is not None:
                state = self._resilience_hooks(state, epoch, n,
                                               n_steps=run,
                                               metrics=metrics,
                                               group=(n - kk, kk))
            t_end = time.monotonic()
            self._blocked_since_log += t_end - t_done
            self._record_dispatch(
                epoch, n, run, t_end - t_rec if want else 0.0,
                t_done - t_rec if want else 0.0, 0.0, t_end - t_done,
                key)
            last = self._log_dispatch(epoch, n, run, metrics, last)
        if metrics is not None:
            float(metrics["loss"])     # fence (see run_epoch)
        self._last_epoch_steps = n
        return state, acc.summary(), time.monotonic() - t0

    def _run_epoch_stream(self, state: TrainState, epoch: int,
                          start_step: int = 0) -> tuple:
        """The beyond-HBM streaming loop: the split lives ON DISK
        (data/stream/), only a fixed window of batches is device-
        resident, and a background thread refills the next buffer
        (disk mmap gather + H2D) while this loop trains the current one
        — each dispatch gathers batch ``n - base`` from the buffer
        in-graph, the sharded-resident batch-major idiom on a
        window-deep leading axis.

        Mid-epoch resume is a pure SEEK (the refill stream just starts
        at ``start_step``; batch content is a pure function of
        (seed, epoch, batch index)).  The window is CLOSED on every
        exit, normal or abnormal — PrefetchIterator's cancel/drain
        lifecycle reclaims the refill thread exactly like the host
        loader's prefetch worker.  Host-iterator fault injection
        (FDT_FAULT_DATA_AT_BATCH) does not apply (no host iterator to
        wrap — the resident path's precedent); step faults and
        preemption inject as everywhere.

        Timing note: the two clock reads bracketing ``buffer_for`` are
        UNCONDITIONAL (unlike the host paths' --telemetry_every-gated
        reads) — the swap wait is the stream-stall metric itself and
        must be measured regardless of whether the step record is kept;
        K>1 amortizes them like every other per-dispatch cost."""
        src = self.stream
        acc = MetricAccumulator()
        t0 = time.monotonic()
        metrics = None
        res = self.resilience
        sent = getattr(res, "sentinel", None) if res is not None else None
        n_steps = src.steps_per_epoch
        if start_step:
            self.log(f"[resume] epoch {epoch}: stream seek to batch "
                     f"{start_step} (window refills start there; no "
                     f"host replay)")
        window = src.epoch_window(epoch, start_step)
        n = start_step
        last = (t0, start_step)
        self._blocked_since_log = 0.0
        # the epoch-INITIAL buffer fill is un-overlapped by construction
        # (nothing trains while the first window loads) — exclude that
        # one wait from the steady-state stall accounting on every
        # epoch, the same way compile-carrying first dispatches are
        epoch_cold = True
        try:
            while n < n_steps:
                t_rec = time.monotonic()
                base, hi, data = window.buffer_for(n)
                t_disp = time.monotonic()
                kk = min(self.k, n_steps - n, hi - n)
                # quarantine-aware plan (see _run_epoch_resident): the
                # in-graph start is buffer-relative, so each segment
                # dispatches at ``s - base``
                segs = (sent.plan(epoch, n, kk) if sent is not None
                        else [(n, kk)])
                if not segs:
                    n += kk
                    continue
                run = sum(l for _, l in segs)
                key = ("stream", segs[-1][1])
                first = key not in self._dispatched
                want = first or self._keep_dispatch_times(key)
                for s, l in segs[:-1]:
                    self._prof_before(l)
                    state, m = self._fused_step(l, src)(
                        state, data, src.dummy_order,
                        jax.numpy.asarray(s - base, jax.numpy.int32))
                    acc.add(m)
                s0, l0 = segs[-1]
                self._prof_before(l0)
                state, metrics = self._fused_step(l0, src)(
                    state, data, src.dummy_order,
                    jax.numpy.asarray(s0 - base, jax.numpy.int32))
                t_done = time.monotonic()
                acc.add(metrics)
                n += kk
                self.global_step += run
                if self._sharding_expect is None:
                    self._observe_state_placement(state)
                self._prof_after(metrics)
                t_step = time.monotonic()
                if res is not None:
                    state = self._resilience_hooks(state, epoch, n,
                                                   n_steps=run,
                                                   metrics=metrics,
                                                   group=(n - kk, kk))
                t_end = time.monotonic()
                self._blocked_since_log += t_end - t_done
                if not first and not epoch_cold:
                    # steady-state stall accounting for stream_stall_pct:
                    # compile-carrying first dispatches AND each epoch's
                    # cold initial fill excluded (telemetry-percentile
                    # rule); the denominator stops BEFORE the resilience
                    # hooks — checkpoint/rendezvous time has its own
                    # overhead metric and must not dilute "fraction of
                    # STEP time blocked on data"
                    self._stream_stall_s += t_disp - t_rec
                    self._stream_wall_s += t_step - t_rec
                epoch_cold = False
                self._record_dispatch(
                    epoch, n, run, t_end - t_rec if want else 0.0,
                    t_done - t_disp if want else 0.0,
                    t_disp - t_rec if want else 0.0,
                    t_end - t_done, key)
                last = self._log_dispatch(epoch, n, run, metrics, last)
        finally:
            # normal AND abnormal exits reclaim the refill thread (the
            # prefetch-closer contract the host paths honor in except:)
            window.close()
        if metrics is not None:
            float(metrics["loss"])     # fence (see run_epoch)
        self._last_epoch_steps = n
        return state, acc.summary(), time.monotonic() - t0

    @property
    def stream_stall_pct(self) -> Optional[float]:
        """Steady-state fraction (percent) of streamed step time spent
        blocked on the data window — the run-level number bench/smoke
        read (None before any steady-state streamed dispatch)."""
        if self.stream is None or self._stream_wall_s <= 0:
            return None
        return 100.0 * self._stream_stall_s / self._stream_wall_s

    def _resilience_hooks(self, state: TrainState, epoch: int,
                          step_in_epoch: int, n_steps: int = 1,
                          metrics=None, group=None) -> TrainState:
        """Per-dispatch resilience work, in hazard order: injected
        faults first (a crash preempts bookkeeping, like the real
        thing), then the sentinel's loss-spike observation, then the
        cross-host-agreed preemption decision (emergency save + clean
        Preempted exit), then cadence checkpointing.  `n_steps` = train
        steps this dispatch advanced (K under the fused dispatch) so
        the goodput step counter stays per-STEP while the polling stays
        per-dispatch.  `metrics`/`group` feed the full-mode sentinel:
        the dispatch's metrics dict and the (start, count) epoch-order
        window it covered — quarantined positions inside the window
        were NOT dispatched; Sentinel.observe re-filters them."""
        res = self.resilience
        step = self.global_step
        res.goodput.count("steps", n_steps)
        if res.faults is not None:
            res.faults.on_step(step)    # may SIGTERM this process / raise
        sent = getattr(res, "sentinel", None)
        if (sent is not None and sent.mode == "full" and metrics is not None
                and group is not None):
            # the ONE per-dispatch device sync --sentinel full buys
            # (bench's sentinel_overhead_pct): the dispatch loss is a
            # replicated global scalar, so every host reads the same
            # value, reaches the same spike verdict, and writes the
            # same quarantine ledger — no cross-host protocol needed.
            # Runs BEFORE the checkpoint hooks so the newest checkpoint
            # always predates the quarantined dispatch and the
            # rollback-replay actually excises it.  May raise LossSpike
            # (restartable; the supervisor replays from the newest
            # valid checkpoint with the indicted batches quarantined).
            loss = float(jax.device_get(metrics["loss"]))
            if res.faults is not None:
                loss = res.faults.perturb_loss(step, loss)
            sent.observe(epoch, group[0], group[1], loss, step)
        if res.coordinator is not None:
            # pod health: feed the step clock to the local watchdog and
            # (cadence-gated) poll the peers' FAIL/heartbeat markers —
            # raises PeerFailure/StepTimeout, both restartable, so the
            # whole pod re-enters the supervisor together.  BEFORE the
            # preemption/save hooks: a dead peer makes the collective
            # emergency save (and the sharded commit barrier) unreachable,
            # so failure observation must preempt anything collective.
            # Multi-slice (r14): a failure confined to one foreign slice
            # PARKS here (bounded await_readmission hold) instead of
            # raising, and a rejoining slice drives its catch-up
            # handshake here.
            res.coordinator.check(step)
            # a completed re-admission re-anchors the checkpoint cadence
            # at the pod's agreed release step, so every host's NEXT
            # save tick is the same pure function of the step sequence
            # again (the two-phase commit barrier depends on that)
            align = res.coordinator.consume_cadence_align()
            if align is not None and res.manager is not None:
                res.manager.align_cadence(align)
        # blocking checkpoint work below (emergency save; cadence saves
        # that DRAIN a prior write's commit barrier, up to
        # commit_timeout_s) is legitimate step-thread stalling — suspend
        # the local hang watchdog so a healthy host is never SIGKILLed
        # mid-save (heartbeats keep running; a wedged save is bounded by
        # its own timeout)
        pause = (res.coordinator.pause_watch()
                 if res.coordinator is not None else contextlib.nullcontext())
        with pause:
            if res.preemption is not None and res.preemption.should_stop(step):
                from faster_distributed_training_tpu.resilience import (
                    Preempted)
                res.goodput.count("preemptions")
                if res.manager is not None:
                    # the manager bills the save's duration into the
                    # emergency_save_s segment itself — wrapping it in
                    # goodput.timed here too would double-count the badput
                    res.manager.save(state, step, epoch=epoch,
                                     step_in_epoch=step_in_epoch,
                                     best_acc=self.best_acc, sync=True,
                                     segment="emergency_save_s")
                    self.log(f"[preempt] emergency checkpoint committed at "
                             f"step {step} (epoch {epoch}); exiting cleanly")
                else:
                    self.log(f"[preempt] no checkpoint manager configured — "
                             f"exiting at step {step} WITHOUT an emergency "
                             f"save (set --checkpoint_every to get one)")
                raise Preempted(f"preempted at step {step}", state=state,
                                step=step)
            if res.manager is not None and not (
                    res.coordinator is not None
                    and res.coordinator.saves_suspended):
                # saves_suspended: during a slice's rejoin catch-up (or
                # a survivor's post-hold catch-up) a cadence tick taken
                # here could never commit — the rest of the pod is not
                # taking it — and would only burn the commit-barrier
                # timeout; the cadence re-aligns at the release step
                res.manager.maybe_save(state, step, epoch=epoch,
                                       step_in_epoch=step_in_epoch,
                                       best_acc=self.best_acc)
        return state

    def _save_epoch_checkpoint(self, name: str, state: TrainState,
                               epoch: int) -> None:
        """Epoch-level save (rolling last-good / best-acc), goodput-timed
        when the resilience bundle is active.

        fs-SIMULATED pods (FDT_POD_INDEX seam): jax is single-process
        per simulated host, so this orbax save is NOT collective — every
        host computes the identical full state and concurrent writers on
        one shared path would race mid-rename.  Host 0 writes it alone;
        a REAL pod's save is collective and every host must enter."""
        res = self.resilience
        if (res is not None and res.pod_simulated and res.pod_count > 1
                and res.pod_index != 0):
            return
        if res is not None:
            with res.goodput.timed("checkpoint_blocking_s"):
                ckpt.save_checkpoint(self.cfg.checkpoint_dir, name, state,
                                     epoch, self.best_acc)
            res.goodput.count("saves")
        else:
            ckpt.save_checkpoint(self.cfg.checkpoint_dir, name, state,
                                 epoch, self.best_acc)

    def evaluate(self, state: TrainState, loader: Iterable) -> Dict[str, float]:
        if self._offload_shardings is not None:
            # one host->device transfer per eval epoch (state is constant
            # across eval batches) instead of an in-graph fetch per batch —
            # and ONLY of the leaves eval reads (params + batch_stats);
            # opt_state stays on pinned_host, which is the point of offload
            dev = lambda sh: sh.with_memory_kind("device")  # noqa: E731
            state = state.replace(
                params=jax.tree.map(
                    lambda x, sh: jax.device_put(x, dev(sh)),
                    state.params, self._offload_shardings.params),
                batch_stats=jax.tree.map(
                    lambda x, sh: jax.device_put(x, dev(sh)),
                    state.batch_stats,
                    self._offload_shardings.batch_stats))
        acc = MetricAccumulator()
        t0 = time.monotonic()
        with spans.span("eval", step=self.global_step):
            for batch in device_prefetch(loader, self.put_eval_batch,
                                         depth=self.cfg.prefetch_depth):
                acc.add(self.eval_step(state, batch))
            summary = acc.summary()   # device->host sync fences the timing
        elapsed = time.monotonic() - t0
        # eval throughput made visible per epoch (VERDICT r5 #7): the
        # routing changes this repo makes at eval shapes must not be
        # able to regress inference silently — bench.py tracks the
        # compiled eval step (resnet_eval_img_per_sec_* /
        # transformer_eval_ex_per_sec_*) under the regression guard,
        # and this line surfaces the full-pipeline number per run.
        total = summary.get("total_sum")
        if total:
            self.log(f"[eval] {total:.0f} samples in {elapsed:.1f}s "
                     f"({total / max(elapsed, 1e-9):.0f} ex/s)")
        return summary

    def fit(self, state: TrainState, train_loader: LoaderFn,
            eval_loader: LoaderFn, ckpt_name: str = "ckpt",
            start_epoch: int = 0, start_step_in_epoch: int = 0
            ) -> TrainState:
        cfg = self.cfg
        self.recoveries = 0
        consecutive_failures = 0
        recover_name = ckpt_name + "_last"
        res = self.resilience
        # re-anchor the host step mirror to the device truth (one sync,
        # once per fit — the restored step after a supervisor restart)
        self.global_step = int(jax.device_get(state.step))
        # a supervisor restart enters fit with a freshly-restored (host)
        # state whose placement legitimately differs: the drift guard
        # re-anchors after the next dispatch instead of comparing across
        # a restore
        self._sharding_expect = None
        self._sharding_detail = None
        # supervisor restarts re-enter fit on the SAME Trainer and replay
        # from the restored epoch: drop any history entries the replay
        # will re-append, or plots/returned history would duplicate the
        # rolled-back epochs
        for series in self.history.values():
            del series[start_epoch:]
        if res is not None:
            res.goodput.start()
        if cfg.auto_recover:
            # Rollback target is a ROLLING last-good snapshot, separate from
            # the best-accuracy checkpoint (which can be arbitrarily stale
            # after a plateau).  Written unconditionally here so (a) a
            # restore point always exists — once an fp32 epoch goes
            # non-finite the live params are poisoned, "retry from current
            # state" can never converge — and (b) a stale snapshot from a
            # previous run in the same dir can never be resurrected.
            self._save_epoch_checkpoint(recover_name, state, start_epoch - 1)
        epoch = start_epoch
        resume_step = start_step_in_epoch
        while epoch < cfg.epochs:
            # resident mode never builds a host train loader (it would
            # spin up a prefetch thread and materialize batches nobody
            # consumes); eval below stays on the host path either way.
            # The pod step watchdog is armed ONLY around the dispatch
            # loop: eval/restore/checkpoint phases have no step clock to
            # advance and must not be able to false-trigger a hang
            # escalation (heartbeats keep running regardless).
            watch = (res.coordinator.watch_steps()
                     if res is not None and res.coordinator is not None
                     else contextlib.nullcontext())
            with watch:
                state, train_m, elapsed = self.run_epoch(
                    state,
                    None if (self.resident is not None
                             or self.stream is not None)
                    else train_loader(epoch),
                    epoch, start_step=resume_step)
            resumed_mid_epoch, resume_step = resume_step, 0
            if res is not None:
                # in-graph bad-step guard accounting: bad_steps was
                # summed on device across the epoch's dispatches and
                # rode the normal metrics fetch — counting it here costs
                # no extra sync (r24: the guard's verdict is read
                # where the epoch summary is already host-side)
                bad = train_m.get("bad_steps_sum")
                if bad:
                    res.goodput.count("skipped_steps",
                                      int(round(float(bad))))
            # Failure detection (a deliberate addition — the reference's
            # only recovery is manual re-launch with --resume, SURVEY.md
            # §5): a non-finite epoch loss means the run is poisoned; roll
            # back to the last good checkpoint and keep going.
            if "loss" not in train_m:
                if resumed_mid_epoch:
                    # the resume checkpoint landed after this epoch's LAST
                    # train step (the pre-eval window): nothing to replay —
                    # fall through to eval/bookkeeping and move on
                    self.log(f"[resume] epoch {epoch} was already fully "
                             f"trained at checkpoint time; running its "
                             f"eval and continuing")
                else:
                    # zero batches ran — a data/config problem (dataset
                    # smaller than one per-host batch, bad shard), not
                    # divergence; letting auto_recover roll back would burn
                    # recovery slots on an error a retry can never fix
                    raise RuntimeError(
                        f"epoch {epoch} produced no batches — dataset too "
                        f"small for batch_size={cfg.batch_size} x "
                        f"{jax.process_count()} process(es)?")
            if ("loss" in train_m and cfg.auto_recover
                    and not host_finite(train_m.get("loss"))):
                consecutive_failures += 1
                if consecutive_failures > cfg.max_recoveries:
                    raise RuntimeError(
                        f"training diverged {consecutive_failures} times in "
                        f"a row (epoch {epoch}); giving up")
                state, ck_epoch, _ = ckpt.restore_checkpoint(
                    cfg.checkpoint_dir, recover_name, state)
                # 2D/offload policies: put the restored (host numpy)
                # leaves back on their shards instead of letting the
                # next jit place uncommitted arrays
                from faster_distributed_training_tpu.parallel.placement \
                    import place_on_shardings
                state = place_on_shardings(state, self._state_shardings)
                # rollback moved state.step — re-anchor the host mirror
                self.global_step = int(jax.device_get(state.step))
                # ...and the sharding-drift baseline: the restored state's
                # placement is a fresh re-placement, not a drift
                self._sharding_expect = None
                self._sharding_detail = None
                self.log(f"[recover] non-finite loss at epoch {epoch}; "
                         f"restored last-good state from epoch {ck_epoch}, "
                         f"retrying")
                if self.telemetry is not None:
                    # rolled-back epochs emit no `epoch` event (their
                    # loss never counted) but the rollback itself is
                    # part of the run's story
                    self.telemetry.recorder.record_event(
                        "rollback", epoch=epoch,
                        restored_epoch=int(ck_epoch),
                        step=self.global_step)
                self.recoveries += 1
                # epoch += 1 gives the retry a fresh data order.  Note the
                # restore rolls state.step (and the optax schedule position
                # inside opt_state) back to the snapshot's value, so the
                # retried epoch trains at the snapshot's LR — the epoch
                # counter and the schedule deliberately diverge by the
                # rolled-back amount.
                epoch += 1
                continue
            consecutive_failures = 0
            # epoch-boundary re-check of the step-1 sharding fingerprint
            # (the always-on cheap hash; a drift warns loudly + lands a
            # memory/sharding_drift event)
            self._check_sharding_drift(state, epoch)
            if cfg.auto_recover:
                # refresh the rolling last-good snapshot after every finite
                # epoch, so recovery rolls back one epoch, not to the last
                # best-accuracy improvement
                self._save_epoch_checkpoint(recover_name, state, epoch)
            if cfg.debug:
                self._debug_checks(state, epoch)
            test_m = self.evaluate(state, eval_loader(epoch))
            if getattr(cfg, "task", "cls") == "lm":
                # LM headline: perplexity of the exact token-weighted
                # epoch loss (train/metrics.perplexity), train and eval
                from faster_distributed_training_tpu.train.metrics import (
                    perplexity)
                if host_finite(train_m.get("loss")):
                    train_m["perplexity"] = perplexity(train_m["loss"])
                if host_finite(test_m.get("loss")):
                    test_m["perplexity"] = perplexity(test_m["loss"])
                self.history["train_ppl"].append(
                    train_m.get("perplexity", 0.0))
                self.history["test_ppl"].append(
                    test_m.get("perplexity", 0.0))
            self.history["train_acc"].append(train_m.get("accuracy", 0.0))
            self.history["train_loss"].append(train_m.get("loss", 0.0))
            self.history["test_acc"].append(test_m.get("accuracy", 0.0))
            self.history["test_loss"].append(test_m.get("loss", 0.0))
            self.history["epoch_time"].append(elapsed)
            peak = peak_memory_bytes()
            # per-host HBM peak rides the epoch summary AND the
            # telemetry stream (r12 satellite — peak_memory_bytes
            # existed but was only consulted ad hoc); None on backends
            # without runtime memory stats (CPU) stays None in history
            self.history["peak_mem_bytes"].append(peak)
            self.log(
                f"epoch {epoch}: train_loss={train_m.get('loss', 0):.4f} "
                f"train_acc={train_m.get('accuracy', 0):.4f} "
                f"test_loss={test_m.get('loss', 0):.4f} "
                f"test_acc={test_m.get('accuracy', 0):.4f} "
                f"time={elapsed:.1f}s"
                + (f" test_ppl={test_m['perplexity']:.2f}"
                   if "perplexity" in test_m else "")
                + (f" peak_mem={peak / 1e6:.0f}MB" if peak else ""))
            # best-acc-gated full-state checkpoint (resnet50_test.py:663-675)
            if test_m.get("accuracy", 0.0) > self.best_acc:
                self.best_acc = test_m["accuracy"]
                self._save_epoch_checkpoint(ckpt_name, state, epoch)
            if res is not None:
                self.log("[goodput] " + format_goodput(res.goodput))
            if self.telemetry is not None:
                rec = self.telemetry.recorder
                trained = self._last_epoch_steps - resumed_mid_epoch
                ev = {"epoch": epoch, "steps": self._last_epoch_steps,
                      "trained_steps": trained, "wall_s": round(elapsed, 3)}
                if "loss" in train_m:
                    ev["loss"] = train_m["loss"]
                if "accuracy" in train_m:
                    ev["accuracy"] = train_m["accuracy"]
                if trained and elapsed:
                    ev["ex_s"] = round(trained * self.cfg.batch_size
                                       / elapsed, 1)
                if "loss" in test_m:
                    ev["eval_loss"] = test_m["loss"]
                if "accuracy" in test_m:
                    ev["eval_accuracy"] = test_m["accuracy"]
                if "perplexity" in train_m:
                    ev["perplexity"] = train_m["perplexity"]
                if "perplexity" in test_m:
                    ev["eval_perplexity"] = test_m["perplexity"]
                if peak:
                    ev["peak_mem_bytes"] = int(peak)
                rec.record_event("epoch", **ev)
                stats = memory_watermarks()
                if stats is not None:
                    # per-epoch device memory watermark as a memory-kind
                    # event (peak + current bytes in use — backends
                    # without runtime memory stats, e.g. CPU, skip it;
                    # the compile-time memory_analysis in the program
                    # events covers them statically)
                    rec.record_event("memory", scope="epoch", epoch=epoch,
                                     peak_bytes=stats["peak_bytes"],
                                     bytes_in_use=stats["bytes_in_use"])
                if res is not None:
                    # goodput/MTTR snapshot in the same stream — one
                    # file tells the whole run's story
                    rec.record_event("goodput", **res.goodput.summary())
                # flush + epoch marker + (process 0) the pod fold:
                # run-level p50/p95/p99 and the straggler line
                self.telemetry.end_epoch(epoch)
            epoch += 1
        if self.profiler is not None:
            # a --profile_steps window the run never reached the end of
            # (B past the last step) still lands its capture
            self.profiler.close()
        if res is not None and res.manager is not None:
            # drain any in-flight async save so a clean exit never leaves
            # an uncommitted newest checkpoint behind
            res.manager.wait()
        return state

    def _debug_checks(self, state: TrainState, epoch: int) -> None:
        """--debug: the reference's never-enabled NGD `_self_test`
        (ngd_optimizer.py:46,330-345), run for real once per epoch."""
        from faster_distributed_training_tpu.optim.ngd import (
            NGDHyperParams, self_test_all)

        cfg = self.cfg
        res = self_test_all(state.opt_state, NGDHyperParams(
            alpha=cfg.ngd_alpha, rank=cfg.ngd_rank,
            update_period=cfg.ngd_update_period, eta=cfg.ngd_eta))
        if res["checked"] and not res["ok"]:
            self.log(f"[debug] epoch {epoch}: NGD Fisher invariant "
                     f"violations: {res['failures']}")
        elif res["checked"]:
            self.log(f"[debug] epoch {epoch}: NGD invariants OK "
                     f"({res['checked']} factor states)")

    def maybe_resume(self, state: TrainState, ckpt_name: str = "ckpt"
                     ) -> tuple:
        """--resume: restore full state if a checkpoint exists."""
        if self.cfg.resume and ckpt.has_checkpoint(self.cfg.checkpoint_dir,
                                                   ckpt_name):
            state, epoch, best = ckpt.restore_checkpoint(
                self.cfg.checkpoint_dir, ckpt_name, state)
            self.best_acc = best
            self.log(f"resumed from epoch {epoch} (best_acc={best:.4f})")
            return state, epoch + 1
        return state, 0
