"""Full-state checkpointing via orbax.

The reference saves only ``{net, acc, epoch}`` on rank 0 gated on best
test accuracy (resnet50_test.py:663-675) and loses optimizer, scheduler,
GradScaler and NGD Fisher state across resumes (SURVEY.md §5).  Here the
complete ``TrainState`` round-trips: params, BN stats, optimizer state
(including every ``OnlineNaturalGradientState``), loss scale, step and
the RNG root — plus ``best_acc``/``epoch`` metadata.  Saves are
process-0-gated for the metadata and collective for arrays (orbax is
multi-host aware)."""

from __future__ import annotations

import json
import os
import re
import time
import warnings
from typing import Any, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from faster_distributed_training_tpu.resilience import storage as storage_mod
from faster_distributed_training_tpu.train.state import TrainState

_META = "meta.json"
# Commit marker: written LAST (atomically, process 0) after the arrays
# AND meta.json are durably on disk.  Its presence is the "this
# checkpoint is restorable" contract has_checkpoint() and the resilience
# manager check — a bare directory (preemption mid-write) is never it.
_COMMIT = "COMMIT"
# orbax's own completion file: Checkpointer.save() stages into a tmp dir
# and renames, writing this marker inside — pre-r7 checkpoints (incl.
# the committed legacy fixture) carry it but not ours.
_OCP_METADATA = "_CHECKPOINT_METADATA"

_LEGACY_LAYER_KEY = re.compile(r"^(attn|ffn|ln_attn|ln_ffn)_(\d+)$")


def _backend(backend: Optional["storage_mod.StorageBackend"]
             ) -> "storage_mod.StorageBackend":
    """Resolve the storage backend every marker/meta/shard write routes
    through (r14): None -> the POSIX default, byte-compatible with every
    pre-r14 checkpoint directory.  The orbax ARRAY write of the
    single-file path is the one seam that stays POSIX-only (orbax owns
    its own staged-rename atomicity); object-store runs therefore use
    the sharded two-phase path, which the manager forces for any
    non-posix backend."""
    return backend if backend is not None else storage_mod.posix_backend()


def _write_json_atomic(path: str, obj: Any) -> None:
    """Atomic JSON publish: a preemption mid-write can never leave a
    torn file at `path` — the previous content (or absence) survives
    intact.  Delegates to the POSIX storage backend (tmp + replace +
    fsync, the historic idiom, now owned by resilience/storage.py)."""
    storage_mod.posix_backend().put_json(path, obj)


def migrate_legacy_transformer_params(model_params: Any,
                                      n_heads: int = 8) -> Any:
    """One-time key remap for pre-round-3 transformer checkpoints
    (ADVICE r3 #1).

    Round 3 restructured the transformer param tree: the flat
    ``attn_{i}/query|key|value|out``, ``ffn_{i}``, ``ln_attn_{i}``,
    ``ln_ffn_{i}`` modules became per-layer ``layer_{i}/...`` and the
    three (d_model, d_model) Q/K/V kernels were fused into ONE
    (d_model, 3, h, d_k) ``qkv`` DenseGeneral kernel.  This folds the
    legacy leaves into the fused layout — the math is identical, so a
    migrated checkpoint reproduces the old model's forward exactly.

    Returns the params unchanged when no legacy keys are present.
    """
    if not isinstance(model_params, dict) or not any(
            _LEGACY_LAYER_KEY.match(k) for k in model_params):
        return model_params
    out = {k: v for k, v in model_params.items()
           if not _LEGACY_LAYER_KEY.match(k)}
    layers = sorted({int(m.group(2)) for k in model_params
                     if (m := _LEGACY_LAYER_KEY.match(k))})
    for i in layers:
        attn = dict(model_params[f"attn_{i}"])
        qp, kp, vp = attn.pop("query"), attn.pop("key"), attn.pop("value")
        d_model = np.shape(qp["kernel"])[0]
        # the fused kernel is laid out (d_model, 3, h, d_k); a legacy
        # checkpoint doesn't record h — the caller supplies it (the
        # restore path reads it off the new-model template)
        h = n_heads
        d_k = d_model // h
        kern = np.stack([np.asarray(qp["kernel"]), np.asarray(kp["kernel"]),
                         np.asarray(vp["kernel"])], axis=1)
        qkv = {"kernel": kern.reshape(d_model, 3, h, d_k)}
        if "bias" in qp:
            qkv["bias"] = np.stack(
                [np.asarray(qp["bias"]), np.asarray(kp["bias"]),
                 np.asarray(vp["bias"])], axis=0).reshape(3, h, d_k)
        out[f"layer_{i}"] = {
            "attn": {"qkv": qkv, **attn},
            "ffn": model_params[f"ffn_{i}"],
            "ln_attn": model_params[f"ln_attn_{i}"],
            "ln_ffn": model_params[f"ln_ffn_{i}"],
        }
    return out


def _ckpt_dir(checkpoint_dir: str, name: str) -> str:
    return os.path.abspath(os.path.join(checkpoint_dir, name))


def _state_pytree(state: TrainState) -> Any:
    """The checkpointable (non-static) part of TrainState."""
    return {"step": state.step, "params": state.params,
            "batch_stats": state.batch_stats, "opt_state": state.opt_state,
            "loss_scale": state.loss_scale, "rng": state.rng}


def opt_state_layout(state) -> dict:
    """{tier: leaf count} summary of where the optimizer state lives
    (sharded / replicated / offloaded — telemetry.programs.leaf_tier's
    vocabulary).  Written into checkpoint meta so an operator can see
    which ZeRO layout produced a checkpoint; restores compare it against
    the live template's layout and LOG a mismatch (values interchange
    freely across layouts — the restore templates re-place them — so a
    change is informational, never an error).  {} unless some leaf is
    actually sharded or offloaded: a fully replicated (or plain-numpy
    host snapshot) layout is the pre-r20 status quo, and recording it
    would perturb meta for every 1D checkpoint ever written.  {} on any
    failure too: layout telemetry must never block a save."""
    try:
        from faster_distributed_training_tpu.telemetry.programs import (
            leaf_tier)
        tiers: dict = {}
        for leaf in jax.tree.leaves(state.opt_state):
            t = leaf_tier(leaf)
            tiers[t] = tiers.get(t, 0) + 1
        if not (tiers.get("sharded") or tiers.get("offloaded")):
            return {}
        return tiers
    except Exception:
        return {}


def params_layout(state) -> dict:
    """opt_state_layout's twin over state.params (r23 per-stage
    residency: pp-sharded params are the first layout where PARAMS can
    be sharded without fsdp).  Same contract: {} unless some leaf is
    actually sharded/offloaded, {} on any failure — meta stays
    byte-identical for every replicated-param checkpoint ever
    written."""
    try:
        from faster_distributed_training_tpu.telemetry.programs import (
            leaf_tier)
        tiers: dict = {}
        for leaf in jax.tree.leaves(state.params):
            t = leaf_tier(leaf)
            tiers[t] = tiers.get(t, 0) + 1
        if not (tiers.get("sharded") or tiers.get("offloaded")):
            return {}
        return tiers
    except Exception:
        return {}


def save_checkpoint(checkpoint_dir: str, name: str, state: TrainState,
                    epoch: int, best_acc: float,
                    extra_meta: Optional[dict] = None) -> str:
    """Overwrites `<checkpoint_dir>/<name>` with the full state.

    `state` may be a real TrainState or any object exposing the same
    checkpointable attributes with HOST (numpy) leaves — the resilience
    manager's async path saves a device_get snapshot this way."""
    path = _ckpt_dir(checkpoint_dir, name)
    layout = opt_state_layout(state)
    players = params_layout(state)
    return save_pytree_checkpoint(
        path, _state_pytree(state),
        {"epoch": int(epoch), "best_acc": float(best_acc),
         **({"opt_state_layout": layout} if layout else {}),
         **({"params_layout": players} if players else {}),
         **(extra_meta or {})})


def save_pytree_checkpoint(path: str, tree: Any, meta: dict,
                           backend=None) -> str:
    """Shared save core: orbax arrays (atomic — staged + renamed), then
    meta.json, then the COMMIT marker, both atomically and in that order
    so the marker's presence implies everything before it is complete.
    A preemption at ANY point leaves either the previous checkpoint
    intact or an uncommitted directory has_checkpoint() rejects.  The
    orbax array write is inherently POSIX (orbax stages + renames
    itself); the meta/COMMIT markers route through the backend — on a
    non-posix backend use the sharded two-phase path instead (the
    manager does)."""
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        ckptr.save(path, tree, force=True)
    if jax.process_index() == 0:
        b = _backend(backend)
        b.put_json(os.path.join(path, _META), meta)
        b.put_json(os.path.join(path, _COMMIT),
                   {"committed_unix_time": round(time.time(), 3)})
    return path


def read_checkpoint_meta(checkpoint_dir: str, name: str,
                         backend=None) -> dict:
    """The meta.json contents ({} when absent/torn — a torn file is
    impossible post-r7, but pre-r7 checkpoints wrote it non-atomically)."""
    meta_path = os.path.join(_ckpt_dir(checkpoint_dir, name), _META)
    return _backend(backend).read_json(meta_path) or {}


def restore_checkpoint(checkpoint_dir: str, name: str, state: TrainState
                       ) -> Tuple[TrainState, int, float]:
    """Restore into the (freshly created) `state` template.  Returns
    (state, start_epoch, best_acc) — the --resume path
    (resnet50_test.py:470-475,680-690), but with optimizer/Fisher/RNG
    state intact."""
    path = _ckpt_dir(checkpoint_dir, name)
    template = _state_pytree(state)
    try:
        with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
            restored = ckptr.restore(
                path, args=ocp.args.StandardRestore(template))
    except Exception as structural:
        # Possibly an UNTIED-lm-head checkpoint (r18 layout) restoring
        # into a tied model (r19 default: no lm_head param): drop the
        # separate projection, warned.  Else possibly a pre-round-3
        # checkpoint (flat attn_{i}/query|key|value layout):
        # raw-restore, remap the param tree, and re-validate.
        # Optimizer state mirrors the param structure and cannot be
        # meaningfully folded (Fisher factors/momenta were tracked per
        # UNFUSED kernel), so it restarts fresh — loudly.  The raw
        # restore runs ONCE; both shims consume the same tree.
        raw = _raw_restore_any(path)
        restored = _restore_untied_lm_head(path, template, raw=raw)
        if restored is None:
            restored = _restore_legacy(path, template, structural,
                                       raw=raw)
    meta = read_checkpoint_meta(checkpoint_dir, name)
    saved_layout = meta.get("opt_state_layout")
    live_layout = opt_state_layout(state)
    if saved_layout and live_layout and saved_layout != live_layout:
        print(f"[ckpt] opt-state layout changed across restore: "
              f"checkpoint was written with {saved_layout}, restoring "
              f"into {live_layout} — values re-placed by the template "
              f"shardings (ZeRO<->replicated interchange)")
    saved_players = meta.get("params_layout")
    live_players = params_layout(state)
    if saved_players and live_players and saved_players != live_players:
        print(f"[ckpt] params layout changed across restore: "
              f"checkpoint was written with {saved_players}, restoring "
              f"into {live_players} — values re-placed by the template "
              f"shardings (pp-residency<->replicated interchange)")
    epoch = int(meta.get("epoch", 0))
    best_acc = float(meta.get("best_acc", 0.0))
    state = state.replace(
        step=restored["step"], params=restored["params"],
        batch_stats=restored["batch_stats"], opt_state=restored["opt_state"],
        loss_scale=state.loss_scale.__class__(*restored["loss_scale"]),
        rng=restored["rng"])
    return state, epoch, best_acc


def _raw_restore_numpy(path: str) -> Any:
    """Raw-restore a checkpoint as NUMPY leaves, ignoring the device
    shardings recorded at save time (topology-independent)."""
    ckptr = ocp.PyTreeCheckpointer()
    meta = ckptr.metadata(path)
    tree = getattr(getattr(meta, "item_metadata", meta), "tree", None)
    if not isinstance(tree, dict):
        raise ValueError(f"unreadable checkpoint metadata at {path}")
    ra = jax.tree_util.tree_map(
        lambda m: ocp.RestoreArgs(restore_type=np.ndarray), tree)
    return ckptr.restore(path, args=ocp.args.PyTreeRestore(restore_args=ra))


def _raw_restore_any(path: str) -> Optional[Any]:
    """The shared raw-restore chain of the compat shims: type-erased
    numpy first (old checkpoints carry the writing machine's device
    shardings), plain restores as same-topology fallbacks.  None when
    every attempt fails (corrupt checkpoint).  Called ONCE per
    structural mismatch — both shims consume the same tree instead of
    re-reading a multi-GB checkpoint from storage twice."""
    for restore in (_raw_restore_numpy,
                    lambda p: ocp.StandardCheckpointer().restore(p),
                    lambda p: ocp.PyTreeCheckpointer().restore(p)):
        try:
            return restore(path)
        except Exception:
            continue
    return None


def _restore_legacy(path: str, template: Any, structural: Exception,
                    raw: Any = None) -> Any:
    """Raw-restore a structurally mismatched checkpoint, migrate the
    legacy transformer param layout, and fit it onto `template`.  Leaves
    that still don't line up re-raise the original error."""
    # Raw-restore semantics documented on _raw_restore_any (proven
    # against the committed round-2 fixture tests/fixtures/
    # legacy_transformer, saved on a TPU v5e); restore_checkpoint
    # passes the already-read tree in so the chain runs once.
    if raw is None:
        raw = _raw_restore_any(path)
    if raw is None:
        raise structural       # corrupt checkpoint: surface the ORIGINAL error
    params = raw.get("params") if isinstance(raw, dict) else None
    if not isinstance(params, dict) or "model" not in params:
        raise structural
    if not (isinstance(params["model"], dict)
            and any(_LEGACY_LAYER_KEY.match(k) for k in params["model"])):
        # structurally mismatched but NOT the known legacy layout — this
        # fallback is only for pre-round-3 trees, not arbitrary mismatches
        raise structural
    n_heads = 8
    try:
        tmpl_model = template["params"]["model"]
        layer0 = next(v for k, v in sorted(tmpl_model.items())
                      if k.startswith("layer_"))
        n_heads = int(np.shape(layer0["attn"]["qkv"]["kernel"])[2])
    except (StopIteration, KeyError, TypeError, IndexError):
        # a wrong head count would reshape the fused Q/K/V kernels
        # incorrectly WITHOUT a shape error (d_model, 3, h, d_k) is
        # size-equal for any h dividing d_model — never guess silently
        # (VERDICT r4 #4)
        warnings.warn(
            "legacy-checkpoint migration could not read n_heads from the "
            f"restore template (no layer_*/attn/qkv kernel found); "
            f"assuming n_heads={n_heads}.  If the checkpointed model used "
            "a different head count the migrated Q/K/V kernels will be "
            "SILENTLY mis-reshaped — pass a template built from the real "
            "model configuration.", stacklevel=3)
    migrated = dict(params)
    migrated["model"] = migrate_legacy_transformer_params(
        params["model"], n_heads)
    try:
        rebuilt = _fit_leaves(migrated, template["params"], "params")
    except ValueError:
        raise structural
    warnings.warn(
        "restored a pre-round-3 checkpoint: transformer Q/K/V kernels "
        "were folded into the fused qkv layout (forward-exact), but the "
        "OPTIMIZER state (momenta / Fisher factors / dual averages) "
        "tracked the unfused kernels and cannot be folded — it restarts "
        "fresh, as do the RNG root and loss scale.  Expect a short "
        "re-warmup of optimizer statistics.", stacklevel=3)
    return {"step": raw.get("step", template["step"]),
            "params": rebuilt,
            "batch_stats": _fit_or_template(
                raw.get("batch_stats"), template["batch_stats"],
                "batch_stats"),
            "opt_state": template["opt_state"],
            "loss_scale": template["loss_scale"],
            "rng": template["rng"]}


def _drop_lm_head(tree: Any) -> Any:
    """The tree minus every ``lm_head`` dict subtree (any depth) — the
    untied→tied compat prune.  lm_head only ever appears as a dict key
    (flax module name), so list/tuple indices never shift."""
    if isinstance(tree, dict):
        return {k: _drop_lm_head(v) for k, v in tree.items()
                if k != "lm_head"}
    if isinstance(tree, (list, tuple)):
        return type(tree)(_drop_lm_head(v) for v in tree)
    return tree


def _restore_untied_lm_head(path: str, template: Any,
                            raw: Any = None) -> Optional[Any]:
    """Compat shim for the r19 tied LM head (ROADMAP r18 follow-on (c)):
    an UNTIED checkpoint (separate ``lm_head`` projection) restores into
    a tied template by DROPPING the projection everywhere it appears —
    params, optimizer state, batch_stats — with a warning; the tied
    model serves logits from token_embedding^T instead.  Returns None
    when the mismatch is not this case (caller falls through to the
    legacy shim / the original structural error)."""
    if raw is None:
        raw = _raw_restore_any(path)
    if raw is None or not isinstance(raw, dict):
        return None
    params = raw.get("params")
    try:
        raw_model = params["model"]
        tmpl_model = template["params"]["model"]
    except (KeyError, TypeError):
        return None
    if not (isinstance(raw_model, dict) and "lm_head" in raw_model
            and isinstance(tmpl_model, dict)
            and "lm_head" not in tmpl_model):
        return None
    try:
        rebuilt = _fit_leaves(_drop_lm_head(params), template["params"],
                              "params")
    except ValueError:
        return None
    warnings.warn(
        "restored an untied-lm-head checkpoint into a tied model "
        "(tie_lm_head=True, the r19 default): the separate lm_head "
        "projection and its optimizer state are DROPPED — logits now "
        "come from token_embedding^T, so the restored model's head "
        "re-converges from the embedding table.  Pass --untie_lm_head "
        "to restore the r18 head exactly.", stacklevel=4)
    return {"step": raw.get("step", template["step"]),
            "params": rebuilt,
            "batch_stats": _fit_or_template(
                _drop_lm_head(raw.get("batch_stats")),
                template["batch_stats"], "batch_stats"),
            "opt_state": _fit_or_template(
                _drop_lm_head(raw.get("opt_state")),
                template["opt_state"], "opt_state"),
            "loss_scale": raw.get("loss_scale", template["loss_scale"]),
            "rng": raw.get("rng", template["rng"])}


def _fit_leaves(raw_sub: Any, template_sub: Any, label: str) -> Any:
    """Fit a raw-restored subtree onto the template's structure: every
    template leaf must exist (matched by key path) with an identical
    shape; returns the rebuilt tree or raises ValueError.  Shared core
    of the params (raise) and batch_stats (warn-and-fallback) paths."""
    t_flat = jax.tree_util.tree_flatten_with_path(template_sub)[0]
    r_leaves = {jax.tree_util.keystr(p): v for p, v in
                jax.tree_util.tree_flatten_with_path(raw_sub)[0]}
    if len(r_leaves) != len(t_flat):
        raise ValueError(f"{label}: leaf count "
                         f"{len(r_leaves)} != {len(t_flat)}")
    leaves = []
    for p, tv in t_flat:
        key = jax.tree_util.keystr(p)
        if key not in r_leaves:
            raise ValueError(f"{label}: missing leaf {key}")
        if np.shape(r_leaves[key]) != np.shape(tv):
            raise ValueError(
                f"{label}: {key} shape {np.shape(r_leaves[key])} != "
                f"template {np.shape(tv)}")
        leaves.append(np.asarray(r_leaves[key]))
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(template_sub), leaves)


def _fit_or_template(raw_sub: Any, template_sub: Any, label: str) -> Any:
    """_fit_leaves with warn-and-fallback (ADVICE r4 #2): on ANY
    mismatch return the template subtree with a warning instead of
    wrong-shaped leaves that fail later."""
    if raw_sub is None:
        return template_sub
    try:
        return _fit_leaves(raw_sub, template_sub, label)
    except Exception as e:
        warnings.warn(
            f"legacy checkpoint's {label} does not fit the restore "
            f"template ({e}); using freshly initialized {label} instead.",
            stacklevel=4)
        return template_sub


# ---------------------------------------------------------------------------
# Per-host shard-streaming checkpoints (pod-scale async saves)
# ---------------------------------------------------------------------------
# Layout of a sharded checkpoint directory:
#   <name>/shards/host_<pi>.npz    raw-byte blocks of the shards host pi OWNS
#   <name>/shards/host_<pi>.json   manifest: leaf key path, index slices,
#                                  dtype, shape per block (npz stores flat
#                                  uint8 — numpy cannot serialize bfloat16)
#   <name>/shards/host_<pi>.DONE   phase-1 marker, written LAST per host
#   <name>/meta.json + COMMIT      phase 2, process 0 only, after EVERY
#                                  host's DONE marker exists (a filesystem
#                                  completion barrier on the shared
#                                  checkpoint dir — the same shared-fs
#                                  assumption the collective orbax path
#                                  already makes)
# A kill ANYWHERE before COMMIT leaves a directory is_committed() rejects.

_SHARDS = "shards"


def _index_to_json(index) -> Optional[list]:
    """A jax shard ``index`` (tuple of slices) as json: [[start, stop] per
    dim], null start/stop = the whole dim; None index (a non-jax leaf,
    saved whole) -> null."""
    if index is None:
        return None
    return [[s.start, s.stop] for s in index]


def _json_to_index(spec, shape) -> Tuple[slice, ...]:
    return tuple(slice(lo if lo is not None else 0,
                       hi if hi is not None else dim)
                 for (lo, hi), dim in zip(spec, shape))


def host_shard_snapshot(state, owner=None) -> list:
    """[(leaf_keystr, index, numpy_block)] — THIS process's owned shard
    blocks of the checkpointable state, fetched to host.  This is the
    only blocking piece of a sharded async save (the very next train
    step donates the buffers).

    ``owner(shard) -> bool`` selects which addressable shards this
    process writes; the default — ``replica_id == 0`` — gives a
    globally disjoint exact cover (each block of every sharded array is
    written by exactly one host; replicated leaves by the host holding
    replica 0).  Non-jax leaves (python/numpy scalars) are saved whole
    by every host and overlay idempotently at restore."""
    flat, _ = jax.tree_util.tree_flatten_with_path(_state_pytree(state))
    blocks = []
    for path_, leaf in flat:
        key = jax.tree_util.keystr(path_)
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            blocks.append((key, None, np.asarray(leaf)))
            continue
        for sh in shards:
            if (owner(sh) if owner is not None else sh.replica_id == 0):
                blocks.append((key, sh.index, np.asarray(sh.data)))
    return blocks


def write_host_shards(path: str, process_index: int, blocks: list,
                      backend=None) -> None:
    """Phase 1 of the two-phase sharded save: write this host's blocks
    (flat raw bytes + manifest), then its DONE marker LAST — the marker's
    presence implies this host's contribution is durably complete.
    Every write routes through the storage backend (r14): atomic
    whole-object puts, no rename assumed — the same code serves the
    shared POSIX filesystem and an object store."""
    b = _backend(backend)
    d = os.path.join(path, _SHARDS)
    b.ensure_dir(d)
    # a DONE marker from a CRASHED earlier attempt at this same step
    # must not be visible while this attempt's blocks are mid-write —
    # process 0's commit barrier would take it as proof this host
    # finished and COMMIT a mix of two attempts' shard files.  Remove
    # ours first (the systematic guard is the restore-time sweep of
    # uncommitted dirs in AsyncCheckpointManager.restore_latest; this
    # covers direct callers of the two-phase primitives too).
    done = os.path.join(d, f"host_{process_index:05d}.DONE")
    b.delete(done)
    arrays, manifest = {}, []
    for i, (key, index, arr) in enumerate(blocks):
        # flat-uint8 VIEW, not a copy (tobytes() would double the
        # writer's host memory across the full owned-shard set); the
        # raw-byte npz entry is what lets non-numpy dtypes (bfloat16)
        # round-trip
        arr = np.asarray(arr)
        # record the shape BEFORE ascontiguousarray: it returns ndim>=1,
        # so a rank-0 leaf (step, loss_scale, opt counters) would land
        # in the manifest as shape [1] against its rank-0 index and
        # restore would push a (1,)-block into a 0-d target (a numpy
        # deprecation headed for a hard error)
        shape = list(arr.shape)
        arrays[f"b{i}"] = np.ascontiguousarray(arr).reshape(-1).view(
            np.uint8)
        manifest.append({"npz": f"b{i}", "leaf": key,
                         "index": _index_to_json(index),
                         "dtype": str(arr.dtype),
                         "shape": shape})
    npz_path = os.path.join(d, f"host_{process_index:05d}.npz")
    b.put_stream(npz_path, lambda f: np.savez(f, **arrays))
    b.put_json(os.path.join(d, f"host_{process_index:05d}.json"), manifest)
    b.put_json(done, {"blocks": len(blocks)})


def commit_sharded_checkpoint(path: str, meta: dict, n_hosts: int,
                              timeout_s: float = 600.0,
                              poll_s: float = 0.05, backend=None) -> None:
    """Phase 2 (process 0 only): wait until EVERY host's DONE marker is
    on the shared backend — the cross-host completion barrier — then
    write meta.json and the COMMIT marker, in that order, atomically.
    The COMMIT itself is a put-if-absent create (GCS
    ``if_generation_match=0``; O_EXCL on POSIX) — the object-store
    equivalent of the historic atomic-rename commit, and a lost race
    means another committer already published the SAME barrier result.
    Raises TimeoutError (leaving the directory uncommitted, hence
    invisible to restore) if a host never finishes within
    ``timeout_s``."""
    b = _backend(backend)
    d = os.path.join(path, _SHARDS)
    want = [os.path.join(d, f"host_{pi:05d}.DONE") for pi in range(n_hosts)]
    deadline = time.monotonic() + timeout_s
    while True:
        missing = [w for w in want if not b.exists(w)]
        if not missing:
            break
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"sharded-checkpoint commit barrier timed out after "
                f"{timeout_s:.0f}s: {len(missing)}/{n_hosts} host DONE "
                f"markers missing under {path} — leaving it uncommitted")
        time.sleep(poll_s)
    b.put_json(os.path.join(path, _META), meta)
    b.create_if_absent(
        os.path.join(path, _COMMIT),
        json.dumps({"committed_unix_time": round(time.time(), 3),
                    "sharded_hosts": int(n_hosts)}).encode("utf-8"))


def is_sharded_checkpoint(path: str, backend=None) -> bool:
    """True when `path` is a per-host shard-streaming checkpoint (vs a
    single-file orbax one) — restore dispatches on this."""
    return _backend(backend).any_prefix(os.path.join(path, _SHARDS))


def _normalized_regions(index, shape) -> Tuple[Tuple[Tuple[int, int], ...]]:
    """One slice-tuple as ((start, stop) per dim), defaults resolved."""
    return tuple((s.start if s.start is not None else 0,
                  s.stop if s.stop is not None else dim)
                 for s, dim in zip(index, shape))


def _region_overlap(a, b) -> int:
    """Element count of the intersection of two ((start, stop), ...)
    regions over the same shape (0 = disjoint; rank-0 regions — both
    empty tuples — overlap fully with count 1)."""
    n = 1
    for (a0, a1), (b0, b1) in zip(a, b):
        lo, hi = max(a0, b0), min(a1, b1)
        if hi <= lo:
            return 0
        n *= hi - lo
    return n


def template_needed_regions(template_leaf) -> Optional[list]:
    """The index regions of `template_leaf` THIS process must fill at
    restore — its addressable shards' indices (deduped: replicated local
    devices share one region), or None = the whole leaf (single-process
    runs, and non-jax template leaves, which have no sharding to
    consult).  The per-host read-filtering seam of
    :func:`restore_sharded_checkpoint`."""
    sharding = getattr(template_leaf, "sharding", None)
    if sharding is None or jax.process_count() == 1:
        return None
    shape = np.shape(template_leaf)
    try:
        index_map = sharding.addressable_devices_indices_map(shape)
    except (AttributeError, TypeError):
        return None      # exotic sharding: fall back to reading everything
    regions = {_normalized_regions(idx, shape)
               for idx in index_map.values() if idx is not None}
    if not regions or None in index_map.values():
        return None
    return [tuple(slice(lo, hi) for lo, hi in r) for r in sorted(regions)]


def restore_sharded_checkpoint(checkpoint_dir: str, name: str,
                               state: TrainState,
                               needed_fn=None, stats: Optional[dict] = None,
                               backend=None
                               ) -> Tuple[TrainState, int, float]:
    """Reassemble the state from the per-host shard files and fit it
    onto the (freshly created) `state` template — the sharded analog of
    :func:`restore_checkpoint`, same return contract.

    Each host reads ONLY the manifest entries overlapping its needed
    regions — by default the template's addressable-shard indices
    (:func:`template_needed_regions`) — and fills a per-host partial
    buffer; the npz members of skipped blocks are never decompressed
    (``np.load`` on an npz reads lazily per member), so per-host bytes
    read scale with the host's shard of the state, not its global size.
    ``_placed_like`` then asks the buffer for exactly the addressable
    indices, so the unfilled remainder is never observed.  Single-process
    restores (and non-jax leaves) need everything and degenerate to the
    full read.  ``needed_fn(leaf_keystr, template_leaf) -> regions|None``
    overrides the region source (the simulated-pod tests' seam);
    ``stats`` (optional dict) receives bytes_read / blocks_read /
    blocks_skipped.  A leaf whose read blocks do not cover every needed
    region exactly raises — the resilience manager's newest-VALID walk
    then falls back past it."""
    b = _backend(backend)
    path = _ckpt_dir(checkpoint_dir, name)
    d = os.path.join(path, _SHARDS)
    template = _state_pytree(state)
    t_flat, treedef = jax.tree_util.tree_flatten(template)
    t_paths, _ = jax.tree_util.tree_flatten_with_path(template)
    keys = [jax.tree_util.keystr(p) for p, _v in t_paths]
    key_to_leaf = dict(zip(keys, t_flat))
    if needed_fn is None:
        needed_fn = lambda _key, tv: template_needed_regions(tv)  # noqa: E731
    # keystr -> [target buffer, [(normalized region, covered count)]]
    # (None regions = whole leaf).  Blocks are globally disjoint (the
    # replica-0 owner cover write_host_shards records), so per-region
    # coverage is an exact sum of block intersections.
    out = {}
    st = {"bytes_read": 0, "blocks_read": 0, "blocks_skipped": 0}
    manifests = sorted(
        k for k in b.list_prefix(os.path.join(d, "host_"))
        if k.endswith(".json") and os.path.basename(k).startswith("host_"))
    for jf in manifests:
        manifest = b.read_json(jf)
        if manifest is None:
            raise ValueError(f"unreadable shard manifest {jf}")
        # backend.open_read keeps np.load's lazy per-member zip access
        # (ranged reads on object stores), so skipped blocks stay unread
        npz = np.load(b.open_read(jf[:-len(".json")] + ".npz"))
        for entry in manifest:
            key = entry["leaf"]
            if key not in key_to_leaf:
                raise ValueError(f"sharded checkpoint leaf {key} not in "
                                 f"the restore template")
            tv = key_to_leaf[key]
            if key not in out:
                dt = tv.dtype if hasattr(tv, "dtype") else \
                    np.asarray(tv).dtype
                shape = np.shape(tv)
                needed = needed_fn(key, tv)
                if needed is None:
                    needed = [tuple(slice(0, s) for s in shape)]
                out[key] = [np.zeros(shape, dt),
                            [[_normalized_regions(r, shape), 0]
                             for r in needed]]
            target, regions = out[key]
            block_region = _normalized_regions(
                _json_to_index(entry["index"], target.shape)
                if entry["index"] is not None
                else tuple(slice(0, s) for s in target.shape),
                target.shape)
            overlaps = [(r, _region_overlap(block_region, r[0]))
                        for r in regions]
            if not any(n for _r, n in overlaps):
                st["blocks_skipped"] += 1
                continue        # npz member never touched: bytes unread
            block = np.frombuffer(
                npz[entry["npz"]].tobytes(),
                np.dtype(entry["dtype"])).reshape(entry["shape"])
            st["blocks_read"] += 1
            st["bytes_read"] += block.nbytes
            if block.shape == target.shape:
                target[...] = block.astype(target.dtype, copy=False)
            else:
                slc = tuple(slice(lo, hi) for lo, hi in block_region)
                target[slc] = block.astype(target.dtype, copy=False)
            for r, n in overlaps:
                r[1] += n
    leaves = []
    for key, tv in zip(keys, t_flat):
        if key not in out:
            raise ValueError(f"sharded checkpoint is missing leaf {key}")
        target, regions = out[key]
        for (region, covered) in regions:
            want = int(np.prod([hi - lo for lo, hi in region])) \
                if region else 1
            if covered < want:
                raise ValueError(
                    f"sharded checkpoint leaf {key} incomplete: region "
                    f"{region} has {covered} of {want} elements covered "
                    f"by the host shard files")
        leaves.append(_placed_like(tv, target))
    if stats is not None:
        stats.update(st)
    restored = jax.tree_util.tree_unflatten(treedef, leaves)
    meta = read_checkpoint_meta(checkpoint_dir, name, backend=b)
    new_state = state.replace(
        step=restored["step"], params=restored["params"],
        batch_stats=restored["batch_stats"],
        opt_state=restored["opt_state"],
        loss_scale=restored["loss_scale"], rng=restored["rng"])
    return (new_state, int(meta.get("epoch", 0)),
            float(meta.get("best_acc", 0.0)))


def _placed_like(template_leaf, value: np.ndarray):
    """Multi-host: re-place a reassembled numpy leaf per the template's
    sharding (each process materializes only its addressable blocks).
    Single-process restores return numpy — matching the orbax path."""
    sharding = getattr(template_leaf, "sharding", None)
    if jax.process_count() > 1 and sharding is not None:
        return jax.make_array_from_callback(value.shape, sharding,
                                            lambda idx: value[idx])
    return value


def is_committed(path: str, backend=None) -> bool:
    """True iff `path` holds a COMPLETE checkpoint.

    Post-r7 saves: the COMMIT marker (written last — arrays AND meta.json
    durably on disk).  Pre-r7 saves are grandfathered via orbax's own
    completion metadata, but ONLY together with meta.json: a post-r7
    save killed between orbax's staged-rename and the meta write leaves
    `_CHECKPOINT_METADATA` with no meta.json, and restoring that torn
    state would default epoch/step to 0 and silently replay the run from
    the start.  A bare directory — a preemption mid-write — is nothing."""
    b = _backend(backend)
    if b.exists(os.path.join(path, _COMMIT)):
        return True
    return (b.exists(os.path.join(path, _OCP_METADATA))
            and b.exists(os.path.join(path, _META)))


def has_checkpoint(checkpoint_dir: str, name: str, backend=None) -> bool:
    """A *restorable* checkpoint exists — not merely a directory.  The
    bare-isdir check it replaces returned True for half-written
    directories, sending --resume into a crash on the next restore."""
    path = _ckpt_dir(checkpoint_dir, name)
    return _backend(backend).any_prefix(path) and is_committed(
        path, backend=backend)
