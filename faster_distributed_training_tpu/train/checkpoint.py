"""Full-state checkpointing via orbax.

The reference saves only ``{net, acc, epoch}`` on rank 0 gated on best
test accuracy (resnet50_test.py:663-675) and loses optimizer, scheduler,
GradScaler and NGD Fisher state across resumes (SURVEY.md §5).  Here the
complete ``TrainState`` round-trips: params, BN stats, optimizer state
(including every ``OnlineNaturalGradientState``), loss scale, step and
the RNG root — plus ``best_acc``/``epoch`` metadata.  Saves are
process-0-gated for the metadata and collective for arrays (orbax is
multi-host aware)."""

from __future__ import annotations

import json
import os
import re
import time
import warnings
from typing import Any, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from faster_distributed_training_tpu.train.state import TrainState

_META = "meta.json"
# Commit marker: written LAST (atomically, process 0) after the arrays
# AND meta.json are durably on disk.  Its presence is the "this
# checkpoint is restorable" contract has_checkpoint() and the resilience
# manager check — a bare directory (preemption mid-write) is never it.
_COMMIT = "COMMIT"
# orbax's own completion file: Checkpointer.save() stages into a tmp dir
# and renames, writing this marker inside — pre-r7 checkpoints (incl.
# the committed legacy fixture) carry it but not ours.
_OCP_METADATA = "_CHECKPOINT_METADATA"

_LEGACY_LAYER_KEY = re.compile(r"^(attn|ffn|ln_attn|ln_ffn)_(\d+)$")


def _write_json_atomic(path: str, obj: Any) -> None:
    """tmp + os.replace so a preemption mid-write can never leave a torn
    file at `path` — the previous content (or absence) survives intact."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def migrate_legacy_transformer_params(model_params: Any,
                                      n_heads: int = 8) -> Any:
    """One-time key remap for pre-round-3 transformer checkpoints
    (ADVICE r3 #1).

    Round 3 restructured the transformer param tree: the flat
    ``attn_{i}/query|key|value|out``, ``ffn_{i}``, ``ln_attn_{i}``,
    ``ln_ffn_{i}`` modules became per-layer ``layer_{i}/...`` and the
    three (d_model, d_model) Q/K/V kernels were fused into ONE
    (d_model, 3, h, d_k) ``qkv`` DenseGeneral kernel.  This folds the
    legacy leaves into the fused layout — the math is identical, so a
    migrated checkpoint reproduces the old model's forward exactly.

    Returns the params unchanged when no legacy keys are present.
    """
    if not isinstance(model_params, dict) or not any(
            _LEGACY_LAYER_KEY.match(k) for k in model_params):
        return model_params
    out = {k: v for k, v in model_params.items()
           if not _LEGACY_LAYER_KEY.match(k)}
    layers = sorted({int(m.group(2)) for k in model_params
                     if (m := _LEGACY_LAYER_KEY.match(k))})
    for i in layers:
        attn = dict(model_params[f"attn_{i}"])
        qp, kp, vp = attn.pop("query"), attn.pop("key"), attn.pop("value")
        d_model = np.shape(qp["kernel"])[0]
        # the fused kernel is laid out (d_model, 3, h, d_k); a legacy
        # checkpoint doesn't record h — the caller supplies it (the
        # restore path reads it off the new-model template)
        h = n_heads
        d_k = d_model // h
        kern = np.stack([np.asarray(qp["kernel"]), np.asarray(kp["kernel"]),
                         np.asarray(vp["kernel"])], axis=1)
        qkv = {"kernel": kern.reshape(d_model, 3, h, d_k)}
        if "bias" in qp:
            qkv["bias"] = np.stack(
                [np.asarray(qp["bias"]), np.asarray(kp["bias"]),
                 np.asarray(vp["bias"])], axis=0).reshape(3, h, d_k)
        out[f"layer_{i}"] = {
            "attn": {"qkv": qkv, **attn},
            "ffn": model_params[f"ffn_{i}"],
            "ln_attn": model_params[f"ln_attn_{i}"],
            "ln_ffn": model_params[f"ln_ffn_{i}"],
        }
    return out


def _ckpt_dir(checkpoint_dir: str, name: str) -> str:
    return os.path.abspath(os.path.join(checkpoint_dir, name))


def _state_pytree(state: TrainState) -> Any:
    """The checkpointable (non-static) part of TrainState."""
    return {"step": state.step, "params": state.params,
            "batch_stats": state.batch_stats, "opt_state": state.opt_state,
            "loss_scale": state.loss_scale, "rng": state.rng}


def save_checkpoint(checkpoint_dir: str, name: str, state: TrainState,
                    epoch: int, best_acc: float,
                    extra_meta: Optional[dict] = None) -> str:
    """Overwrites `<checkpoint_dir>/<name>` with the full state.

    `state` may be a real TrainState or any object exposing the same
    checkpointable attributes with HOST (numpy) leaves — the resilience
    manager's async path saves a device_get snapshot this way."""
    path = _ckpt_dir(checkpoint_dir, name)
    return save_pytree_checkpoint(
        path, _state_pytree(state),
        {"epoch": int(epoch), "best_acc": float(best_acc),
         **(extra_meta or {})})


def save_pytree_checkpoint(path: str, tree: Any, meta: dict) -> str:
    """Shared save core: orbax arrays (atomic — staged + renamed), then
    meta.json, then the COMMIT marker, both atomically and in that order
    so the marker's presence implies everything before it is complete.
    A preemption at ANY point leaves either the previous checkpoint
    intact or an uncommitted directory has_checkpoint() rejects."""
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        ckptr.save(path, tree, force=True)
    if jax.process_index() == 0:
        _write_json_atomic(os.path.join(path, _META), meta)
        _write_json_atomic(os.path.join(path, _COMMIT),
                           {"committed_unix_time": round(time.time(), 3)})
    return path


def read_checkpoint_meta(checkpoint_dir: str, name: str) -> dict:
    """The meta.json contents ({} when absent/torn — a torn file is
    impossible post-r7, but pre-r7 checkpoints wrote it non-atomically)."""
    meta_path = os.path.join(_ckpt_dir(checkpoint_dir, name), _META)
    try:
        with open(meta_path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def restore_checkpoint(checkpoint_dir: str, name: str, state: TrainState
                       ) -> Tuple[TrainState, int, float]:
    """Restore into the (freshly created) `state` template.  Returns
    (state, start_epoch, best_acc) — the --resume path
    (resnet50_test.py:470-475,680-690), but with optimizer/Fisher/RNG
    state intact."""
    path = _ckpt_dir(checkpoint_dir, name)
    template = _state_pytree(state)
    try:
        with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
            restored = ckptr.restore(
                path, args=ocp.args.StandardRestore(template))
    except Exception as structural:
        # Possibly a pre-round-3 checkpoint (flat attn_{i}/query|key|value
        # layout): raw-restore, remap the param tree, and re-validate.
        # Optimizer state mirrors the param structure and cannot be
        # meaningfully folded (Fisher factors/momenta were tracked per
        # UNFUSED kernel), so it restarts fresh — loudly.
        restored = _restore_legacy(path, template, structural)
    meta = read_checkpoint_meta(checkpoint_dir, name)
    epoch = int(meta.get("epoch", 0))
    best_acc = float(meta.get("best_acc", 0.0))
    state = state.replace(
        step=restored["step"], params=restored["params"],
        batch_stats=restored["batch_stats"], opt_state=restored["opt_state"],
        loss_scale=state.loss_scale.__class__(*restored["loss_scale"]),
        rng=restored["rng"])
    return state, epoch, best_acc


def _raw_restore_numpy(path: str) -> Any:
    """Raw-restore a checkpoint as NUMPY leaves, ignoring the device
    shardings recorded at save time (topology-independent)."""
    ckptr = ocp.PyTreeCheckpointer()
    meta = ckptr.metadata(path)
    tree = getattr(getattr(meta, "item_metadata", meta), "tree", None)
    if not isinstance(tree, dict):
        raise ValueError(f"unreadable checkpoint metadata at {path}")
    ra = jax.tree_util.tree_map(
        lambda m: ocp.RestoreArgs(restore_type=np.ndarray), tree)
    return ckptr.restore(path, args=ocp.args.PyTreeRestore(restore_args=ra))


def _restore_legacy(path: str, template: Any, structural: Exception) -> Any:
    """Raw-restore a structurally mismatched checkpoint, migrate the
    legacy transformer param layout, and fit it onto `template`.  Leaves
    that still don't line up re-raise the original error."""
    # Genuine old checkpoints carry the DEVICE SHARDINGS of the machine
    # that wrote them (e.g. a TPU that isn't attached at restore time),
    # so the raw restore must be type-erased to numpy via metadata-driven
    # RestoreArgs — proven against the committed round-2 fixture
    # (tests/fixtures/legacy_transformer, saved on a TPU v5e).  The
    # plain StandardCheckpointer/PyTreeCheckpointer raw restores remain
    # as fallbacks for same-topology layouts.
    raw = None
    for restore in (_raw_restore_numpy,
                    lambda p: ocp.StandardCheckpointer().restore(p),
                    lambda p: ocp.PyTreeCheckpointer().restore(p)):
        try:
            raw = restore(path)
            break
        except Exception:
            continue
    if raw is None:
        raise structural       # corrupt checkpoint: surface the ORIGINAL error
    params = raw.get("params") if isinstance(raw, dict) else None
    if not isinstance(params, dict) or "model" not in params:
        raise structural
    if not (isinstance(params["model"], dict)
            and any(_LEGACY_LAYER_KEY.match(k) for k in params["model"])):
        # structurally mismatched but NOT the known legacy layout — this
        # fallback is only for pre-round-3 trees, not arbitrary mismatches
        raise structural
    n_heads = 8
    try:
        tmpl_model = template["params"]["model"]
        layer0 = next(v for k, v in sorted(tmpl_model.items())
                      if k.startswith("layer_"))
        n_heads = int(np.shape(layer0["attn"]["qkv"]["kernel"])[2])
    except (StopIteration, KeyError, TypeError, IndexError):
        # a wrong head count would reshape the fused Q/K/V kernels
        # incorrectly WITHOUT a shape error (d_model, 3, h, d_k) is
        # size-equal for any h dividing d_model — never guess silently
        # (VERDICT r4 #4)
        warnings.warn(
            "legacy-checkpoint migration could not read n_heads from the "
            f"restore template (no layer_*/attn/qkv kernel found); "
            f"assuming n_heads={n_heads}.  If the checkpointed model used "
            "a different head count the migrated Q/K/V kernels will be "
            "SILENTLY mis-reshaped — pass a template built from the real "
            "model configuration.", stacklevel=3)
    migrated = dict(params)
    migrated["model"] = migrate_legacy_transformer_params(
        params["model"], n_heads)
    try:
        rebuilt = _fit_leaves(migrated, template["params"], "params")
    except ValueError:
        raise structural
    warnings.warn(
        "restored a pre-round-3 checkpoint: transformer Q/K/V kernels "
        "were folded into the fused qkv layout (forward-exact), but the "
        "OPTIMIZER state (momenta / Fisher factors / dual averages) "
        "tracked the unfused kernels and cannot be folded — it restarts "
        "fresh, as do the RNG root and loss scale.  Expect a short "
        "re-warmup of optimizer statistics.", stacklevel=3)
    return {"step": raw.get("step", template["step"]),
            "params": rebuilt,
            "batch_stats": _fit_or_template(
                raw.get("batch_stats"), template["batch_stats"],
                "batch_stats"),
            "opt_state": template["opt_state"],
            "loss_scale": template["loss_scale"],
            "rng": template["rng"]}


def _fit_leaves(raw_sub: Any, template_sub: Any, label: str) -> Any:
    """Fit a raw-restored subtree onto the template's structure: every
    template leaf must exist (matched by key path) with an identical
    shape; returns the rebuilt tree or raises ValueError.  Shared core
    of the params (raise) and batch_stats (warn-and-fallback) paths."""
    t_flat = jax.tree_util.tree_flatten_with_path(template_sub)[0]
    r_leaves = {jax.tree_util.keystr(p): v for p, v in
                jax.tree_util.tree_flatten_with_path(raw_sub)[0]}
    if len(r_leaves) != len(t_flat):
        raise ValueError(f"{label}: leaf count "
                         f"{len(r_leaves)} != {len(t_flat)}")
    leaves = []
    for p, tv in t_flat:
        key = jax.tree_util.keystr(p)
        if key not in r_leaves:
            raise ValueError(f"{label}: missing leaf {key}")
        if np.shape(r_leaves[key]) != np.shape(tv):
            raise ValueError(
                f"{label}: {key} shape {np.shape(r_leaves[key])} != "
                f"template {np.shape(tv)}")
        leaves.append(np.asarray(r_leaves[key]))
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(template_sub), leaves)


def _fit_or_template(raw_sub: Any, template_sub: Any, label: str) -> Any:
    """_fit_leaves with warn-and-fallback (ADVICE r4 #2): on ANY
    mismatch return the template subtree with a warning instead of
    wrong-shaped leaves that fail later."""
    if raw_sub is None:
        return template_sub
    try:
        return _fit_leaves(raw_sub, template_sub, label)
    except Exception as e:
        warnings.warn(
            f"legacy checkpoint's {label} does not fit the restore "
            f"template ({e}); using freshly initialized {label} instead.",
            stacklevel=4)
        return template_sub


def is_committed(path: str) -> bool:
    """True iff `path` holds a COMPLETE checkpoint.

    Post-r7 saves: the COMMIT marker (written last — arrays AND meta.json
    durably on disk).  Pre-r7 saves are grandfathered via orbax's own
    completion metadata, but ONLY together with meta.json: a post-r7
    save killed between orbax's staged-rename and the meta write leaves
    `_CHECKPOINT_METADATA` with no meta.json, and restoring that torn
    state would default epoch/step to 0 and silently replay the run from
    the start.  A bare directory — a preemption mid-write — is nothing."""
    if os.path.exists(os.path.join(path, _COMMIT)):
        return True
    return (os.path.exists(os.path.join(path, _OCP_METADATA))
            and os.path.exists(os.path.join(path, _META)))


def has_checkpoint(checkpoint_dir: str, name: str) -> bool:
    """A *restorable* checkpoint exists — not merely a directory.  The
    bare-isdir check it replaces returned True for half-written
    directories, sending --resume into a crash on the next restore."""
    path = _ckpt_dir(checkpoint_dir, name)
    return os.path.isdir(path) and is_committed(path)
