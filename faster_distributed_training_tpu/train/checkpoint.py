"""Full-state checkpointing via orbax.

The reference saves only ``{net, acc, epoch}`` on rank 0 gated on best
test accuracy (resnet50_test.py:663-675) and loses optimizer, scheduler,
GradScaler and NGD Fisher state across resumes (SURVEY.md §5).  Here the
complete ``TrainState`` round-trips: params, BN stats, optimizer state
(including every ``OnlineNaturalGradientState``), loss scale, step and
the RNG root — plus ``best_acc``/``epoch`` metadata.  Saves are
process-0-gated for the metadata and collective for arrays (orbax is
multi-host aware)."""

from __future__ import annotations

import json
import os
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from faster_distributed_training_tpu.train.state import TrainState

_META = "meta.json"


def _ckpt_dir(checkpoint_dir: str, name: str) -> str:
    return os.path.abspath(os.path.join(checkpoint_dir, name))


def _state_pytree(state: TrainState) -> Any:
    """The checkpointable (non-static) part of TrainState."""
    return {"step": state.step, "params": state.params,
            "batch_stats": state.batch_stats, "opt_state": state.opt_state,
            "loss_scale": state.loss_scale, "rng": state.rng}


def save_checkpoint(checkpoint_dir: str, name: str, state: TrainState,
                    epoch: int, best_acc: float) -> str:
    """Overwrites `<checkpoint_dir>/<name>` with the full state."""
    path = _ckpt_dir(checkpoint_dir, name)
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        ckptr.save(path, _state_pytree(state), force=True)
    if jax.process_index() == 0:
        with open(os.path.join(path, _META), "w") as f:
            json.dump({"epoch": int(epoch), "best_acc": float(best_acc)}, f)
    return path


def restore_checkpoint(checkpoint_dir: str, name: str, state: TrainState
                       ) -> Tuple[TrainState, int, float]:
    """Restore into the (freshly created) `state` template.  Returns
    (state, start_epoch, best_acc) — the --resume path
    (resnet50_test.py:470-475,680-690), but with optimizer/Fisher/RNG
    state intact."""
    path = _ckpt_dir(checkpoint_dir, name)
    template = _state_pytree(state)
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        restored = ckptr.restore(path, args=ocp.args.StandardRestore(template))
    meta_path = os.path.join(path, _META)
    epoch, best_acc = 0, 0.0
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        epoch, best_acc = int(meta["epoch"]), float(meta["best_acc"])
    state = state.replace(
        step=restored["step"], params=restored["params"],
        batch_stats=restored["batch_stats"], opt_state=restored["opt_state"],
        loss_scale=state.loss_scale.__class__(*restored["loss_scale"]),
        rng=restored["rng"])
    return state, epoch, best_acc


def has_checkpoint(checkpoint_dir: str, name: str) -> bool:
    return os.path.isdir(_ckpt_dir(checkpoint_dir, name))
