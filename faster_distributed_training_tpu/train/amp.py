"""Mixed precision policy + dynamic loss scaling.

On TPU the primary policy is pure bf16 compute with fp32 params/stats —
no gradient scaler needed (bf16 has fp32's exponent range).  This
replaces the reference's fp16 autocast + GradScaler machinery
(resnet50_test.py:533-548) and the Apex O1 fallback
(resnet50_test.py:569-593).

For parity experiments an fp16 mode with a torch-GradScaler-compatible
*dynamic loss scale* is provided: scale the loss, unscale the grads,
skip the step and halve the scale on non-finite grads, double the scale
after ``growth_interval`` consecutive good steps — the exact GradScaler
policy, but as a pure pytree inside the jitted step (no host sync)."""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

COMPUTE_DTYPES = {"bf16": jnp.bfloat16, "fp16": jnp.float16,
                  "fp32": jnp.float32}


class LossScaleState(NamedTuple):
    scale: jax.Array          # () f32 — current loss scale
    growth_count: jax.Array   # () i32 — consecutive finite steps


def fresh_loss_scale(init_scale: float = 2.0 ** 16) -> LossScaleState:
    return LossScaleState(scale=jnp.asarray(init_scale, jnp.float32),
                          growth_count=jnp.asarray(0, jnp.int32))


def scale_loss(loss: jax.Array, state: LossScaleState,
               enabled: bool) -> jax.Array:
    return loss * state.scale if enabled else loss


def unscale_and_check(grads, state: LossScaleState, enabled: bool
                      ) -> Tuple[jax.Array, jax.Array]:
    """Returns (unscaled_grads, grads_finite)."""
    if not enabled:
        return grads, jnp.asarray(True)
    inv = 1.0 / state.scale
    grads = jax.tree.map(lambda g: g * inv, grads)
    finite = jnp.all(jnp.stack(
        [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)]))
    return grads, finite


def update_loss_scale(state: LossScaleState, grads_finite: jax.Array,
                      enabled: bool, growth_factor: float = 2.0,
                      backoff_factor: float = 0.5,
                      growth_interval: int = 2000) -> LossScaleState:
    if not enabled:
        return state
    grew = state.growth_count + 1 >= growth_interval
    new_scale = jnp.where(
        grads_finite,
        jnp.where(grew, state.scale * growth_factor, state.scale),
        state.scale * backoff_factor)
    new_count = jnp.where(grads_finite,
                          jnp.where(grew, 0, state.growth_count + 1), 0)
    return LossScaleState(scale=new_scale,
                          growth_count=new_count.astype(jnp.int32))
