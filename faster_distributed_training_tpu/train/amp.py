"""Mixed precision policy + dynamic loss scaling.

On TPU the primary policy is pure bf16 compute with fp32 params/stats —
no gradient scaler needed (bf16 has fp32's exponent range).  This
replaces the reference's fp16 autocast + GradScaler machinery
(resnet50_test.py:533-548) and the Apex O1 fallback
(resnet50_test.py:569-593).

For parity experiments an fp16 mode with a torch-GradScaler-compatible
*dynamic loss scale* is provided: scale the loss, unscale the grads,
skip the step and halve the scale on non-finite grads, double the scale
after ``growth_interval`` consecutive good steps — the exact GradScaler
policy, but as a pure pytree inside the jitted step (no host sync)."""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

COMPUTE_DTYPES = {"bf16": jnp.bfloat16, "fp16": jnp.float16,
                  "fp32": jnp.float32}


class QuantPolicy(NamedTuple):
    """Static description of the quantized-training mode (r13) — the
    low-precision sibling of the loss-scale machinery below.  Where the
    fp16 mode scales the LOSS so small gradients survive the format,
    the quantized mode scales each GEMM OPERAND so its values fill the
    int8/fp8 grid: per-tensor delayed scaling with a tracked amax
    history (ops/quant.py owns the math and the kernels).

    The policy itself is static (hashable — it rides flax module
    fields); the per-tensor STATE (amax histories) lives in the model's
    ``batch_stats`` collection, which the train step already threads
    through the r8 fused-dispatch carry, checkpoints and the kill-at-N
    bitwise resume — the same carry contract LossScaleState has.

    fmt: "int8" (127-grid symmetric, s8xs8->s32 GEMMs) or "fp8"
      (E4M3 forward operands, fp32 accumulation; E5M2 gradient
      quantization is a documented future step).
    amax_history_len: delayed-scaling window (Transformer Engine's
      default neighborhood; the scale is qmax / max(history)).
    margin: extra headroom multiplier on the running amax.
    use_pallas: None = auto (Pallas kernel on TPU when the shape fits
      the VMEM budget); False = force the XLA reference path — the
      REGISTERED warned fallback cli.build_model sets when the r19
      shard_map kernel layer can't serve a tp mesh (FDT_KERNEL_SHARD=0
      or non-dividing shapes); serviceable tp meshes keep None and the
      kernel runs per-shard (parallel/kernel_shard.py).
    frozen_scales: inference mode (serve/): quantize at the scales the
      RESTORED amax history implies and never roll it — serving is
      state-free and bitwise-reproducible per request
      (cli.build_model(serving=True) sets it; training must keep
      False — delayed scaling needs the roll).
    grad_fmt: None, or "fp8_e5m2" (--quant_grad): quantize the
      backward's cotangents to the wide-range E5M2 grid at a
      just-in-time per-tensor scale and run BOTH gradient GEMMs on
      quantized operands — the FP8-LM completion (ops/quant.py)."""
    fmt: str
    amax_history_len: int = 16
    margin: float = 1.0
    use_pallas: Optional[bool] = None
    frozen_scales: bool = False
    grad_fmt: Optional[str] = None


def resolve_quant_policy(cfg) -> Optional["QuantPolicy"]:
    """cfg.quant -> QuantPolicy or None ("" / "none").  Mesh/backend
    routing (use_pallas) is layered on by cli.build_model, which knows
    the mesh."""
    mode = (getattr(cfg, "quant", "none") or "none").lower()
    grad = (getattr(cfg, "quant_grad", "none") or "none").lower()
    if mode in ("", "none"):
        if grad not in ("", "none"):
            import warnings
            warnings.warn(
                f"--quant_grad {grad} requires --quant int8/fp8 (gradient "
                f"quantization rides the quantized GEMM sites); running "
                f"full-precision", stacklevel=2)
        return None
    if mode not in ("int8", "fp8"):
        raise ValueError(f"--quant must be none/int8/fp8, got {mode!r}")
    if grad in ("", "none"):
        grad_fmt = None
    elif grad in ("fp8_e5m2", "e5m2"):
        grad_fmt = "fp8_e5m2"
    else:
        raise ValueError(f"--quant_grad must be none/fp8_e5m2, got {grad!r}")
    return QuantPolicy(fmt=mode, grad_fmt=grad_fmt)


class LossScaleState(NamedTuple):
    scale: jax.Array          # () f32 — current loss scale
    growth_count: jax.Array   # () i32 — consecutive finite steps


def fresh_loss_scale(init_scale: float = 2.0 ** 16) -> LossScaleState:
    return LossScaleState(scale=jnp.asarray(init_scale, jnp.float32),
                          growth_count=jnp.asarray(0, jnp.int32))


def scale_loss(loss: jax.Array, state: LossScaleState,
               enabled: bool) -> jax.Array:
    return loss * state.scale if enabled else loss


def unscale_and_check(grads, state: LossScaleState, enabled: bool
                      ) -> Tuple[jax.Array, jax.Array]:
    """Returns (unscaled_grads, grads_finite)."""
    if not enabled:
        return grads, jnp.asarray(True)
    inv = 1.0 / state.scale
    grads = jax.tree.map(lambda g: g * inv, grads)
    finite = jnp.all(jnp.stack(
        [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)]))
    return grads, finite


def update_loss_scale(state: LossScaleState, grads_finite: jax.Array,
                      enabled: bool, growth_factor: float = 2.0,
                      backoff_factor: float = 0.5,
                      growth_interval: int = 2000) -> LossScaleState:
    if not enabled:
        return state
    grew = state.growth_count + 1 >= growth_interval
    # backoff floors at fp32's smallest NORMAL: XLA:CPU flushes f32
    # denormals to zero, and a zero scale is terminal (1/scale = inf
    # poisons every later unscale, so the run could never recover even
    # if the divergence was transient).  torch's GradScaler never
    # reaches this range in practice; the floor only changes the
    # already-doomed tail (pinned by tests/test_amp.py).
    floor = float(np.finfo(np.float32).tiny)
    new_scale = jnp.where(
        grads_finite,
        jnp.where(grew, state.scale * growth_factor, state.scale),
        jnp.maximum(state.scale * backoff_factor, floor))
    new_count = jnp.where(grads_finite,
                          jnp.where(grew, 0, state.growth_count + 1), 0)
    return LossScaleState(scale=new_scale,
                          growth_count=new_count.astype(jnp.int32))
