"""The mixup family, as pure functions of an explicit PRNG key.

Re-design of resnet50_test.py:355-457:
  * ``mixup_data``       — static mixup, one Beta(alpha,alpha) lambda per
                           batch (resnet50_test.py:355-376), with the
                           ``intra_only`` same-class variant;
  * ``meta_mixup_apply`` — learnable per-sample lambda
                           (resnet50_test.py:388-401).  The reference
                           re-instantiates the module every batch so its
                           lambda NEVER trains (resnet50_test.py:525 —
                           SURVEY.md §2 flags it); here the lambda is a
                           genuine parameter leaf the caller owns and
                           passes through the optimizer, so it trains;
  * ``attn_mixup_apply`` — attention-map mixup: a per-pixel lambda map
                           (resnet50_test.py:404-424);
  * the paired criteria (resnet50_test.py:451-457).

All shapes are NHWC (TPU layout); lambda broadcast shapes follow.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp


def sample_lam(key: jax.Array, alpha) -> jax.Array:
    """lambda ~ Beta(alpha, alpha) when alpha > 0, else the constant alpha
    (resnet50_test.py:357-361).  Accepts a traced alpha (the vmap-over-
    trials sweep, tuning/vmap_sweep.py, maps over it)."""
    if isinstance(alpha, (int, float)):
        if alpha > 0:
            return jax.random.beta(key, alpha, alpha)
        return jnp.asarray(alpha, jnp.float32)
    alpha = jnp.asarray(alpha, jnp.float32)
    safe = jnp.maximum(alpha, 1e-6)
    return jnp.where(alpha > 0, jax.random.beta(key, safe, safe), alpha)


def mixup_data(key: jax.Array, x: jax.Array, y: jax.Array, alpha: float = 0.99,
               intra_only: bool = False
               ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Returns (mixed_x, y_a, y_b, lam) — resnet50_test.py:355-376."""
    k_lam, k_perm = jax.random.split(key)
    lam = sample_lam(k_lam, alpha)
    index = jax.random.permutation(k_perm, x.shape[0])
    x_perm = x[index]
    lam_b = lam.astype(x.dtype)
    mixed = lam_b * x + (1.0 - lam_b) * x_perm
    if intra_only:
        # same-class pairs keep the original sample (the reference's Python
        # loop at resnet50_test.py:365-373, vectorized)
        same = (y == y[index]).reshape((-1,) + (1,) * (x.ndim - 1))
        mixed = jnp.where(same, x, mixed)
    return mixed, y, y[index], lam


def init_meta_lambda(key: jax.Array, batch_size: int) -> jax.Array:
    """Pre-sigmoid per-sample lambda parameter, U[0,1) init like the
    reference (resnet50_test.py:390)."""
    return jax.random.uniform(key, (batch_size, 1, 1, 1))


def init_attn_lambda(key: jax.Array, batch_size: int, height: int, width: int,
                     channels: int = 3) -> jax.Array:
    """Per-pixel lambda map parameter (resnet50_test.py:410), NHWC."""
    return jax.random.uniform(key, (batch_size, height, width, channels))


def meta_mixup_apply(lam_param: jax.Array, key: jax.Array, x: jax.Array,
                     y: jax.Array
                     ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Learnable mixup: lam = sigmoid(lam_param) per sample
    (resnet50_test.py:396-401).  `lam_param` is a trainable leaf —
    gradients flow through the mixed input into it."""
    index = jax.random.permutation(key, x.shape[0])
    lam = jax.nn.sigmoid(lam_param).astype(x.dtype)
    mixed = lam * x + (1.0 - lam) * x[index]
    return mixed, y, y[index], lam.reshape(x.shape[0])


def attn_mixup_apply(lam_param: jax.Array, key: jax.Array, x: jax.Array,
                     y: jax.Array
                     ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Attention-map mixup (resnet50_test.py:417-424): per-pixel sigmoid
    map mixes the images; the per-sample loss weight is the map's squared
    norm (the reference's ``lam_scale``) — NORMALIZED by the pixel count.

    Deliberate delta: the reference computes the raw inner product
    ``flat @ flat`` over all H*W*C sigmoid values (resnet50_test.py:
    420-424), a weight of order 10^3 — which makes the paired criterion
    ``lam*CE_a + (1-lam)*CE_b`` unbounded below (the (1-lam) term is
    ~-10^3), so training on that dead code path could only diverge
    (observed empirically: loss runs to large negative values within one
    epoch).  The mean of squares keeps the exact semantics — "how much
    of sample a survives the map, quadratically" — in [0, 1], where the
    mixup criterion is a genuine convex combination."""
    index = jax.random.permutation(key, x.shape[0])
    lam_map = jax.nn.sigmoid(lam_param).astype(x.dtype)
    mixed = lam_map * x + (1.0 - lam_map) * x[index]
    lam_scale = jnp.mean(lam_map.reshape(x.shape[0], -1) ** 2, axis=1)
    return mixed, y, y[index], lam_scale


def mixup_criterion(criterion: Callable, pred: jax.Array, y_a: jax.Array,
                    y_b: jax.Array, lam: jax.Array) -> jax.Array:
    """lam * CE(pred, y_a) + (1-lam) * CE(pred, y_b) — resnet50_test.py:451."""
    return lam * criterion(pred, y_a) + (1.0 - lam) * criterion(pred, y_b)


def mixup_criterion_meta(per_sample_criterion: Callable, pred: jax.Array,
                         y_a: jax.Array, y_b: jax.Array,
                         lam: jax.Array) -> jax.Array:
    """Per-sample-lambda criterion (resnet50_test.py:455-457): reduction
    'none' then mean, with lam shaped (batch,)."""
    losses = (lam * per_sample_criterion(pred, y_a)
              + (1.0 - lam) * per_sample_criterion(pred, y_b))
    return jnp.mean(losses)
