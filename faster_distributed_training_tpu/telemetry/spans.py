"""Span API: one name, two observability surfaces.

``with spans.span("restore"):`` does two things at once:

  * records the HOST wall time of the block into the active
    :class:`~faster_distributed_training_tpu.telemetry.recorder.
    TelemetryRecorder` (a ``{"kind": "span", ...}`` JSONL event), so
    ordinary runs get a span breakdown without any profiler attached;
  * wraps the block in ``jax.profiler.TraceAnnotation`` under the same
    ``fdt/<name>`` label, so when a trace IS being captured (``--profile``
    or the windowed ``--profile_steps A:B``) the identical names appear
    on the XLA timeline — the JSONL numbers and the trace annotate each
    other instead of living in two vocabularies.

The recorder is installed process-globally (:func:`set_recorder`) rather
than threaded through every constructor: the instrumented seams live in
modules that predate telemetry (resilience/manager.py's background
writer thread, data/device_resident.py's upload path) and must stay
usable — at zero overhead beyond two clock reads and the trace
annotation — when no recorder is active (bench floors, library use).
The recorder's buffer is lock-guarded, so spans may be recorded from
any thread (the checkpoint background writer does).

Span names in use (append-only — new names may be added, existing ones
are never renamed; README "Observability" documents them):

  ``h2d_upload``            device_resident split upload (once per run)
  ``epoch_reshard``         per-epoch order upload / batch-major re-shard
  ``ckpt_snapshot``         blocking device->host state fetch of a save
  ``ckpt_commit``           background serialize + two-phase commit
  ``ckpt_sync_save``        blocking (sync/emergency) collective save
  ``restore``               checkpoint restore walk (manager)
  ``rendezvous``            pod restore-agreement barrier (coordinator)
  ``eval``                  the per-epoch eval pass
  ``first_dispatch_compile`` first execution of a train program (compile)
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterator, List, Optional

_ACTIVE = None   # the installed TelemetryRecorder (or None)

# spans currently OPEN, any thread ({token: {name, t0, step, thread}}):
# the crash flight recorder (telemetry/flight.py) reads this so a host
# that dies inside restore/ckpt_commit/rendezvous names the phase it
# died in.  One lock-guarded dict add/remove per span — spans live at
# checkpoint/epoch boundaries, never per dispatch.
_OPEN: dict = {}
_OPEN_LOCK = threading.Lock()


def active_spans() -> List[dict]:
    """[{name, elapsed_ms, step?, thread}] of every span open right now
    (the flight-dump payload; empty when nothing is in flight)."""
    now = time.monotonic()
    with _OPEN_LOCK:
        out = []
        for info in _OPEN.values():
            rec = {"name": info["name"],
                   "elapsed_ms": round((now - info["t0"]) * 1e3, 3),
                   "thread": info["thread"]}
            if info["step"] is not None:
                rec["step"] = info["step"]
            out.append(rec)
        return out


def set_recorder(recorder) -> Optional[object]:
    """Install `recorder` as the process-global span sink; returns the
    previously installed one so callers can restore it (tests nest)."""
    global _ACTIVE
    prev, _ACTIVE = _ACTIVE, recorder
    return prev


def get_recorder():
    return _ACTIVE


@contextlib.contextmanager
def span(name: str, step: Optional[int] = None) -> Iterator[None]:
    """Record `name`'s host wall time to the active recorder AND label
    the same region ``fdt/<name>`` in any in-flight profiler trace.
    Exception-safe: a span that raises still records its duration (a
    failed restore's cost is exactly the kind of time MTTR wants)."""
    import jax

    t0 = time.monotonic()
    token = object()
    with _OPEN_LOCK:
        _OPEN[token] = {"name": name, "t0": t0, "step": step,
                        "thread": threading.current_thread().name}
    try:
        with jax.profiler.TraceAnnotation(f"fdt/{name}"):
            yield
    finally:
        with _OPEN_LOCK:
            _OPEN.pop(token, None)
        rec = _ACTIVE
        if rec is not None:
            rec.record_span(name, (time.monotonic() - t0) * 1e3, step=step)
