"""Pod aggregation + straggler visibility over per-host telemetry.

Each host writes its own ``host_<pi>.jsonl`` (recorder.py); process 0
folds them into run-level step-time percentiles at epoch end and flags
stragglers.  Transport is the r10 marker-file idiom — the one medium
every host (real pod on a shared checkpoint fs, or the
FDT_POD_INDEX-simulated pod) can reach without a working collective:
after flushing epoch ``e`` a host atomically writes
``epoch_<e>_host_<pi>.done``; process 0 waits a BOUNDED grace for the
markers (peers reach the epoch boundary seconds apart — aggregation is
observability, so it proceeds with whichever hosts reported rather than
stalling process 0's training on a slow peer) and logs one
``[telemetry]`` line:

    [telemetry] epoch 3: pod step p50=101.2ms p95=110.4ms p99=121.0ms
        over 1536 steps, 2/2 hosts
    [telemetry] straggler: host 1 p95=312.4ms > 2.0x pod median p95
        104.1ms

Straggler rule: a host whose own step-time p95 exceeds
``straggler_ratio`` x the pod's median host-p95.  The median is the LOW
median (``statistics.median_low``) so a 2-host pod can still flag its
slow half — an interpolated median of [fast, slow] sits between them
and a 3x-slow host would never cross 2x it.

Step-time definition (:func:`step_time_ms` — the ONE place it lives;
per-host stats, the pooled pod percentiles, and the incremental fold
all call it): ``dispatch_ms / k`` of non-``compile`` step records — the
jitted call alone, per train step; data wait and checkpoint blocking
are broken out per record and excluded, and first-execution (compile)
records never pollute the percentiles.

Run scoping: markers are TIME-SCOPED like the r10 EXIT markers —
process 0 honors a marker only when it is newer than this run's
telemetry (``newer_than``), so a relaunch into a reused directory can
never satisfy the epoch barrier with a previous attempt's residue.
The JSONL files themselves append across relaunches of the SAME run
(a supervised resume's pre-crash records are part of the run's story);
a FRESH run wants a fresh directory — the same contract the checkpoint
dir already documents (README: Observability / attempt()'s docstring).

The per-epoch fold on process 0 goes through :class:`RunFold`, which
remembers per-host byte offsets and accumulated reductions so each
epoch parses only the newly appended tail — a full-file re-parse per
epoch would be quadratic over the run.  :func:`aggregate_run` remains
the stateless whole-directory fold (report script, run end, tests).
"""

from __future__ import annotations

import glob
import json
import os
import re
import statistics
import time
from typing import Callable, Dict, List, Optional

from faster_distributed_training_tpu.train.metrics import percentiles

_HOST_FILE = re.compile(r"^host_(?P<pi>\d{5})\.jsonl$")
SUMMARY = "pod_summary.json"


def _epoch_marker(directory: str, epoch: int, pi: int) -> str:
    return os.path.join(directory, f"epoch_{epoch:04d}_host_{pi:05d}.done")


def publish_epoch_marker(directory: str, epoch: int, pi: int) -> None:
    """Durably announce that host ``pi`` has flushed its records through
    epoch ``epoch`` (written AFTER a flush(wait=True)).  Carries a wall
    timestamp so the aggregator can ignore a previous attempt's residue
    in a reused directory (time-scoping, the r10 EXIT-marker idiom)."""
    from faster_distributed_training_tpu.telemetry.recorder import (
        _write_json_atomic)
    _write_json_atomic(_epoch_marker(directory, epoch, pi),
                       {"epoch": int(epoch),
                        "unix_time": round(time.time(), 3)})


def step_time_ms(rec: dict, upto_epoch: Optional[int] = None
                 ) -> Optional[float]:
    """Per-train-step time of one JSONL record, or None when the record
    doesn't contribute (non-step kinds, compile records, epochs past
    ``upto_epoch``).  THE step-time definition — every consumer
    (per-host stats, pooled percentiles, incremental fold, report
    script) goes through here so they can never disagree."""
    if rec.get("kind") != "step" or rec.get("compile"):
        return None
    if upto_epoch is not None and rec.get("epoch", 0) > upto_epoch:
        return None
    return rec["dispatch_ms"] / max(rec.get("k", 1), 1)


def read_host_records(directory: str) -> Dict[int, List[dict]]:
    """{process_index: [records]} from every ``host_*.jsonl`` present.
    Torn trailing lines (a host mid-append) are skipped, not fatal —
    the stream is advisory, the next aggregation sees them whole."""
    out: Dict[int, List[dict]] = {}
    for path in sorted(glob.glob(os.path.join(directory, "host_*.jsonl"))):
        m = _HOST_FILE.match(os.path.basename(path))
        if not m:
            continue
        recs = []
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        recs.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
        out[int(m.group("pi"))] = recs
    return out


# -- per-host reductions (shared by the stateless and incremental paths) --

def _new_fold() -> dict:
    return {"steps": 0, "records": 0, "per_step_ms": [],
            "ex_s_sum": 0.0, "ex_s_n": 0,
            "data_ms_total": 0.0, "block_ms_total": 0.0}


def _accumulate(fold: dict, rec: dict,
                upto_epoch: Optional[int] = None) -> None:
    t = step_time_ms(rec, upto_epoch=upto_epoch)
    if t is None:
        return
    fold["per_step_ms"].append(t)
    fold["steps"] += int(rec.get("k", 1))
    fold["records"] += 1
    if rec.get("ex_s"):
        fold["ex_s_sum"] += float(rec["ex_s"])
        fold["ex_s_n"] += 1
    fold["data_ms_total"] += float(rec.get("data_ms", 0.0))
    fold["block_ms_total"] += float(rec.get("block_ms", 0.0))


def _host_stats(fold: dict) -> dict:
    stats = {"steps": fold["steps"], "records": fold["records"]}
    stats.update({f"step_ms_p{q}": v
                  for q, v in percentiles(fold["per_step_ms"]).items()})
    if fold["ex_s_n"]:
        stats["ex_s_mean"] = round(fold["ex_s_sum"] / fold["ex_s_n"], 1)
    stats["data_ms_total"] = round(fold["data_ms_total"], 1)
    stats["block_ms_total"] = round(fold["block_ms_total"], 1)
    return stats


def span_breakdown(records: List[dict]) -> Dict[str, dict]:
    """{span name: {count, total_ms, mean_ms}} over one host's stream."""
    out: Dict[str, dict] = {}
    for r in records:
        if r.get("kind") != "span":
            continue
        s = out.setdefault(r.get("name", "?"),
                           {"count": 0, "total_ms": 0.0})
        s["count"] += 1
        s["total_ms"] += float(r.get("dur_ms", 0.0))
    for s in out.values():
        s["total_ms"] = round(s["total_ms"], 3)
        s["mean_ms"] = round(s["total_ms"] / s["count"], 3)
    return out


def aggregate_folds(folds: Dict[int, dict],
                    straggler_ratio: float = 2.0) -> dict:
    """One summary from per-host folds: per-host and pooled p50/p95/p99
    per-step dispatch times + the straggler table (module docstring)."""
    folds = {pi: f for pi, f in folds.items() if f["records"]}
    per_host = {pi: _host_stats(f) for pi, f in sorted(folds.items())}
    out: dict = {"hosts": {str(pi): st for pi, st in per_host.items()},
                 "host_count": len(per_host),
                 "straggler_ratio": float(straggler_ratio),
                 "stragglers": []}
    pooled: List[float] = []
    for f in folds.values():
        pooled.extend(f["per_step_ms"])
    if pooled:
        out["pod"] = {"steps": sum(st["steps"]
                                   for st in per_host.values()),
                      **{f"step_ms_p{q}": v
                         for q, v in percentiles(pooled).items()}}
    if len(per_host) > 1:
        p95s = [st["step_ms_p95"] for st in per_host.values()]
        median_p95 = statistics.median_low(p95s)
        out["pod_median_host_p95_ms"] = median_p95
        for pi, st in per_host.items():
            if median_p95 > 0 and st["step_ms_p95"] > (straggler_ratio
                                                       * median_p95):
                out["stragglers"].append(
                    {"host": pi, "step_ms_p95": st["step_ms_p95"],
                     "pod_median_p95_ms": median_p95,
                     "ratio": round(st["step_ms_p95"] / median_p95, 2)})
    return out


def aggregate_run(directory: str, straggler_ratio: float = 2.0,
                  upto_epoch: Optional[int] = None) -> dict:
    """Stateless whole-directory fold (the report script, run end,
    tests); the per-epoch in-run path uses :class:`RunFold` instead."""
    folds: Dict[int, dict] = {}
    for pi, recs in read_host_records(directory).items():
        fold = _new_fold()
        for r in recs:
            _accumulate(fold, r, upto_epoch=upto_epoch)
        folds[pi] = fold
    return aggregate_folds(folds, straggler_ratio=straggler_ratio)


class RunFold:
    """Process 0's incremental run-level fold: remembers a byte offset
    into each host's JSONL and the accumulated reductions, so each
    epoch-end fold parses only the tail appended since the previous one
    (re-parsing every file from 0 each epoch is quadratic over the
    run).  Only COMPLETE lines are consumed — a host caught mid-append
    contributes that line next time.  A file that SHRANK (a relaunch
    replaced it) resets that host's state and re-reads from 0."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        self._offsets: Dict[int, int] = {}
        self._folds: Dict[int, dict] = {}

    def _consume(self) -> None:
        for path in sorted(glob.glob(os.path.join(self.directory,
                                                  "host_*.jsonl"))):
            m = _HOST_FILE.match(os.path.basename(path))
            if not m:
                continue
            pi = int(m.group("pi"))
            off = self._offsets.get(pi, 0)
            try:
                if os.path.getsize(path) < off:
                    off = 0
                    self._folds[pi] = _new_fold()
                with open(path, "rb") as f:
                    f.seek(off)
                    chunk = f.read()
            except OSError:
                continue
            cut = chunk.rfind(b"\n") + 1
            if not cut:
                continue
            fold = self._folds.setdefault(pi, _new_fold())
            for line in chunk[:cut].splitlines():
                if not line.strip():
                    continue
                try:
                    _accumulate(fold, json.loads(line))
                except ValueError:
                    continue
            self._offsets[pi] = off + cut

    def summary(self, straggler_ratio: float = 2.0) -> dict:
        self._consume()
        return aggregate_folds(self._folds,
                               straggler_ratio=straggler_ratio)


def pod_epoch_aggregate(directory: str, epoch: int, pi: int, pc: int,
                        straggler_ratio: float = 2.0,
                        log: Callable[[str], None] = print,
                        wait_s: float = 2.0,
                        fold: Optional[RunFold] = None,
                        newer_than: Optional[float] = None
                        ) -> Optional[dict]:
    """Process 0's epoch-end fold: wait (bounded) for every host's epoch
    marker, aggregate whatever reported, log the ``[telemetry]`` pod
    line + any straggler flags, and refresh ``pod_summary.json``.
    ``fold`` (a :class:`RunFold`) makes the parse incremental;
    ``newer_than`` (unix time) time-scopes the markers so a reused
    directory's residue can't satisfy the barrier.  Non-zero hosts
    return immediately (their work was the flush + marker the caller
    already did)."""
    if pi != 0:
        return None

    def _marker_fresh(p: int) -> bool:
        got = None
        try:
            with open(_epoch_marker(directory, epoch, p)) as f:
                got = json.load(f)
        except (OSError, ValueError):
            return False
        return (newer_than is None
                or got.get("unix_time", 0.0) > newer_than)

    deadline = time.monotonic() + max(wait_s, 0.0)
    want = set(range(pc))
    while True:
        have = {p for p in want if _marker_fresh(p)}
        if have >= want or time.monotonic() >= deadline:
            break
        time.sleep(0.05)
    if fold is not None:
        summary = fold.summary(straggler_ratio=straggler_ratio)
    else:
        summary = aggregate_run(directory, straggler_ratio=straggler_ratio,
                                upto_epoch=epoch)
    summary["epoch"] = int(epoch)
    summary["hosts_reported"] = sorted(have)
    # skipped hosts land in pod_summary.json, not just the log line — a
    # postmortem reading only the committed summary must see that the
    # fold was partial (and what grace it waited); --aggregate_grace_s
    # sizes the wait for slow CI hosts
    summary["hosts_missing"] = sorted(want - have)
    summary["grace_s"] = round(max(wait_s, 0.0), 3)
    pod = summary.get("pod")
    if pod:
        log(f"[telemetry] epoch {epoch}: pod step "
            f"p50={pod['step_ms_p50']:.1f}ms "
            f"p95={pod['step_ms_p95']:.1f}ms "
            f"p99={pod['step_ms_p99']:.1f}ms over {pod['steps']} steps, "
            f"{len(have)}/{pc} hosts")
    if len(have) < pc:
        log(f"[telemetry] epoch {epoch}: host(s) "
            f"{sorted(want - have)} had not flushed within "
            f"{wait_s:.1f}s — aggregated without them")
    for s in summary["stragglers"]:
        log(f"[telemetry] straggler: host {s['host']} "
            f"p95={s['step_ms_p95']:.1f}ms > {straggler_ratio:.1f}x pod "
            f"median p95 {s['pod_median_p95_ms']:.1f}ms "
            f"({s['ratio']:.2f}x)")
    try:
        from faster_distributed_training_tpu.telemetry.recorder import (
            _write_json_atomic)
        _write_json_atomic(os.path.join(directory, SUMMARY), summary)
    except OSError as e:
        log(f"[telemetry] could not write {SUMMARY}: {e!r}")
    return summary
