"""Run-scoped telemetry subsystem (r12).

Every training run — not just ``bench.py`` — emits a structured,
machine-readable record of itself:

  * ``recorder``  — :class:`TelemetryRecorder`: low-overhead host-side
    ring buffer of per-dispatch records (step, wall ms, examples/s,
    data-wait ms, checkpoint-blocking ms, K, epoch) flushed as JSONL to
    ``<telemetry_dir>/host_<pi>.jsonl`` by a background writer (the r7
    off-critical-path idiom), plus the run manifest
    (:func:`write_manifest`: config, mesh, jax/jaxlib versions, device
    kind) written once at startup;
  * ``spans``     — ``with spans.span("restore"):`` records host wall
    time AND labels the region in any in-flight ``jax.profiler`` trace
    under the same name; instrumented seams: H2D upload / epoch
    re-shard (data/device_resident.py), checkpoint snapshot/commit
    (resilience/manager.py), restore/rendezvous
    (resilience/{manager,coordinator}.py), eval, first-dispatch compile;
  * ``aggregate`` — process 0 folds the per-host JSONL into run-level
    p50/p95/p99 step times at epoch end (marker-file transport, the r10
    idiom) and flags stragglers in a ``[telemetry]`` log line;
  * windowed profiler capture rides beside it:
    ``--profile_steps A:B`` (utils/profiling.StepWindowProfiler) starts/
    stops ``jax.profiler`` around a step range mid-run.

Kill switch: ``FDT_TELEMETRY=0`` (or ``--no_telemetry``) disables the
whole subsystem — :func:`build_telemetry` returns None and the Trainer's
hot loop has zero new work.  The ``telemetry_overhead_pct`` bench arm
guards the enabled cost at <1% of median step time.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional

from faster_distributed_training_tpu.telemetry import flight  # noqa: F401
from faster_distributed_training_tpu.telemetry import programs  # noqa: F401
from faster_distributed_training_tpu.telemetry import spans  # noqa: F401
from faster_distributed_training_tpu.telemetry.aggregate import (  # noqa: F401,E501
    RunFold, aggregate_run, pod_epoch_aggregate, publish_epoch_marker,
    read_host_records, span_breakdown, step_time_ms)
from faster_distributed_training_tpu.telemetry.programs import (  # noqa: F401,E501
    ObservedJit, ProgramObservatory, sharding_fingerprint, sharding_table,
    state_bytes_table)
from faster_distributed_training_tpu.telemetry.recorder import (  # noqa: F401,E501
    ENV_KILL, MANIFEST, SCHEMA_VERSION, TELEMETRY_SCHEMA, TelemetryRecorder,
    update_manifest, write_manifest)


def resolve_telemetry_dir(cfg) -> str:
    """The run's telemetry directory: ``--telemetry_dir`` when set, else
    ``<checkpoint_dir>/telemetry`` — beside the checkpoints so pods
    already sharing a checkpoint fs share the telemetry surface too
    (the aggregation transport depends on it)."""
    explicit = getattr(cfg, "telemetry_dir", "") or ""
    if explicit:
        return explicit
    return os.path.join(getattr(cfg, "checkpoint_dir", "."), "telemetry")


class RunTelemetry:
    """The bundle the Trainer/cli consume: the recorder plus the pod
    aggregation policy.  Thin by design — the hot path talks straight to
    ``self.recorder``; this object owns the epoch-boundary fold and the
    lifecycle."""

    def __init__(self, recorder: TelemetryRecorder,
                 straggler_ratio: float = 2.0,
                 aggregate_wait_s: float = 2.0,
                 log: Callable[[str], None] = print):
        self.recorder = recorder
        self.directory = recorder.directory
        self.pi, self.pc = recorder.pi, recorder.pc
        self.straggler_ratio = float(straggler_ratio)
        self.aggregate_wait_s = float(aggregate_wait_s)
        self._log = log
        self._closed = False
        # the compile observatory (telemetry/programs.py): the Trainer
        # routes its jit compiles through it so every program records
        # compile ms / HLO fingerprint / cache verdict / memory bytes.
        # FDT_PROGRAM_OBS=0 removes it (plain jit dispatch, no program
        # events) while the rest of telemetry stays on.
        self.observatory = (ProgramObservatory(recorder=recorder, log=log)
                            if programs.observatory_enabled() else None)
        # incremental per-epoch fold state (process 0 only): each epoch
        # parses only the JSONL tails appended since the last fold
        self._fold = RunFold(self.directory) if self.pi == 0 else None
        # epoch markers older than this run's telemetry are a previous
        # attempt's residue in a reused directory and must not satisfy
        # the aggregation barrier (time-scoping, the r10 idiom)
        self._created_t = time.time()

    def end_epoch(self, epoch: int) -> Optional[dict]:
        """Epoch boundary: flush this host's records to disk, publish
        the epoch marker, and (process 0) fold all hosts into the
        ``[telemetry]`` pod line + straggler flags."""
        self.recorder.flush(wait=True)
        publish_epoch_marker(self.directory, epoch, self.pi)
        return pod_epoch_aggregate(
            self.directory, epoch, self.pi, self.pc,
            straggler_ratio=self.straggler_ratio, log=self._log,
            wait_s=self.aggregate_wait_s if self.pc > 1 else 0.0,
            fold=self._fold, newer_than=self._created_t)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.observatory is not None and self.pi == 0:
            # merge the program table into manifest.json (written at
            # STARTUP, before anything compiled): per program, compile
            # ms / fingerprint / cache verdict / memory breakdown — the
            # run's compile story survives the process.  Before
            # recorder.close() so a manifest-write crash can't orphan
            # the stream tail.
            try:
                from faster_distributed_training_tpu.telemetry.recorder \
                    import update_manifest
                update_manifest(self.directory,
                                {"compile": self.observatory.summary()})
            except Exception:
                pass
        self.recorder.close()
        if self.pi == 0:
            # refresh the committed run-level summary one last time (the
            # last epoch's fold may predate the final records); quiet —
            # the per-epoch lines already told the story
            try:
                from faster_distributed_training_tpu.telemetry.aggregate \
                    import SUMMARY
                from faster_distributed_training_tpu.telemetry.recorder \
                    import _write_json_atomic
                summary = aggregate_run(
                    self.directory, straggler_ratio=self.straggler_ratio)
                if summary.get("hosts"):
                    _write_json_atomic(
                        os.path.join(self.directory, SUMMARY), summary)
            except OSError:
                pass


def build_telemetry(cfg, log: Callable[[str], None] = print
                    ) -> Optional[RunTelemetry]:
    """RunTelemetry for a TrainConfig, or None when disabled
    (``--no_telemetry`` / ``FDT_TELEMETRY=0`` — the kill switch the
    bench overhead arm and emergency rollbacks rely on)."""
    if os.environ.get(ENV_KILL, "1") == "0":
        return None
    if not getattr(cfg, "telemetry", True):
        return None
    recorder = TelemetryRecorder(
        resolve_telemetry_dir(cfg),
        step_every=int(getattr(cfg, "telemetry_every", 1) or 1), log=log)
    return RunTelemetry(
        recorder,
        straggler_ratio=float(getattr(cfg, "straggler_ratio", 2.0) or 2.0),
        # --aggregate_grace_s: how long process 0 waits for the peers'
        # epoch markers before folding without them (the hard-coded 2 s
        # raced slow CI hosts; skipped hosts are now also recorded in
        # pod_summary.json, aggregate.pod_epoch_aggregate)
        aggregate_wait_s=float(
            getattr(cfg, "aggregate_grace_s", 2.0) or 0.0),
        log=log)
