"""Compile observatory: per-PROGRAM evidence for every jit the run builds.

r12 gave every run per-dispatch telemetry; this module climbs one level
to the PROGRAM.  ROADMAP's "instant restart" item says real-hardware
MTTR is compile-dominated, yet until now no run recorded what its
compiles actually cost, whether the persistent compilation cache served
them, or whether a program quietly re-traced — and the ZeRO item needs
``opt_state_bytes_per_chip`` before anyone can size that win.  Three
pieces close the gap:

  * :class:`ProgramObservatory` + :class:`ObservedJit` — the Trainer's
    jitted programs (train per (path, K), eval, the device-resident
    epoch re-shard) go through an EXPLICIT ``lower()`` / ``compile()``
    on their first call per input signature, so every program records:
    compile wall ms, a stable HLO fingerprint (sha256 of
    ``lowered.as_text()``), a persistent-compilation-cache verdict
    (cache-dir stat before/after, falling back to the
    min-compile-time threshold — the method used is recorded beside the
    verdict), and the executable's ``memory_analysis()`` byte breakdown
    (argument/output/temp/generated).  Steady-state calls go straight to
    the AOT executable (measured ~0.5 us over the jit C++ fast path on
    CPU — program collection happens at compile boundaries, never
    per-dispatch, which is what keeps ``telemetry_overhead_pct`` under
    its <1% guard).
  * the RETRACE detector — lowerings are counted per program name; a
    name lowering again with the SAME signature, or with a signature
    that differs only in dtype/weak-type (the classic non-weak-type
    scalar leak), or past ``max_variants`` total (a shape leak), emits a
    loud ``retrace`` telemetry event AND a Python warning.  Legitimate
    shape polymorphism (text bucket widths, the padded final eval batch)
    shows up as counted VARIANTS of one name, not as retraces;
    tests/test_programs.py pins the exact program set a CPU run
    compiles, so an accidental extra program fails tier-1.
  * HBM attribution helpers — :func:`state_bytes_table` splits the
    train state's per-chip bytes params vs opt_state vs batch_stats
    (``opt_state_bytes_per_chip`` is THE number ROADMAP's ZeRO item is
    specified against; bench.py lands it as a committed baseline), and
    :func:`sharding_fingerprint` / :func:`sharding_table` are the
    sharding-DRIFT guard: the Trainer fingerprints the live state's
    shardings after step 1 and re-checks at every epoch boundary,
    raising the r11 params-drift bug class from "measured once" to
    "guarded" (cheap hash always on; ``--debug`` keeps the per-leaf
    table so a drift names the leaves that moved).

Every event lands in the r12 JSONL stream (kinds are APPEND-ONLY:
``program``, ``retrace``, ``memory`` join the r12 set) and the program
table is merged into ``manifest.json`` at run end, so a telemetry
directory answers "what did this run compile and what did it cost"
without the process that wrote it.

Kill switch: ``FDT_PROGRAM_OBS=0`` — the Trainer falls back to plain
``jax.jit`` dispatch (byte-identical programs, no program events).
``FDT_HLO_FINGERPRINT=0`` skips the ``as_text()`` hash for very large
programs (the rest of the record is unaffected).

r17 instant restart: when a
:class:`~faster_distributed_training_tpu.resilience.executable_cache
.ExecutableCache` is installed on the observatory, observe_compile
becomes lookup-before-compile / store-after-compile and every program
record carries a ``cache_source`` verdict —  ``"deserialized"`` (the
executable tier served it; compile_ms is the deserialize time),
``"persistent_dir"`` (XLA's persistent cache dir served the compile),
or ``"compiled"`` (full price paid, and the executable tier stored it
for the next restart).  ``summary()``'s ``total_compile_ms`` therefore
reads as the run's total program-ACQUISITION cost either way, which is
exactly the restart-MTTR compile component.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

ENV_KILL = "FDT_PROGRAM_OBS"
ENV_FINGERPRINT = "FDT_HLO_FINGERPRINT"

# process-global observatory (the spans.set_recorder idiom): modules
# that predate telemetry (data/device_resident.py's epoch re-shard)
# reach it without threading it through their constructors
_ACTIVE = None


def set_observatory(obs) -> Optional[object]:
    """Install the process-global observatory; returns the previous one
    so callers can restore it (tests nest)."""
    global _ACTIVE
    prev, _ACTIVE = _ACTIVE, obs
    return prev


def get_observatory():
    return _ACTIVE


def observatory_enabled() -> bool:
    return os.environ.get(ENV_KILL, "1") != "0"


def _leaf_sig(x) -> Tuple[tuple, str, bool]:
    """(shape, dtype, weak) of one argument leaf — the aval identity the
    retrace detector compares.  Python scalars are weak-typed (jax
    semantics); arrays carry their own weak_type flag."""
    import numpy as np

    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype),
                bool(getattr(x, "weak_type", False)))
    a = np.asarray(x)
    return (tuple(a.shape), str(a.dtype),
            isinstance(x, (bool, int, float, complex)))


def args_signature(args, argnums) -> tuple:
    """Hashable signature of the designated positional args: tree
    structure + per-leaf (shape, dtype, weak)."""
    import jax

    parts = []
    for i in argnums:
        leaves, treedef = jax.tree_util.tree_flatten(args[i])
        parts.append((treedef, tuple(_leaf_sig(x) for x in leaves)))
    return tuple(parts)


def _sig_shapes(sig) -> tuple:
    """The shape-only projection of a signature — two signatures with
    equal shapes but unequal dtypes/weak flags are the scalar-leak
    retrace class."""
    return tuple((treedef, tuple(s[0] for s in leaf_sigs))
                 for treedef, leaf_sigs in sig)


def _sig_text(sig, limit: int = 240) -> str:
    """Compact human-readable aval summary for retrace diagnostics."""
    bits = []
    for _treedef, leaf_sigs in sig:
        for shape, dtype, weak in leaf_sigs:
            bits.append(f"{dtype}{list(shape)}" + ("w" if weak else ""))
    txt = ",".join(bits)
    return txt if len(txt) <= limit else txt[:limit] + "..."


def memory_analysis_dict(compiled) -> Optional[Dict[str, int]]:
    """The executable's memory_analysis() as plain bytes fields, None
    when the backend exposes none.  Shares field meaning with
    utils.profiling.compiled_memory_bytes (which nets out aliased
    donated buffers for the single peak estimate); here the raw
    components are kept separate — attribution, not one headline."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for field in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "generated_code_size_in_bytes",
                  "alias_size_in_bytes"):
        out[field.replace("_size_in_bytes", "_bytes")] = int(
            getattr(ma, field, 0) or 0)
    return out


class ProgramObservatory:
    """Owns the run's compile record.  Thread-safe (the checkpoint
    background writer never compiles, but nothing here assumes that).

    ``recorder`` (a TelemetryRecorder, optional) receives one
    ``program`` event per observed compile and one ``retrace`` event per
    detection; :meth:`summary` is the table RunTelemetry merges into
    manifest.json at run end."""

    def __init__(self, recorder=None, log: Callable[[str], None] = print,
                 max_variants: int = 8):
        self.recorder = recorder
        self._log = log
        self.max_variants = int(max_variants)
        self._lock = threading.Lock()
        # name -> [entry dicts in lowering order]; entries keep their
        # signature under the private "_sig" key (stripped from events)
        self.programs: Dict[str, List[dict]] = {}
        self.retraces: List[dict] = []
        self._variant_flood_warned: set = set()
        # r17 instant-restart wiring, both installed post-construction
        # by cli.run_training:
        #  * executable_cache (resilience/executable_cache.py) turns
        #    observe_compile into lookup-before-compile /
        #    store-after-compile — a restarted process deserializes its
        #    programs (cache_source="deserialized") instead of
        #    recompiling; any cache failure degrades to plain compile;
        #  * goodput (resilience/goodput.py) receives every observed
        #    program-acquisition cost (lower + compile OR deserialize)
        #    so restart MTTR can split into _compile_s vs _restore_s
        #    components — the compile-dominated half was invisible
        #    before.
        self.executable_cache = None
        self.goodput = None

    # -- the compile path --------------------------------------------------

    def wrap(self, name: str, jitted, sig_argnums: Tuple[int, ...] = ()
             ) -> "ObservedJit":
        return ObservedJit(name, jitted, self, sig_argnums=sig_argnums)

    def observe_compile(self, name: str, jitted, args,
                        sig: Optional[tuple] = None):
        """Explicit lower+compile of ``jitted`` for ``args`` under
        observation; returns the AOT compiled callable, or None when the
        AOT path is unavailable (caller falls back to plain jit dispatch
        — observability must never kill training)."""
        try:
            t0 = time.monotonic()
            lowered = jitted.lower(*args)
            lower_ms = (time.monotonic() - t0) * 1e3
            fingerprint = self._fingerprint(lowered)
            # r17 executable cache: lookup-before-compile.  A hit
            # deserializes the stored executable (compile_ms below IS
            # the deserialize time — the restart-MTTR number the A/B
            # reads); any load failure returned None and the plain
            # compile below serves the program.  Fingerprint "" (the
            # FDT_HLO_FINGERPRINT=0 escape) has no key and skips the
            # tier entirely.
            ec = self.executable_cache
            exec_key = (ec.key_for(name, fingerprint)
                        if ec is not None and fingerprint else None)
            compiled = None
            if exec_key is not None:
                t0 = time.monotonic()
                compiled = ec.load(exec_key, lowered)
            if compiled is not None:
                compile_ms = (time.monotonic() - t0) * 1e3
                cache, method = "bypassed", "executable_cache"
                source = "deserialized"
            else:
                before = self._cache_listing()
                t0 = time.monotonic()
                compiled = lowered.compile()
                compile_ms = (time.monotonic() - t0) * 1e3
                cache, method = self._cache_verdict(before, compile_ms)
                # "persistent_dir": XLA's own persistent cache served
                # the compile (the executable tier's designed fallback)
                source = "persistent_dir" if cache == "hit" else "compiled"
                if exec_key is not None:
                    if cache in ("miss", "off", "below_threshold"):
                        ec.store(exec_key, compiled)  # best-effort, counted
                    else:
                        # served (or unverifiable, remote-dir "unknown"):
                        # a persistent-cache-served executable does NOT
                        # serialize round-trippably on XLA:CPU (missing
                        # function symbols at deserialize) — only fresh
                        # compiles are stored; the persistent dir keeps
                        # serving this program at restart regardless
                        ec.note_skipped_served()
            mem = memory_analysis_dict(compiled)
        except Exception as e:
            self._log(f"[programs] could not observe-compile {name!r} "
                      f"({e!r}); plain jit dispatch serves it (no program "
                      f"record)")
            return None
        if self.goodput is not None:
            # program-acquisition cost (trace + compile-or-deserialize):
            # the MTTR compile component a restarted process pays
            try:
                self.goodput.add_compile((lower_ms + compile_ms) / 1e3)
            except Exception:
                pass  # accounting must never kill the compile path
        self._record(name, sig, lower_ms, compile_ms, fingerprint, cache,
                     method, mem, source)
        return compiled

    def _record(self, name, sig, lower_ms, compile_ms, fingerprint,
                cache, method, mem, source: str = "compiled") -> None:
        with self._lock:
            entries = self.programs.setdefault(name, [])
            self._detect_retrace(name, entries, sig)
            entry = {"variant": len(entries),
                     "compile_ms": round(compile_ms, 2),
                     "lower_ms": round(lower_ms, 2),
                     "fingerprint": fingerprint,
                     "cache": cache, "cache_method": method,
                     "cache_source": source,
                     "avals": _sig_text(sig) if sig else "",
                     "_sig": sig}
            if mem:
                entry.update(mem)
            entries.append(entry)
        if self.recorder is not None:
            ev = {"name": name, "lowerings": len(entries),
                  "variant": entry["variant"],
                  "compile_ms": entry["compile_ms"],
                  "lower_ms": entry["lower_ms"],
                  "fingerprint": entry["fingerprint"],
                  "cache": entry["cache"],
                  "cache_method": entry["cache_method"],
                  "cache_source": entry["cache_source"],
                  "avals": entry["avals"]}
            if mem:
                ev.update(mem)
            self.recorder.record_event("program", **ev)

    def _detect_retrace(self, name, entries, sig) -> None:
        """Called under the lock BEFORE the new entry lands.  Three
        accidental-retrace classes (module docstring); legitimate shape
        variants pass silently."""
        reason = None
        prev = None
        if sig is not None:
            for e in entries:
                if e["_sig"] == sig:
                    reason, prev = "duplicate-avals", e
                    break
                if (e["_sig"] is not None
                        and _sig_shapes(e["_sig"]) == _sig_shapes(sig)):
                    reason, prev = "dtype-or-weak-type-leak", e
                    break
        if (reason is None and len(entries) + 1 > self.max_variants
                and name not in self._variant_flood_warned):
            self._variant_flood_warned.add(name)
            reason = "variant-flood"
        if reason is None:
            return
        msg = (f"program {name!r} re-traced ({reason}): lowering "
               f"#{len(entries) + 1}, avals "
               f"[{_sig_text(sig) if sig else '?'}]"
               + (f" vs prior [{prev['avals']}]" if prev else "")
               + " — an accidental retrace re-pays the whole compile "
                 "(check for a non-weak-type scalar or shape leak)")
        warnings.warn(msg, stacklevel=3)
        self._log(f"[programs] WARNING: {msg}")
        ev = {"name": name, "reason": reason,
              "lowerings": len(entries) + 1,
              "avals": _sig_text(sig) if sig else "",
              "prev_avals": prev["avals"] if prev else ""}
        self.retraces.append(ev)
        if self.recorder is not None:
            self.recorder.record_event("retrace", **ev)

    # -- cache + fingerprint ----------------------------------------------

    def _fingerprint(self, lowered) -> str:
        if os.environ.get(ENV_FINGERPRINT, "1") == "0":
            return ""
        try:
            return hashlib.sha256(
                lowered.as_text().encode()).hexdigest()[:16]
        except Exception:
            return ""

    @staticmethod
    def _cache_config() -> Tuple[Optional[str], float]:
        import jax

        d = getattr(jax.config, "jax_compilation_cache_dir", None)
        mn = getattr(jax.config,
                     "jax_persistent_cache_min_compile_time_secs", 1.0)
        return d or None, float(mn or 0.0)

    def _cache_listing(self) -> Optional[set]:
        d, _ = self._cache_config()
        if not d or "://" in d or not os.path.isdir(d):
            return None
        try:
            return set(os.listdir(d))
        except OSError:
            return None

    def _cache_verdict(self, before: Optional[set],
                       compile_ms: float) -> Tuple[str, str]:
        """(verdict, method): "miss" = a new cache entry appeared (this
        compile paid full price and stored it), "hit" = no new entry and
        the compile was above the store threshold (served from cache),
        "below_threshold" = too fast to ever be stored, "off" = no cache
        configured at all, "unknown" = a cache IS configured but cannot
        be stat'd (a remote gs:// cache dir) and the compile was above
        the store threshold — hit and miss are indistinguishable from
        timing alone there.  The method field records which rule
        produced the verdict ("dir_stat" vs "timing_threshold")."""
        d, min_secs = self._cache_config()
        after = self._cache_listing()
        if before is None or after is None:
            if not d:
                return "off", "none"
            # a cache dir exists but can't be stat'd (object store URI):
            # the threshold heuristic is all we have
            return (("below_threshold"
                     if compile_ms < min_secs * 1e3 else "unknown"),
                    "timing_threshold")
        if after - before:
            return "miss", "dir_stat"
        if compile_ms < min_secs * 1e3:
            return "below_threshold", "dir_stat"
        return "hit", "dir_stat"

    # -- the run-level table ----------------------------------------------

    def summary(self) -> dict:
        """The manifest section: per program name, lowerings + every
        variant's compile record; plus the retrace list and the run's
        total compile spend."""
        with self._lock:
            progs = []
            total_ms = 0.0
            for name, entries in sorted(self.programs.items()):
                variants = [{k: v for k, v in e.items() if k != "_sig"}
                            for e in entries]
                total_ms += sum(e["compile_ms"] for e in entries)
                progs.append({"name": name, "lowerings": len(entries),
                              "variants": variants})
            return {"programs": progs,
                    "retraces": list(self.retraces),
                    "total_compile_ms": round(total_ms, 1)}


class ObservedJit:
    """A jitted callable under observation: the first call per input
    signature goes through the observatory's explicit lower/compile;
    every later call goes straight to the AOT executable.

    ``sig_argnums`` names the positional args whose avals may legally
    vary between calls (the batch; text buckets compile one variant per
    width) — everything else (the train state) is signature-stable by
    contract.  If that contract is ever violated the AOT call raises
    before executing (donation untouched), the wrapper re-observes, and
    the duplicate lowering surfaces as a ``retrace`` event — the
    detector and the dispatcher are the same mechanism.  Any observe
    failure degrades permanently to plain jit dispatch for this
    program."""

    def __init__(self, name: str, jitted, observatory: ProgramObservatory,
                 sig_argnums: Tuple[int, ...] = ()):
        self.name = name
        self._jit = jitted
        self._obs = observatory
        self._sig_argnums = tuple(sig_argnums)
        self._by_sig: Dict[tuple, Any] = {}
        self._single = None        # the fast path while one variant exists
        self._fallback = False

    def __call__(self, *args):
        if self._fallback:
            return self._jit(*args)
        one = self._single
        if one is not None:
            try:
                return one(*args)
            except (TypeError, ValueError):
                # signature changed under us (both checks run BEFORE
                # execution, so donated buffers are untouched): resolve
                # through the slow path below
                pass
        sig = args_signature(args, self._sig_argnums)
        fn = self._by_sig.get(sig)
        if fn is not None:
            try:
                return fn(*args)
            except (TypeError, ValueError):
                # a non-signature arg's avals moved (the state): the
                # re-observe below records the duplicate as a retrace
                fn = None
        fn = self._obs.observe_compile(self.name, self._jit, args, sig=sig)
        if fn is None:
            self._fallback = True
            return self._jit(*args)
        self._by_sig[sig] = fn
        self._single = fn if len(self._by_sig) == 1 else None
        return fn(*args)


def wrap_jit(name: str, jitted, sig_argnums: Tuple[int, ...] = ()):
    """Wrap through the process-global observatory when one is active;
    identity otherwise (zero overhead for library use without
    telemetry)."""
    obs = get_observatory()
    if obs is None:
        return jitted
    return obs.wrap(name, jitted, sig_argnums=sig_argnums)


# -- HBM attribution ------------------------------------------------------

# the state table's field vocabulary, shared with the telemetry schema
# registry (scripts/check_telemetry_schema.py resolves the
# record_event("memory", **state_bytes_table(...)) splat through this
# tuple — renaming a field here without the registry fails tier-1)
STATE_MEMORY_FIELDS = (
    "scope", "params_bytes_per_chip", "params_leaves",
    "opt_state_bytes_per_chip", "opt_state_leaves",
    "batch_stats_bytes_per_chip", "batch_stats_leaves",
    "total_bytes_per_chip", "top_leaves", "opt_state_tiers",
    "pp_residency")


def leaf_bytes_per_chip(leaf) -> int:
    """Bytes ONE chip holds for this leaf: the sum of its addressable
    shards on a single device (replicated leaf -> full nbytes; a leaf
    sharded tp-ways -> nbytes/tp).  Host numpy leaves (a just-restored
    state) count their full size — they land replicated."""
    shards = getattr(leaf, "addressable_shards", None)
    if shards:
        dev = shards[0].device
        return int(sum(s.data.nbytes for s in shards if s.device == dev))
    return int(getattr(leaf, "nbytes", 0))


def leaf_spec_axes(leaf) -> set:
    """The set of mesh axis names a live leaf's PartitionSpec uses
    (tuple entries flattened); empty for replicated/host leaves.  The
    r23 pp-residency column of the HBM table is built from it."""
    sh = getattr(leaf, "sharding", None)
    spec = getattr(sh, "spec", None)
    axes: set = set()
    if spec is None:
        return axes
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            axes.update(a for a in entry if a)
        else:
            axes.add(entry)
    return axes


def leaf_tier(leaf) -> str:
    """Placement tier of one live leaf, for the ZeRO per-leaf
    attribution: 'offloaded' (pinned_host memory kind), 'sharded'
    (split across devices), 'replicated' (full copy per chip), or
    'host' (plain numpy — a restored-not-yet-placed state)."""
    sh = getattr(leaf, "sharding", None)
    if sh is None:
        return "host"
    if getattr(sh, "memory_kind", None) == "pinned_host":
        return "offloaded"
    try:
        if sh.is_fully_replicated:
            return "replicated"
    except Exception:
        pass
    return "sharded"


def state_bytes_table(state, top: int = 5) -> dict:
    """Per-chip byte attribution of a TrainState, split params vs
    opt_state vs batch_stats.  ``opt_state_bytes_per_chip`` is the
    number ROADMAP's ZeRO item sized its win against (r15 committed the
    replicated baseline; the ZeRO overlay's drop is measured from it);
    ``top_leaves`` names the largest individual leaves with their
    placement tier, and ``opt_state_tiers`` attributes every opt-state
    leaf to its sharded/replicated/offloaded tier so the ZeRO layout is
    auditable per run."""
    import jax

    out: dict = {"scope": "state"}
    sized: List[Tuple[int, str, str]] = []
    total = 0
    tiers: Dict[str, Dict[str, int]] = {}
    # r23 per-stage residency column: how many leaves of each group
    # actually occupy a pp coordinate, and how many bytes one chip
    # holds for them — the per-run record that ~1/S of the stage-owned
    # state lives on each stage (all zeros on every pp=1 or
    # --no_pp_residency run)
    ppres: Dict[str, Dict[str, int]] = {}
    for group in ("params", "opt_state", "batch_stats"):
        tree = getattr(state, group, None)
        flat = jax.tree_util.tree_flatten_with_path(tree)[0]
        b = 0
        pp_leaves = pp_bytes = 0
        for path, leaf in flat:
            n = leaf_bytes_per_chip(leaf)
            b += n
            tier = leaf_tier(leaf)
            sized.append((n, group + jax.tree_util.keystr(path), tier))
            if "pp" in leaf_spec_axes(leaf):
                pp_leaves += 1
                pp_bytes += n
            if group == "opt_state":
                agg = tiers.setdefault(tier,
                                       {"leaves": 0, "bytes_per_chip": 0})
                agg["leaves"] += 1
                agg["bytes_per_chip"] += n
        out[f"{group}_bytes_per_chip"] = b
        out[f"{group}_leaves"] = len(flat)
        ppres[group] = {"leaves": pp_leaves, "bytes_per_chip": pp_bytes}
        total += b
    out["total_bytes_per_chip"] = total
    out["top_leaves"] = [
        {"path": p, "bytes_per_chip": n, "tier": t}
        for n, p, t in sorted(sized, reverse=True)[:top]]
    out["opt_state_tiers"] = tiers
    out["pp_residency"] = ppres
    return out


# -- sharding drift guard -------------------------------------------------

def sharding_table(state) -> Dict[str, str]:
    """{leaf path: sharding descriptor} over the whole train state —
    the debug-mode side of the drift guard (a drift names its leaves).
    Host (numpy) leaves read "host": a restored-but-not-yet-re-placed
    state legitimately differs from the live one, which is why the
    Trainer re-anchors the fingerprint after every restore instead of
    comparing across one."""
    import jax

    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        sh = getattr(leaf, "sharding", None)
        out[jax.tree_util.keystr(path)] = repr(sh) if sh is not None \
            else "host"
    return out


def sharding_fingerprint(state) -> str:
    """Cheap always-on hash of the live state's actual shardings —
    computed after step 1 and re-checked at epoch boundaries by the
    Trainer.  The r11 bug class this guards: without the output
    constraint, XLA re-sharded donated params between steps (measured:
    pos_embedding drifted onto sp after step 1); the constraint fixed
    it, this keeps it fixed."""
    h = hashlib.sha1()
    for path, desc in sorted(sharding_table(state).items()):
        h.update(path.encode())
        h.update(desc.encode())
    return h.hexdigest()[:16]
