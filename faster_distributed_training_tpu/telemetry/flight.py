"""Crash flight recorder: a dead process leaves forensics behind.

The r12 JSONL stream tells a run's story — but only the part the
background writer flushed before the process died, and a SIGKILLed or
crashed host's most interesting seconds are exactly the unflushed tail.
This module durably dumps, at the moment of failure, everything the
process knows about itself:

  * the recorder's in-memory RING of recent records (recorder.py keeps
    the last ``recent`` records — flushed or not — in a bounded deque
    precisely for this dump);
  * the spans currently OPEN (a host that dies inside ``restore`` or
    ``ckpt_commit`` names the phase it died in, with elapsed ms);
  * the goodput/MTTR snapshot, the compile-observatory program table
    (telemetry/programs.py), the triggering exception with traceback,
    and the drop counter.

The dump rides the r14 :class:`StorageBackend` when the resilience
bundle has one (``telemetry/flight_<pi>_<ts>.json`` — on a pod the
shared medium is exactly where the survivors/postmortem can read it;
posix otherwise).  Callers are the failure seams ISSUE 11 names:
``Supervisor.run``'s except branch and ``PodCoordinator.record_failure``
(every restartable failure), the watchdog's hard-abort path (dumped
from a side thread with a bounded join so a wedged filesystem cannot
veto the SIGKILL), and ``cli.run_training``'s unhandled-exception
escape.  Dumps are deduplicated per exception object, so one incident
traversing several seams lands one file.

Everything here is best-effort by construction: a flight recorder that
can itself crash the plane is worse than none — every failure path
logs and returns None.

Render with ``python scripts/telemetry_report.py <dir> --flight``.
"""

from __future__ import annotations

import json
import os
import time
import traceback
from typing import Callable, List, Optional, Tuple

FLIGHT_PREFIX = "flight_"

# process-global dump target, installed by cli.run_training beside the
# span recorder (configure/restore in its finally): the failure seams
# (supervisor, coordinator watchdog) reach it without new constructor
# plumbing, and an unconfigured process (library use, telemetry off)
# makes every dump a no-op
_CONFIG: Optional[dict] = None
# dedupe marker set ON the exception object itself (built-in exceptions
# are not weakref-able, and a bare-id() registry would let a gc'd
# exception's reused address silently suppress the dump for a later,
# unrelated crash — the opposite of best-effort); an attribute dies
# with the object, so the dedupe is exactly as long-lived as the
# incident it marks
_DUMPED_ATTR = "_fdt_flight_dumped"


def configure(directory: Optional[str], backend=None, goodput=None,
              log: Callable[[str], None] = print) -> Optional[dict]:
    """Install the dump target (None disables).  Returns the previous
    configuration so callers can restore it."""
    global _CONFIG
    prev = _CONFIG
    _CONFIG = (None if directory is None
               else {"directory": directory, "backend": backend,
                     "goodput": goodput, "log": log})
    return prev


def restore(prev: Optional[dict]) -> None:
    global _CONFIG
    _CONFIG = prev


def configured() -> bool:
    return _CONFIG is not None


def emergency_dump(reason: str, exc: Optional[BaseException] = None,
                   step: Optional[int] = None,
                   extra: Optional[dict] = None) -> Optional[str]:
    """Write the flight dump; returns its path, or None (unconfigured,
    duplicate exception, or a dump failure — logged, never raised)."""
    cfg = _CONFIG
    if cfg is None:
        return None
    if exc is not None:
        # one incident traverses several seams (record_failure, then the
        # supervisor-exhausted re-raise escaping run_training): dump once
        if getattr(exc, _DUMPED_ATTR, False):
            return None
        try:
            setattr(exc, _DUMPED_ATTR, True)
        except (AttributeError, TypeError):
            pass    # __slots__ exception without a dict: dump every time
    log = cfg.get("log") or (lambda *_: None)
    try:
        payload = build_payload(reason, exc=exc, step=step,
                                goodput=cfg.get("goodput"), extra=extra)
        path = os.path.join(
            cfg["directory"],
            f"{FLIGHT_PREFIX}{payload['process_index']:05d}_"
            f"{int(payload['unix_time'] * 1e3)}.json")
        backend = cfg.get("backend")
        if backend is not None:
            backend.put_json(path, payload)
        else:
            from faster_distributed_training_tpu.telemetry.recorder import (
                _write_json_atomic)
            os.makedirs(cfg["directory"], exist_ok=True)
            _write_json_atomic(path, payload)
    except Exception as e:
        try:
            log(f"[flight] could not write flight dump ({e!r}) — the "
                f"JSONL stream (whatever was flushed) is the remaining "
                f"record")
        except Exception:
            pass
        return None
    try:
        log(f"[flight] {reason}: flight dump written to {path}")
        from faster_distributed_training_tpu.telemetry import spans
        rec = spans.get_recorder()
        if rec is not None:
            rec.record_event("flight", path=path, reason=str(reason))
            # best-effort flush so the stream itself mentions the dump
            # (the dump file, already durable, is the real record)
            rec.flush(wait=False)
    except Exception:
        pass
    return path


def build_payload(reason: str, exc: Optional[BaseException] = None,
                  step: Optional[int] = None, goodput=None,
                  extra: Optional[dict] = None) -> dict:
    """The dump itself, assembled from the process-global telemetry
    state (span recorder, compile observatory).  Pure + side-effect
    free so tests can assert on it without touching disk."""
    from faster_distributed_training_tpu.telemetry import programs, spans

    rec = spans.get_recorder()
    payload: dict = {"schema": 1, "reason": str(reason),
                     "unix_time": round(time.time(), 3)}
    if rec is not None:
        payload["process_index"] = rec.pi
    else:
        from faster_distributed_training_tpu.resilience.coordinator import (
            pod_identity)
        payload["process_index"] = pod_identity()[0]
    if step is not None:
        payload["step"] = int(step)
    if exc is not None:
        payload["exception"] = {
            "type": type(exc).__name__,
            "message": str(exc)[:2000],
            "traceback": "".join(traceback.format_exception(
                type(exc), exc, exc.__traceback__))[-8000:]}
    payload["active_spans"] = spans.active_spans()
    if rec is not None:
        payload["recent_records"] = rec.recent_records()
        payload["dropped_records"] = rec.dropped_records
    if goodput is not None:
        try:
            payload["goodput"] = goodput.summary()
        except Exception:
            pass
    obs = programs.get_observatory()
    if obs is not None:
        payload["programs"] = obs.summary()
    if extra:
        payload.update(extra)
    return payload


def read_flights(directory: str) -> List[Tuple[str, dict]]:
    """[(path, payload)] of every parseable flight dump in ``directory``
    (posix — object-store dumps are read through the backend that wrote
    them, e.g. pod_restart_smoke's inspection backend)."""
    import glob

    out = []
    for path in sorted(glob.glob(os.path.join(directory,
                                              FLIGHT_PREFIX + "*.json"))):
        try:
            with open(path) as f:
                out.append((path, json.load(f)))
        except (OSError, ValueError):
            continue
    return out
