"""Run-scoped telemetry: per-dispatch records + run manifest as JSONL.

Every ordinary training run emits machine-readable evidence — not just
dedicated ``bench.py`` runs: a :class:`TelemetryRecorder` buffers one
small host-side record per dispatch (step, wall ms, examples/s,
data-wait ms, checkpoint-blocking ms, K, epoch) plus span/epoch/goodput
events, and a single background writer appends them as JSONL to
``<telemetry_dir>/host_<pi>.jsonl`` — the r7 off-critical-path idiom
(one worker thread, the step thread only appends to a list under a
lock).  A run manifest (config, mesh, jax/jaxlib versions, device kind)
is written once at startup (:func:`write_manifest`) so a telemetry
directory is self-describing.

Cost accounting (the ``telemetry_overhead_pct`` bench arm pins <1% of
median step): the hot-path cost per dispatch is a few ``time.monotonic``
reads, one dict construction, and one lock-guarded list append; JSON
encoding and file IO happen on the background thread.  The buffer is a
RING in spirit — bounded, never a backlog: when ``capacity`` records
accumulate they are handed to the writer as one batch, and if the
writer falls more than a few batches behind (a wedged filesystem) new
batches are DROPPED and counted (``dropped_records``) rather than
queued — observability must never grow unbounded host memory or stall
the step loop.  ``FDT_TELEMETRY=0`` kills the whole subsystem
(cli.build_telemetry).

Schema (APPEND-ONLY — fields may be added, never renamed; consumers
must ignore unknown fields).  One JSON object per line, discriminated
by ``"kind"``:

  ``run_start``  {t, process_index, process_count, schema}
  ``step``       {step, epoch, n, k, wall_ms, dispatch_ms, data_ms,
                  block_ms, examples, ex_s, compile?}
                 step = global step AFTER the dispatch; n = step in
                 epoch; wall_ms = full host wall since the previous
                 record (data wait + dispatch + resilience hooks);
                 dispatch_ms = the jitted call alone; ex_s =
                 examples / wall; compile=true marks a first execution
                 (compile time — aggregation excludes these from
                 step-time percentiles)
  ``span``       {name, dur_ms, step?}           (telemetry/spans.py)
  ``epoch``      {epoch, steps, trained_steps, loss?, accuracy?,
                  wall_s, ex_s, peak_mem_bytes?, eval_loss?,
                  eval_accuracy?}
  ``goodput``    {… GoodputTracker.summary() …}  (per-epoch snapshot)
  ``goodput_event`` {counter, total}             (restart/preemption/
                  peer-failure counters as they happen — the MTTR
                  story rides the same stream)
  ``flush_stats``  {dropped_records}             (emitted at close when
                  any batch was dropped)
  ``program``    {name, variant, lowerings, compile_ms, lower_ms,
                  fingerprint, cache, cache_method, avals,
                  argument_bytes, output_bytes, temp_bytes,
                  generated_code_bytes, alias_bytes}
                 one per observed compile (telemetry/programs.py)
  ``retrace``    {name, reason, lowerings, avals, prev_avals}
                 an accidental re-lowering was detected (loud WARNING
                 beside it)
  ``memory``     {scope, ...} — scope "state": the per-chip
                 params/opt_state/batch_stats byte table
                 (programs.state_bytes_table — opt_state_bytes_per_chip
                 is ROADMAP's ZeRO-sizing number, opt_state_tiers the
                 per-tier sharded/replicated/offloaded split the ZeRO
                 overlay is audited by); scope "epoch":
                 device memory watermarks; scope "sharding_drift": the
                 guard fired (expected/got fingerprints + changed
                 leaves under --debug)
  ``flight``     {path, reason}                  (a crash flight dump
                  was written — telemetry/flight.py)
  ``serve_batch``   {bucket, size, real, pad, replica, dispatch_ms,
                  attempts}                      (one per dispatched
                  serving batch — serve/scheduler.py)
  ``serve_request`` {bucket, len, queue_ms, total_ms, replica}
                 (one per fulfilled request; len is the raw
                  pre-truncation length)
  ``spare``      {event, spare, seat, slice, generation, step}
                 (r17 warm-spare lifecycle: parked / claimed — the
                  swap duration rides the goodput stream as
                  warm_spare_swap_s)
  ``decode_admit`` {replica, slot, bucket, len, queue_ms}
                 (one per mid-stream admission: a prompt prefilled and
                  its K/V swapped into a running decode batch —
                  serve/decode/scheduler.py)
  ``decode_step``  {replica, pages, active, batch, step_ms}
                 (one per decode step over the slot batch; pages is
                  the page-count program that served it)
  ``slot_evict``   {replica, slot, tokens, reason}
                 (one per reclaimed cache slot; reason "budget" =
                  token budget met, "capacity" = cache/position
                  ceiling)

r17 append-only field addition: ``program`` records grew
``cache_source`` ({deserialized, persistent_dir, compiled} — which
tier served the executable; resilience/executable_cache.py).

The machine-checkable registry of the above is TELEMETRY_SCHEMA below;
``scripts/check_telemetry_schema.py`` AST-scans every emission site in
the package against it (tier-1), so a renamed kind/field fails CI
instead of silently breaking telemetry_report.py consumers.

Run scoping: the host file is opened in APPEND mode — a supervised
relaunch of the same run (same checkpoint_dir) continues the same
story, pre-crash records included.  A fresh run wants a fresh
directory, exactly like the checkpoint dir (cli.attempt's docstring);
the aggregation barrier is additionally time-scoped
(telemetry/aggregate.py) so a reused directory's markers can't lie.

Wall-time caveat, documented rather than hidden: per-dispatch wall time
is HOST time between dispatch returns.  Under async dispatch the host
can briefly run ahead of the device, but donated-buffer backpressure
re-couples them within one step, so percentiles over an epoch track
device step time; the bench arms remain the fenced ground truth.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Optional

SCHEMA_VERSION = 1
ENV_KILL = "FDT_TELEMETRY"
MANIFEST = "manifest.json"

# -- APPEND-ONLY schema registry (scripts/check_telemetry_schema.py) ------
# kind -> the complete set of fields records of that kind may carry.
# Fields are ADDED here when an emitter grows one and NEVER removed or
# renamed (consumers parse by literal name; old entries document
# history).  A kind mapped to None is OPEN: its fields come from a
# runtime dict the lint resolves separately ("goodput" =
# GoodputTracker.summary()'s keys, dynamic per-segment).
TELEMETRY_SCHEMA: Dict[str, Optional[frozenset]] = {
    "run_start": frozenset({"t", "process_index", "process_count",
                            "schema"}),
    "step": frozenset({"step", "epoch", "n", "k", "wall_ms",
                       "dispatch_ms", "data_ms", "block_ms", "examples",
                       "ex_s", "compile"}),
    "span": frozenset({"name", "dur_ms", "step"}),
    # perplexity/eval_perplexity (r18 LM workload, append-only): only
    # emitted on --task lm runs (exp of the token-weighted epoch loss)
    "epoch": frozenset({"epoch", "steps", "trained_steps", "loss",
                        "accuracy", "wall_s", "ex_s", "peak_mem_bytes",
                        "eval_loss", "eval_accuracy", "perplexity",
                        "eval_perplexity"}),
    "goodput": None,
    "goodput_event": frozenset({"counter", "total"}),
    "rollback": frozenset({"epoch", "restored_epoch", "step"}),
    "flush_stats": frozenset({"dropped_records"}),
    # cache_source (r17 instant restart, append-only): where the
    # executable came from — "deserialized" (persistent executable
    # cache, resilience/executable_cache.py), "persistent_dir" (XLA's
    # compilation-cache dir served the compile), "compiled" (full price)
    "program": frozenset({"name", "variant", "lowerings", "compile_ms",
                          "lower_ms", "fingerprint", "cache",
                          "cache_method", "cache_source", "avals",
                          "argument_bytes", "output_bytes", "temp_bytes",
                          "generated_code_bytes", "alias_bytes"}),
    "retrace": frozenset({"name", "reason", "lowerings", "avals",
                          "prev_avals"}),
    "memory": frozenset({"scope", "epoch", "step",
                         "params_bytes_per_chip", "params_leaves",
                         "opt_state_bytes_per_chip", "opt_state_leaves",
                         "batch_stats_bytes_per_chip",
                         "batch_stats_leaves", "total_bytes_per_chip",
                         "top_leaves", "opt_state_tiers", "pp_residency",
                         "peak_bytes",
                         "bytes_in_use", "expected", "got",
                         "changed_leaves"}),
    "flight": frozenset({"path", "reason"}),
    # r16 serving tier (serve/scheduler.py) — append-only additions:
    # one record per dispatched batch, one per fulfilled request
    "serve_batch": frozenset({"bucket", "size", "real", "pad", "replica",
                              "dispatch_ms", "attempts"}),
    "serve_request": frozenset({"bucket", "len", "queue_ms", "total_ms",
                                "replica"}),
    # r18 streaming data plane (data/stream/window.py) — append-only:
    # one stream_refill per background buffer fill (disk read + H2D
    # split out), one stream_stall per buffer swap the consumer had to
    # wait for (the numerator of bench's stream_stall_pct, <1% target)
    "stream_refill": frozenset({"epoch", "base", "batches", "bytes",
                                "read_ms", "h2d_ms"}),
    "stream_stall": frozenset({"epoch", "step", "wait_ms"}),
    # r17 warm-spare slices (cli._run_warm_spare) — append-only: one
    # record when a spare parks (event="parked") and one when it claims
    # a failed seat (event="claimed", with the adopted seat/slice/
    # generation); the swap duration itself lands in the goodput stream
    # (warm_spare_swap_s)
    "spare": frozenset({"event", "spare", "seat", "slice", "generation",
                        "step"}),
    # r21 decode serving tier (serve/decode/scheduler.py) — append-only:
    # one decode_admit per mid-stream admission (prefill + K/V swap into
    # the running batch), one decode_step per slot-batch decode step
    # (pages = the page-count program that served it), one slot_evict
    # per reclaimed cache slot (reason: budget | capacity)
    "decode_admit": frozenset({"replica", "slot", "bucket", "len",
                               "queue_ms"}),
    "decode_step": frozenset({"replica", "pages", "active", "batch",
                              "step_ms"}),
    "slot_evict": frozenset({"replica", "slot", "tokens", "reason"}),
    # r22 pipeline parallelism (parallel/pipeline.py; emitted once at
    # startup by cli.run_training on pp>1 meshes) — append-only: one
    # pp_bubble with the schedule's analytic accounting (the executed
    # program pays exactly this — fill/drain ticks compute on recycled
    # (discarded) microbatch data, never zeros: see the 0*inf
    # constant-fold note in pipeline.py), one pp_stage per stage with
    # its layer block and idle/active slot-tick split (what
    # pp_stage_idle_ms scales by measured tick time)
    "pp_bubble": frozenset({"n_stages", "n_microbatches", "n_ticks",
                            "schedule", "bubble_pct"}),
    "pp_stage": frozenset({"stage", "layers", "idle_ticks",
                           "active_ticks"}),
}
# kinds that once existed but are no longer emitted (none today): the
# lint's staleness rule consults this instead of forcing removal from
# the append-only registry above
RETIRED_KINDS: frozenset = frozenset()

# background-writer backlog bound (batches, not records): beyond this
# the recorder drops instead of queueing — a wedged shared fs must not
# grow snapshots of the run in host memory
_MAX_PENDING_BATCHES = 4


def _write_json_atomic(path: str, obj) -> None:
    # local tmp+replace+fsync copy (the coordinator/checkpoint idiom) so
    # a reader never observes a torn manifest/summary
    tmp = f"{path}.tmp{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1, default=str)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_manifest(directory: str, cfg=None, mesh=None,
                   extra: Optional[dict] = None) -> str:
    """``<directory>/manifest.json``, written once at startup (process
    0): everything needed to interpret the host JSONL files without the
    process that wrote them — config, mesh, jax/jaxlib versions, device
    kind/count.  Returns the path."""
    import dataclasses

    import jax

    man: dict = {"schema": SCHEMA_VERSION,
                 "unix_time": round(time.time(), 3)}
    try:
        import jaxlib
        man["jaxlib_version"] = getattr(jaxlib, "__version__", "?")
    except ImportError:
        man["jaxlib_version"] = ""
    man["jax_version"] = jax.__version__
    try:
        dev = jax.local_devices()[0]
        man["backend"] = jax.default_backend()
        man["device_kind"] = getattr(dev, "device_kind", str(dev))
        man["device_count"] = jax.device_count()
        man["process_count"] = jax.process_count()
    except Exception:
        pass  # an uninitializable backend must not kill the run
    if mesh is not None:
        try:
            man["mesh"] = {str(k): int(v) for k, v in dict(mesh.shape).items()}
        except Exception:
            man["mesh"] = str(mesh)
    if cfg is not None:
        man["config"] = (dataclasses.asdict(cfg)
                         if dataclasses.is_dataclass(cfg) else dict(cfg))
    if extra:
        man.update(extra)
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, MANIFEST)
    _write_json_atomic(path, man)
    return path


def update_manifest(directory: str, extra: dict) -> Optional[str]:
    """Merge ``extra`` into an existing manifest.json (atomic rewrite) —
    how the compile observatory's program table lands at run end: the
    manifest is written once at STARTUP, but per-program compile
    ms/fingerprint/cache/memory only exist after the programs compiled.
    Missing/corrupt manifests get a fresh one holding just ``extra``;
    returns the path, or None when the write fails (best-effort — a
    full disk at shutdown must not mask the run's real outcome)."""
    path = os.path.join(directory, MANIFEST)
    man: dict = {}
    try:
        with open(path) as f:
            man = json.load(f)
    except (OSError, ValueError):
        pass
    man.update(extra)
    try:
        os.makedirs(directory, exist_ok=True)
        _write_json_atomic(path, man)
    except OSError:
        return None
    return path


class TelemetryRecorder:
    """Host-side ring buffer of telemetry records, flushed as JSONL off
    the critical path (single background writer, append-mode file).

    ``process_index``/``process_count`` default to the pod identity (the
    FDT_POD_INDEX/FDT_POD_COUNT simulation seam, else the jax runtime —
    same resolution as resilience/coordinator.py), and exist as explicit
    arguments so tier-1 tests can run two recorders in one process as a
    simulated two-host pod sharing a telemetry directory."""

    def __init__(self, directory: str,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 capacity: int = 256,
                 step_every: int = 1,
                 recent: int = 256,
                 log: Callable[[str], None] = print):
        if process_index is None or process_count is None:
            # lazy import: resilience.coordinator imports telemetry.spans
            # at module level, so importing it from THIS module's top
            # would be circular
            from faster_distributed_training_tpu.resilience.coordinator \
                import pod_identity
            pi, pc, _sim = pod_identity()
            process_index = pi if process_index is None else process_index
            process_count = pc if process_count is None else process_count
        self.pi = int(process_index)
        self.pc = int(process_count)
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.path = os.path.join(self.directory,
                                 f"host_{self.pi:05d}.jsonl")
        self.capacity = max(int(capacity), 1)
        # --telemetry_every N: keep every Nth step record (the r12 note's
        # mitigation for per-dispatch clock pressure under async
        # dispatch).  Sampling drops whole records, never rewrites them,
        # so surviving records carry their true step numbers; compile-
        # marked first dispatches are always kept (there is exactly one
        # per program and aggregation keys on them), and span/epoch/
        # goodput events are never sampled.
        self.step_every = max(int(step_every or 1), 1)
        self._steps_seen = 0
        self._log = log
        self._lock = threading.Lock()
        self._buf: list = []
        # the flight-recorder RING: the last `recent` records, retained
        # ACROSS flushes (a crash's most interesting records are the
        # flushed-or-not tail) — telemetry/flight.py dumps it durably
        # from the failure seams.  One deque append per record on the
        # hot path; bounded by construction.
        self._recent: collections.deque = collections.deque(
            maxlen=max(int(recent), 1))
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pending = 0
        self.dropped_records = 0
        self._closed = False
        self.record_event("run_start", t=round(time.time(), 3),
                          process_index=self.pi, process_count=self.pc,
                          schema=SCHEMA_VERSION)

    # -- recording (hot path) ---------------------------------------------

    def record_step(self, step: int, epoch: int, n: int, k: int,
                    wall_ms: float, dispatch_ms: float, examples: int,
                    data_ms: float = 0.0, block_ms: float = 0.0,
                    compile_: bool = False) -> None:
        self._steps_seen += 1
        if (self.step_every > 1 and not compile_
                and self._steps_seen % self.step_every):
            return
        rec = {"kind": "step", "step": int(step), "epoch": int(epoch),
               "n": int(n), "k": int(k), "wall_ms": round(wall_ms, 3),
               "dispatch_ms": round(dispatch_ms, 3),
               "data_ms": round(data_ms, 3), "block_ms": round(block_ms, 3),
               "examples": int(examples),
               "ex_s": round(examples / max(wall_ms / 1e3, 1e-9), 1)}
        if compile_:
            rec["compile"] = True
        self._append(rec)

    def next_step_kept(self) -> bool:
        """Whether the NEXT record_step call will be kept by the
        --telemetry_every cadence (compile-marked records are kept
        regardless).  The Trainer reads this BEFORE a dispatch so
        sampled-out dispatches skip their telemetry-only clock reads
        entirely — the actual point of the mitigation (dropping an
        already-timed record would keep 100% of the monotonic
        pressure); record_step remains the single counter owner."""
        return (self.step_every <= 1
                or (self._steps_seen + 1) % self.step_every == 0)

    def record_span(self, name: str, dur_ms: float,
                    step: Optional[int] = None) -> None:
        rec = {"kind": "span", "name": str(name),
               "dur_ms": round(dur_ms, 3)}
        if step is not None:
            rec["step"] = int(step)
        self._append(rec)

    def record_event(self, kind: str, **fields) -> None:
        self._append({"kind": str(kind), **fields})

    def goodput_event_sink(self, counter: str, total: int) -> None:
        """Adapter for ``GoodputTracker.set_event_sink`` — restart/
        preemption/peer-failure counters land in the stream as they
        happen, so one file tells the run's whole story."""
        self.record_event("goodput_event", counter=str(counter),
                          total=int(total))

    def _append(self, rec: dict) -> None:
        with self._lock:
            if self._closed:
                return
            self._buf.append(rec)
            self._recent.append(rec)
            if len(self._buf) >= self.capacity:
                self._flush_locked()

    def recent_records(self) -> list:
        """Snapshot of the in-memory ring (newest last) — the crash
        flight recorder's payload (telemetry/flight.py)."""
        with self._lock:
            return list(self._recent)

    # -- flushing (background) --------------------------------------------

    def _flush_locked(self, wait: bool = False):
        if not self._buf:
            return None
        batch, self._buf = self._buf, []
        if self._pending >= _MAX_PENDING_BATCHES and not wait:
            # the writer is wedged (filesystem stall): drop, don't queue
            self.dropped_records += len(batch)
            return None
        if self._pool is None:
            self._pool = ThreadPoolExecutor(max_workers=1,
                                            thread_name_prefix="fdt-telem")
        self._pending += 1
        return self._pool.submit(self._write_batch, batch)

    def _write_batch(self, batch: list) -> None:
        try:
            with open(self.path, "a") as f:
                for rec in batch:
                    f.write(json.dumps(rec, default=str))
                    f.write("\n")
        except OSError as e:
            self.dropped_records += len(batch)
            self._log(f"[telemetry] could not append {len(batch)} records "
                      f"to {self.path}: {e!r}")
        finally:
            # under the SAME lock the step thread increments with: a
            # bare `-= 1` is a read-modify-write that can interleave
            # with the locked `+= 1`, and a lost decrement would drift
            # the backlog counter up until every batch is dropped
            with self._lock:
                self._pending -= 1

    def flush(self, wait: bool = False) -> None:
        """Hand the current buffer to the writer; ``wait=True`` blocks
        until it (and it alone) is on disk — epoch boundaries flush-wait
        before publishing their aggregation marker so process 0 reads a
        complete epoch."""
        with self._lock:
            fut = self._flush_locked(wait=wait)
        if wait and fut is not None:
            fut.result()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            if self.dropped_records:
                self._buf.append({"kind": "flush_stats",
                                  "dropped_records": self.dropped_records})
            fut = self._flush_locked(wait=True)
            self._closed = True
        if fut is not None:
            try:
                fut.result()
            except Exception:
                pass
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
