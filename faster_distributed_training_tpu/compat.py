"""Version-compat shims for JAX APIs that moved between releases.

The repo targets current JAX (``jax.shard_map`` with ``check_vma``),
but CI/driver containers have been observed on jaxlib 0.4.x where
shard_map still lives in ``jax.experimental.shard_map`` and the
replication-checking kwarg is named ``check_rep``.  One chokepoint here
keeps every consumer (ops/sequence_parallel.py, ops/fused_ffn.py)
source-identical across both.
"""

from __future__ import annotations

import jax


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """jax.shard_map / jax.experimental.shard_map.shard_map, whichever
    this jax provides; check_vma maps onto the old check_rep."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def xla_accepts_flags(candidate_flags: str, timeout: int = 120) -> bool:
    """True iff this jaxlib's XLA accepts ``candidate_flags`` as
    XLA_FLAGS.  XLA hard-ABORTS the process (parse_flags_from_env.cc
    F-check) on any unknown flag, so support must be probed in a
    THROWAWAY subprocess: older jaxlibs (observed: 0.4.37) predate e.g.
    the CPU collective-timeout flags, and passing them unconditionally
    turns the caller into a hard abort at first backend use.  Shared by
    tests/conftest.py and __graft_entry__.dryrun_multichip so the two
    gates can never drift.  Any probe failure (incl. timeout on a cold
    import cache) degrades to False — callers keep their un-augmented
    flags rather than risking the abort."""
    import os
    import subprocess
    import sys
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            env={**os.environ, "XLA_FLAGS": candidate_flags,
                 "JAX_PLATFORMS": "cpu"},
            capture_output=True, timeout=timeout)
    except Exception:
        return False
    return r.returncode == 0


def axis_size(axis_name) -> int:
    """lax.axis_size (new jax) as a STATIC int — consumers use it for
    Python-level loop/scan lengths.  On 0.4.x it predates lax, but the
    tracing axis env knows the bound size."""
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    from jax._src import core as _core
    return _core.get_axis_env().axis_size(axis_name)
