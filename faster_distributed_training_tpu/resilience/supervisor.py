"""Self-restarting supervisor: bounded-retry, backoff, no futile loops.

Wraps the train loop (cli.run_training builds the ``attempt`` closure:
restore from the newest VALID checkpoint via the manager, then
``Trainer.fit`` from there).  Policy:

  * a crash triggers a restart after exponential backoff (base·2^k,
    capped) — transient faults (flaky storage, a dying host being
    rescheduled) get room to clear;
  * restarts are BOUNDED (``max_restarts`` total) — a run that keeps
    dying is surfaced, not silently retried forever;
  * DETERMINISTIC crashes short-circuit: if two consecutive attempts
    fail at the same global step, the bug reproduces on replay (bad
    batch, NaN-poisoned state older than every checkpoint, code bug) and
    retrying is futile — the original exception re-raises immediately,
    with retries still in budget;
  * :class:`Preempted` passes straight through — an emergency save
    already landed and the PLATFORM owns the restart, so retrying
    in-process would fight the scheduler for the grace window.

The supervisor knows nothing about jax or checkpoints — it sequences
``attempt``/``progress`` callables, which is what makes it testable with
plain functions and reusable by the smoke script."""

from __future__ import annotations

import time
from typing import Any, Callable, Optional

from faster_distributed_training_tpu.resilience import Preempted


class Supervisor:
    def __init__(self, max_restarts: int = 3, backoff_base: float = 1.0,
                 backoff_cap: float = 30.0, goodput=None,
                 sleep: Callable[[float], None] = time.sleep,
                 log: Callable[[str], None] = print):
        self.max_restarts = int(max_restarts)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self._goodput = goodput
        self._sleep = sleep
        self._log = log

    def run(self, attempt: Callable[[int], Any],
            progress: Callable[[], Optional[int]]) -> Any:
        """attempt(restart_index) runs one training attempt (index 0 is
        the first run; the closure re-restores on every call so attempt
        k resumes from whatever checkpoint is newest AFTER failure k-1).
        progress() reports the global step reached, read after a failure
        for the deterministic-crash check."""
        last_fail_step: Optional[int] = None
        restarts = 0
        while True:
            try:
                return attempt(restarts)
            except Preempted:
                raise                       # clean shutdown, never retried
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                step = progress()
                if last_fail_step is not None and step == last_fail_step:
                    self._log(
                        f"[supervisor] step {step} failed twice in a row — "
                        f"the crash is deterministic (reproduces on replay "
                        f"from the same checkpoint); re-raising instead of "
                        f"looping")
                    raise
                restarts += 1
                if restarts > self.max_restarts:
                    self._log(f"[supervisor] giving up after "
                              f"{self.max_restarts} restarts "
                              f"(last failure at step {step}: {e!r})")
                    raise
                delay = min(self.backoff_cap,
                            self.backoff_base * 2.0 ** (restarts - 1))
                self._log(f"[supervisor] attempt {restarts - 1} failed at "
                          f"step {step} ({e!r}); restarting from the newest "
                          f"valid checkpoint in {delay:.1f}s "
                          f"({restarts}/{self.max_restarts})")
                if self._goodput:
                    self._goodput.count("restarts")
                if delay > 0:
                    if self._goodput:
                        with self._goodput.timed("restart_backoff_s"):
                            self._sleep(delay)
                    else:
                        self._sleep(delay)
                last_fail_step = step
