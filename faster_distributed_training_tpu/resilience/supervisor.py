"""Self-restarting supervisor: bounded-retry, backoff, no futile loops.

Wraps the train loop (cli.run_training builds the ``attempt`` closure:
restore from the newest VALID checkpoint via the manager, then
``Trainer.fit`` from there).  Policy:

  * the FIRST restart is immediate and exponential backoff (base·2^k,
    capped) starts at the second (r17 satellite fix: the measured
    1.07 s restart MTTR was ~1.0 s of base backoff paid on the very
    first attempt — a single transient fault now recovers at restore
    speed, while a host that keeps dying still backs off so flaky
    storage / a rescheduling host get room to clear);
  * restarts are BOUNDED (``max_restarts`` total) — a run that keeps
    dying is surfaced, not silently retried forever;
  * DETERMINISTIC crashes short-circuit: if two consecutive attempts
    fail at the same global step WITH the same exception type, the bug
    reproduces on replay (bad batch, NaN-poisoned state older than
    every checkpoint, code bug) and retrying is futile — the original
    exception re-raises immediately, with retries still in budget.
    The type comparison matters (r10 satellite fix): two DIFFERENT
    transient faults landing at one step — a storage flake, then a peer
    failure at the same checkpoint-cadence step — are not evidence of
    determinism and keep retrying while budget remains.  Two failures
    with progress() None (neither attempt completed a step) compare
    like any other repeated step: same exception type twice before
    step 0 means the run cannot even start, and replaying is futile;
  * :class:`Preempted` passes straight through — an emergency save
    already landed and the PLATFORM owns the restart, so retrying
    in-process would fight the scheduler for the grace window.

Pod coordination (r10): given a ``coordinator``
(resilience/coordinator.py), every attempt is entered through
``coordinator.begin_attempt()`` — the shared-fs generation rendezvous
that makes all hosts of a pod restart into the SAME generation — and
every failure is published through ``coordinator.record_failure()``
before the backoff, so the peers observe it at their next poll instead
of blocking forever inside the next collective.  A
:class:`~faster_distributed_training_tpu.resilience.coordinator.PeerFailure`
is just another restartable exception here: each host burns a restart
for it, so a flapping peer exhausts EVERY host's budget together and
the pod converges on giving up rather than half-running.  (It is
exempt from the deterministic-crash check — a PeerFailure's step is
the poll-quantized OBSERVATION point, not the fault point, so two at
one step carry no replay-determinism signal.)  A host that completes
``attempt`` durably records its completion, so a peer restarting after
this host exits fails fast instead of waiting out the restore barrier.

The supervisor knows nothing about jax or checkpoints — it sequences
``attempt``/``progress`` callables, which is what makes it testable with
plain functions and reusable by the smoke scripts."""

from __future__ import annotations

import time
from typing import Any, Callable, Optional, Tuple

from faster_distributed_training_tpu.resilience import Preempted
from faster_distributed_training_tpu.resilience.coordinator import (
    PeerFailure, SeatTaken)
from faster_distributed_training_tpu.resilience.sentinel import LossSpike


class Supervisor:
    def __init__(self, max_restarts: int = 3, backoff_base: float = 1.0,
                 backoff_cap: float = 30.0, goodput=None,
                 sleep: Callable[[float], None] = time.sleep,
                 log: Callable[[str], None] = print, coordinator=None):
        self.max_restarts = int(max_restarts)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self._goodput = goodput
        self._sleep = sleep
        self._log = log
        self._coordinator = coordinator

    def run(self, attempt: Callable[[int], Any],
            progress: Callable[[], Optional[int]]) -> Any:
        """attempt(restart_index) runs one training attempt (index 0 is
        the first run; the closure re-restores on every call so attempt
        k resumes from whatever checkpoint is newest AFTER failure k-1).
        progress() reports the global step reached, read after a failure
        for the deterministic-crash check."""
        # (step-or-None, exception type) of the previous failure: the
        # deterministic-crash check needs BOTH to call a replay futile
        last_fail: Optional[Tuple[Optional[int], type]] = None
        restarts = 0
        while True:
            try:
                if self._coordinator is not None:
                    self._coordinator.begin_attempt()
                result = attempt(restarts)
                if self._coordinator is not None:
                    # durably mark this host DONE so a peer restarting
                    # AFTER our exit fails its restore barrier fast
                    # ("pod already finished") instead of waiting out
                    # the full gather timeout for a host that is gone
                    self._coordinator.record_completion()
                return result
            except Preempted:
                raise                       # clean shutdown, never retried
            except SeatTaken:
                # r17 warm spares: a spare already claimed this host's
                # pod seat — retrying can never win it back (the claim
                # marker is durable and first-writer-wins), so this
                # relaunch is redundant by protocol, not failed
                raise
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                step = progress()
                if self._coordinator is not None:
                    # publish to the pod BEFORE the backoff so the peers'
                    # next poll observes it while this host sleeps
                    self._coordinator.record_failure(e, step=step)
                # crash flight recorder (r15 observability): dump the
                # telemetry ring + open spans + program table durably
                # BEFORE the restart eats the evidence.  A no-op when
                # telemetry is off; per-exception deduplicated, so the
                # final budget-exhausted re-raise escaping to
                # run_training doesn't dump the same incident twice.
                # Lazy import: this module stays jax-free and the
                # failure path is the only caller.
                from faster_distributed_training_tpu.telemetry import (
                    flight)
                flight.emergency_dump("supervisor_failure", exc=e,
                                      step=step)
                # PeerFailure never participates in the deterministic-
                # crash check: its step is the OBSERVATION point (poll-
                # boundary-quantized, typically the restored step), not
                # the fault point, so two observations at one step carry
                # no replay-determinism signal — and short-circuiting
                # here would make a survivor give up on a flapping peer
                # with retry budget remaining, breaking the "the pod
                # exhausts every host's budget together" contract.
                # LossSpike is exempt for the inverse reason: the spike
                # QUARANTINED its batches before raising, so the replay
                # is a DIFFERENT program of work — a second spike at the
                # same step is a new batch spiking, not evidence that
                # retrying is futile (resilience/sentinel.py).
                transient_peer = isinstance(e, (PeerFailure, LossSpike))
                if not transient_peer and last_fail == (step, type(e)):
                    self._log(
                        f"[supervisor] step {step} failed twice in a row "
                        f"with {type(e).__name__} — the crash is "
                        f"deterministic (reproduces on replay from the "
                        f"same checkpoint); re-raising instead of looping")
                    raise
                restarts += 1
                if restarts > self.max_restarts:
                    self._log(f"[supervisor] giving up after "
                              f"{self.max_restarts} restarts "
                              f"(last failure at step {step}: {e!r})")
                    raise
                # first restart immediate, backoff from the second (r17
                # satellite): one transient failure recovers at restore
                # speed — restart_mttr_backoff_s pins ≈ 0 for it — and
                # only a host that keeps dying pays the exponential ramp
                delay = (0.0 if restarts == 1
                         else min(self.backoff_cap,
                                  self.backoff_base * 2.0 ** (restarts - 2)))
                self._log(f"[supervisor] attempt {restarts - 1} failed at "
                          f"step {step} ({e!r}); restarting from the newest "
                          f"valid checkpoint "
                          + ("immediately" if delay == 0
                             else f"in {delay:.1f}s")
                          + f" ({restarts}/{self.max_restarts})")
                if self._goodput:
                    self._goodput.count("restarts")
                if delay > 0:
                    if self._goodput:
                        with self._goodput.timed("restart_backoff_s"):
                            self._sleep(delay)
                    else:
                        self._sleep(delay)
                if not transient_peer:
                    # a PeerFailure neither records NOR clears the pair:
                    # an own-crash recurring at one step with a peer
                    # incident in between is still deterministic
                    last_fail = (step, type(e))
