"""Anomaly sentinel: bad-step quarantine, loss-spike rollback-and-skip.

The resilience stack through r17 recovers from LOUD failures — crashes,
hangs, dead slices.  This module defends against the SILENT ones that
dominate long production runs:

  * **non-finite steps** — a poisoned gradient written into the params
    is unrecoverable except by rollback; the in-graph guard
    (train/steps.py, armed by ``--sentinel guard|full``) fuses one
    non-finite check over loss + global grad norm onto the existing
    loss-scale unscale check and gates the whole optimizer update on
    it, so a bad step leaves params/opt-state/RNG folds
    bitwise-untouched, advances only ``state.step`` (the fp16
    GradScaler skip generalized to every precision), and is COUNTED
    (the ``bad_steps`` metric -> the ``skipped_steps`` goodput
    counter).  The verdict is a single bit computed from global scalars
    inside the jitted program, so it is identical on every (dp, tp, pp)
    host by construction — no host round-trip, no cross-host agreement
    protocol needed;

  * **loss spikes** — a finite-but-wrong dispatch (bad batch, data
    corruption upstream of the checksums) that the non-finite guard
    cannot see.  ``--sentinel full`` feeds the per-dispatch loss stream
    into a windowed median/MAD detector (:class:`SpikeDetector`); on a
    spike the offending global-batch POSITIONS are quarantined in a
    durable ledger (:class:`QuarantineLedger`, written through the
    r14 ``StorageBackend`` so restarts and peers agree), and
    :class:`LossSpike` is raised — a restartable exception the
    supervisor recovers exactly like a crash: newest-VALID restore,
    then replay.  Because batch content is a pure function of
    ``(seed, epoch, position)`` (``loader.pod_epoch_order``), the
    replay skips the quarantined positions DETERMINISTICALLY on every
    host and every data path (the PaLM rollback-and-skip recipe);

  * **shard bit-rot** — handled upstream by the ``data/stream`` CRC
    verification (data/stream/reader.py); a corrupt shard lands here
    only as a ledger entry + the ``quarantined_shards`` counter.

The sentinel is HOST-side bookkeeping only: nothing in this module
imports jax, and the ``--sentinel none`` default builds no Sentinel at
all — those programs stay byte-identical to the unguarded build
(pinned by tests/test_sentinel.py)."""

from __future__ import annotations

import math
import warnings
from collections import deque
from typing import Callable, Dict, List, Optional, Set, Tuple

LEDGER_KEY = "quarantine/ledger.json"


def host_finite(x) -> bool:
    """Host-side finiteness check on an ALREADY-FETCHED metric
    (MetricAccumulator.summary() returns Python floats).  Deliberately
    not jax.numpy.isfinite: that would accept a still-on-device scalar
    and add a blocking device round-trip at the epoch boundary.  The
    ONE host-side non-finite definition — the in-graph guard's device
    bit (train/steps.py) is the same predicate computed under jit, and
    the epoch-level auto-recover check reads it through the summary
    this function screens."""
    try:
        return x is not None and math.isfinite(float(x))
    except (TypeError, ValueError):
        return False


class LossSpike(RuntimeError):
    """A detected loss spike: the offending batch positions are already
    quarantined (durably) by the time this raises, so the supervisor's
    standard newest-VALID restore + replay recovers WITHOUT the bad
    batches.  Restartable — and exempt from the supervisor's
    deterministic-crash short-circuit: the quarantine changes the
    replay, so a second spike at the same step is a NEW incident (a
    different batch spiking), not evidence that retrying is futile."""

    def __init__(self, message: str, epoch: int = 0,
                 positions: Tuple[int, ...] = ()):
        super().__init__(message)
        self.epoch = int(epoch)
        self.positions = tuple(positions)


class SpikeDetector:
    """Windowed median/MAD spike statistic over the dispatch loss
    stream.  Median/MAD (not mean/std): a single outlier inflates a
    std enough to mask itself, while the median absolute deviation is
    robust to exactly the contamination being hunted.  A loss more
    than ``threshold`` MADs above the trailing window's median is a
    spike; ``min_history`` observations are required before anything
    can flag (early training is legitimately volatile), and the MAD is
    floored at a small fraction of the median so a perfectly flat
    window (synthetic data) cannot divide by ~zero and flag noise."""

    def __init__(self, window: int = 32, threshold: float = 8.0,
                 min_history: int = 8):
        self.window = max(int(window), 2)
        self.threshold = float(threshold)
        self.min_history = max(int(min_history), 2)
        self._losses: deque = deque(maxlen=self.window)

    def observe(self, loss: float) -> bool:
        """Feed one dispatch loss; True when it spikes vs the trailing
        window (the spiking loss itself is NOT added to the window —
        after the rollback the replay re-observes the healthy stream)."""
        loss = float(loss)
        if not math.isfinite(loss):
            # non-finite is the in-graph guard's jurisdiction (the step
            # was already skipped); don't poison the window with it
            return False
        if len(self._losses) >= self.min_history:
            hist = sorted(self._losses)
            m = len(hist)
            median = (hist[m // 2] if m % 2
                      else 0.5 * (hist[m // 2 - 1] + hist[m // 2]))
            devs = sorted(abs(v - median) for v in hist)
            mad = (devs[m // 2] if m % 2
                   else 0.5 * (devs[m // 2 - 1] + devs[m // 2]))
            mad = max(mad, 1e-3 * max(abs(median), 1e-6))
            if loss > median + self.threshold * mad:
                return True
        self._losses.append(loss)
        return False

    def reset(self) -> None:
        """Clear the window — called on rollback so the replayed
        stream is not double-observed."""
        self._losses.clear()


class QuarantineLedger:
    """The durable record of what was quarantined: global-batch
    POSITIONS per epoch (skipped deterministically by every data path
    via the pure ``pod_epoch_order`` algebra) and corrupt stream-shard
    indices (informational — shard verdicts re-derive deterministically
    from the CRCs, the ledger is the run's record of them).

    Written through the resilience ``StorageBackend`` under
    ``quarantine/ledger.json`` so a killed-mid-replay restart (same
    host or a peer) reloads the identical skip set before its first
    dispatch.  Format::

        {"version": 1,
         "batches": {"<epoch>": [position, ...]},
         "shards":  [shard_index, ...]}

    ``backend=None`` (no resilience bundle — bench probes) degrades to
    in-memory only."""

    def __init__(self, backend=None, key: str = LEDGER_KEY):
        self._backend = backend
        self._key = key
        self._batches: Dict[int, Set[int]] = {}
        self._shards: Set[int] = set()
        self.load()

    def load(self) -> None:
        if self._backend is None:
            return
        try:
            obj = self._backend.read_json(self._key)
        except Exception:
            obj = None
        if not obj:
            return
        self._batches = {int(e): set(int(p) for p in ps)
                         for e, ps in (obj.get("batches") or {}).items()}
        self._shards = set(int(s) for s in obj.get("shards") or ())

    def _flush(self) -> None:
        if self._backend is None:
            return
        self._backend.put_json(self._key, {
            "version": 1,
            "batches": {str(e): sorted(ps)
                        for e, ps in sorted(self._batches.items())},
            "shards": sorted(self._shards)})

    def add_batches(self, epoch: int, positions) -> None:
        self._batches.setdefault(int(epoch), set()).update(
            int(p) for p in positions)
        self._flush()

    def add_shard(self, index: int) -> None:
        self._shards.add(int(index))
        self._flush()

    def batches_for(self, epoch: int) -> Set[int]:
        return self._batches.get(int(epoch), set())

    def shards(self) -> Set[int]:
        return set(self._shards)


class Sentinel:
    """The host half of the anomaly ladder (mode ``guard`` or
    ``full``): owns the spike detector + quarantine ledger and plans
    the deterministic skips for the dispatch loops.

    ``observe(...)`` is only called in ``full`` mode — it costs one
    device->host loss readback per dispatch (the documented sync the
    ``sentinel_overhead_pct`` bench arm measures); ``guard`` mode adds
    ZERO host work (the in-graph guard is self-contained)."""

    def __init__(self, mode: str, backend=None, goodput=None,
                 window: int = 32, threshold: float = 8.0,
                 log: Callable[[str], None] = print, root: str = ""):
        if mode not in ("guard", "full"):
            raise ValueError(f"sentinel mode must be guard/full, got "
                             f"{mode!r} (none builds no Sentinel)")
        self.mode = mode
        self.goodput = goodput
        self.log = log
        self.detector = (SpikeDetector(window=window, threshold=threshold)
                         if mode == "full" else None)
        # anchor the ledger under the run's checkpoint root: PosixBackend
        # keys are filesystem paths verbatim, so a bare LEDGER_KEY would
        # land relative to the process CWD and a restart launched from
        # anywhere else would silently miss the quarantine set
        key = (backend.join(root, LEDGER_KEY)
               if backend is not None and root else LEDGER_KEY)
        self.ledger = QuarantineLedger(backend=backend, key=key)

    # -- deterministic quarantine skips (all data paths) ---------------

    def quarantined(self, epoch: int, position: int) -> bool:
        return position in self.ledger.batches_for(epoch)

    def plan(self, epoch: int, start: int, count: int
             ) -> List[Tuple[int, int]]:
        """Contiguous (start, length) sub-segments of the dispatch
        group ``[start, start + count)`` that are NOT quarantined for
        ``epoch`` — the dispatch loops run one fused dispatch per
        segment (a tail-program per length already exists for any
        length <= K).  ``[(start, count)]`` when nothing overlaps (the
        hot path: one comparison against an empty set)."""
        bad = self.ledger.batches_for(epoch)
        if not bad:
            return [(start, count)]
        segs: List[Tuple[int, int]] = []
        s = None
        for p in range(start, start + count):
            if p in bad:
                if s is not None:
                    segs.append((s, p - s))
                    s = None
            elif s is None:
                s = p
        if s is not None:
            segs.append((s, start + count - s))
        return segs

    # -- loss-spike detection ------------------------------------------

    def observe(self, epoch: int, start: int, count: int, loss: float,
                step: int) -> None:
        """Feed one dispatch's mean loss (positions ``[start,
        start + count)`` of ``epoch``); on a spike: quarantine the
        group durably, count the rollback, reset the detector window
        (the replay re-observes the healthy stream) and raise
        :class:`LossSpike` for the supervisor to roll back through."""
        if self.detector is None:
            return
        if not self.detector.observe(loss):
            return
        positions = [p for p in range(start, start + count)
                     if p not in self.ledger.batches_for(epoch)]
        self.ledger.add_batches(epoch, positions)
        if self.goodput is not None:
            self.goodput.count("rollbacks")
            self.goodput.count("quarantined_batches", len(positions))
        self.detector.reset()
        self.log(f"[sentinel] loss SPIKE at step {step} (epoch {epoch}, "
                 f"batches {start}..{start + count - 1}, loss "
                 f"{loss:.4g} vs trailing window): quarantining "
                 f"{len(positions)} batch position(s) durably and "
                 f"rolling back to the newest valid checkpoint")
        raise LossSpike(
            f"loss spike at step {step}: dispatch loss {loss:.4g} "
            f"breached the median/MAD window; batches "
            f"{positions} of epoch {epoch} quarantined",
            epoch=epoch, positions=tuple(positions))

    # -- data-integrity reporting (data/stream CRC verdicts) -----------

    def quarantine_shard(self, index: int, path: str = "") -> None:
        """Record a CRC-failed stream shard (reader.py already remapped
        its rows): ledger entry + counter + loud warning — the run
        CONTINUES, never crashes."""
        self.ledger.add_shard(index)
        if self.goodput is not None:
            self.goodput.count("quarantined_shards")
        msg = (f"stream shard {index} failed its CRC check"
               + (f" ({path})" if path else "")
               + " — rows remapped to a healthy shard; shard "
                 "quarantined in the ledger")
        warnings.warn("[sentinel] " + msg, stacklevel=2)
        self.log("[sentinel] WARNING: " + msg)
