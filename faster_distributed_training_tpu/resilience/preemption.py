"""Preemption awareness: SIGTERM/SIGINT → cross-host-agreed emergency save.

Preemptible TPU pods deliver SIGTERM with a grace window; the reference
(and this repo before r7) simply died, losing everything since the last
best-accuracy epoch checkpoint.  The handler here turns the signal into
a FLAG that the train loop polls at step boundaries — signal handlers
must never touch jax or the filesystem directly (they interrupt
arbitrary bytecode; an orbax save from handler context can deadlock on
its own locks).

Multi-host, the emergency save is a COLLECTIVE (orbax gathers every
host's shards), so every host must enter it at the same step or the pod
deadlocks inside the save while the grace window burns.  SIGTERM
delivery is per-host and not simultaneous; :meth:`should_stop` therefore
reduces the local flag across hosts (MAX — "any host saw it") at an
agreed step cadence, so all hosts reach the identical decision at the
identical step before anyone starts saving.  The reduction itself is the
agreement bit the ISSUE prescribes."""

from __future__ import annotations

import signal
import threading
from typing import Callable, Optional, Tuple

import jax
import numpy as np

_DEFAULT_SIGNALS: Tuple[int, ...] = (signal.SIGTERM, signal.SIGINT)


class PreemptionHandler:
    def __init__(self, signals: Tuple[int, ...] = _DEFAULT_SIGNALS,
                 sync_every: int = 1, log: Callable[[str], None] = print):
        self._signals = tuple(signals)
        self._sync_every = max(int(sync_every), 1)
        self._log = log
        self._flag = threading.Event()
        self._old = {}
        self._installed = False
        self._last_polled = 0   # last step should_stop saw (multi-host
                                # boundary-crossing sync cadence)

    # -- signal plumbing ---------------------------------------------------

    def _on_signal(self, signum, frame) -> None:
        if not self._flag.is_set():
            # log() from handler context is best-effort but a plain
            # print/flag set is async-signal-safe enough for CPython
            self._log(f"[preempt] received signal {signum}; will emergency-"
                      f"save at the next step boundary")
        self._flag.set()

    def install(self) -> "PreemptionHandler":
        """Idempotent; degrades with a warning off the main thread
        (CPython only allows signal.signal there)."""
        if self._installed:
            return self
        try:
            for s in self._signals:
                self._old[s] = signal.signal(s, self._on_signal)
            self._installed = True
        except ValueError as e:     # not the main thread
            self._log(f"[preempt] could not install signal handlers ({e}); "
                      f"preemption awareness disabled in this context")
            self._old.clear()
        return self

    def uninstall(self) -> None:
        for s, h in self._old.items():
            try:
                signal.signal(s, h)
            except (ValueError, TypeError):
                pass
        self._old.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> Optional[bool]:
        self.uninstall()
        return None

    # -- the agreement bit -------------------------------------------------

    def seen(self) -> bool:
        """This host's local flag (no collective)."""
        return self._flag.is_set()

    def should_stop(self, step: int) -> bool:
        """Cross-host-agreed stop decision, polled once per train step.

        Single-process: the local flag.  Multi-host: at steps where
        ``step % sync_every == 0`` EVERY host allgathers its local bit
        and ORs — a pure function of the gathered bits, so all hosts
        agree; between sync steps it returns False everywhere (including
        hosts that already saw SIGTERM), so no host can enter the
        collective emergency save alone.  sync_every bounds both the
        agreement latency and the per-step collective cost."""
        if jax.process_count() == 1:
            return self._flag.is_set()
        # boundary-CROSSING, not exact modulo: with a K-step fused
        # dispatch the poll only sees dispatch-boundary steps (K, 2K, …)
        # which may never be exact multiples of sync_every; every host
        # sees the SAME step sequence, so "crossed a sync boundary since
        # the last poll" is still a pure function all hosts agree on.
        prev = self._last_polled
        self._last_polled = step
        if step // self._sync_every <= prev // self._sync_every:
            return False
        from jax.experimental import multihost_utils
        bits = multihost_utils.process_allgather(
            np.asarray([1 if self._flag.is_set() else 0], np.int32))
        return bool(np.asarray(bits).max() > 0)
