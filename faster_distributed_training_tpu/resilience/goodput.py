"""Goodput/badput accounting — how much wall time actually trained.

The methodology mirrors Google's ML Goodput accounting: total wall time
splits into PRODUCTIVE time (steps that contributed to the final model)
and BADPUT categories — checkpoint-save blocking, emergency preemption
saves, restore time, supervisor restart backoff, and progress lost to a
rollback (steps re-run because the newest checkpoint predated the
crash).  Everything here is host-side bookkeeping: a few float adds per
event, nothing per-step on the hot path.

Consumed by: the Trainer (epoch ``[goodput]`` log line via
``train/metrics.py:attach_goodput``), ``cli.run_training`` (summary in
the result dict), and the ``ckpt_*`` arms in bench.py (checkpoint
overhead per step, async vs sync vs off)."""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Callable, Dict, Optional

# badput wall-time segments (seconds); anything not in a segment while
# the clock runs is counted productive.  detect_s = failure-to-observed
# latency (a peer's FAIL marker / heartbeat staleness, the pod
# coordinator's time-to-detect MTTR component; own-crash restarts cost
# ~0 detection).  readmission_hold_s = a survivor's parked time while a
# failed slice restarts and rejoins (r14 elastic recovery — the hold
# component of slice MTTR).
_SEGMENTS = ("checkpoint_blocking_s", "emergency_save_s", "restore_s",
             "restart_backoff_s", "rollback_lost_s", "detect_s",
             "readmission_hold_s")
# event counters (peer_failures / step_timeouts / restart_generations:
# pod-coordinated restarts, resilience/coordinator.py;
# slice_readmissions / pod_fallback_restarts: r14 slice-granular
# recovery — completed re-admissions vs holds/rejoins that degraded to
# the whole-pod protocol; warm_spare_claims / warm_spare_swaps: r17
# warm-spare slices — seats claimed vs swaps completed through release;
# skipped_steps / rollbacks / quarantined_batches / quarantined_shards:
# the anomaly sentinel — optimizer updates skipped by the in-graph
# non-finite guard, loss-spike rollbacks, batch positions durably
# quarantined by them, and CRC-failed stream shards remapped away
# (resilience/sentinel.py))
_COUNTERS = ("saves", "skipped_saves", "save_failures", "shard_writes",
             "restores", "restarts", "preemptions", "steps",
             "peer_failures", "step_timeouts", "restart_generations",
             "slice_readmissions", "pod_fallback_restarts",
             "warm_spare_claims", "warm_spare_swaps",
             "skipped_steps", "rollbacks", "quarantined_batches",
             "quarantined_shards")


class GoodputTracker:
    """Accumulates badput segments + event counters against a wall clock
    started at :meth:`start` (idempotent — the first caller wins, so the
    supervisor's clock spans every retry)."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._t0: Optional[float] = None
        self._seg: Dict[str, float] = {k: 0.0 for k in _SEGMENTS}
        self._cnt: Dict[str, int] = {k: 0 for k in _COUNTERS}
        # restore_s accrued BEFORE the first restart (a --resume/auto-
        # resume start) is not recovery work — snapshotted when the
        # first restart lands so the MTTR numerator excludes it
        self._restore_pre_restart: Optional[float] = None
        # program-acquisition (trace + compile-or-deserialize) seconds,
        # fed by the compile observatory (telemetry/programs.py) when
        # wired.  Tracked BESIDE the badput segments, not among them:
        # reclassifying compile as badput would shift every run's
        # goodput_pct — this exists to SPLIT restart MTTR into its
        # compile vs restore components (the ROADMAP "compile-dominated
        # on real hardware" half that restore_s alone can't see), so
        # only the post-restart share enters the MTTR numerator, same
        # pre/post-restart snapshot idiom as restore_s.
        self._compile_s = 0.0
        self._compile_pre_restart: Optional[float] = None
        # warm-spare swap wall time (claim -> release), also tracked
        # BESIDE the segments rather than among them: the swap window
        # CONTAINS a restore (already a badput segment) and the
        # catch-up training steps — counting it as a segment too would
        # double-bill badput and understate the spare's goodput_pct
        self._swap_s = 0.0
        # optional (counter, total) feed — the telemetry recorder
        # installs itself here (r12) so restarts/preemptions/peer
        # failures land in the run's JSONL stream AS THEY HAPPEN, not
        # only in the epoch-end snapshot.  `steps` is excluded: it ticks
        # every dispatch and the per-dispatch step records already carry
        # that information.
        self._event_sink: Optional[Callable[[str, int], None]] = None

    def set_event_sink(self, sink: Optional[Callable[[str, int], None]]
                       ) -> None:
        self._event_sink = sink

    def start(self) -> "GoodputTracker":
        if self._t0 is None:
            self._t0 = self._clock()
        return self

    def add(self, segment: str, seconds: float) -> None:
        if segment not in self._seg:
            raise KeyError(f"unknown badput segment {segment!r}; "
                           f"want one of {_SEGMENTS}")
        self._seg[segment] += float(seconds)

    def add_compile(self, seconds: float) -> None:
        """Program-acquisition seconds (compile OR cache deserialize) —
        the observatory's feed for the restart-MTTR compile split."""
        self._compile_s += float(seconds)

    def add_warm_spare_swap(self, seconds: float) -> None:
        """Warm-spare swap wall time (coordinator claim -> release) —
        published in the summary, never summed into badput (the window
        overlaps the restore segment and productive catch-up steps)."""
        self._swap_s += float(seconds)

    def count(self, counter: str, n: int = 1) -> None:
        if counter not in self._cnt:
            raise KeyError(f"unknown counter {counter!r}; "
                           f"want one of {_COUNTERS}")
        if counter == "restarts" and self._restore_pre_restart is None:
            self._restore_pre_restart = self._seg["restore_s"]
            self._compile_pre_restart = self._compile_s
        self._cnt[counter] += n
        if self._event_sink is not None and counter != "steps":
            try:
                self._event_sink(counter, self._cnt[counter])
            except Exception:
                pass  # observability must never fail accounting

    @contextmanager
    def timed(self, segment: str):
        t0 = self._clock()
        try:
            yield
        finally:
            self.add(segment, self._clock() - t0)

    def summary(self) -> Dict[str, float]:
        """One flat dict: wall/badput/productive seconds, goodput %, and
        the event counters.  Safe to call before start() (all zeros)."""
        total = (self._clock() - self._t0) if self._t0 is not None else 0.0
        badput = sum(self._seg.values())
        productive = max(total - badput, 0.0)
        out: Dict[str, float] = {
            "wall_s": round(total, 3),
            "productive_s": round(productive, 3),
            "badput_s": round(badput, 3),
            "goodput_pct": round(100.0 * productive / total, 2) if total
            else 100.0,
        }
        for k, v in self._seg.items():
            out[k] = round(v, 3)
        out.update(self._cnt)
        out["compile_s"] = round(self._compile_s, 3)
        out["warm_spare_swap_s"] = round(self._swap_s, 3)
        if self._cnt["steps"]:
            out["productive_step_ms"] = round(
                productive / self._cnt["steps"] * 1e3, 3)
        if self._cnt["restarts"]:
            # mean time-to-recover per restart: detection latency (peer
            # marker/staleness observation) + supervisor backoff +
            # checkpoint restore + program re-acquisition (recompile or
            # cache deserialize — r17: the compile-dominated component
            # real-hardware MTTR was blind to), with the compile and
            # restore halves published as restart_mttr_compile_s /
            # restart_mttr_restore_s so the executable cache's win is a
            # readable split.  Rollback replay cost is deliberately
            # separate (rollback_lost_s): it scales with checkpoint
            # cadence, not with recovery machinery.  Only restore/
            # compile time spent AFTER the first restart counts — the
            # restore (and first-compile) a resumed run starts from is
            # startup, not recovery, and would otherwise inflate the
            # headline.
            restarts = self._cnt["restarts"]
            recovery_restore = (self._seg["restore_s"]
                                - (self._restore_pre_restart or 0.0))
            recovery_compile = (self._compile_s
                                - (self._compile_pre_restart or 0.0))
            out["restart_mttr_restore_s"] = round(
                recovery_restore / restarts, 3)
            out["restart_mttr_compile_s"] = round(
                recovery_compile / restarts, 3)
            out["restart_mttr_s"] = round(
                (self._seg["detect_s"] + self._seg["restart_backoff_s"]
                 + recovery_restore + recovery_compile) / restarts, 3)
        return out
