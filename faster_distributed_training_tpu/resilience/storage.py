"""Pluggable checkpoint/coordination storage backend (r14 tentpole).

Every durable-write seam in the resilience stack — the two-phase sharded
checkpoint (npz blocks, manifests, DONE/COMMIT markers), the manager's
retention GC, and the pod coordinator's FAIL/HB/EXIT/RESTORE marker
transport — historically assumed ONE POSIX shared filesystem: atomic
writes were tmp + ``os.replace`` + fsync, retention was
``shutil.rmtree``, and the restore/commit barriers polled
``os.path.exists``.  Production multi-slice TPU pods break both halves
of that assumption: each slice mounts its own filesystem, and the only
durable medium every host can reach is an object store (GCS), which has
NO rename primitive — only whole-object PUT, generation-preconditioned
create, list-by-prefix and per-object delete.

:class:`StorageBackend` is the narrow contract both worlds satisfy:

  * ``put_bytes`` / ``put_stream`` / ``put_json`` — atomic whole-object
    publish: a reader sees the previous object (or absence) or the new
    one, never a torn middle.  POSIX implements it with the historic
    tmp+replace+fsync idiom (byte-compatible with every pre-r14
    checkpoint directory); object stores get it natively from PUT;
  * ``create_if_absent`` — the put-if-absent marker primitive (GCS
    ``if_generation_match=0``): first writer wins, losers observe False;
  * ``read_bytes(start, length)`` / ``open_read`` — ranged reads, so
    the block-filtered sharded restore (r10) can keep skipping npz
    members it doesn't need even when the "file" is a remote object
    (``open_read`` returns a seekable file-like whose reads translate
    to ranged GETs);
  * ``list_prefix`` / ``delete_prefix`` — discovery and BATCHED
    retention over a key prefix (an object store has no directories and
    no rmtree; prefix enumeration + batched delete is the native
    shape, and the POSIX implementation maps it back onto the tree);
  * ``exists`` / ``size`` / ``mtime`` — cheap metadata probes (the
    commit barrier polls ``exists``; heartbeat staleness reads
    ``mtime``).

Keys are plain "/"-separated paths (the same strings the call sites
always built with ``os.path.join``), so routing through the backend did
not require re-keying the world: :class:`PosixBackend` treats them as
filesystem paths verbatim, while the object-store backends relativize
them against their configured root.

Three implementations:

  * :class:`PosixBackend` — today's semantics, bit-for-bit.  The ONLY
    place in ``resilience/`` + ``train/checkpoint.py`` allowed to call
    ``os.replace``/``os.rename``/``shutil.rmtree``
    (``scripts/check_storage_routing.py`` lints the ban, tier-1).
  * :class:`FakeObjectStoreBackend` — object-store semantics with no
    rename anywhere: whole-object PUT, generation-preconditioned
    create, ranged reads, per-key delete.  Backed by a pluggable
    medium: :class:`MemoryMedium` (in-process dict — the tier-1 suite's
    simulated pods share one instance across host threads) or
    :class:`FileMedium` (a flat, rename-free on-disk encoding —
    footer-framed generation files created with ``O_EXCL`` — so the
    pod_restart_smoke script can run REAL multi-process pods against
    object-store semantics).  Fault-injectable (``fail_puts``) and
    op-counting (``counts``), so tests can both break it on purpose and
    prove "zero rename operations issued".
  * :class:`GCSBackend` — a thin real-object-store binding
    (``gs://bucket/prefix``).  COMMIT markers use the
    precondition-create path (the compose-or-precondition equivalent of
    the POSIX atomic rename), retention uses batched prefix deletes.
    The google-cloud-storage client is imported lazily and its absence
    is a clear error, not an import-time crash — this container does
    not ship it, so tier-1 exercises the object-store CODE PATHS
    against :class:`FakeObjectStoreBackend` and the GCS binding stays a
    documented, structurally-mirrored thin shim (README caveat).
"""

from __future__ import annotations

import io
import json
import os
import shutil
import threading
import time
import urllib.parse
from typing import Callable, Dict, List, Optional, Tuple


class StorageBackend:
    """Base class + shared helpers.  ``kind`` identifies the semantics
    class ("posix" | "fake_object_store" | "gcs"); everything that is
    not plain POSIX must survive without a rename primitive, which is
    what the manager keys its "force the sharded two-phase path" and
    "skip the orbax single-file path" decisions on."""

    kind: str = "abstract"

    # -- writes ------------------------------------------------------------

    def put_bytes(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def put_stream(self, key: str, write_fn: Callable) -> None:
        """Atomic publish of content produced by ``write_fn(fileobj)``.
        POSIX streams into the staging tmp file (no extra copy of a
        multi-GB shard set in host memory); object stores buffer and
        issue one whole-object PUT — inherent to the medium."""
        buf = io.BytesIO()
        write_fn(buf)
        self.put_bytes(key, buf.getvalue())

    def put_json(self, key: str, obj) -> None:
        self.put_bytes(key, json.dumps(obj).encode("utf-8"))

    def create_if_absent(self, key: str, data: bytes) -> bool:
        """Put-if-absent: True iff this call created the object (GCS
        ``if_generation_match=0``; POSIX ``O_EXCL``).  Losers must be
        able to trust that SOME complete object exists at `key`."""
        raise NotImplementedError

    # -- reads -------------------------------------------------------------

    def read_bytes(self, key: str, start: int = 0,
                   length: Optional[int] = None) -> bytes:
        raise NotImplementedError

    def read_json(self, key: str) -> Optional[dict]:
        """Parsed JSON object, or None when absent/torn — the marker-
        read contract every poller relies on."""
        try:
            return json.loads(self.read_bytes(key).decode("utf-8"))
        except (OSError, ValueError, KeyError):
            return None

    def open_read(self, key: str):
        """Seekable binary file-like over the object (ranged reads
        under the hood for object stores) — what lets ``np.load`` keep
        its lazy per-member npz access on every backend."""
        return _RangeReader(self, key)

    # -- metadata ----------------------------------------------------------

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    def size(self, key: str) -> int:
        raise NotImplementedError

    def mtime(self, key: str) -> float:
        """Last-modified unix time; raises OSError when absent."""
        raise NotImplementedError

    # -- listing / deletion ------------------------------------------------

    def list_prefix(self, prefix: str) -> List[str]:
        """Every object key starting with `prefix` (full keys, any
        depth).  Directories are not objects and never appear."""
        raise NotImplementedError

    def list_entries(self, prefix: str) -> List[str]:
        """Immediate child NAMES under a directory-like prefix — one
        path component, no recursion.  THE discovery primitive for the
        hot enumeration sites (checkpoint entries after every save,
        generation dirs / FAIL markers every poll): object stores
        derive it from the key listing; POSIX overrides with a single
        readdir so a large checkpoint tree (telemetry JSONL, orbax
        epoch trees) is never walked whole just to name its top
        level."""
        base = prefix.rstrip("/").rstrip(os.sep) + os.sep
        out = set()
        for key in self.list_prefix(base):
            rel = key[len(base):]
            out.add(rel.split(os.sep, 1)[0].split("/", 1)[0])
        return sorted(n for n in out if n)

    def any_prefix(self, prefix: str) -> bool:
        return bool(self.list_prefix(prefix))

    def delete(self, key: str) -> None:
        """Idempotent single-object delete (absent key is a no-op)."""
        raise NotImplementedError

    def delete_prefix(self, prefix: str) -> int:
        """Batched delete of every object under `prefix`; returns the
        number of objects removed.  THE retention/GC primitive — maps
        to rmtree on POSIX and to list+batched-delete on object
        stores."""
        n = 0
        for k in self.list_prefix(prefix):
            self.delete(k)
            n += 1
        return n

    # -- conveniences ------------------------------------------------------

    def ensure_dir(self, path: str) -> None:
        """POSIX needs parent directories to exist before an atomic
        write can stage next to its target; object stores have no
        directories and no-op."""

    def join(self, *parts: str) -> str:
        return "/".join(p.rstrip("/") for p in parts if p)


class _RangeReader(io.RawIOBase):
    """Seekable read-only file over ``backend.read_bytes`` ranged
    GETs.  Small sequential reads are the np.load/zipfile access
    pattern; each ``read`` issues exactly one ranged fetch."""

    def __init__(self, backend: StorageBackend, key: str):
        self._b, self._key = backend, key
        self._size = backend.size(key)
        self._pos = 0

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def seek(self, offset: int, whence: int = io.SEEK_SET) -> int:
        if whence == io.SEEK_SET:
            self._pos = offset
        elif whence == io.SEEK_CUR:
            self._pos += offset
        elif whence == io.SEEK_END:
            self._pos = self._size + offset
        else:
            raise ValueError(f"bad whence {whence}")
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, n: int = -1) -> bytes:
        if self._pos >= self._size:
            return b""
        length = self._size - self._pos if n is None or n < 0 else \
            min(n, self._size - self._pos)
        data = self._b.read_bytes(self._key, start=self._pos, length=length)
        self._pos += len(data)
        return data

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)


# ---------------------------------------------------------------------------
# POSIX
# ---------------------------------------------------------------------------


class PosixBackend(StorageBackend):
    """The historic shared-filesystem semantics, byte-compatible with
    every existing checkpoint directory: atomic publish is tmp +
    ``os.replace`` + fsync (exactly the pre-r14 ``_write_json_atomic``
    idiom, staged beside the target so the rename never crosses a
    filesystem), listing walks the tree, prefix deletion is rmtree.
    Keys are filesystem paths verbatim."""

    kind = "posix"

    def put_bytes(self, key: str, data: bytes) -> None:
        self.put_stream(key, lambda f: f.write(data))

    def put_stream(self, key: str, write_fn: Callable) -> None:
        self.ensure_dir(os.path.dirname(key))
        # pid + thread ident in the staging name: markers are written
        # from both the watchdog thread and the main thread — a shared
        # tmp path would let one thread's replace consume the other's
        # staged file (the r10 coordinator lesson, kept here)
        tmp = f"{key}.tmp{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, key)

    def create_if_absent(self, key: str, data: bytes) -> bool:
        self.ensure_dir(os.path.dirname(key))
        try:
            fd = os.open(key, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        return True

    def read_bytes(self, key: str, start: int = 0,
                   length: Optional[int] = None) -> bytes:
        with open(key, "rb") as f:
            if start:
                f.seek(start)
            return f.read() if length is None else f.read(length)

    def open_read(self, key: str):
        return open(key, "rb")        # the real thing beats a shim

    def exists(self, key: str) -> bool:
        return os.path.exists(key)

    def size(self, key: str) -> int:
        return os.path.getsize(key)

    def mtime(self, key: str) -> float:
        return os.path.getmtime(key)

    def list_prefix(self, prefix: str) -> List[str]:
        # `prefix` is a path prefix, not necessarily a directory: walk
        # the deepest existing directory at-or-above it and filter.
        # Empty LEAF directories surface as pseudo-keys (their own
        # path): an object store cannot have them, but POSIX crash
        # residue can (a mkdir with nothing staged yet), and the
        # manager's torn-dir sweep must still see it.
        root = prefix if os.path.isdir(prefix) else os.path.dirname(prefix)
        out = []
        for dirpath, dirs, files in os.walk(root):
            for name in files:
                p = os.path.join(dirpath, name)
                if p.startswith(prefix):
                    out.append(p)
            if not dirs and not files and dirpath.startswith(prefix) \
                    and dirpath != root:
                out.append(dirpath)
        return sorted(out)

    def any_prefix(self, prefix: str) -> bool:
        return os.path.isdir(prefix) or os.path.exists(prefix) \
            or bool(self.list_prefix(prefix))

    def list_entries(self, prefix: str) -> List[str]:
        # one readdir — names of files AND directories (a bare mkdir
        # from a crashed save is an entry the torn-dir sweep must see)
        try:
            with os.scandir(prefix) as it:
                return sorted(e.name for e in it)
        except OSError:
            return []

    def delete(self, key: str) -> None:
        try:
            os.remove(key)
        except OSError:
            pass

    def delete_prefix(self, prefix: str) -> int:
        if os.path.isdir(prefix):
            n = sum(len(files) for _d, _s, files in os.walk(prefix))
            shutil.rmtree(prefix, ignore_errors=True)
            return n
        n = 0
        for k in self.list_prefix(prefix):
            self.delete(k)
            n += 1
        return n

    def ensure_dir(self, path: str) -> None:
        if path:
            os.makedirs(path, exist_ok=True)


# module singleton: the default backend of every routed call site, so
# pre-r14 callers (and the orbax single-file path) behave identically
# without threading a backend through code that never needs another one
_POSIX = PosixBackend()


def posix_backend() -> PosixBackend:
    return _POSIX


# ---------------------------------------------------------------------------
# Fake object store (tier-1's GCS stand-in)
# ---------------------------------------------------------------------------


class MemoryMedium:
    """In-process object map — the unit the simulated-pod THREADS
    share.  All mutation under one lock; values are
    (bytes, generation, mtime)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._objects: Dict[str, Tuple[bytes, int, float]] = {}

    def put(self, name: str, data: bytes) -> None:
        with self._lock:
            gen = self._objects.get(name, (b"", 0, 0.0))[1] + 1
            self._objects[name] = (bytes(data), gen, time.time())

    def create(self, name: str, data: bytes) -> bool:
        with self._lock:
            if name in self._objects:
                return False
            self._objects[name] = (bytes(data), 1, time.time())
            return True

    def get(self, name: str) -> Optional[Tuple[bytes, float]]:
        with self._lock:
            got = self._objects.get(name)
            return None if got is None else (got[0], got[2])

    def list(self) -> List[str]:
        with self._lock:
            return sorted(self._objects)

    def remove(self, name: str) -> bool:
        with self._lock:
            return self._objects.pop(name, None) is not None


class FileMedium:
    """Rename-free on-disk object map, so a fake-object-store pod can
    span real PROCESSES (scripts/pod_restart_smoke.py --backend
    fake_object_store).  One flat directory; each object is a family of
    *generation files* ``<quoted-key>.g<N>`` written with
    ``O_CREAT|O_EXCL`` (the creation itself is the atomicity: no
    staging, no rename) and framed as

        8-byte big-endian payload length | payload | 8-byte magic

    A torn write (killed mid-PUT) lacks the trailing magic or the full
    length and is ignored by readers; the newest VALID generation wins,
    which is exactly an object store's last-writer-wins PUT.  Old
    generations are best-effort unlinked after a successful put."""

    _MAGIC = b"FDTOBJ\r\n"

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _enc(self, name: str) -> str:
        return urllib.parse.quote(name, safe="")

    def _dec(self, fname: str) -> str:
        return urllib.parse.unquote(fname)

    def _gens(self, name: str) -> List[Tuple[int, str]]:
        enc = self._enc(name) + ".g"
        out = []
        try:
            for f in os.listdir(self.root):
                if f.startswith(enc):
                    try:
                        out.append((int(f[len(enc):]),
                                    os.path.join(self.root, f)))
                    except ValueError:
                        pass
        except OSError:
            return []
        return sorted(out)

    def _read_valid(self, path: str) -> Optional[Tuple[bytes, float]]:
        try:
            with open(path, "rb") as f:
                raw = f.read()
            st = os.stat(path)
        except OSError:
            return None
        if len(raw) < 16 or raw[-8:] != self._MAGIC:
            return None
        n = int.from_bytes(raw[:8], "big")
        if len(raw) != 16 + n:
            return None
        return raw[8:8 + n], st.st_mtime

    def _frame(self, data: bytes) -> bytes:
        return len(data).to_bytes(8, "big") + data + self._MAGIC

    def _write_gen(self, name: str, gen0: int, data: bytes) -> bool:
        gen = gen0
        framed = self._frame(data)
        while True:
            path = os.path.join(self.root, f"{self._enc(name)}.g{gen:06d}")
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                if gen0 == 0 and gen == 0:
                    return False        # create-if-absent lost the race
                gen += 1
                continue
            with os.fdopen(fd, "wb") as f:
                f.write(framed)
                f.flush()
                os.fsync(f.fileno())
            # sweep superseded generations (best-effort — a concurrent
            # reader that already opened one still reads it to the end)
            for g, p in self._gens(name):
                if g < gen:
                    try:
                        os.remove(p)
                    except OSError:
                        pass
            return True

    def put(self, name: str, data: bytes) -> None:
        gens = self._gens(name)
        self._write_gen(name, (gens[-1][0] + 1) if gens else 1, data)

    def create(self, name: str, data: bytes) -> bool:
        # an object exists iff ANY valid generation does; O_EXCL on
        # gen 0 arbitrates true creation races.  A key whose valid
        # generations were all deleted (or torn) re-creates at the next
        # generation number — that path's concurrent-create arbitration
        # is best-effort only, like an object store recreated right
        # after a delete (generation preconditions restart)
        if self.get(name) is not None:
            return False
        gens = self._gens(name)
        if not gens:
            return self._write_gen(name, 0, data)
        return self._write_gen(name, gens[-1][0] + 1, data)

    def get(self, name: str) -> Optional[Tuple[bytes, float]]:
        for _g, path in reversed(self._gens(name)):
            got = self._read_valid(path)
            if got is not None:
                return got
        return None

    def list(self) -> List[str]:
        names = set()
        try:
            files = os.listdir(self.root)
        except OSError:
            return []
        for f in files:
            enc, _, tail = f.rpartition(".g")
            if enc and tail.isdigit():
                names.add(self._dec(enc))
        return sorted(n for n in names if self.get(n) is not None)

    def remove(self, name: str) -> bool:
        hit = False
        for _g, path in self._gens(name):
            try:
                os.remove(path)
                hit = True
            except OSError:
                pass
        return hit


class FakeObjectStoreBackend(StorageBackend):
    """Object-store semantics for tier-1 (and rename-free multi-process
    smokes): no rename primitive EXISTS on this class — writes are
    whole-object PUTs, markers are generation-preconditioned creates,
    retention is list+delete.  ``counts`` tallies every operation (the
    acceptance's "zero rename operations issued" is checked both ways:
    the op vocabulary has no rename, and tests additionally trap
    ``os.replace``/``os.rename`` while the backend runs).  ``fail_puts``
    arms deterministic write faults for the torn-save tests."""

    kind = "fake_object_store"

    def __init__(self, medium=None, root: str = ""):
        self.medium = medium if medium is not None else MemoryMedium()
        self.root = os.path.abspath(root) if root else ""
        self.counts: Dict[str, int] = {
            "put": 0, "create": 0, "read": 0, "list": 0, "delete": 0}
        self._fail_puts_match: Optional[str] = None
        self._fail_puts_left = 0
        self._lock = threading.Lock()

    # keys arrive as the same absolute-ish paths the POSIX world uses;
    # the store's namespace is rooted, so relativize when a root is set.
    # A trailing separator (a "directory" prefix) survives abspath
    # normalization — prefix listings depend on it.
    def _k(self, key: str) -> str:
        trailing = key.endswith(os.sep) or key.endswith("/")
        if self.root:
            key = os.path.abspath(key)
            if key == self.root:
                return ""
            if key.startswith(self.root + os.sep):
                key = key[len(self.root) + 1:]
        key = key.replace(os.sep, "/")
        if trailing and key and not key.endswith("/"):
            key += "/"
        return key

    def fail_puts(self, substring: str, count: int = 1) -> None:
        """Arm the next `count` puts whose key contains `substring` to
        raise OSError — the injected-storage-fault seam."""
        with self._lock:
            self._fail_puts_match = substring
            self._fail_puts_left = int(count)

    def _maybe_fail(self, key: str) -> None:
        with self._lock:
            if (self._fail_puts_left > 0 and self._fail_puts_match is not None
                    and self._fail_puts_match in key):
                self._fail_puts_left -= 1
                raise OSError(f"injected object-store PUT failure: {key}")

    def put_bytes(self, key: str, data: bytes) -> None:
        k = self._k(key)
        self._maybe_fail(k)
        self.counts["put"] += 1
        self.medium.put(k, data)

    def create_if_absent(self, key: str, data: bytes) -> bool:
        k = self._k(key)
        self._maybe_fail(k)
        self.counts["create"] += 1
        return self.medium.create(k, data)

    def read_bytes(self, key: str, start: int = 0,
                   length: Optional[int] = None) -> bytes:
        got = self.medium.get(self._k(key))
        if got is None:
            raise FileNotFoundError(f"no object {key!r}")
        self.counts["read"] += 1
        data = got[0]
        if start or length is not None:
            stop = None if length is None else start + length
            return data[start:stop]
        return data

    def exists(self, key: str) -> bool:
        return self.medium.get(self._k(key)) is not None

    def size(self, key: str) -> int:
        got = self.medium.get(self._k(key))
        if got is None:
            raise FileNotFoundError(f"no object {key!r}")
        return len(got[0])

    def mtime(self, key: str) -> float:
        got = self.medium.get(self._k(key))
        if got is None:
            raise OSError(f"no object {key!r}")
        return got[1]

    def list_prefix(self, prefix: str) -> List[str]:
        self.counts["list"] += 1
        p = self._k(prefix)
        return [self._unk(name) for name in self.medium.list()
                if name.startswith(p)]

    def _unk(self, name: str) -> str:
        return (self.root + os.sep + name.replace("/", os.sep)) \
            if self.root else name

    def delete(self, key: str) -> None:
        if self.medium.remove(self._k(key)):
            self.counts["delete"] += 1


# ---------------------------------------------------------------------------
# GCS (thin; exercised against the fake in tier-1 — README caveat)
# ---------------------------------------------------------------------------


class GCSBackend(StorageBackend):
    """``gs://bucket/prefix`` binding of the same contract.  Atomic
    publish is the object store's native PUT; the COMMIT/DONE marker
    creates use ``if_generation_match=0`` (the compose-or-precondition
    equivalent of the POSIX atomic-rename commit); retention issues
    batched prefix deletes (one HTTP batch per 100 objects, the client
    library's batch limit).  Local paths relativize against ``root``
    (the run's checkpoint_dir) exactly like the fake backend, so the
    manager/coordinator key-building code is shared verbatim.

    The google-cloud-storage client is resolved lazily; this container
    does not ship it, so construction raises a clear RuntimeError and
    tier-1 proves the object-store code paths on
    :class:`FakeObjectStoreBackend` instead (ROADMAP caveat)."""

    kind = "gcs"

    def __init__(self, bucket: str, prefix: str = "", root: str = ""):
        try:
            from google.cloud import storage as gcs  # noqa: PLC0415
        except ImportError as e:
            raise RuntimeError(
                "GCSBackend needs the google-cloud-storage client, which "
                "is not installed in this environment — use "
                "--storage_backend fake_object_store to exercise the "
                "object-store code paths, or install the client where "
                "GCS is reachable") from e
        try:
            self._client = gcs.Client()
        except Exception as e:
            raise RuntimeError(
                f"GCSBackend could not construct a client ({e}) — "
                f"missing credentials?  Set up Application Default "
                f"Credentials on every pod host, or use "
                f"--storage_backend fake_object_store for local "
                f"object-semantics testing") from e
        self._bucket = self._client.bucket(bucket)
        self.bucket_name = bucket
        self.prefix = prefix.strip("/")
        self.root = os.path.abspath(root) if root else ""

    def _k(self, key: str) -> str:
        if self.root:
            key = os.path.abspath(key)
            if key.startswith(self.root + os.sep):
                key = key[len(self.root) + 1:]
            elif key == self.root:
                key = ""
        key = key.replace(os.sep, "/").lstrip("/")
        return f"{self.prefix}/{key}" if self.prefix else key

    def put_bytes(self, key: str, data: bytes) -> None:
        self._bucket.blob(self._k(key)).upload_from_string(
            data, content_type="application/octet-stream")

    def create_if_absent(self, key: str, data: bytes) -> bool:
        from google.api_core import exceptions as gexc  # noqa: PLC0415
        try:
            self._bucket.blob(self._k(key)).upload_from_string(
                data, content_type="application/octet-stream",
                if_generation_match=0)
            return True
        except gexc.PreconditionFailed:
            return False

    def read_bytes(self, key: str, start: int = 0,
                   length: Optional[int] = None) -> bytes:
        end = None if length is None else start + length - 1
        return self._bucket.blob(self._k(key)).download_as_bytes(
            start=start or None, end=end)

    def exists(self, key: str) -> bool:
        return self._bucket.blob(self._k(key)).exists()

    def size(self, key: str) -> int:
        blob = self._bucket.get_blob(self._k(key))
        if blob is None:
            raise FileNotFoundError(f"no object {key!r}")
        return int(blob.size)

    def mtime(self, key: str) -> float:
        blob = self._bucket.get_blob(self._k(key))
        if blob is None or blob.updated is None:
            raise OSError(f"no object {key!r}")
        return blob.updated.timestamp()

    def list_prefix(self, prefix: str) -> List[str]:
        p = self._k(prefix)
        out = []
        for blob in self._client.list_blobs(self._bucket, prefix=p):
            name = blob.name
            if self.prefix:
                name = name[len(self.prefix) + 1:]
            local = name.replace("/", os.sep)
            out.append(self.root + os.sep + local if self.root else local)
        return out

    def delete(self, key: str) -> None:
        from google.api_core import exceptions as gexc  # noqa: PLC0415
        try:
            self._bucket.blob(self._k(key)).delete()
        except gexc.NotFound:
            pass

    def delete_prefix(self, prefix: str) -> int:
        keys = self.list_prefix(prefix)
        for i in range(0, len(keys), 100):     # client batch limit
            chunk = keys[i:i + 100]
            try:
                # deletes inside a batch context are DEFERRED: per-call
                # NotFound suppression cannot work, errors surface at
                # batch __exit__ — so the whole chunk is try/excepted
                with self._client.batch():
                    for k in chunk:
                        self._bucket.blob(self._k(k)).delete()
            except Exception:
                # a concurrently-deleted object (another host's sweep,
                # a lifecycle rule) fails the batch: fall back to
                # per-object tolerant deletes — retention must never
                # crash training over a deletion race
                for k in chunk:
                    self.delete(k)
        return len(keys)


# ---------------------------------------------------------------------------
# construction
# ---------------------------------------------------------------------------


def build_backend(spec: str, root: str,
                  log: Callable[[str], None] = print) -> StorageBackend:
    """Backend from a --storage_backend spec:

      * ""/"posix"            -> :class:`PosixBackend` (the default;
                                 byte-compatible with every existing
                                 checkpoint directory)
      * "fake_object_store"   -> :class:`FakeObjectStoreBackend` over a
                                 :class:`FileMedium` under
                                 ``<root>/_objects`` (cross-process
                                 durable, rename-free — the smoke /
                                 simulated-pod configuration)
      * "gs://bucket[/prefix]"-> :class:`GCSBackend`

    ``root`` (the run's checkpoint_dir) anchors key relativization for
    the object-store backends."""
    spec = (spec or "posix").strip()
    if spec in ("", "posix"):
        return _POSIX
    root = os.path.abspath(root)
    if spec == "fake_object_store":
        log(f"[storage] fake object store (rename-free FileMedium) under "
            f"{root}/_objects — markers/shards live as framed objects, "
            f"not plain files")
        return FakeObjectStoreBackend(
            FileMedium(os.path.join(root, "_objects")), root=root)
    if spec.startswith("gs://"):
        rest = spec[len("gs://"):]
        bucket, _, prefix = rest.partition("/")
        if not bucket:
            raise ValueError(f"malformed GCS spec {spec!r}: want "
                             f"gs://bucket[/prefix]")
        log(f"[storage] GCS backend bucket={bucket} prefix={prefix!r}")
        return GCSBackend(bucket, prefix=prefix, root=root)
    raise ValueError(
        f"unknown --storage_backend {spec!r}: want posix, "
        f"fake_object_store, or gs://bucket[/prefix]")
