"""Resilience subsystem: async + preemption-aware checkpointing, fault
injection, supervised restarts, and goodput accounting.

The reference has no fault-tolerance story at all (SURVEY.md §5: rank-0
``{net, acc, epoch}`` saves gated on best accuracy; recovery is a manual
re-launch) — on preemptible TPU pods every interruption costs whole
epochs.  This package closes that gap in five orthogonal pieces, each
layered on machinery the repo already has:

  * ``manager``     — :class:`AsyncCheckpointManager`: step/wall-clock
    cadence saves layered on ``train/checkpoint.py``, keep-last-K
    retention, atomic commit markers, off-critical-path writes;
  * ``preemption``  — :class:`PreemptionHandler`: SIGTERM/SIGINT →
    cross-host-agreed emergency save (the agreement bit makes the
    collective save deadlock-proof);
  * ``supervisor``  — :class:`Supervisor`: bounded-retry exponential-
    backoff restarts from the newest *valid* checkpoint, refusing to
    loop on deterministic crashes;
  * ``faults``      — :class:`FaultPlan`: deterministic env-driven fault
    injection (die/SIGTERM at step N, data-iterator raise, checkpoint
    corruption) that the CPU test suite drives;
  * ``goodput``     — :class:`GoodputTracker`: productive time vs.
    checkpoint/restore/restart badput, surfaced per epoch through
    ``train/metrics.py`` and benched by the ``ckpt_*`` bench.py arms.

``Resilience`` bundles the pieces for the Trainer; ``build_resilience``
constructs the bundle from a TrainConfig (cli.run_training's path).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional


class Preempted(Exception):
    """Raised by the train loop after a cross-host-agreed preemption and
    a successful emergency save.  Carries the post-save train state so
    the caller can exit cleanly — this is a clean shutdown, NOT a
    failure: the supervisor re-raises it instead of retrying (the
    platform, not this process, owns the restart after a preemption)."""

    def __init__(self, message: str, state=None, step: Optional[int] = None):
        super().__init__(message)
        self.state = state
        self.step = step


from faster_distributed_training_tpu.resilience.goodput import (  # noqa: E402,F401,E501
    GoodputTracker)
from faster_distributed_training_tpu.resilience.manager import (  # noqa: E402,F401,E501
    AsyncCheckpointManager, RestoreDivergence)
from faster_distributed_training_tpu.resilience.preemption import (  # noqa: E402,F401,E501
    PreemptionHandler)
from faster_distributed_training_tpu.resilience.supervisor import (  # noqa: E402,F401,E501
    Supervisor)
from faster_distributed_training_tpu.resilience.faults import (  # noqa: E402,F401,E501
    FaultPlan, InjectedFault, corrupt_newest_checkpoint)


@dataclasses.dataclass
class Resilience:
    """The bundle the Trainer consumes (train/loop.py).  Any piece may be
    None; ``goodput`` always exists so accounting never needs guards."""

    manager: Optional[AsyncCheckpointManager] = None
    preemption: Optional[PreemptionHandler] = None
    faults: Optional[FaultPlan] = None
    goodput: GoodputTracker = dataclasses.field(default_factory=GoodputTracker)

    def close(self) -> None:
        if self.manager is not None:
            self.manager.close()
        if self.preemption is not None:
            self.preemption.uninstall()


def build_resilience(cfg, log: Callable[[str], None] = print
                     ) -> Optional[Resilience]:
    """Resilience bundle for a TrainConfig, or None when every knob is
    off (the default — the Trainer's hot loop then has zero new work).

    Enabled by any of: --checkpoint_every / --checkpoint_every_secs
    (step-cadence manager + preemption handler), --supervise, or an
    armed FDT_FAULT_* plan (fault injection needs the hooks even when
    checkpointing is off)."""
    faults = FaultPlan.from_env()
    cadence = bool(cfg.checkpoint_every or cfg.checkpoint_every_secs)
    if not (cadence or cfg.supervise or faults is not None):
        return None
    goodput = GoodputTracker()
    manager = None
    if cadence:
        manager = AsyncCheckpointManager(
            cfg.checkpoint_dir,
            # mirror the epoch-checkpoint naming (loop.py ckpt_name) so
            # two workloads sharing a checkpoint_dir never restore each
            # other's step checkpoints
            prefix=("transformer" if cfg.model == "transformer"
                    else "resnet"),
            every_steps=cfg.checkpoint_every,
            every_secs=cfg.checkpoint_every_secs,
            keep=cfg.checkpoint_keep,
            async_save=cfg.checkpoint_async,
            goodput=goodput, log=log)
    preemption = PreemptionHandler(sync_every=cfg.preempt_sync_every,
                                   log=log).install()
    return Resilience(manager=manager, preemption=preemption,
                      faults=faults, goodput=goodput)
